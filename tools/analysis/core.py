"""Pass-manager core for the project-native static-analysis suite.

Generic linters see syntax; every correctness bug PR 3 fixed was a
*cross-layer invariant* (engine-dispatch drift, int32 offset wrap, a
blocking payload path into the shared coalescer) that only a checker
with project knowledge can state. This module is the machinery those
checkers share:

- ``Project``: a source tree rooted anywhere (the real repo in tier-1,
  a fixture tree in tests), with lazily parsed ASTs per file.
- ``SourceFile.index`` -> :class:`ModuleIndex`: the cached per-file
  symbol table (functions with enclosing class, awaited-call set,
  loops, classes) so fourteen-plus passes stop re-walking the same
  tree N times.
- :class:`CallGraph`: name-keyed intra-module call edges with
  one-level propagation — the generalization of the async-blocking
  pass's "a sync helper containing a blocking call taints its async
  call sites" hack, now shared by any pass that needs "callers of X
  inherit property P".
- :class:`ReachingDefs`: an intraprocedural reaching-definitions
  dataflow walk (branch-merging, loop-approximating, closure-aware)
  answering "which loads can this assignment's value reach?" — what
  the task-lifecycle pass uses to prove a ``create_task`` result is
  awaited/cancelled/stored rather than leaked.
- :class:`CFG`: a per-function exception-edge-aware control-flow
  graph (statement-granularity nodes; raise/return/break/continue
  edges; every ``await`` carries a potential-cancellation exit;
  ``with``/``finally`` coverage per node) — the third core layer,
  shared by the resource-lifecycle and cancel-safety passes and
  cached per function via :meth:`SourceFile.cfg`.
- ``Pass``: one named rule (``rule`` id, ``doc`` rationale) producing
  ``Finding``s. Passes are registered in ``tools.analysis.passes``.
- Suppressions: ``# klogs: ignore[rule-id]`` on the flagged line or the
  line above waives that rule there (``ignore[*]`` waives all). A
  suppressed finding is still reported — as suppressed — so waivers
  stay visible instead of rotting silently. ``run`` records which
  suppression comments actually matched a finding, and the
  suppression-audit pass flags the ones that no longer do (a stale
  waiver is a hole the next regression walks through).
- ``run``: execute passes, apply suppressions, return an exit code
  (non-zero iff any unsuppressed finding), with human, JSON, or SARIF
  output.

Passes must stay import-light (ast/re + pure-CPU project modules, never
jax): the whole suite runs inside tier-1's budget as one short test.
"""

from __future__ import annotations

import ast
import json
import os
import re
import time
from dataclasses import asdict, dataclass, field
from typing import Any, Iterable, Iterator


@dataclass
class Finding:
    """One rule violation at a source location. ``line`` 0 means the
    finding is file- or project-level (e.g. a docs-parity mismatch) and
    cannot be suppressed inline."""

    rule: str
    path: str  # repo-relative, '/'-separated
    line: int
    message: str
    suppressed: bool = False

    def format(self) -> str:
        where = f"{self.path}:{self.line}" if self.line else self.path
        tag = " (suppressed)" if self.suppressed else ""
        return f"{where}: [{self.rule}]{tag} {self.message}"


_SUPPRESS_RE = re.compile(r"#\s*klogs:\s*ignore\[([a-z0-9*,-]+)\]")


def dotted(node: ast.AST) -> str:
    """'a.b.c' for Attribute/Name chains, '' otherwise. The shared
    spelling every pass used to redefine privately."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


_DEFS = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda, ast.ClassDef)


def own_nodes(fn: ast.AST, *,
              include_nested_sync: bool = False) -> list[ast.AST]:
    """Nodes of ``fn`` excluding nested function/class bodies (they run
    in their own context and are analyzed as their own functions).
    ``include_nested_sync=True`` prunes ONLY nested ``async def``
    subtrees — the async-blocking semantics, where sync helpers,
    lambdas, and class bodies defined inside an ``async def`` all run
    on the loop (when called / at definition time)."""
    out: list[ast.AST] = []
    stack: list[ast.AST] = list(ast.iter_child_nodes(fn))
    while stack:
        n = stack.pop()
        if include_nested_sync:
            if isinstance(n, ast.AsyncFunctionDef):
                continue
        elif isinstance(n, _DEFS):
            continue
        out.append(n)
        stack.extend(ast.iter_child_nodes(n))
    return out


@dataclass
class FuncInfo:
    """One function/method with its enclosing-class context."""

    node: "ast.FunctionDef | ast.AsyncFunctionDef"
    name: str
    cls: "str | None"  # enclosing class name, None for module level
    is_async: bool

    @property
    def qualname(self) -> str:
        return f"{self.cls}.{self.name}" if self.cls else self.name


class ModuleIndex:
    """The per-file symbol table passes share (``SourceFile.index``):
    every function def with its enclosing class, the set of awaited
    call nodes, top-level classes, and loop statements — computed in
    ONE walk and cached on the file."""

    def __init__(self, tree: ast.AST):
        self.functions: list[FuncInfo] = []
        self.classes: list[ast.ClassDef] = []
        self.loops: "list[ast.For | ast.AsyncFor | ast.While]" = []
        self.awaited: set[int] = set()
        # (node, enclosing_class) DFS; a method's class is the nearest
        # enclosing ClassDef, functions nested in functions keep it.
        stack: list[tuple[ast.AST, "str | None"]] = [(tree, None)]
        while stack:
            node, cls = stack.pop()
            if isinstance(node, ast.ClassDef):
                self.classes.append(node)
                cls = node.name
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.functions.append(FuncInfo(
                    node, node.name, cls,
                    isinstance(node, ast.AsyncFunctionDef)))
            elif isinstance(node, (ast.For, ast.AsyncFor, ast.While)):
                self.loops.append(node)
            elif (isinstance(node, ast.Await)
                    and isinstance(node.value, ast.Call)):
                self.awaited.add(id(node.value))
            stack.extend((c, cls) for c in ast.iter_child_nodes(node))
        self.functions.sort(key=lambda f: f.node.lineno)
        self._by_name: dict[str, list[FuncInfo]] = {}
        for f in self.functions:
            self._by_name.setdefault(f.name, []).append(f)

    def functions_named(self, name: str) -> list[FuncInfo]:
        return self._by_name.get(name, [])

    @property
    def async_functions(self) -> list[FuncInfo]:
        return [f for f in self.functions if f.is_async]

    @property
    def sync_functions(self) -> list[FuncInfo]:
        return [f for f in self.functions if not f.is_async]

    @staticmethod
    def callee_name(call: ast.Call) -> "str | None":
        """Intra-module callee key: ``helper(...)`` -> ``helper``,
        ``self.helper(...)`` -> ``helper`` (methods dispatch on the
        same class in practice), anything else -> None."""
        func = call.func
        if isinstance(func, ast.Name):
            return func.id
        if (isinstance(func, ast.Attribute)
                and isinstance(func.value, ast.Name)
                and func.value.id == "self"):
            return func.attr
        return None


class CallGraph:
    """Name-keyed call edges within one module, with ONE level of
    propagation: a property proven about a function's own body
    (``seeds``) taints its direct call sites. One level is the honest
    scope — deeper transitive closure over dynamic dispatch would
    claim precision the name-keyed edges don't have."""

    def __init__(self, index: ModuleIndex):
        self.index = index

    def calls_in(self, fn: ast.AST, *,
                 include_nested_sync: bool = False) -> list[ast.Call]:
        return [n for n in own_nodes(
            fn, include_nested_sync=include_nested_sync)
            if isinstance(n, ast.Call)]

    def propagate(self, seeds: dict[str, Any], *,
                  callers: "Iterable[FuncInfo] | None" = None,
                  include_nested_sync: bool = False,
                  skip_awaited: bool = True,
                  ) -> "Iterator[tuple[FuncInfo, ast.Call, str, Any]]":
        """Yield ``(caller, call_node, callee_name, seed_value)`` for
        every call site in ``callers`` (default: every function) whose
        callee name is seeded. ``skip_awaited`` drops awaited calls
        (an awaited helper isn't the blocking/fire-and-forget shape)."""
        pool = self.index.functions if callers is None else callers
        for caller in pool:
            for call in self.calls_in(
                    caller.node, include_nested_sync=include_nested_sync):
                if skip_awaited and id(call) in self.index.awaited:
                    continue
                name = self.index.callee_name(call)
                if name is not None and name in seeds:
                    yield caller, call, name, seeds[name]


# Spawn primitives that start a NEW execution context: the callable
# they receive runs later, on the loop or on another thread, with none
# of the spawner's lexical state (locks held, loop affinity) carried
# over. ``(dotted-suffix, argument position)``; position -1 means the
# ``target=`` keyword (threading.Thread).
_SPAWN_SITES: "tuple[tuple[str, int], ...]" = (
    ("create_task", 0),
    ("ensure_future", 0),
    ("to_thread", 0),
    ("run_in_executor", 1),
    ("submit", 0),
    ("Thread", -1),
)


def spawn_target_names(index: ModuleIndex) -> set[str]:
    """Names of functions/methods handed to a spawn primitive anywhere
    in the module (``create_task(self.f(...))`` spawns a call result,
    so the target there is the inner call's callee). A name in this
    set runs in its own execution context: lexical facts about its
    call sites (a caller's ``with`` block, loop affinity) must not be
    credited to it."""
    out: set[str] = set()

    def _target_name(node: ast.AST) -> "str | None":
        if isinstance(node, ast.Call):  # create_task(self.f())
            return ModuleIndex.callee_name(node)
        if isinstance(node, ast.Name):
            return node.id
        if isinstance(node, ast.Attribute):
            return node.attr
        return None

    for info in index.functions:
        for call in (n for n in ast.walk(info.node)
                     if isinstance(n, ast.Call)):
            spelled = dotted(call.func)
            for suffix, pos in _SPAWN_SITES:
                if not (spelled == suffix
                        or spelled.endswith(f".{suffix}")):
                    continue
                arg: "ast.AST | None" = None
                if pos == -1:
                    arg = next((kw.value for kw in call.keywords
                                if kw.arg == "target"), None)
                elif len(call.args) > pos:
                    arg = call.args[pos]
                name = _target_name(arg) if arg is not None else None
                if name is not None:
                    out.add(name)
    return out


class ReachingDefs:
    """Intraprocedural reaching definitions for one function.

    Statements are walked in order with an environment mapping each
    local name to the set of assignments that may currently bind it;
    branches fork and merge the environment, loop bodies run twice (the
    one-iteration fixpoint approximation), and loads inside nested
    defs/lambdas count as uses of EVERY definition of that name in the
    function (closures capture by reference — the final binding is
    what they see, and for lint purposes any capture is a use).

    Query with :meth:`uses_of`: the Name-load nodes a given assignment
    statement's value can reach. An empty answer for a
    ``t = create_task(...)`` statement is exactly the hedge-loser leak
    shape the task-lifecycle pass hunts."""

    def __init__(self, fn: "ast.FunctionDef | ast.AsyncFunctionDef"):
        self._uses: dict[int, list[ast.Name]] = {}
        self._defs_by_name: dict[str, list[int]] = {}
        self._nested_loads: set[str] = set()
        env: dict[str, set[int]] = {}
        for arg in self._arg_names(fn):
            env[arg] = set()
        self._walk_block(fn.body, env)
        # Closure captures: a load of `name` inside a nested def uses
        # every def of that name in this function.
        for name in self._nested_loads:
            for d in self._defs_by_name.get(name, []):
                self._uses.setdefault(d, []).append(
                    ast.Name(id=name, ctx=ast.Load()))

    @staticmethod
    def _arg_names(fn: "ast.FunctionDef | ast.AsyncFunctionDef") -> list[str]:
        a = fn.args
        names = [x.arg for x in (
            list(a.posonlyargs) + list(a.args) + list(a.kwonlyargs))]
        if a.vararg:
            names.append(a.vararg.arg)
        if a.kwarg:
            names.append(a.kwarg.arg)
        return names

    def uses_of(self, stmt: ast.AST) -> list[ast.Name]:
        """Name loads reached by the bindings ``stmt`` created."""
        return self._uses.get(id(stmt), [])

    # -- the walk -----------------------------------------------------

    def _bind(self, name: str, stmt: ast.AST,
              env: dict[str, set[int]]) -> None:
        env[name] = {id(stmt)}
        self._defs_by_name.setdefault(name, []).append(id(stmt))

    def _load(self, node: ast.Name, env: dict[str, set[int]]) -> None:
        for d in env.get(node.id, ()):
            self._uses.setdefault(d, []).append(node)

    def _visit_expr(self, node: "ast.AST | None",
                    env: dict[str, set[int]]) -> None:
        if node is None:
            return
        stack = [node]
        while stack:
            n = stack.pop()
            if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load):
                self._load(n, env)
                continue
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.Lambda)):
                body = n.body if isinstance(n.body, list) else [n.body]
                for sub in body:
                    for x in ast.walk(sub):
                        if (isinstance(x, ast.Name)
                                and isinstance(x.ctx, ast.Load)):
                            self._nested_loads.add(x.id)
                continue
            stack.extend(ast.iter_child_nodes(n))

    def _bind_target(self, target: ast.AST, stmt: ast.AST,
                     env: dict[str, set[int]]) -> None:
        if isinstance(target, ast.Name):
            self._bind(target.id, stmt, env)
        elif isinstance(target, (ast.Tuple, ast.List)):
            for el in target.elts:
                self._bind_target(el, stmt, env)
        elif isinstance(target, ast.Starred):
            self._bind_target(target.value, stmt, env)
        else:
            # self.x = v / d[k] = v: the target expression READS names.
            self._visit_expr(target, env)

    @staticmethod
    def _merge(a: dict[str, set[int]],
               b: dict[str, set[int]]) -> dict[str, set[int]]:
        out = {k: set(v) for k, v in a.items()}
        for k, v in b.items():
            out.setdefault(k, set()).update(v)
        return out

    def _walk_block(self, stmts: list[ast.stmt],
                    env: dict[str, set[int]]) -> dict[str, set[int]]:
        for stmt in stmts:
            env = self._walk_stmt(stmt, env)
        return env

    def _walk_stmt(self, stmt: ast.stmt,
                   env: dict[str, set[int]]) -> dict[str, set[int]]:
        if isinstance(stmt, ast.Assign):
            self._visit_expr(stmt.value, env)
            for t in stmt.targets:
                self._bind_target(t, stmt, env)
        elif isinstance(stmt, ast.AnnAssign):
            self._visit_expr(stmt.value, env)
            if stmt.value is not None:
                self._bind_target(stmt.target, stmt, env)
        elif isinstance(stmt, ast.AugAssign):
            self._visit_expr(stmt.value, env)
            if isinstance(stmt.target, ast.Name):
                # x += v reads x (a use of prior defs), then rebinds it.
                for d in env.get(stmt.target.id, ()):
                    self._uses.setdefault(d, []).append(stmt.target)
                self._bind(stmt.target.id, stmt, env)
            else:
                self._visit_expr(stmt.target, env)
        elif isinstance(stmt, (ast.If,)):
            self._visit_expr(stmt.test, env)
            env_then = self._walk_block(stmt.body,
                                        {k: set(v) for k, v in env.items()})
            env_else = self._walk_block(stmt.orelse,
                                        {k: set(v) for k, v in env.items()})
            env = self._merge(env_then, env_else)
        elif isinstance(stmt, (ast.For, ast.AsyncFor)):
            self._visit_expr(stmt.iter, env)
            self._bind_target(stmt.target, stmt, env)
            once = self._walk_block(stmt.body,
                                    {k: set(v) for k, v in env.items()})
            merged = self._merge(env, once)
            again = self._walk_block(stmt.body, merged)
            env = self._merge(merged, again)
            env = self._walk_block(stmt.orelse, env)
        elif isinstance(stmt, ast.While):
            self._visit_expr(stmt.test, env)
            once = self._walk_block(stmt.body,
                                    {k: set(v) for k, v in env.items()})
            merged = self._merge(env, once)
            self._visit_expr(stmt.test, merged)
            again = self._walk_block(stmt.body, merged)
            env = self._merge(merged, again)
            env = self._walk_block(stmt.orelse, env)
        elif isinstance(stmt, ast.Try):
            env_body = self._walk_block(stmt.body,
                                        {k: set(v) for k, v in env.items()})
            merged = self._merge(env, env_body)
            for h in stmt.handlers:
                henv = {k: set(v) for k, v in merged.items()}
                if h.name:
                    self._bind(h.name, h, henv)
                merged = self._merge(merged, self._walk_block(h.body, henv))
            merged = self._walk_block(stmt.orelse, merged)
            env = self._walk_block(stmt.finalbody, merged)
        elif isinstance(stmt, (ast.With, ast.AsyncWith)):
            for item in stmt.items:
                self._visit_expr(item.context_expr, env)
                if item.optional_vars is not None:
                    self._bind_target(item.optional_vars, stmt, env)
            env = self._walk_block(stmt.body, env)
        elif isinstance(stmt, ast.Match):
            # match/case: each case body forks the env; capture names
            # in the pattern (MatchAs/MatchStar/MatchMapping.rest) bind
            # there. Merged with the fall-through env (no case may
            # match).
            self._visit_expr(stmt.subject, env)
            merged = {k: set(v) for k, v in env.items()}
            for case in stmt.cases:
                cenv = {k: set(v) for k, v in env.items()}
                for p in ast.walk(case.pattern):
                    name = getattr(p, "name", None) or getattr(
                        p, "rest", None)
                    if isinstance(name, str):
                        self._bind(name, case, cenv)
                self._visit_expr(case.guard, cenv)
                merged = self._merge(merged,
                                     self._walk_block(case.body, cenv))
            env = merged
        elif isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                               ast.ClassDef)):
            self._visit_expr(stmt, env)  # nested scope: capture scan
        elif isinstance(stmt, (ast.Return, ast.Expr, ast.Raise,
                               ast.Assert, ast.Delete, ast.Await)):
            for child in ast.iter_child_nodes(stmt):
                self._visit_expr(child, env)
        elif isinstance(stmt, (ast.Global, ast.Nonlocal, ast.Pass,
                               ast.Break, ast.Continue, ast.Import,
                               ast.ImportFrom)):
            pass
        else:
            for child in ast.iter_child_nodes(stmt):
                self._visit_expr(child, env)
        return env


class CFGNode:
    """One statement-granularity node of a :class:`CFG`."""

    __slots__ = ("idx", "stmt", "line", "can_raise", "has_await",
                 "in_finally", "withs")

    def __init__(self, idx: int, stmt: ast.AST, line: int, *,
                 can_raise: bool, has_await: bool, in_finally: bool,
                 withs: "tuple[str, ...]"):
        self.idx = idx
        self.stmt = stmt
        self.line = line
        self.can_raise = can_raise    # any call/await/yield in the stmt
        self.has_await = has_await    # a potential-cancellation point
        self.in_finally = in_finally  # lexically inside a finally body
        self.withs = withs            # dotted names of enclosing `with`s


class _Fin:
    """An active ``finally`` region during CFG construction: abrupt
    edges raised inside the try route to ``entry``; when the Try
    completes, ``exits`` (the finally body's dangling frontier) is
    connected onward to every recorded continuation in ``conts``."""

    __slots__ = ("entry", "exits", "conts")

    def __init__(self, entry: int,
                 exits: "list[tuple[int, str]]"):
        self.entry = entry
        self.exits = exits
        # (kind, remaining outer-fin chain, final sink token)
        self.conts: "list[tuple[str, tuple[_Fin, ...], tuple[Any, ...]]]" = []


class _Loop:
    __slots__ = ("head", "breaks", "fin_depth")

    def __init__(self, head: int, fin_depth: int):
        self.head = head
        self.breaks: "list[tuple[int, str]]" = []
        self.fin_depth = fin_depth


class CFG:
    """Exception-edge-aware control-flow graph for one function.

    Statement-granularity nodes; edges carry a kind. Besides the
    ordinary ``next``/``true``/``false``/``loop``/``case`` flow, every
    statement that can raise (contains a call/await/yield, or is an
    ``assert``/``raise``) gets a ``raise`` edge to each handler of the
    nearest enclosing ``try`` *and* an abrupt ``raise`` path through
    the enclosing ``finally`` chain to EXIT (handlers are matched
    conservatively — an ``except Exception`` never catches
    ``KeyboardInterrupt``, so the escape path is always real). In an
    ``async def``, every await additionally gets a ``cancel`` edge:
    cancellation routes through enclosing ``finally`` bodies to EXIT
    but deliberately NOT into ``except`` handlers — on Python >= 3.8
    ``CancelledError`` is a ``BaseException`` that ``except
    Exception`` does not see, which is exactly the semantics the
    cancel-safety pass leans on. ``return``/``break``/``continue``
    route through intervening finallies likewise. A finally body's
    exit frontier is connected to *every* recorded continuation (the
    standard over-approximation), and to the normal fall-through only
    when some normal path actually enters the finally.

    Known over-approximations, accepted for lint purposes: unmatched
    handlers still receive raise edges; ``while`` loops with a
    non-constant test always have a false edge; a try-inside-finally
    uses the inner region's first node as the finally entry.

    Query with :meth:`succ` / :meth:`node_of` /
    :meth:`path_to_exit`."""

    EXIT = -1

    def __init__(self, fn: "ast.FunctionDef | ast.AsyncFunctionDef"):
        self.fn = fn
        self.is_async = isinstance(fn, ast.AsyncFunctionDef)
        self.nodes: "list[CFGNode]" = []
        self.entry: "int | None" = None
        self._succ: "dict[int, list[tuple[int, str]]]" = {}
        self._node_of: "dict[int, int]" = {}
        self._fins: "list[_Fin]" = []
        self._loops: "list[_Loop]" = []
        # (raiser node list, catch-all?) per active try-with-handlers
        self._tries: "list[tuple[list[int], bool]]" = []
        self._withs: "list[str]" = []
        self._fin_depth = 0
        tail = self._block(fn.body, [])
        for src, _kind in tail:
            self._edge(src, self.EXIT, "fall")

    # -- queries ------------------------------------------------------

    def succ(self, idx: int) -> "list[tuple[int, str]]":
        return self._succ.get(idx, [])

    def node_of(self, stmt: ast.AST) -> "int | None":
        """Node index of a statement (identity keyed), None if the
        statement placed no node (e.g. a bare ``try``)."""
        return self._node_of.get(id(stmt))

    def exit_edges(self) -> "list[tuple[int, str]]":
        out = []
        for src, edges in self._succ.items():
            out.extend((src, kind) for dst, kind in edges
                       if dst == self.EXIT)
        return out

    def path_to_exit(self, start: int,
                     stop: "Any") -> "tuple[int, str] | None":
        """BFS from ``start``'s successors; ``stop(node) -> bool``
        halts traversal through a node (the obligation was met on that
        path). Returns the ``(src_idx, kind)`` of the first EXIT edge
        a surviving path reaches, else None. ``start``'s own exit
        edges are skipped (an acquire that raises never produced the
        resource)."""
        seen = {start}
        queue: "list[tuple[int, int, str]]" = [
            (start, dst, kind) for dst, kind in self.succ(start)]
        pos = 0
        while pos < len(queue):
            src, dst, kind = queue[pos]
            pos += 1
            if dst == self.EXIT:
                if src == start:
                    continue
                return (src, kind)
            if dst in seen:
                continue
            seen.add(dst)
            if stop(self.nodes[dst]):
                continue
            queue.extend((dst, d2, k2) for d2, k2 in self.succ(dst))
        return None

    # -- construction -------------------------------------------------

    def _edge(self, src: int, dst: int, kind: str) -> None:
        self._succ.setdefault(src, []).append((dst, kind))

    def _place(self, stmt: ast.AST, frontier: "list[tuple[int, str]]",
               *, can_raise: bool, has_await: bool) -> int:
        idx = len(self.nodes)
        self.nodes.append(CFGNode(
            idx, stmt, getattr(stmt, "lineno", 0),
            can_raise=can_raise, has_await=has_await,
            in_finally=self._fin_depth > 0, withs=tuple(self._withs)))
        self._node_of[id(stmt)] = idx
        if self.entry is None:
            self.entry = idx
        for src, kind in frontier:
            self._edge(src, idx, kind)
        return idx

    @staticmethod
    def _scan(*exprs: "ast.AST | None") -> "tuple[bool, bool]":
        """(can_raise, has_await) over expressions. Calls, awaits and
        yields can raise; nested def/lambda bodies are included (an
        over-approximation that only widens the graph)."""
        can_raise = has_await = False
        for e in exprs:
            if e is None:
                continue
            for n in ast.walk(e):
                if isinstance(n, (ast.Call, ast.Await, ast.Yield,
                                  ast.YieldFrom)):
                    can_raise = True
                if isinstance(n, ast.Await):
                    has_await = True
        return can_raise, has_await

    def _abrupt(self, srcs: "list[int]", kind: str,
                chain: "list[_Fin]",
                sink: "tuple[Any, ...]") -> None:
        """Route an abrupt edge through ``chain`` (innermost finally
        first) toward ``sink``: ("exit",) | ("break", loop) |
        ("continue", loop)."""
        if not chain:
            if sink[0] == "exit":
                for src in srcs:
                    self._edge(src, self.EXIT, kind)
            elif sink[0] == "break":
                sink[1].breaks.extend((src, kind) for src in srcs)
            else:  # continue
                for src in srcs:
                    self._edge(src, sink[1].head, kind)
            return
        fin = chain[0]
        for src in srcs:
            self._edge(src, fin.entry, kind)
        fin.conts.append((kind, tuple(chain[1:]), sink))

    def _raise_and_cancel(self, idx: int, *, can_raise: bool,
                          has_await: bool) -> None:
        chain = list(reversed(self._fins))
        if can_raise:
            catch_all = False
            if self._tries:
                raisers, catch_all = self._tries[-1]
                raisers.append(idx)
            # A bare `except:` / `except BaseException` region lets
            # nothing escape; anything narrower (incl. `except
            # Exception`) leaves the raise edge out — KeyboardInterrupt
            # and friends still walk it.
            if not catch_all:
                self._abrupt([idx], "raise", chain, ("exit",))
        if has_await and self.is_async:
            self._abrupt([idx], "cancel", chain, ("exit",))

    def _block(self, stmts: "list[ast.stmt]",
               frontier: "list[tuple[int, str]]",
               ) -> "list[tuple[int, str]]":
        for stmt in stmts:
            frontier = self._stmt(stmt, frontier)
        return frontier

    def _stmt(self, stmt: ast.stmt,
              frontier: "list[tuple[int, str]]",
              ) -> "list[tuple[int, str]]":
        if isinstance(stmt, ast.If):
            cr, aw = self._scan(stmt.test)
            idx = self._place(stmt, frontier, can_raise=cr,
                              has_await=aw)
            self._raise_and_cancel(idx, can_raise=cr, has_await=aw)
            out = self._block(stmt.body, [(idx, "true")])
            if stmt.orelse:
                out += self._block(stmt.orelse, [(idx, "false")])
            else:
                out.append((idx, "false"))
            return out

        if isinstance(stmt, ast.While):
            cr, aw = self._scan(stmt.test)
            idx = self._place(stmt, frontier, can_raise=cr,
                              has_await=aw)
            self._raise_and_cancel(idx, can_raise=cr, has_await=aw)
            loop = _Loop(idx, len(self._fins))
            self._loops.append(loop)
            body_f = self._block(stmt.body, [(idx, "true")])
            for src, _k in body_f:
                self._edge(src, idx, "loop")
            self._loops.pop()
            always = (isinstance(stmt.test, ast.Constant)
                      and bool(stmt.test.value))
            out = [] if always else [(idx, "false")]
            if stmt.orelse:
                out = self._block(stmt.orelse, out)
            return out + loop.breaks

        if isinstance(stmt, (ast.For, ast.AsyncFor)):
            cr, aw = self._scan(stmt.iter, stmt.target)
            cr = True  # advancing the iterator can raise
            aw = aw or isinstance(stmt, ast.AsyncFor)
            idx = self._place(stmt, frontier, can_raise=cr,
                              has_await=aw)
            self._raise_and_cancel(idx, can_raise=cr, has_await=aw)
            loop = _Loop(idx, len(self._fins))
            self._loops.append(loop)
            body_f = self._block(stmt.body, [(idx, "true")])
            for src, _k in body_f:
                self._edge(src, idx, "loop")
            self._loops.pop()
            out = [(idx, "false")]
            if stmt.orelse:
                out = self._block(stmt.orelse, out)
            return out + loop.breaks

        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            exprs: "list[ast.AST | None]" = []
            names: "list[str]" = []
            for item in stmt.items:
                exprs.append(item.context_expr)
                exprs.append(item.optional_vars)
                name = dotted(item.context_expr)
                if item.optional_vars is not None:
                    name = dotted(item.optional_vars) or name
                if name:
                    names.append(name)
            cr, aw = self._scan(*exprs)
            aw = aw or isinstance(stmt, ast.AsyncWith)
            idx = self._place(stmt, frontier, can_raise=cr,
                              has_await=aw)
            self._raise_and_cancel(idx, can_raise=cr, has_await=aw)
            self._withs.extend(names)
            out = self._block(stmt.body, [(idx, "next")])
            del self._withs[len(self._withs) - len(names):]
            return out

        if isinstance(stmt, ast.Try):
            return self._try(stmt, frontier)

        if isinstance(stmt, ast.Match):
            cr, aw = self._scan(stmt.subject)
            idx = self._place(stmt, frontier, can_raise=cr,
                              has_await=aw)
            self._raise_and_cancel(idx, can_raise=cr, has_await=aw)
            out = [(idx, "nomatch")]
            for case in stmt.cases:
                out += self._block(case.body, [(idx, "case")])
            return out

        if isinstance(stmt, ast.Return):
            cr, aw = self._scan(stmt.value)
            idx = self._place(stmt, frontier, can_raise=cr,
                              has_await=aw)
            self._raise_and_cancel(idx, can_raise=cr, has_await=aw)
            self._abrupt([idx], "return", list(reversed(self._fins)),
                         ("exit",))
            return []

        if isinstance(stmt, ast.Raise):
            idx = self._place(stmt, frontier, can_raise=True,
                              has_await=False)
            self._raise_and_cancel(idx, can_raise=True,
                                   has_await=False)
            return []

        if isinstance(stmt, (ast.Break, ast.Continue)):
            idx = self._place(stmt, frontier, can_raise=False,
                              has_await=False)
            if self._loops:
                loop = self._loops[-1]
                kind = ("break" if isinstance(stmt, ast.Break)
                        else "continue")
                chain = list(reversed(self._fins[loop.fin_depth:]))
                self._abrupt([idx], kind, chain, (kind, loop))
            return []

        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef, ast.Global, ast.Nonlocal,
                             ast.Pass, ast.Import, ast.ImportFrom)):
            idx = self._place(stmt, frontier, can_raise=False,
                              has_await=False)
            return [(idx, "next")]

        # Simple statement: scan the whole thing.
        cr, aw = self._scan(stmt)
        cr = cr or isinstance(stmt, ast.Assert)
        idx = self._place(stmt, frontier, can_raise=cr, has_await=aw)
        self._raise_and_cancel(idx, can_raise=cr, has_await=aw)
        return [(idx, "next")]

    @staticmethod
    def _catch_all(handlers: "list[ast.ExceptHandler]") -> bool:
        for h in handlers:
            if h.type is None:
                return True
            types = (h.type.elts if isinstance(h.type, ast.Tuple)
                     else [h.type])
            for t in types:
                if dotted(t).split(".")[-1] == "BaseException":
                    return True
        return False

    def _try(self, stmt: ast.Try,
             frontier: "list[tuple[int, str]]",
             ) -> "list[tuple[int, str]]":
        fin: "_Fin | None" = None
        if stmt.finalbody:
            # Build the finally body eagerly (with only OUTER fins
            # active) so abrupt edges inside the try have a target.
            marker = len(self.nodes)
            self._fin_depth += 1
            fin_exits = self._block(stmt.finalbody, [])
            self._fin_depth -= 1
            fin = _Fin(marker, fin_exits)

        raisers: "list[int]" = []
        if stmt.handlers:
            self._tries.append((raisers, self._catch_all(stmt.handlers)))
        if fin is not None:
            self._fins.append(fin)
        body_f = self._block(stmt.body, frontier)
        if stmt.handlers:
            self._tries.pop()
        # The else block runs after normal completion; exceptions
        # there are NOT caught by this try's handlers.
        else_f = (self._block(stmt.orelse, body_f)
                  if stmt.orelse else body_f)
        handler_f: "list[tuple[int, str]]" = []
        for h in stmt.handlers:
            hidx = self._place(h, [], can_raise=False,
                               has_await=False)
            for r in raisers:
                self._edge(r, hidx, "raise")
            handler_f += self._block(h.body, [(hidx, "except")])

        if fin is None:
            return else_f + handler_f

        self._fins.pop()
        normal = else_f + handler_f
        for src, kind in normal:
            self._edge(src, fin.entry, kind)
        srcs = [s for s, _k in fin.exits]
        done: "set[tuple[Any, ...]]" = set()
        for kind, chain, sink in fin.conts:
            key = (kind, tuple(id(c) for c in chain), sink[0],
                   id(sink[1]) if len(sink) > 1 else 0)
            if key in done:
                continue
            done.add(key)
            self._abrupt(srcs, kind, list(chain), sink)
        return list(fin.exits) if normal else []


class SourceFile:
    """One parsed source file: text, AST (lazy), the cached
    :class:`ModuleIndex`, and the per-line suppression table."""

    def __init__(self, root: str, relpath: str):
        self.relpath = relpath
        self.path = os.path.join(root, *relpath.split("/"))
        with open(self.path, encoding="utf-8") as f:
            self.text = f.read()
        self._tree: "ast.AST | None" = None
        self._index: "ModuleIndex | None" = None
        self._suppress: "dict[int, set[str]] | None" = None
        self._cfgs: "dict[int, CFG]" = {}

    @property
    def tree(self) -> ast.AST:
        if self._tree is None:
            # A syntax error is not a finding: the tree is unanalyzable,
            # so crash loudly (py_compile/tier-1 owns syntax).
            self._tree = ast.parse(self.text, filename=self.path)
        return self._tree

    @property
    def index(self) -> ModuleIndex:
        """The cached symbol table — built once, shared by every pass
        that looks at this file."""
        if self._index is None:
            self._index = ModuleIndex(self.tree)
        return self._index

    def cfg(self, fn: "ast.FunctionDef | ast.AsyncFunctionDef") -> CFG:
        """The cached exception-edge CFG for a function in this file
        (identity keyed) — built once, shared between the
        resource-lifecycle and cancel-safety passes."""
        got = self._cfgs.get(id(fn))
        if got is None:
            got = self._cfgs[id(fn)] = CFG(fn)
        return got

    def suppressions(self) -> dict[int, set[str]]:
        """Per-line ignore table, from COMMENT tokens only — a
        docstring quoting the ``# klogs: ignore[...]`` grammar must not
        register as a waiver (it bit this module's own docstring).
        Non-Python files (the C sources some passes read) fall back to
        the raw line scan, where strings can't embed ``#`` comments."""
        if self._suppress is None:
            table: dict[int, set[str]] = {}
            try:
                import io
                import tokenize

                for tok in tokenize.generate_tokens(
                        io.StringIO(self.text).readline):
                    if tok.type != tokenize.COMMENT:
                        continue
                    m = _SUPPRESS_RE.search(tok.string)
                    if m:
                        table[tok.start[0]] = {
                            r.strip() for r in m.group(1).split(",")}
            except (SyntaxError, tokenize.TokenError, ValueError):
                table = {}
                for i, line in enumerate(self.text.splitlines(), start=1):
                    m = _SUPPRESS_RE.search(line)
                    if m:
                        table[i] = {r.strip()
                                    for r in m.group(1).split(",")}
            self._suppress = table
        return self._suppress

    def is_suppressed(self, rule: str, line: int) -> bool:
        """True when the flagged line (or the line above, for comments
        that would overlong the flagged one) waives ``rule``."""
        return self.matching_suppression(rule, line) is not None

    def matching_suppression(self, rule: str,
                             line: int) -> "tuple[int, str] | None":
        """The (comment line, matched token) that waives ``rule`` at
        ``line``, or None — the token is the rule id or ``*``. Exposed
        so ``run`` can record which waivers are actually load-bearing
        (the suppression-audit pass flags the rest)."""
        table = self.suppressions()
        for ln in (line, line - 1):
            rules = table.get(ln)
            if not rules:
                continue
            if rule in rules:
                return ln, rule
            if "*" in rules:
                return ln, "*"
        return None


class Project:
    """A source tree; passes ask it for files by relative path or
    prefix. Missing files yield None / empty — a pass scoped to a file
    a fixture tree doesn't seed simply has nothing to say there."""

    def __init__(self, root: str):
        self.root = os.path.abspath(root)
        self._cache: dict[str, "SourceFile | None"] = {}
        self._walk_cache: dict[str, list[str]] = {}

    def file(self, relpath: str) -> "SourceFile | None":
        if relpath not in self._cache:
            try:
                self._cache[relpath] = SourceFile(self.root, relpath)
            except OSError:
                self._cache[relpath] = None
        return self._cache[relpath]

    def loaded_files(self) -> list[SourceFile]:
        """Every file any pass has touched this run (the
        suppression-audit working set)."""
        return [sf for sf in self._cache.values() if sf is not None]

    def _walk(self, prefix: str) -> list[str]:
        if prefix not in self._walk_cache:
            full = os.path.join(self.root, *prefix.split("/"))
            rels: list[str] = []
            for dirpath, dirnames, filenames in os.walk(full):
                dirnames[:] = sorted(
                    d for d in dirnames if d != "__pycache__")
                rel_dir = os.path.relpath(dirpath, self.root).replace(
                    os.sep, "/")
                for fn in sorted(filenames):
                    if fn.endswith(".py"):
                        rels.append(f"{rel_dir}/{fn}")
            self._walk_cache[prefix] = rels
        return self._walk_cache[prefix]

    def files(self, *prefixes: str) -> list[SourceFile]:
        """Every .py file under the given repo-relative prefixes (a
        prefix may also name a single file)."""
        out: list[SourceFile] = []
        for prefix in prefixes:
            full = os.path.join(self.root, *prefix.split("/"))
            if os.path.isfile(full):
                sf = self.file(prefix)
                if sf is not None:
                    out.append(sf)
                continue
            for rel in self._walk(prefix):
                sf = self.file(rel)
                if sf is not None:
                    out.append(sf)
        return out

    def read_text(self, relpath: str) -> "str | None":
        """Non-Python project files (docs, C sources) — no AST; C files
        get their own regex-level checks (native-tier)."""
        try:
            with open(os.path.join(self.root, *relpath.split("/")),
                      encoding="utf-8") as f:
                return f.read()
        except OSError:
            return None


class Pass:
    """One named invariant. Subclasses set ``rule`` (the id that
    appears in output and ``ignore[...]`` comments) and ``doc`` (one
    line of rationale, shown by --list), and implement ``run``.

    A pass that needs the whole run's outcome (the suppression audit)
    implements ``run_post(project, report, executed, used)`` instead
    and leaves ``run`` returning []."""

    rule = "base"
    doc = ""

    def run(self, project: Project) -> list[Finding]:
        raise NotImplementedError

    def run_post(self, project: Project, report: "Report",
                 executed: set, used: set) -> list[Finding]:
        """Post-run hook: ``executed`` is the rule-id set that actually
        ran, ``used`` the (path, comment-line, token) triples whose
        suppression matched a finding. Default: nothing."""
        return []

    def finding(self, path: str, line: int, message: str) -> Finding:
        return Finding(self.rule, path, line, message)


@dataclass
class Report:
    findings: list[Finding] = field(default_factory=list)
    errors: list[str] = field(default_factory=list)
    # Wall-clock per pass (seconds, rule-keyed) plus the whole run
    # under "total" — the analysis suite rides tier-1 against a hard
    # time budget, so growth must stay visibly accounted.
    timings: "dict[str, float]" = field(default_factory=dict)

    @property
    def active(self) -> list[Finding]:
        return [f for f in self.findings if not f.suppressed]

    @property
    def suppressed(self) -> list[Finding]:
        return [f for f in self.findings if f.suppressed]

    @property
    def exit_code(self) -> int:
        return 1 if (self.active or self.errors) else 0

    def to_json(self) -> str:
        return json.dumps(
            {
                "findings": [asdict(f) for f in self.findings],
                "errors": list(self.errors),
                "counts": {
                    "active": len(self.active),
                    "suppressed": len(self.suppressed),
                },
                "timings_s": {k: round(v, 4)
                              for k, v in self.timings.items()},
            },
            indent=1,
        )

    def to_sarif(self, passes: "list[Pass]") -> str:
        """SARIF 2.1.0 — what CI annotation surfaces consume. Exit-code
        semantics live in ``exit_code``; this is serialization only.
        Suppressed findings carry an inSource suppression object so
        they render as waived, not failing."""
        rules = [{
            "id": p.rule,
            "shortDescription": {"text": p.doc or p.rule},
            "helpUri": "docs/STATIC_ANALYSIS.md",
        } for p in passes]
        results = []
        for f in self.findings:
            res: dict[str, Any] = {
                "ruleId": f.rule,
                "level": "error",
                "message": {"text": f.message},
                "locations": [{
                    "physicalLocation": {
                        "artifactLocation": {
                            "uri": f.path,
                            "uriBaseId": "SRCROOT",
                        },
                        "region": {"startLine": max(f.line, 1)},
                    },
                }],
            }
            if f.suppressed:
                res["suppressions"] = [{"kind": "inSource"}]
            results.append(res)
        doc = {
            "version": "2.1.0",
            "$schema": ("https://json.schemastore.org/sarif-2.1.0.json"),
            "runs": [{
                "tool": {"driver": {
                    "name": "klogs-tools-analysis",
                    "informationUri": "docs/STATIC_ANALYSIS.md",
                    "rules": rules,
                }},
                "results": results,
                "invocations": [{
                    "executionSuccessful": self.exit_code == 0,
                }],
            }],
        }
        return json.dumps(doc, indent=1)


def run(root: str, rules: "list[str] | None" = None,
        passes: "list[Pass] | None" = None) -> Report:
    """Run the (selected) passes over ``root`` and fold in
    suppressions. A pass that raises is an analyzer bug and is reported
    as an error (non-zero exit) rather than silently passing the tree
    it failed to check."""
    if passes is None:
        from tools.analysis.passes import all_passes

        passes = all_passes()
    project = Project(root)
    report = Report()
    if rules is not None:
        # A typoed rule id must not silently select nothing — that
        # would turn a gate into a vacuous pass.
        known = {p.rule for p in passes}
        for r in rules:
            if r not in known:
                report.errors.append(f"unknown rule {r!r} "
                                     f"(known: {', '.join(sorted(known))})")

    executed: set = set()
    used: set = set()  # (path, comment line, matched token)

    def _fold(found: list[Finding]) -> None:
        for f in found:
            sf = project.file(f.path) if f.line else None
            if sf is not None:
                hit = sf.matching_suppression(f.rule, f.line)
                if hit is not None:
                    f.suppressed = True
                    used.add((f.path, hit[0], hit[1]))
            report.findings.append(f)

    t_run = time.perf_counter()
    post: list[Pass] = []
    for p in passes:
        if rules is not None and p.rule not in rules:
            continue
        executed.add(p.rule)
        if type(p).run_post is not Pass.run_post:
            post.append(p)
            continue
        t0 = time.perf_counter()
        try:
            found = p.run(project)
        except Exception as e:  # noqa: BLE001 - analyzer must not lie
            report.errors.append(f"pass {p.rule} crashed: {e!r}")
            continue
        finally:
            report.timings[p.rule] = time.perf_counter() - t0
        _fold(found)
    for p in post:
        t0 = time.perf_counter()
        try:
            found = p.run_post(project, report, executed, used)
        except Exception as e:  # noqa: BLE001
            report.errors.append(f"pass {p.rule} crashed: {e!r}")
            continue
        finally:
            report.timings[p.rule] = time.perf_counter() - t0
        _fold(found)
    report.findings.sort(key=lambda f: (f.path, f.line, f.rule))
    report.timings["total"] = time.perf_counter() - t_run
    return report
