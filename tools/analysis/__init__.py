"""Project-native static-analysis suite (``python -m tools.analysis``).

Encodes the cross-layer invariants behind every correctness bug fixed
in PR 3 — engine-dispatch feature drift, int32 frame-offset overflow,
blocking/poisoning paths into the shared coalescer — as AST-level
passes that run in tier-1, so those bug *classes* stay dead instead of
being re-chased one instance at a time. Rule catalog and suppression
syntax: docs/STATIC_ANALYSIS.md.
"""

from tools.analysis.core import Finding, Pass, Project, Report, run

__all__ = ["Finding", "Pass", "Project", "Report", "run"]
