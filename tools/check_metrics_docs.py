#!/usr/bin/env python
"""Lint: the metric inventory in code and docs must agree.

Compares ``klogs_tpu.obs.inventory.SPECS`` (the single place metric
names/types/help live; ``Registry.family`` resolves through it, so a
name used anywhere in the code is in SPECS by construction) against the
inventory table in docs/OBSERVABILITY.md, in both directions:

- a SPECS entry missing from the doc table = undocumented metric;
- a doc table row naming no SPECS entry = stale documentation.

Run standalone (exit 1 on any finding) or via tier-1
tests/test_obs.py::test_metrics_docs_lint.
"""

import os
import re
import sys

_ROOT = os.path.join(os.path.dirname(os.path.abspath(__file__)), os.pardir)
DOC = os.path.join(_ROOT, "docs", "OBSERVABILITY.md")

if __package__ in (None, ""):  # standalone `python tools/check_...py`
    sys.path.insert(0, os.path.abspath(_ROOT))

# Inventory-table rows only: "| `klogs_...` | type | ..." — prose
# mentions of metric names elsewhere in the doc are not inventory.
_ROW = re.compile(r"^\|\s*`(klogs_[a-z0-9_]+)`\s*\|", re.MULTILINE)


def check(doc_path: str = DOC) -> list[str]:
    """Returns a list of problems (empty = consistent)."""
    from klogs_tpu.obs.inventory import SPECS

    try:
        with open(doc_path) as f:
            doc = f.read()
    except OSError as e:
        return [f"cannot read {doc_path}: {e}"]
    documented = set(_ROW.findall(doc))
    problems = []
    for name in sorted(set(SPECS) - documented):
        problems.append(
            f"{name} is registered in obs/inventory.py but missing from "
            "the docs/OBSERVABILITY.md inventory table")
    for name in sorted(documented - set(SPECS)):
        problems.append(
            f"{name} is documented in docs/OBSERVABILITY.md but not in "
            "obs/inventory.py SPECS (stale doc row?)")
    return problems


def main() -> int:
    problems = check()
    for p in problems:
        print(f"check_metrics_docs: {p}", file=sys.stderr)
    if not problems:
        from klogs_tpu.obs.inventory import SPECS

        print("check_metrics_docs: inventory and docs agree "
              f"({len(SPECS)} metrics)")
    return 1 if problems else 0


if __name__ == "__main__":
    sys.exit(main())
