#!/usr/bin/env python
"""Lint: the metric inventory in code and docs must agree.

Folded into the project-native static-analysis suite as the
``metrics-docs`` pass (tools/analysis/passes/metrics_docs.py — see
docs/STATIC_ANALYSIS.md); this shim keeps the standalone CLI and the
``from tools.check_metrics_docs import check`` tier-1 entry point
working unchanged. Run standalone (exit 1 on any finding), via
``python -m tools.analysis``, or via tier-1
tests/test_obs.py::test_metrics_docs_lint.
"""

import os
import sys

_ROOT = os.path.join(os.path.dirname(os.path.abspath(__file__)), os.pardir)
DOC = os.path.join(_ROOT, "docs", "OBSERVABILITY.md")

if __package__ in (None, ""):  # standalone `python tools/check_...py`
    sys.path.insert(0, os.path.abspath(_ROOT))

from tools.analysis.passes.metrics_docs import check  # noqa: E402

__all__ = ["check", "DOC", "main"]


def main() -> int:
    problems = check(DOC)
    for p in problems:
        print(f"check_metrics_docs: {p}", file=sys.stderr)
    if not problems:
        from klogs_tpu.obs.inventory import SPECS

        print("check_metrics_docs: inventory and docs agree "
              f"({len(SPECS)} metrics)")
    return 1 if problems else 0


if __name__ == "__main__":
    sys.exit(main())
