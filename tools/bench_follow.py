"""Follow-mode latency/throughput bench (BASELINE.md config 4).

Drives the FULL production pipeline (FakeCluster follow streams →
fan-out → framing → coalescing async filter → gated file writes) at a
controlled offered load and reports sustained lines/sec plus batch
latency percentiles from FilterStats.

Distinct from bench.py (the driver contract) because follow mode needs
wall-clock dwell time; run it by hand / from CI:

    python tools/bench_follow.py --pods 200 --seconds 60 --backend tpu

Env: KLOGS_FOLLOW_RATE_HZ per-stream line rate (default 100).
"""

import argparse
import asyncio
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from klogs_tpu.utils.env import read as env_read  # noqa: E402

from klogs_tpu import app  # noqa: E402
from klogs_tpu.cli import parse_args  # noqa: E402
from klogs_tpu.cluster.fake import FakeCluster  # noqa: E402


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--pods", type=int, default=200)
    ap.add_argument("--seconds", type=float, default=60)
    ap.add_argument("--backend", choices=["cpu", "tpu"], default="tpu")
    ap.add_argument("--match", action="append",
                    default=None, help="patterns (default: 'failed')")
    ap.add_argument("--backlog-lines", type=int, default=50,
                    help="historical lines per container at start")
    ns = ap.parse_args()
    patterns = ns.match or ["failed"]
    rate = float(env_read("KLOGS_FOLLOW_RATE_HZ", "100"))

    out_dir = tempfile.mkdtemp(prefix="klogs-bench-follow-")
    fc = FakeCluster.synthetic(
        n_pods=ns.pods, n_containers=1,
        lines_per_container=ns.backlog_lines,
        follow_interval_s=1.0 / rate,
    )
    print(f"offered load: {ns.pods} streams x {rate:.0f} lines/s "
          f"= {ns.pods * rate:,.0f} lines/s for {ns.seconds:.0f}s "
          f"(+{ns.backlog_lines} backlog lines/stream); latency "
          f"percentiles from FilterStats are end-to-end per batch, with "
          f"queue vs device split printed when the async service runs")
    argv = ["-n", "default", "-a", "-f", "-p", out_dir,
            "--backend", ns.backend, "--stats"]
    for p in patterns:
        argv += ["--match", p]
    opts = parse_args(argv)

    async def run():
        stop = asyncio.Event()

        async def stopper():
            await asyncio.sleep(ns.seconds)
            stop.set()

        asyncio.create_task(stopper())
        t0 = time.perf_counter()
        await app.run_async(opts, backend=fc, stop=stop)
        print(f"run returned {time.perf_counter() - t0 - ns.seconds:.1f}s "
              f"after stop (drain+teardown)")

    asyncio.run(run())


if __name__ == "__main__":
    main()
