"""Follow-mode latency/throughput bench (BASELINE.md config 4).

Drives the FULL production pipeline (FakeCluster follow streams →
fan-out → framing → coalescing async filter → gated file writes) at a
controlled offered load and reports sustained lines/sec plus batch
latency percentiles from FilterStats.

Distinct from bench.py (the driver contract) because follow mode needs
wall-clock dwell time; run it by hand / from CI:

    python tools/bench_follow.py --pods 200 --seconds 60 --backend tpu

``--source replay`` swaps the FakeCluster for the PR 18 replay source:
the bench pre-writes one live log file per "pod" with the backlog,
appends lines at the offered rate for the duration, and drives the app
through ``--source replay:DIR`` — same pipeline, file-tail ingest
instead of the cluster transport, so the FOLLOW_BENCH source=replay
rows price the source abstraction at identical offered load.

Env: KLOGS_FOLLOW_RATE_HZ per-stream line rate (default 100).
"""

import argparse
import asyncio
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from klogs_tpu.utils.env import read as env_read  # noqa: E402

from klogs_tpu import app  # noqa: E402
from klogs_tpu.cli import parse_args  # noqa: E402
from klogs_tpu.cluster.fake import FakeCluster  # noqa: E402


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--pods", type=int, default=200)
    ap.add_argument("--seconds", type=float, default=60)
    ap.add_argument("--backend", choices=["cpu", "tpu"], default="tpu")
    ap.add_argument("--match", action="append",
                    default=None, help="patterns (default: 'failed')")
    ap.add_argument("--backlog-lines", type=int, default=50,
                    help="historical lines per container at start")
    ap.add_argument("--source", choices=["fake", "replay"], default="fake",
                    help="ingest path: FakeCluster follow streams, or "
                    "live log files tailed via --source replay:DIR")
    ns = ap.parse_args()
    patterns = ns.match or ["failed"]
    rate = float(env_read("KLOGS_FOLLOW_RATE_HZ", "100"))

    out_dir = tempfile.mkdtemp(prefix="klogs-bench-follow-")
    fc = None
    src_dir = None
    argv = ["-n", "default", "-a", "-f", "-p", out_dir,
            "--backend", ns.backend, "--stats"]
    if ns.source == "replay":
        src_dir = tempfile.mkdtemp(prefix="klogs-bench-follow-src-")
        for s in range(ns.pods):
            with open(os.path.join(src_dir, f"pod-{s:04d}.log"), "wb") as f:
                for i in range(ns.backlog_lines):
                    f.write(b"backlog line %d with nothing to see\n" % i)
        argv += ["--source", f"replay:{src_dir}"]
    else:
        fc = FakeCluster.synthetic(
            n_pods=ns.pods, n_containers=1,
            lines_per_container=ns.backlog_lines,
            follow_interval_s=1.0 / rate,
        )
    print(f"offered load: {ns.pods} streams x {rate:.0f} lines/s "
          f"= {ns.pods * rate:,.0f} lines/s for {ns.seconds:.0f}s "
          f"(+{ns.backlog_lines} backlog lines/stream, source={ns.source}); "
          f"latency percentiles from FilterStats are end-to-end per batch, "
          f"with queue vs device split printed when the async service runs")
    for p in patterns:
        argv += ["--match", p]
    opts = parse_args(argv)

    async def writer(stop: asyncio.Event) -> None:
        # Append at the offered rate across all files in ~20ms ticks —
        # one buffered write per file per tick, which is how a real
        # log-emitting fleet looks to the tailer (bursts, not a line
        # at a time).
        assert src_dir is not None
        files = [open(os.path.join(src_dir, f"pod-{s:04d}.log"), "ab")
                 for s in range(ns.pods)]
        try:
            tick = 0.02
            per_tick = max(1, int(rate * tick))
            seq = 0
            while not stop.is_set():
                t_next = time.perf_counter() + tick
                for f in files:
                    f.write(b"".join(
                        b"tick line %d maybe failed maybe not\n" % (seq + i)
                        for i in range(per_tick)))
                    f.flush()
                seq += per_tick
                delay = t_next - time.perf_counter()
                if delay > 0:
                    await asyncio.sleep(delay)
                else:
                    await asyncio.sleep(0)
        finally:
            for f in files:
                f.close()

    async def run():
        stop = asyncio.Event()

        async def stopper():
            await asyncio.sleep(ns.seconds)
            stop.set()

        asyncio.create_task(stopper())
        if src_dir is not None:
            asyncio.create_task(writer(stop))
        t0 = time.perf_counter()
        await app.run_async(opts, backend=fc, stop=stop)
        print(f"run returned {time.perf_counter() - t0 - ns.seconds:.1f}s "
              f"after stop (drain+teardown)")

    asyncio.run(run())


if __name__ == "__main__":
    main()
