"""Archive-backfill throughput bench (BENCH_BACKFILL.json).

The acceptance measurement for PR 18's `--backfill` path: rotated,
gzip-compressed archives driven through the FULL production pipeline —
ArchiveSource producer threads (decompress → newline-aligned slabs →
bounded read-ahead queue) → FanoutRunner → framing → coalescing async
filter → gated FileSink writes — and, per K, sustained end-to-end
lines/sec plus the continuous profiler's per-stage attribution.

The row's ``source_bound`` field is the claim under test: with the
decompressors fanned out across stream producer threads (zlib releases
the GIL), the bottleneck attribution must land on an ENGINE stage, not
``source.read`` — i.e. backfill feeds the engine at its real speed and
the source abstraction costs nothing.

    python tools/bench_backfill.py         # writes BENCH_BACKFILL.json

Each K runs once per corpus codec ("gzip,plain" by default): the gzip
rows price real rotated archives including inflate, the plain rows
isolate the source/framing/engine path — on a single-core host inflate
CPU is strictly additive to engine CPU (there is no second core to
hide it behind), and the pair of rows makes that arithmetic visible.

Env knobs (KLOGS_BENCH_* family): KLOGS_BENCH_BACKFILL_K ("1024"),
KLOGS_BENCH_BACKFILL_LINES, KLOGS_BENCH_BACKFILL_STREAMS,
KLOGS_BENCH_BACKFILL_BATCH, KLOGS_BENCH_BACKFILL_READAHEAD_MB,
KLOGS_BENCH_BACKFILL_CODECS ("gzip,plain"), KLOGS_BENCH_REPEATS,
KLOGS_BENCH_BACKFILL_OUT.
"""

import asyncio
import gzip
import json
import multiprocessing
import os
import shutil
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402

import bench  # noqa: E402
from klogs_tpu.cluster.types import LogOptions  # noqa: E402
from klogs_tpu.filters.base import frame_lines  # noqa: E402
from klogs_tpu.filters.sink import make_pipeline  # noqa: E402
from klogs_tpu.obs import trace  # noqa: E402
from klogs_tpu.obs.profiler import PROFILER  # noqa: E402
from klogs_tpu.runtime.fanout import FanoutRunner, plan_source_jobs  # noqa: E402
from klogs_tpu.sources.archive import ArchiveSource  # noqa: E402
from klogs_tpu.utils.env import read as env_read  # noqa: E402

DEFAULT_K = "1024"
DEFAULT_LINES = 4_000_000
DEFAULT_STREAMS = 8
DEFAULT_BATCH = 8192
DEFAULT_READAHEAD_MB = 8
WARMUP_LINES = 160_000  # past the index re-tune threshold (~67k seen)


def build_archives(root: str, lines: "list[bytes]", n_streams: int,
                   codec: str) -> int:
    """Write the corpus as ``n_streams`` rotated sets — two older
    generations plus a plain live file per stream, the shape logrotate
    leaves behind. ``codec`` gzips the rotated generations ("gzip") or
    leaves them plain ("plain"). Returns total archive bytes."""
    per = (len(lines) + n_streams - 1) // n_streams
    total_bytes = 0
    for s in range(n_streams):
        chunk = lines[s * per:(s + 1) * per]
        if not chunk:
            continue
        third = (len(chunk) + 2) // 3
        parts = [chunk[:third], chunk[third:2 * third], chunk[2 * third:]]
        base = os.path.join(root, f"app-{s:02d}.log")
        for gen, part in zip((2, 1), parts[:2]):
            if codec == "gzip":
                path = f"{base}.{gen}.gz"
                # Level 1: rotation compresses for space, not ratio —
                # and the bench measures OUR decompress fan-out, not
                # zlib's best-compression encode speed.
                with gzip.open(path, "wb", compresslevel=1) as f:
                    f.writelines(part)
            else:
                path = f"{base}.{gen}"
                with open(path, "wb") as f:
                    f.writelines(part)
            total_bytes += os.path.getsize(path)
        with open(base, "wb") as f:
            f.writelines(parts[2])
        total_bytes += os.path.getsize(base)
    return total_bytes


async def run_backfill(archive_dir: str, codec: str, k: int, n_lines: int,
                       batch_lines: int, readahead_mb: int) -> dict:
    patterns = bench.make_patterns(k)
    out_dir = tempfile.mkdtemp(prefix="klogs-bench-backfill-out-")
    pipeline = make_pipeline(patterns, "cpu", batch_lines=batch_lines)
    # Warm the engine past its one-time costs (K=1024 DFA compile, the
    # ~67k-line index re-tune) before the clock starts — same
    # discipline as bench.py's warm pass; a real backfill amortizes
    # these over the whole archive set anyway.
    filt = pipeline.log_filter
    if filt is not None:
        warm = [ln.rstrip(b"\n") for ln in bench.make_lines(WARMUP_LINES)]
        for i in range(0, len(warm), batch_lines):
            payload, offsets, _ = frame_lines(warm[i:i + batch_lines])
            filt.fetch_framed(filt.dispatch_framed(
                payload, np.asarray(offsets, dtype=np.int32)))
    source = ArchiveSource([archive_dir], readahead_mb=readahead_mb)
    try:
        await source.start()
        jobs = plan_source_jobs(await source.discover(), out_dir)
        await pipeline.start()
        runner = FanoutRunner(None, "local", LogOptions(follow=False),
                              sink_factory=pipeline.sink_factory,
                              create_files=True, source=source)
        before = PROFILER.tick() or {"stages": {}}
        t0 = time.perf_counter()
        results = await runner.run(jobs)
        # The drain is part of the run: lines/sec counts bytes ON DISK,
        # not bytes parked in the coalescer.
        await pipeline.aclose()
        dt = time.perf_counter() - t0
        after = PROFILER.tick() or {"stages": {}}
        errors = [r.error for r in results if r.error]
        if errors:
            raise SystemExit(f"bench_backfill: stream errors: {errors}")
        s = pipeline.stats
        if s.lines_in != n_lines:
            raise SystemExit(f"bench_backfill: pipeline saw {s.lines_in} "
                             f"of {n_lines} lines")
        stages = {}
        for name, st in after["stages"].items():
            prev = before["stages"].get(name, {})
            busy = st["busy_s"] - prev.get("busy_s", 0.0)
            spans = st["spans"] - prev.get("spans", 0)
            if spans <= 0:
                continue
            stages[name] = {"busy_s": round(busy, 4), "spans": spans,
                            "utilization": round(busy / dt, 4)}
        # The source runs one producer thread per stream, so its busy
        # sum is spread over n_streams-way parallelism: "source-bound"
        # means the producers themselves were (nearly) saturated, not
        # that their summed busy beat a serial stage's. Capacity is
        # what the producers could have delivered flat out.
        src_busy = stages.get("source.read", {}).get("busy_s", 0.0)
        n_streams = len(jobs)
        src_frac = (src_busy / (n_streams * dt)) if dt else 0.0
        src_capacity = (n_lines * n_streams / src_busy) if src_busy \
            else float("inf")
        source_bound = src_frac > 0.8
        rest = {n: s for n, s in stages.items() if n != "source.read"}
        bottleneck = ("source.read" if source_bound else
                      max(rest, key=lambda n: rest[n]["busy_s"])
                      if rest else None)
        return {
            "k": k,
            "codec": codec,
            "n_lines": n_lines,
            "streams": len(jobs),
            "batch_lines": batch_lines,
            "readahead_mb": readahead_mb,
            "lps": round(n_lines / dt, 1),
            "wall_s": round(dt, 3),
            "matched": s.lines_matched,
            "shed": s.degraded_lines,
            "stages": stages,
            "bottleneck": bottleneck,
            "source_busy_frac": round(src_frac, 4),
            "source_capacity_lps": (round(src_capacity, 1)
                                    if src_busy else None),
            "source_bound": source_bound,
        }
    finally:
        await source.close()
        shutil.rmtree(out_dir, ignore_errors=True)


def main() -> None:
    ks = [int(x) for x in env_read("KLOGS_BENCH_BACKFILL_K",
                                   DEFAULT_K).split(",") if x]
    n_lines = int(env_read("KLOGS_BENCH_BACKFILL_LINES",
                           str(DEFAULT_LINES)))
    n_streams = int(env_read("KLOGS_BENCH_BACKFILL_STREAMS",
                             str(DEFAULT_STREAMS)))
    batch_lines = int(env_read("KLOGS_BENCH_BACKFILL_BATCH",
                               str(DEFAULT_BATCH)))
    readahead_mb = int(env_read("KLOGS_BENCH_BACKFILL_READAHEAD_MB",
                                str(DEFAULT_READAHEAD_MB)))
    codecs = [c for c in env_read("KLOGS_BENCH_BACKFILL_CODECS",
                                  "gzip,plain").split(",") if c]
    repeats = int(env_read("KLOGS_BENCH_REPEATS", "2"))

    # On a single-core host (this bench records cpu_count for exactly
    # this reason) the default 5ms GIL switch interval convoys the
    # producer threads against the event loop: each thread holds the
    # core for a full quantum while the others' queues run dry.
    # Shortening it recovered ~25% end-to-end on the 1-core CI box and
    # is noise on multi-core hosts.
    sys.setswitchinterval(0.0005)

    root = tempfile.mkdtemp(prefix="klogs-bench-backfill-arch-")
    try:
        t0 = time.perf_counter()
        lines = bench.make_lines(n_lines)
        total = len(lines)
        dirs = {}
        for codec in codecs:
            d = os.path.join(root, codec)
            os.makedirs(d, exist_ok=True)
            arch_bytes = build_archives(d, lines, n_streams, codec)
            dirs[codec] = d
            print(f"bench_backfill: [{codec}] corpus {total:,} lines -> "
                  f"{arch_bytes / 1e6:,.0f} MB of archives", file=sys.stderr)
        del lines
        print(f"bench_backfill: corpus built in "
              f"{time.perf_counter() - t0:.1f}s", file=sys.stderr)
        # Span stream fully on: the attribution IS the measurement
        # (and the honest one — the committed lps carries the
        # profiler's cost, same discipline as bench_fleet rows).
        trace.reset(1.0)
        rows = []
        for codec in codecs:
            for k in ks:
                best = None
                for _ in range(repeats):
                    PROFILER.reset()
                    PROFILER.enable(1.0)
                    row = asyncio.run(run_backfill(
                        dirs[codec], codec, k, total, batch_lines,
                        readahead_mb))
                    PROFILER.reset()
                    if best is None or row["lps"] > best["lps"]:
                        best = row
                rows.append(best)
                print(f"bench_backfill: [{codec}] K={k} -> "
                      f"{best['lps']:,.0f} l/s "
                      f"bottleneck={best['bottleneck']} "
                      f"source_bound={best['source_bound']}",
                      file=sys.stderr)
        trace.reset(None)
    finally:
        shutil.rmtree(root, ignore_errors=True)

    payload = {
        "metric": "archive backfill end-to-end lines/sec (rotated "
                  "archive sets -> ArchiveSource producer threads -> fan-out -> "
                  "framing -> coalescing cpu filter -> gated file "
                  "writes), with per-stage attribution from the "
                  "continuous profiler",
        "unit": "lines/sec",
        "corpus": "needle-finding synthetic pod logs, ~128B lines, "
                  "rotated sets per codec (gzip -1 generations, and "
                  "the same set uncompressed to isolate decompress "
                  "cost from the source/engine path)",
        "cpu_count": multiprocessing.cpu_count(),
        "rows": rows,
    }
    out = env_read("KLOGS_BENCH_BACKFILL_OUT") or os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "BENCH_BACKFILL.json")
    with open(out, "w") as f:
        json.dump(payload, f, indent=1)
        f.write("\n")
    print(json.dumps({"rows": len(rows),
                      "lps": {r["codec"]: r["lps"] for r in rows},
                      "source_bound": any(r["source_bound"] for r in rows),
                      "out": out}))


if __name__ == "__main__":
    main()
