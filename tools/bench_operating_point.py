"""Sweep the device operating point: batch size x pipeline depth.

BASELINE.md's dispatch-overhead fit (time = a*dispatches + b*lines across
operating points) says the engine alone sustains ~17M lines/s and the
measured 8.1M at batch 262k x 64-in-flight is still ~50% per-dispatch
tunnel overhead. Bigger batches amortize that overhead further; this tool
measures where the curve flattens (and where HBM/VMEM stops it), so
bench.py's default operating point is evidence-backed.

Method matches bench.py: host-classified ids resident on device, N kernel
dispatches in flight, one block + one representative mask fetch at the
end. Appends one JSON record to OPERATING_POINT.json.

Usage:  python tools/bench_operating_point.py [--date YYYY-MM-DD]
Env:    KLOGS_OP_BATCHES (comma list, default 262144,524288,1048576)
        KLOGS_OP_FLIGHTS (comma list, default 8,16,32,64)
        KLOGS_OP_REPEATS (default 3)
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from klogs_tpu.utils.env import read as env_read  # noqa: E402

import bench  # noqa: E402


def fit_runs(runs):
    """Least-squares fit of time = c + a*dispatches + b*lines.

    The constant term c (one per timed measurement: the final
    block_until_ready + mask fetch + ramp, ~2x tunnel RTT) is what makes
    throughput rise with pipeline depth at fixed batch — a model without
    it (time = a*dispatches + b*lines) predicts depth-independent
    throughput, contradicts the measured nf-dependence by up to 30%, and
    mis-attributes the fixed cost to per-dispatch overhead. With c the
    12-point residuals drop under 3%. 1/b is the engine-only ceiling."""
    import numpy as np

    A = np.array([[1.0, r["n_flight"], r["n_flight"] * r["batch"]]
                  for r in runs], dtype=np.float64)
    y = np.array([r["n_flight"] * r["batch"] / r["lps"] for r in runs])
    (c, a, b), *_ = np.linalg.lstsq(A, y, rcond=None)
    pred = A @ np.array([c, a, b])
    return {"model": "time = c + a*dispatches + b*lines",
            "per_measurement_ms": round(c * 1e3, 1),
            "per_dispatch_ms": round(a * 1e3, 3),
            "engine_only_lps": round(1.0 / b, 1) if b > 0 else None,
            "max_residual_pct": round(float(np.max(np.abs(pred - y) / y)) * 100, 1)}


def main() -> None:
    import jax
    import numpy as np

    from klogs_tpu.filters.tpu import pack_classify
    from klogs_tpu.ops import nfa
    from klogs_tpu.ops.pallas_nfa import match_cls_grouped_pallas

    batches = [int(x) for x in env_read(
        "KLOGS_OP_BATCHES", "262144,524288,1048576").split(",")]
    flights = [int(x) for x in env_read(
        "KLOGS_OP_FLIGHTS", "8,16,32,64").split(",")]
    repeats = int(env_read("KLOGS_OP_REPEATS", "3"))

    dev = jax.devices()[0]
    print(f"attached: {dev}", flush=True)

    dp, live, acc = nfa.compile_grouped(bench.PATTERNS)
    table = np.asarray(dp.byte_class).astype(np.int8)

    lines = bench.make_lines(max(batches))
    bodies = [ln.rstrip(b"\n") for ln in lines]
    t0 = time.perf_counter()
    cls_full = pack_classify(bodies, 128, table, dp.begin_class,
                             dp.end_class, dp.pad_class)
    host_prep = len(bodies) / (time.perf_counter() - t0)
    print(f"host pack_classify: {host_prep:,.0f} lines/s", flush=True)

    runs = []
    for B in batches:
        dcls = jax.device_put(cls_full[:B])
        run = lambda: match_cls_grouped_pallas(dp, live, acc, dcls)
        np.asarray(run())  # compile + warm
        for nf in flights:
            best = bench.measure_pipelined(run, B, nf, repeats)
            runs.append({"batch": B, "n_flight": nf,
                         "lps": round(best, 1)})
            print(f"batch {B:>8} x {nf:>2} in flight: "
                  f"{best:>12,.0f} lines/s", flush=True)
        del dcls

    fit = fit_runs(runs)
    print(f"fit: {fit}", flush=True)

    try:
        date = sys.argv[sys.argv.index("--date") + 1]
    except (ValueError, IndexError):
        date = time.strftime("%Y-%m-%d")
    record = {
        "date": date,
        "device": str(dev),
        "n_patterns": len(bench.PATTERNS),
        "line_width_bytes": 128,
        "host_pack_classify_lps": round(host_prep, 1),
        "runs": runs,
        "dispatch_fit": fit,
    }
    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "OPERATING_POINT.json")
    existing = []
    if os.path.exists(path):
        with open(path) as f:
            existing = json.load(f)
    existing.append(record)
    with open(path, "w") as f:
        json.dump(existing, f, indent=1)
    print(f"wrote {path}", flush=True)


if __name__ == "__main__":
    main()
