"""Remote filter service throughput (the host<->TPU gRPC transport —
the framework's DCN-boundary analog, SURVEY.md §5 "Distributed
communication backend").

Spawns filterd in a subprocess (owns the device), then drives it from
this process with N concurrent Match RPCs over one HTTP/2 channel —
the collector-side shape (many FilteredSink flushes pipelining through
RemoteFilterClient). Reports sustained lines/s at several concurrency
levels and batch sizes; appends SERVICE_BENCH.json at the repo root.

    python tools/bench_service.py --backend cpu   # transport-only
    python tools/bench_service.py --backend tpu   # server owns the TPU
"""

import argparse
import asyncio
import json
import os
import subprocess
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import bench  # noqa: E402
from klogs_tpu.service.client import RemoteFilterClient  # noqa: E402

PORT = 50917


async def run_bench(backend: str, seconds: float, target: str,
                    patterns: "list[str]") -> dict:
    client = RemoteFilterClient(target)
    # Wait for the server to come up (TPU attach can take ~20-40s).
    deadline = time.monotonic() + 120
    while True:
        try:
            await client.verify_patterns(patterns)
            break
        except Exception:
            if time.monotonic() > deadline:
                raise
            await asyncio.sleep(1.0)

    from klogs_tpu.filters.base import frame_lines

    lines = [ln.rstrip(b"\n") for ln in bench.make_lines(262144)]
    results = []
    # Legacy per-line rows (the round-4 configs, for trend comparison)
    # then framed rows: same volume, O(1) wire cost per batch. The
    # jumbo framed configs are the production collector shape (a 1000-
    # pod follow fans into few coalesced flushes).
    configs = [
        ("legacy", 1024, 4), ("legacy", 8192, 8), ("legacy", 8192, 16),
        ("framed", 8192, 8), ("framed", 8192, 16),
        ("framed", 65536, 8), ("framed", 65536, 16),
        ("framed", 262144, 8),
    ]
    for mode, batch_lines, conc in configs:
        if mode == "framed":
            batches = [frame_lines(lines[i : i + batch_lines])[:2]
                       for i in range(0, len(lines), batch_lines)]
            await client.match_framed(*batches[0])  # warm jit caches

            async def one(k, batches=batches):
                await client.match_framed(*batches[k % len(batches)])
        else:
            batches = [lines[i : i + batch_lines]
                       for i in range(0, len(lines), batch_lines)]
            await client.match(batches[0])

            async def one(k, batches=batches):
                await client.match(batches[k % len(batches)])

        done = 0
        stop_at = time.monotonic() + seconds

        async def worker():
            nonlocal done
            k = 0
            while time.monotonic() < stop_at:
                await one(k)
                done += batch_lines
                k += 1

        t0 = time.perf_counter()
        await asyncio.gather(*[worker() for _ in range(conc)])
        lps = done / (time.perf_counter() - t0)
        results.append({"mode": mode, "batch_lines": batch_lines,
                        "concurrency": conc, "lines_per_s": round(lps, 1)})
        print(f"{mode} batch={batch_lines} conc={conc}: {lps:,.0f} lines/s",
              flush=True)
    await client.aclose()
    return {"backend": backend, "runs": results}


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--backend", choices=["cpu", "tpu"], default="tpu")
    ap.add_argument("--seconds", type=float, default=10.0)
    ap.add_argument("--uds", action="store_true",
                    help="unix-domain-socket loopback instead of TCP")
    ap.add_argument("--null-engine", action="store_true",
                    help="serve the match-all pattern (engine cost "
                    "zero): measures the PURE transport+coalescing "
                    "ceiling of the service path")
    ns = ap.parse_args()
    patterns = [""] if ns.null_engine else bench.PATTERNS

    if ns.uds:
        target = f"unix:/tmp/klogs_bench_{os.getpid()}.sock"
        argv = [sys.executable, "-m", "klogs_tpu.service",
                "--host", target, "--backend", ns.backend]
    else:
        target = f"127.0.0.1:{PORT}"
        argv = [sys.executable, "-m", "klogs_tpu.service",
                "--port", str(PORT), "--backend", ns.backend]
    for p in patterns:
        argv += ["--match", p]
    env = dict(os.environ)
    if ns.backend == "cpu" or ns.null_engine:
        # Null-engine runs never touch the device (match-all shortcuts
        # at dispatch): keep the server off the TPU attach so the row
        # isolates transport, not tunnel bring-up.
        env["JAX_PLATFORMS"] = "cpu"
    server = subprocess.Popen(argv, env=env,
                              stdout=subprocess.DEVNULL,
                              stderr=subprocess.DEVNULL)
    try:
        res = asyncio.run(run_bench(ns.backend, ns.seconds, target,
                                    patterns))
        if ns.uds:
            res["transport"] = "uds"
        if ns.null_engine:
            res["null_engine"] = True
    finally:
        server.terminate()
        server.wait()
    from datetime import date

    res["date"] = date.today().isoformat()
    res["n_patterns"] = len(patterns)
    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "SERVICE_BENCH.json")
    doc = []
    if os.path.exists(path):
        with open(path) as f:
            doc = json.load(f)
    doc.append(res)
    with open(path, "w") as f:
        json.dump(doc, f, indent=1)
    print(f"wrote {path}", flush=True)


if __name__ == "__main__":
    main()
