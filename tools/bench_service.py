"""Remote filter service throughput (the host<->TPU gRPC transport —
the framework's DCN-boundary analog, SURVEY.md §5 "Distributed
communication backend").

Spawns filterd in a subprocess (owns the device), then drives it from
this process with N concurrent Match RPCs over one HTTP/2 channel —
the collector-side shape (many FilteredSink flushes pipelining through
RemoteFilterClient). Reports sustained lines/s at several concurrency
levels and batch sizes; appends SERVICE_BENCH.json at the repo root.

    python tools/bench_service.py --backend cpu   # transport-only
    python tools/bench_service.py --backend tpu   # server owns the TPU
"""

import argparse
import asyncio
import json
import os
import subprocess
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import bench  # noqa: E402
from klogs_tpu.service.client import RemoteFilterClient  # noqa: E402

PORT = 50917


async def run_bench(backend: str, seconds: float) -> dict:
    client = RemoteFilterClient(f"127.0.0.1:{PORT}")
    # Wait for the server to come up (TPU attach can take ~20-40s).
    deadline = time.monotonic() + 120
    while True:
        try:
            await client.verify_patterns(bench.PATTERNS)
            break
        except Exception:
            if time.monotonic() > deadline:
                raise
            await asyncio.sleep(1.0)

    lines = [ln.rstrip(b"\n") for ln in bench.make_lines(65536)]
    results = []
    for batch_lines, conc in ((1024, 4), (8192, 8), (8192, 16)):
        batches = [lines[i : i + batch_lines]
                   for i in range(0, len(lines), batch_lines)]
        await client.match(batches[0])  # warm the server's jit caches
        done = 0
        stop_at = time.monotonic() + seconds

        async def worker():
            nonlocal done
            k = 0
            while time.monotonic() < stop_at:
                await client.match(batches[k % len(batches)])
                done += batch_lines
                k += 1

        t0 = time.perf_counter()
        await asyncio.gather(*[worker() for _ in range(conc)])
        lps = done / (time.perf_counter() - t0)
        results.append({"batch_lines": batch_lines, "concurrency": conc,
                        "lines_per_s": round(lps, 1)})
        print(f"batch={batch_lines} conc={conc}: {lps:,.0f} lines/s",
              flush=True)
    await client.aclose()
    return {"backend": backend, "runs": results}


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--backend", choices=["cpu", "tpu"], default="tpu")
    ap.add_argument("--seconds", type=float, default=10.0)
    ns = ap.parse_args()

    argv = [sys.executable, "-m", "klogs_tpu.service",
            "--port", str(PORT), "--backend", ns.backend]
    for p in bench.PATTERNS:
        argv += ["--match", p]
    env = dict(os.environ)
    if ns.backend == "cpu":
        env["JAX_PLATFORMS"] = "cpu"
    server = subprocess.Popen(argv, env=env,
                              stdout=subprocess.DEVNULL,
                              stderr=subprocess.DEVNULL)
    try:
        res = asyncio.run(run_bench(ns.backend, ns.seconds))
    finally:
        server.terminate()
        server.wait()
    from datetime import date

    res["date"] = date.today().isoformat()
    res["n_patterns"] = len(bench.PATTERNS)
    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "SERVICE_BENCH.json")
    doc = []
    if os.path.exists(path):
        with open(path) as f:
            doc = json.load(f)
    doc.append(res)
    with open(path, "w") as f:
        json.dump(doc, f, indent=1)
    print(f"wrote {path}", flush=True)


if __name__ == "__main__":
    main()
