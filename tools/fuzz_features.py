"""Feature-interaction fuzzer: random flag combinations end-to-end.

The unit/e2e suites cover each feature and `tests/test_chaos_e2e.py`
covers one hand-picked interaction; this tool drives the REAL app
orchestration with randomized combinations of the whole batch-mode flag
surface (--match/--exclude/-I, -c/-E, -o/--format, --tail/--since/
--since-time, --timestamps, --previous, -i init containers, label
selection, fault injection) against a randomized FakeCluster, and
checks EXACT invariants in both directions:

- the run exits 0 (per-stream faults must never kill the run);
- the file SET equals the planned selection exactly (every selected
  container's file exists — created up front, reference semantics —
  and no unselected container leaks one);
- every file's CONTENT is byte-identical to the oracle: the same
  deterministic stream re-opened and re-read (the fake's delivery,
  including tail/since/since-time/timestamps/previous and
  mid-stream faults, is covered by its own unit suite), framed to
  lines, filtered through an independent host-regex include/exclude
  oracle — so silent DROPS of kept lines fail, not just leaks;
- stdout mode writes no files; every nonempty stdout line is either a
  known "pod container " prefix (text) or a valid {pod, container,
  line} object (json).

Run:  python tools/fuzz_features.py --trials 20000 [--seed N]
Writes one summary line; nonzero exit on any invariant violation.
"""

import argparse
import asyncio
import contextlib
import io
import json
import os
import random
import re
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")

from klogs_tpu import app  # noqa: E402
from klogs_tpu.cli import parse_args  # noqa: E402
from klogs_tpu.cluster.backend import StreamError  # noqa: E402
from klogs_tpu.cluster.fake import FakeCluster, Faults  # noqa: E402
from klogs_tpu.cluster.types import LogOptions  # noqa: E402
from klogs_tpu.filters.framer import LineFramer  # noqa: E402
from klogs_tpu.runtime.fanout import plan_jobs  # noqa: E402
from klogs_tpu.ui import term  # noqa: E402
from klogs_tpu.utils.naming import split_log_file_name  # noqa: E402

MATCH_POOL = ["ERROR", "WARN", r"code=\d00", "failed", r"seq=\d*[02468] ",
              r"latency=\d{1,2}ms", r"^2026", "zzz-never"]
CONTAINERS = ["srv", "web", "sidecar", "istio-proxy", "worker"]


def build_cluster(rng: random.Random) -> FakeCluster:
    fc = FakeCluster(chunk_size=rng.choice([7, 64, 4096]))
    n_pods = rng.randint(1, 6)
    for i in range(n_pods):
        containers = rng.sample(CONTAINERS, rng.randint(1, 3))
        init = ["setup"] if rng.random() < 0.3 else []
        pod = fc.add_pod(
            "default", f"pod-{i}", containers=containers,
            init_containers=init,
            labels={"app": f"app-{i % 2}"},
            lines_per_container=rng.randint(0, 120),
        )
        for c in pod.containers.values():
            if rng.random() < 0.4:  # a previous terminated instance
                for k in range(rng.randint(1, 20)):
                    c.previous_lines.append(
                        (1_000.0 + k, b"prev ERROR line %d\n" % k))
            r = rng.random()
            if r < 0.12:
                c.faults = Faults(fail_open=True)
            elif r < 0.22:
                c.faults = Faults(cut_after_lines=rng.randint(0, 30))
            elif r < 0.30:
                c.faults = Faults(error_after_lines=rng.randint(0, 30))
    return fc


def build_argv(rng: random.Random, out_dir: str) -> list[str]:
    argv = ["-n", "default", "-p", out_dir]
    if rng.random() < 0.8:
        argv.append("-a")
    else:
        argv += ["-l", f"app=app-{rng.randint(0, 1)}"]
    match = rng.sample(MATCH_POOL, rng.randint(0, 2))
    for p in match:
        argv += ["--match", p]
    if rng.random() < 0.4:
        argv += ["--exclude", rng.choice(MATCH_POOL)]
    if rng.random() < 0.3:
        argv.append("-I")
    if rng.random() < 0.4:
        argv += ["-c", rng.choice(["^s", "w", "srv|worker", "xyz-none"])]
    if rng.random() < 0.3:
        argv += ["-E", rng.choice(["istio", "side", "^w"])]
    if rng.random() < 0.5:
        argv += ["-t", str(rng.choice([0, 1, 5, 50]))]
    if rng.random() < 0.2:
        argv += ["-s", rng.choice(["1h", "24h"])]
    elif rng.random() < 0.2:
        argv += ["--since-time", "2000-01-01T00:00:00Z"]
    if rng.random() < 0.25:
        argv.append("--timestamps")
    if rng.random() < 0.15:
        argv.append("-i")  # include init containers
    if rng.random() < 0.15:
        argv.append("--previous")
    out_mode = rng.choice(["files", "files", "stdout", "both"])
    argv += ["-o", out_mode]
    if out_mode != "files" and rng.random() < 0.4:
        argv += ["--format", "json"]
    return argv


def oracle_keep(line: bytes, match, exclude, ignore_case) -> bool:
    flags = re.IGNORECASE if ignore_case else 0
    body = line.rstrip(b"\n")
    inc = (not match) or any(re.search(p.encode(), body, flags)
                             for p in match)
    exc = exclude and any(re.search(p.encode(), body, flags)
                          for p in exclude)
    return inc and not exc


def expected_jobs(fc: FakeCluster, opts, out_dir: str):
    """Re-derive the plan exactly as the app does."""
    pods = asyncio.run(fc.list_pods("default"))
    if opts.labels:
        from klogs_tpu.cluster.types import match_label_selector

        sel = []
        for lab in opts.labels:
            sel.extend(p for p in pods
                       if match_label_selector(p.labels, lab))
        pods = sel
    else:
        pods = [p for p in pods if p.ready]
    cre = re.compile(opts.container) if opts.container else None
    ere = (re.compile(opts.exclude_container)
           if opts.exclude_container else None)
    return plan_jobs(pods, out_dir, opts.init_containers,
                     container_re=cre, exclude_container_re=ere)


def expected_file_bytes(fc: FakeCluster, opts, job) -> bytes:
    """The delivery oracle: re-open the same deterministic stream, read
    what it delivers (including mid-stream faults), frame to lines, and
    filter through the independent regex oracle."""
    lo = LogOptions(
        container=job.container,
        tail_lines=opts.tail if opts.tail != -1 else None,
        since_seconds=None,
        follow=False,
        previous=opts.previous,
        timestamps=opts.timestamps,
        since_time=opts.since_time or None,
    )
    if opts.since:
        from klogs_tpu.utils import parse_duration

        lo.since_seconds = int(parse_duration(opts.since))

    async def read():
        try:
            s = await fc.open_log_stream("default", job.pod, lo)
        except StreamError:
            return b""  # open failure: file stays truncated-empty
        data = b""
        try:
            async for chunk in s:
                data += chunk
        except StreamError:
            pass  # mid-stream error: keep what was delivered
        finally:
            await s.close()
        return data

    delivered = asyncio.run(read())
    if not opts.match and not opts.exclude:
        return delivered  # unfiltered path: byte-identical copy
    framer = LineFramer()
    lines = framer.feed(delivered)
    rest = framer.flush()
    if rest is not None:
        lines.append(rest)
    return b"".join(ln for ln in lines
                    if oracle_keep(ln, opts.match, opts.exclude,
                                   opts.ignore_case))


class _Buf(io.TextIOBase):
    """Text stdout shim exposing the bytes console sinks write."""

    def __init__(self):
        self.buffer = io.BytesIO()

    def write(self, s):
        self.buffer.write(s.encode())
        return len(s)

    def flush(self):
        pass

    def isatty(self):
        return False


def run_one(rng: random.Random, trial: int) -> None:
    fc = build_cluster(rng)
    with tempfile.TemporaryDirectory() as tmp:
        out_dir = os.path.join(tmp, "logs")
        argv = build_argv(rng, out_dir)
        opts = parse_args(argv)
        cap = io.StringIO()
        shim = _Buf()
        with contextlib.redirect_stdout(shim), \
                contextlib.redirect_stderr(cap):
            rc = asyncio.run(app.run_async(opts, backend=fc))
        assert rc == 0, (trial, argv, "rc", rc, cap.getvalue()[-400:])

        jobs = expected_jobs(fc, opts, out_dir)
        stdout_bytes = shim.buffer.getvalue()

        if opts.output == "stdout":
            assert not os.path.exists(out_dir), (trial, argv)
        else:
            # Exact file-set equality: every planned container has a
            # file (created up front, even on open failure), none else.
            actual = sorted(os.listdir(out_dir)) \
                if os.path.exists(out_dir) else []
            expect = sorted(os.path.basename(j.path) for j in jobs)
            assert actual == expect, (trial, argv, actual, expect)
            for f in actual:
                pod, container = split_log_file_name(f)
                job = next(j for j in jobs if j.pod == pod
                           and j.container == container)
                with open(os.path.join(out_dir, f), "rb") as fh:
                    got = fh.read()
                want = expected_file_bytes(fc, opts, job)
                assert got == want, (trial, argv, f,
                                     got[:120], want[:120])

        if opts.output in ("stdout", "both"):
            if opts.format == "json":
                for ln in stdout_bytes.splitlines():
                    if not ln:
                        continue
                    o = json.loads(ln)
                    assert set(o) == {"pod", "container", "line"}, \
                        (trial, argv)
            else:
                prefixes = tuple(
                    f"{j.pod} {j.container} ".encode() for j in jobs)
                for ln in stdout_bytes.splitlines():
                    if not ln:
                        continue
                    assert ln.startswith(prefixes), (trial, argv,
                                                     ln[:120])


def run_one_follow(rng: random.Random, trial: int) -> None:
    """Follow-mode variant: short live runs with reconnecting faults,
    optional --watch-new discovery of a pod added mid-run, and an
    explicit stop. Delivery is timing-nondeterministic here, so the
    content invariant is the SOUNDNESS direction only (every written/
    streamed line passes the oracle); structure invariants (rc, file
    set, console purity) stay exact."""
    fc = build_cluster(rng)
    with tempfile.TemporaryDirectory() as tmp:
        out_dir = os.path.join(tmp, "logs")
        argv = [a for a in build_argv(rng, out_dir)
                if a not in ("--previous",)]
        argv.append("-f")
        watch_new = rng.random() < 0.5
        if watch_new:
            argv.append("--watch-new")
        opts = parse_args(argv)
        stop = asyncio.Event()
        cap = io.StringIO()
        shim = _Buf()
        os.environ["KLOGS_WATCH_INTERVAL_S"] = "0.2"

        async def drive():
            async def stopper():
                await asyncio.sleep(rng.uniform(0.2, 0.6))
                if watch_new and (opts.all_pods or opts.labels):
                    fc.add_pod("default", "late-pod",
                               containers=[rng.choice(CONTAINERS)],
                               labels={"app": "app-0"},
                               lines_per_container=5,
                               follow_interval_s=0.01)
                    await asyncio.sleep(0.5)
                stop.set()

            t = asyncio.create_task(stopper())
            rc = await app.run_async(opts, backend=fc, stop=stop)
            await t
            return rc

        try:
            with contextlib.redirect_stdout(shim), \
                    contextlib.redirect_stderr(cap):
                rc = asyncio.run(drive())
        finally:
            os.environ.pop("KLOGS_WATCH_INTERVAL_S", None)
        assert rc == 0, (trial, argv, rc, cap.getvalue()[-400:])

        stdout_bytes = shim.buffer.getvalue()
        if opts.output == "stdout":
            assert not os.path.exists(out_dir), (trial, argv)
        else:
            actual = sorted(os.listdir(out_dir)) \
                if os.path.exists(out_dir) else []
            allowed = {os.path.basename(j.path)
                       for j in expected_jobs(fc, opts, out_dir)}
            # Discovery timing decides whether late-pod's file exists;
            # anything OUTSIDE the final selection is a leak.
            assert set(actual) <= allowed, (trial, argv, actual, allowed)
            if opts.match or opts.exclude:
                for f in actual:
                    with open(os.path.join(out_dir, f), "rb") as fh:
                        for ln in fh.read().splitlines(keepends=True):
                            assert oracle_keep(
                                ln, opts.match, opts.exclude,
                                opts.ignore_case), (trial, argv, f,
                                                    ln[:120])
        if opts.output in ("stdout", "both"):
            jobs = expected_jobs(fc, opts, out_dir)
            if opts.format == "json":
                for ln in stdout_bytes.splitlines():
                    if ln:
                        o = json.loads(ln)
                        assert set(o) == {"pod", "container", "line"}, \
                            (trial, argv)
            else:
                prefixes = tuple(
                    f"{j.pod} {j.container} ".encode() for j in jobs)
                for ln in stdout_bytes.splitlines():
                    if ln:
                        assert ln.startswith(prefixes), (trial, argv,
                                                         ln[:120])


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--trials", type=int, default=200)
    ap.add_argument("--follow-trials", type=int, default=0)
    ap.add_argument("--seed", type=int, default=None)
    ns = ap.parse_args()
    seed = ns.seed if ns.seed is not None else int(time.time())
    rng = random.Random(seed)
    term.set_colors(False)
    t0 = time.time()
    for trial in range(ns.trials):
        run_one(rng, trial)
        if trial and trial % 2000 == 0:
            print(f"  {trial} combos, {time.time()-t0:.0f}s", flush=True)
    for trial in range(ns.follow_trials):
        run_one_follow(rng, trial)
        if trial and trial % 100 == 0:
            print(f"  {trial} follow combos, {time.time()-t0:.0f}s",
                  flush=True)
    print(f"feature-fuzz OK: {ns.trials} batch + {ns.follow_trials} "
          f"follow random flag combos, {time.time()-t0:.0f}s, "
          f"seed={seed}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
