"""Device measurement of the class-domain prefilter (follow-up to
bench_device_ab.py): class mask alone, clustering alone, and the gated
kernel with class tables at several tile sizes — appended into
BENCH_DEVICE.json under "class_prefilter"."""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from klogs_tpu.utils.env import read as env_read  # noqa: E402

import bench  # noqa: E402


def pipelined_lps(run, n_lines, repeats=3, n_flight=8):
    import numpy as np

    np.asarray(run())
    best = 0.0
    for _ in range(repeats):
        t0 = time.perf_counter()
        outs = [run() for _ in range(n_flight)]
        outs[-1].block_until_ready()
        np.asarray(outs[-1])
        best = max(best, n_flight * n_lines / (time.perf_counter() - t0))
    return best


def main():
    B = int(env_read("KLOGS_BENCH_DEVICE_BATCH", "32768"))
    import jax
    import jax.numpy as jnp
    import numpy as np

    print(f"attached: {jax.devices()[0].device_kind}", flush=True)

    from klogs_tpu.filters.compiler.prefilter import compile_prefilter
    from klogs_tpu.filters.tpu import pack_lines
    from klogs_tpu.ops import nfa
    from klogs_tpu.ops.nfa import classify_chunk
    from klogs_tpu.ops.pallas_nfa import match_batch_grouped_pallas
    from klogs_tpu.ops.prefilter import (
        candidate_mask_from_cls,
        class_tables,
        cluster_candidates,
    )

    lines = bench.make_lines(B)
    bodies = [ln.rstrip(b"\n") for ln in lines]
    batch, lengths = pack_lines(bodies, 128)
    db, dl = jax.device_put(batch), jax.device_put(lengths)
    n = batch.shape[0]

    cpu = bench.cpu_lps(lines[:30000], 3)
    print(f"cpu_regex_lps: {cpu:,.0f}", flush=True)

    dp, live, acc = nfa.compile_grouped(bench.PATTERNS)
    pf = compile_prefilter(bench.PATTERNS)
    ct = class_tables(pf, dp.byte_class, dp.n_classes)
    assert ct is not None
    print(f"slots={ct[0].shape[1]} classes={ct[0].shape[0]}", flush=True)

    res = {}

    @jax.jit
    def mask_only(db, dl):
        cls = classify_chunk(dp, db, dl, first=True, final=True)
        cls = jnp.concatenate(
            [cls, jnp.full((n, 1), dp.pad_class, dtype=jnp.int32)], axis=1)
        return candidate_mask_from_cls(ct, cls)

    lps = pipelined_lps(lambda: mask_only(db, dl), n)
    cand = np.asarray(mask_only(db, dl))
    res["class_mask_only_lps"] = round(lps, 1)
    res["candidate_fraction"] = round(float(cand.mean()), 4)
    print(f"class mask alone: {lps:,.0f} lines/s, "
          f"fraction {cand.mean():.4f}", flush=True)

    @jax.jit
    def mask_and_cluster(db, dl):
        cls = classify_chunk(dp, db, dl, first=True, final=True)
        cls = jnp.concatenate(
            [cls, jnp.full((n, 1), dp.pad_class, dtype=jnp.int32)], axis=1)
        cand = candidate_mask_from_cls(ct, cls)
        order, inv, tl = cluster_candidates(cand, 1024)
        return cls[order].sum() + inv.sum() + tl.sum()

    lps = pipelined_lps(lambda: mask_and_cluster(db, dl), n)
    res["mask_cluster_reorder_lps"] = round(lps, 1)
    print(f"mask+cluster+reorder: {lps:,.0f} lines/s", flush=True)

    for tile in (512, 1024, 2048, 4096):
        try:
            lps = pipelined_lps(
                lambda: match_batch_grouped_pallas(
                    dp, live, acc, db, dl, tile_b=tile,
                    prefilter_tables=ct),
                n)
        except Exception as e:
            print(f"gated_class tile={tile} FAILED: {str(e)[:120]}", flush=True)
            continue
        res[f"gated_class_tile{tile}"] = {
            "lps": round(lps, 1), "vs_cpu": round(lps / cpu, 3)}
        print(f"gated class tile={tile}: {lps:,.0f} lines/s "
              f"({lps / cpu:.2f}x)", flush=True)

    res["cpu_regex_lps_session"] = round(cpu, 1)
    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "BENCH_DEVICE.json")
    with open(path) as f:
        doc = json.load(f)
    doc["class_prefilter"] = res
    best = max((v["lps"] for k, v in res.items()
                if k.startswith("gated_class")), default=0.0)
    doc["class_prefilter"]["decision"] = (
        f"best gated-class {best:.0f} vs best plain "
        f"{doc['best_plain']['lps']:.0f}")
    with open(path, "w") as f:
        json.dump(doc, f, indent=1)
    print("DECISION:", doc["class_prefilter"]["decision"], flush=True)


if __name__ == "__main__":
    main()
