"""Differential fuzz of the batched native group scan (confirm stage).

``group_scan`` in ``klogs_tpu/native/_hostops.c`` walks every (row,
group) candidate cell of a slab through the MultiDFA program blob in
one GIL-released call — group-major with early-out, memchr-accelerated
start states, and an interleaved-lane walk. Its verdicts must equal,
row for row, BOTH of:

- the **python oracle**: pure-Python ``scan_python`` (the DFA scan's
  reference loop) per DFA group plus ``match_lines`` for the
  combined-re/re remainder, OR-gated by the same candidate matrix;
- the **per-group-native path**: the pre-PR-14 dispatch loop
  (``KLOGS_NATIVE_GROUPSCAN=off`` — gathered sub-frames through
  ``dfa_scan``), which is also the engine's production fallback.

Three-way equality on ADVERSARIAL inputs is what lets the fallback act
as the kernel's parity oracle. Each trial builds a random pattern set
(fuzz_sweep's generator: every factor tier, OR guards, unguarded
always-candidate shapes), plants/splits factors across framed lines,
then drives BOTH the engine's real sweep-derived candidate matrix and
a RANDOM candidate matrix (the kernel must honor any gating the caller
hands it — random matrices exercise early-out orderings, empty
columns, and always-columns the sweep would never produce together).

Usage: python tools/fuzz_groupscan.py [--trials N] [--seed S]
Exit 1 on divergence (repro printed), 2 = SKIP without the native
extension. A seeded ~40-trial subset runs in tier-1
(tests/test_groupscan.py); this long loop is `slow` territory.
"""

import argparse
import os
import random
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402

from klogs_tpu.filters.base import frame_lines  # noqa: E402
from klogs_tpu.filters.compiler.dfa import scan_python  # noqa: E402
from tools.fuzz_sweep import rand_lines, rand_patterns  # noqa: E402


def oracle_mask(filt, lines: "list[bytes]",
                gm: np.ndarray) -> np.ndarray:
    """Pure-Python reference: OR over groups of (candidate AND group
    verdict), group verdicts via scan_python for DFA groups and the
    group engine's own match_lines otherwise."""
    B = len(lines)
    out = np.zeros(B, dtype=bool)
    for g, grp in enumerate(filt.groups):
        if grp.kind == "dfa":
            verd = np.asarray(scan_python(grp.filt.tables, lines),
                              dtype=bool)
        else:
            verd = np.asarray(grp.filt.match_lines(lines), dtype=bool)
        out |= gm[:, g] & verd
    return out


def run_trials(trials: int, seed: int, quiet: bool = True) -> int:
    """Run ``trials`` differential trials (python oracle vs byte and
    packed kernel modes); returns the
    number checked. Raises AssertionError with a repro line on the
    first divergence. The caller owns KLOGS_NATIVE_GROUPSCAN
    restoration."""
    from klogs_tpu import native

    if native.hostops is None or not hasattr(native.hostops,
                                             "group_scan"):
        raise RuntimeError("native extension unavailable")
    from klogs_tpu.filters.indexed import IndexedFilter
    from klogs_tpu.utils.env import read as env_read

    rng = random.Random(seed)
    saved = env_read("KLOGS_NATIVE_GROUPSCAN")
    checked = 0
    try:
        for trial in range(trials):
            pats = rand_patterns(rng)
            try:
                filt = IndexedFilter(
                    pats, cache=False, sweep="host",
                    max_group_patterns=rng.choice((2, 3, 32)))
            except Exception:
                continue  # outside the analyzable subset
            if not filt._dfa_cols:
                continue  # nothing for the batched kernel to do
            lines = rand_lines(rng, pats)
            payload, offsets, _ = frame_lines(lines)
            offsets = np.asarray(offsets, dtype=np.int32)
            B = len(lines)
            G = len(filt.groups)
            # The engine's real candidate matrix, then a random one:
            # the kernel must honor ANY gating the caller hands it.
            mats = [filt.index.group_candidates(payload, offsets,
                                                impl="numpy")]
            rand_gm = np.frombuffer(
                bytes(rng.getrandbits(1) for _ in range(B * G)),
                dtype=np.uint8).reshape(B, G).astype(bool)
            if G and rng.random() < 0.5:
                rand_gm = rand_gm.copy()
                rand_gm[:, rng.randrange(G)] = True  # always-column
            mats.append(rand_gm)
            for which, gm in enumerate(mats):
                expect = oracle_mask(filt, lines, gm)
                # The same matrix in the sweep kernel's packed u32
                # form: the packed group_scan must agree bit for bit
                # with the byte-matrix walk and the Python loop.
                W = (G + 31) // 32
                pb = np.packbits(gm, axis=1, bitorder="little")
                pbuf = np.zeros((B, W * 4), dtype=np.uint8)
                pbuf[:, :pb.shape[1]] = pb
                packed = pbuf.view("<u4")
                got = {}
                for mode in ("off", "native"):
                    os.environ["KLOGS_NATIVE_GROUPSCAN"] = mode
                    got[mode] = filt._scan_candidates(
                        payload, offsets, np.ascontiguousarray(gm))
                    got[mode + "-packed"] = filt._scan_candidates(
                        payload, offsets, None, packed=packed)
                for mode, mask in got.items():
                    assert np.array_equal(expect, mask), (
                        f"DIVERGENCE: seed={seed} trial={trial} "
                        f"matrix={'sweep' if which == 0 else 'random'} "
                        f"mode={mode} patterns={pats!r} "
                        f"lines={lines!r}\n"
                        f"oracle:{expect.astype(int)}\n"
                        f"{mode}:  {mask.astype(int)}")
                checked += 1
            if not quiet and trial and trial % 200 == 0:
                print(f"  {trial} trials, {checked} checked",
                      flush=True)
    finally:
        if saved is None:
            os.environ.pop("KLOGS_NATIVE_GROUPSCAN", None)
        else:
            os.environ["KLOGS_NATIVE_GROUPSCAN"] = saved
    return checked


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--trials", type=int, default=1000)
    ap.add_argument("--seed", type=int, default=None)
    args = ap.parse_args()
    seed = args.seed if args.seed is not None else int(time.time())
    print(f"fuzz-groupscan: seed={seed} trials={args.trials}",
          flush=True)
    t0 = time.time()
    try:
        checked = run_trials(args.trials, seed, quiet=False)
    except RuntimeError as e:
        print(f"SKIP: {e}")
        return 2
    except AssertionError as e:
        print(str(e), flush=True)
        return 1
    print(f"fuzz-groupscan OK: {checked} differential matrices across "
          f"{args.trials} trials, {time.time() - t0:.0f}s, seed={seed}",
          flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
