"""Differential fuzz of the native SIMD sweep vs the numpy sweep.

The native literal sweep (``sweep_candidates`` in
``klogs_tpu/native/_hostops.c``) must produce BYTE-IDENTICAL
group-candidate masks to ``FactorIndex.group_candidates``'s vectorized
numpy path — that equality is what lets the numpy sweep act as the
parity oracle for hand-written SIMD C (and, transitively, for the
device sweep, which is oracled against the same numpy masks in
tests/test_sweep.py). This fuzzer generates adversarial pattern sets ×
framed payloads and asserts full mask equality every trial, rotating
KLOGS_NATIVE_SIMD across all stage-1 tiers (scalar / ssse3 / avx2 /
avx512 / auto — the kernel clamps each to what the CPU really has, so
unsupported tiers exercise the dispatch ladder, never fault) AND
KLOGS_SWEEP_BUCKETS across auto / 8 / 16, so every kernel variant ×
bucket plane combination is exercised.

Deliberately covered shapes (the cases a buffer-arithmetic slip would
miss silently):

- factors in every tier: 3-byte (256-extension), narrow (4-7B), wide
  (>= 8B), and past SWEEP_FACTOR_CAP (swept as a rarest 24B window);
- factors planted at offset 0, flush against the line end, exactly the
  line, one byte short of fitting;
- a factor SPLIT across two adjacent framed lines (must count for
  neither — the cross-line false positive);
- empty lines, empty payloads, runs of duplicate offsets;
- OR-guard alternations and unguarded patterns (always-candidate
  groups).

Usage: python tools/fuzz_sweep.py [--trials N] [--seed S]
Exit 1 on any divergence (repro line printed), 2 = SKIP when the
native extension is unavailable. A seeded fast subset runs in tier-1
(tests/test_native_sweep.py); the default loop here is the long form.
"""

import argparse
import os
import random
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402

from klogs_tpu.filters.base import frame_lines  # noqa: E402
from klogs_tpu.filters.compiler.groups import analyze, plan_groups  # noqa: E402
from klogs_tpu.filters.compiler.index import (  # noqa: E402
    SWEEP_FACTOR_CAP,
    FactorIndex,
)

ALPHA = b"abcdef0123-=/ :\t.XYZ"
SIMD_LEVELS = ("scalar", "ssse3", "avx2", "avx512", "auto")
BUCKET_MODES = ("auto", "8", "16")


def rand_patterns(rng: random.Random) -> "list[str]":
    """2-12 patterns mixing every factor tier plus guard shapes."""
    import re as _re

    pats: "list[str]" = []
    for _ in range(rng.randrange(2, 12)):
        kind = rng.random()
        n = rng.choice((3, 3, 4, 5, 7, 8, 9, 14, 23, 24, 25,
                        SWEEP_FACTOR_CAP + rng.randrange(1, 16)))
        lit = "".join(chr(ALPHA[rng.randrange(len(ALPHA))])
                      for _ in range(n))
        if kind < 0.6:
            pats.append(_re.escape(lit))
        elif kind < 0.75:  # OR guard: both branches must stay guarded
            lit2 = "".join(chr(ALPHA[rng.randrange(len(ALPHA))])
                           for _ in range(rng.randrange(3, 10)))
            pats.append(f"(?:{_re.escape(lit)}|{_re.escape(lit2)})")
        elif kind < 0.9:  # literal head + regex tail
            pats.append(_re.escape(lit) + r"\d+")
        else:  # unguarded -> always-candidate group
            pats.append(r"[a-z]*\d?")
    return pats


def rand_lines(rng: random.Random,
               pats: "list[str]") -> "list[bytes]":
    """Random lines with planted/split factors and boundary shapes."""
    raws = [p.replace("\\", "").replace("(?:", "").replace(")", "")
            .replace("|", "").encode() for p in pats]
    lines: "list[bytes]" = []
    for _ in range(rng.randrange(1, 60)):
        body = bytes(ALPHA[rng.randrange(len(ALPHA))]
                     for _ in range(rng.randrange(0, 56)))
        roll = rng.random()
        if roll < 0.45 and raws:
            raw = raws[rng.randrange(len(raws))]
            at = rng.choice([0, len(body), rng.randrange(len(body) + 1)])
            body = body[:at] + raw + body[at:]
            if rng.random() < 0.15 and len(body) > 1:
                body = body[:-1]  # one byte short of the full factor
        elif roll < 0.55 and raws:
            # Cross-line split: this line ends with a factor prefix,
            # the next begins with its suffix.
            raw = raws[rng.randrange(len(raws))]
            if len(raw) >= 2:
                cut = rng.randrange(1, len(raw))
                lines.append(body + raw[:cut])
                body = raw[cut:] + bytes(
                    ALPHA[rng.randrange(len(ALPHA))]
                    for _ in range(rng.randrange(0, 8)))
        elif roll < 0.65:
            body = b""  # empty line (duplicate offsets)
        lines.append(body)
    return lines


def run_trials(trials: int, seed: int, quiet: bool = True) -> int:
    """Run ``trials`` differential trials; returns the number checked.
    Raises AssertionError with a repro line on the first divergence.
    The caller owns KLOGS_NATIVE_SIMD restoration."""
    from klogs_tpu import native

    if native.hostops is None or not hasattr(native.hostops,
                                             "sweep_candidates"):
        raise RuntimeError("native extension unavailable")
    from klogs_tpu.utils.env import read as env_read

    rng = random.Random(seed)
    saved = env_read("KLOGS_NATIVE_SIMD")
    saved_buckets = env_read("KLOGS_SWEEP_BUCKETS")
    checked = 0
    try:
        for trial in range(trials):
            # Rotate the stage-1 bucket plane too: coprime strides
            # (5 SIMD levels x 3 bucket modes) cover every pairing.
            bmode = BUCKET_MODES[trial % len(BUCKET_MODES)]
            os.environ["KLOGS_SWEEP_BUCKETS"] = bmode
            pats = rand_patterns(rng)
            try:
                infos = analyze(pats)
                idx = FactorIndex(
                    infos, plan_groups(
                        infos,
                        max_group_patterns=rng.choice((2, 3, 32))))
            except Exception:
                continue  # outside the analyzable subset
            lines = rand_lines(rng, pats)
            payload, offsets, _ = frame_lines(lines)
            offsets = np.asarray(offsets, dtype=np.int32)
            expect = idx.group_candidates(payload, offsets, impl="numpy")
            level = SIMD_LEVELS[trial % len(SIMD_LEVELS)]
            os.environ["KLOGS_NATIVE_SIMD"] = level
            got = idx.group_candidates(payload, offsets, impl="native")
            assert np.array_equal(expect, got), (
                f"DIVERGENCE: seed={seed} trial={trial} simd={level} "
                f"buckets={bmode} "
                f"patterns={pats!r} lines={lines!r}\n"
                f"numpy:\n{expect.astype(int)}\n"
                f"native:\n{got.astype(int)}")
            checked += 1
            if not quiet and trial and trial % 200 == 0:
                print(f"  {trial} trials, {checked} checked", flush=True)
    finally:
        if saved is None:
            os.environ.pop("KLOGS_NATIVE_SIMD", None)
        else:
            os.environ["KLOGS_NATIVE_SIMD"] = saved
        if saved_buckets is None:
            os.environ.pop("KLOGS_SWEEP_BUCKETS", None)
        else:
            os.environ["KLOGS_SWEEP_BUCKETS"] = saved_buckets
    return checked


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--trials", type=int, default=2000)
    ap.add_argument("--seed", type=int, default=None)
    args = ap.parse_args()
    seed = args.seed if args.seed is not None else int(time.time())
    print(f"fuzz-sweep: seed={seed} trials={args.trials}", flush=True)
    t0 = time.time()
    try:
        checked = run_trials(args.trials, seed, quiet=False)
    except RuntimeError as e:
        print(f"SKIP: {e}")
        return 2
    except AssertionError as e:
        print(str(e), flush=True)
        return 1
    print(f"fuzz-sweep OK: {checked} mask comparisons across "
          f"{args.trials} trials, {time.time() - t0:.0f}s, seed={seed}",
          flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
