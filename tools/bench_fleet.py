"""Fleet scale-out curve + profiler overhead (BENCH_FLEET.json).

The autoscaling-signal bench (ROADMAP item 3's observability half):
drive the SAME sharded collector against 1 -> 8 simulated filterd
endpoints and record, per fleet size, sustained lines/s, the
per-stage utilization attribution the continuous profiler
(obs/profiler.py) folded from the run's spans, and each endpoint's
advertised headroom — so the scale-out curve carries WHY it bends,
not just where.

Endpoints are *simulated devices* behind REAL plumbing: each fleet
member is a real in-process gRPC FilterServer whose engine is replaced
by ``SimulatedDeviceFilter`` — a device model that serializes batches
through one lock and sleeps ``lines / capacity_lps`` per batch with
the GIL released. Everything else (framed wire protocol, msgpack
codecs, tenancy-free match path, coalescer, sharded routing, capacity
accounting, Hello advertisement) is the production code. On a
many-core host the curve measures fleet aggregation; on a small host
it honestly bends where the collector's single-core wire work
saturates — and the stage attribution in the row says so
(rpc.client/shard.dispatch busy-seconds dominating device.fetch).

The corpus reaches the senders the way a backfill run would: rotated
into gzip archive members and ingested through the real ArchiveSource
(producer thread, bounded readahead), then framed into wire batches —
every row carries ``"source": "archive"``. A final HETEROGENEOUS row
runs one full-rate device next to one at a quarter rate and records
each endpoint's admitted batch share next to its advertised headroom:
the acceptance signal that capacity-weighted routing steers load
toward headroom instead of splitting 1/N.

The ``overhead`` block is the acceptance measurement for the <2%
profiler budget: the K=1024 BENCH_K bench path (IndexedFilter, host
sweep, same corpus/builder as bench.py --k-axis) timed with the
profiler off and on, best-of-N each, overhead recorded.

    python tools/bench_fleet.py            # writes BENCH_FLEET.json

Env knobs (KLOGS_BENCH_* family): KLOGS_BENCH_FLEET_ENDPOINTS
("1,2,4,8"), KLOGS_BENCH_FLEET_LINES, KLOGS_BENCH_FLEET_BATCH,
KLOGS_BENCH_FLEET_SENDERS, KLOGS_BENCH_FLEET_CAP_LPS (per-endpoint
simulated device capacity), KLOGS_BENCH_FLEET_K /
KLOGS_BENCH_FLEET_OVERHEAD_LINES (overhead stage sizing),
KLOGS_BENCH_REPEATS, KLOGS_BENCH_FLEET_OUT.
"""

import asyncio
import gzip
import json
import os
import sys
import tempfile
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402

import bench  # noqa: E402
from klogs_tpu.filters.base import LogFilter, frame_lines  # noqa: E402
from klogs_tpu.obs import trace  # noqa: E402
from klogs_tpu.obs.profiler import PROFILER  # noqa: E402
from klogs_tpu.utils.env import read as env_read  # noqa: E402

DEFAULT_ENDPOINTS = "1,2,4,8"
DEFAULT_LINES = 262144
DEFAULT_BATCH = 8192
DEFAULT_SENDERS = 16
DEFAULT_CAP_LPS = 300000.0
DEFAULT_OVERHEAD_K = 1024
DEFAULT_OVERHEAD_LINES = 100000


class SimulatedDeviceFilter(LogFilter):
    """One simulated device: batches serialize through a lock and each
    costs ``lines / capacity_lps`` of GIL-released wall time — the
    round-trip shape of a real accelerator attach without needing N
    accelerators (or N cores) to draw a scale-out curve."""

    def __init__(self, capacity_lps: float) -> None:
        self._cap = capacity_lps
        self._mu = threading.Lock()

    def _serve(self, n: int) -> None:
        with self._mu:  # one device: its batches do not overlap
            time.sleep(n / self._cap)

    def match_lines(self, lines: "list[bytes]") -> "list[bool]":
        self._serve(len(lines))
        return [b"ERROR" in ln for ln in lines]

    def dispatch_framed(self, payload: bytes, offsets):
        return offsets

    def fetch_framed(self, handle):
        n = len(handle) - 1
        self._serve(n)
        return np.zeros(n, dtype=bool)


def _write_corpus(tmpdir: str, n_lines: int, members: int = 4
                  ) -> "list[str]":
    """Rotate the synthetic corpus into gzip archive members — the
    exact artifact shape ``--backfill`` ingests in production."""
    lines = bench.make_lines(n_lines)
    per = max(1, (len(lines) + members - 1) // members)
    paths = []
    for i in range(members):
        chunk = lines[i * per:(i + 1) * per]
        if not chunk:
            break
        path = os.path.join(tmpdir, f"pod.log.{i}.gz")
        with gzip.open(path, "wb") as f:
            f.writelines(chunk)
        paths.append(path)
    return paths


async def _archive_batches(paths: "list[str]", batch_lines: int
                           ) -> "list[tuple]":
    """Ingest the rotated corpus through the real ArchiveSource
    (producer thread, bounded readahead, gzip decode) and frame it
    into wire batches — so the senders replay exactly what a backfill
    run would have put on the wire."""
    from klogs_tpu.cluster.types import LogOptions
    from klogs_tpu.sources.archive import ArchiveSource

    src = ArchiveSource(paths)
    await src.start()
    batches: "list[tuple]" = []
    pend: "list[bytes]" = []

    def flush(minimum: int) -> None:
        nonlocal pend
        while len(pend) >= max(1, minimum):
            chunk, pend = pend[:batch_lines], pend[batch_lines:]
            payload, offsets, _ = frame_lines(chunk)
            batches.append((payload, offsets, len(chunk)))

    try:
        buf = b""
        for ref in await src.discover():
            stream = await src.open_stream(ref, LogOptions())
            try:
                async for slab in stream:
                    buf += slab
                    parts = buf.split(b"\n")
                    buf = parts.pop()
                    pend.extend(p for p in parts if p)
                    flush(batch_lines)
            finally:
                await stream.close()
        if buf:
            pend.append(buf)
        flush(1)  # tail partial batch
    finally:
        await src.close()
    return batches


async def _drive_fleet(caps: "list[float]", batches: "list[tuple]",
                       batch_lines: int, senders: int,
                       patterns: "list[str]") -> dict:
    from klogs_tpu.obs import Registry, register_all
    from klogs_tpu.service.server import FilterServer
    from klogs_tpu.service.shard import ShardedFilterClient

    servers = []
    targets = []
    for cap in caps:
        srv = FilterServer(patterns, backend="cpu", port=0)
        # Swap the compiled engine for the simulated device BEFORE
        # start() so even the warmup batch rides the model, and pin
        # the capacity envelope so the Hello headroom advertisement
        # reflects THIS endpoint's (possibly heterogeneous) device.
        srv._service._filter.close()
        srv._service._filter = SimulatedDeviceFilter(cap)
        srv.capacity._envelope = cap
        srv.capacity._envelope_resolved = True
        srv.capacity._envelope_from_ctor = True
        port = await srv.start()
        servers.append(srv)
        targets.append(f"127.0.0.1:{port}")

    heterogeneous = len(set(caps)) > 1
    registry = Registry()
    register_all(registry)
    client = ShardedFilterClient(targets, shard_mode="round-robin",
                                 hedge_s=None, registry=registry)
    try:
        await client.verify_patterns(patterns)

        async def drive() -> "tuple[list[int], float]":
            queue: "asyncio.Queue" = asyncio.Queue()
            for b in batches:
                queue.put_nowait(b)

            async def sender() -> int:
                done = 0
                while True:
                    try:
                        payload, offsets, n = queue.get_nowait()
                    except asyncio.QueueEmpty:
                        return done
                    await client.match_framed(payload, offsets)
                    done += n

            t0 = time.perf_counter()
            counts = await asyncio.gather(
                *[sender() for _ in range(senders)])
            return counts, time.perf_counter() - t0

        fam = registry.family("klogs_shard_batches_total")
        won0 = [0.0] * len(targets)
        if heterogeneous:
            # Learn pass: age each endpoint's admitted-rate window and
            # let the prober fold the diverging headroom advertisements
            # into routing weights; the measured pass below then runs
            # at the steady operating point. Shares are deltas.
            await drive()
            won0 = [fam.labels(endpoint=t).value for t in targets]
        before = PROFILER.tick() or {"stages": {}}
        counts, dt = await drive()
        after = PROFILER.tick() or {"stages": {}}
        stages = {}
        for name, st in after["stages"].items():
            prev = before["stages"].get(name, {})
            busy = st["busy_s"] - prev.get("busy_s", 0.0)
            spans = st["spans"] - prev.get("spans", 0)
            if spans <= 0:
                continue
            stages[name] = {"busy_s": round(busy, 4), "spans": spans,
                            "utilization": round(busy / dt, 4)}
        bottleneck = (max(stages, key=lambda k: stages[k]["busy_s"])
                      if stages else None)
        headroom = []
        for srv in servers:
            headroom.append(srv.capacity.doc()["headroom"])
        row = {
            "endpoints": len(caps),
            "source": "archive",
            "n_lines": sum(counts),
            "batch_lines": batch_lines,
            "senders": senders,
            "capacity_lps_per_endpoint": (list(caps) if heterogeneous
                                          else caps[0]),
            "lps": round(sum(counts) / dt, 1),
            "stages": stages,
            "bottleneck": bottleneck,
            "headroom": headroom,
        }
        if heterogeneous:
            # The acceptance signal for capacity-weighted routing: the
            # share of batches each endpoint won should track its
            # advertised headroom, not 1/N.
            won = [fam.labels(endpoint=t).value - w0
                   for t, w0 in zip(targets, won0)]
            total = sum(won) or 1.0
            row["heterogeneous"] = True
            row["per_endpoint"] = [
                {"endpoint": t, "capacity_lps": c,
                 "batches": int(n), "share": round(n / total, 4),
                 "headroom": h}
                for t, c, n, h in zip(targets, caps, won, headroom)]
        return row
    finally:
        await client.aclose()
        for srv in servers:
            await srv.stop()


def measure_overhead(k: int, n_lines: int, repeats: int) -> dict:
    """The <2% acceptance measurement: the K=1024 bench path (same
    builder/corpus discipline as bench.py --k-axis) with the profiler
    (and the span stream it needs) fully off vs fully on."""
    from klogs_tpu.filters.indexed import IndexedFilter

    pats = bench.make_patterns(k)
    lines = [ln.rstrip(b"\n") for ln in bench.make_lines(n_lines)]
    payload, offsets, _ = frame_lines(lines)
    offsets = np.asarray(offsets, dtype=np.int32)
    filt = IndexedFilter(pats, sweep="host")
    filt._bypass_min_lines = 1 << 62  # measure the index, not the remedy

    def rate() -> float:
        best = 0.0
        for _ in range(repeats):
            t0 = time.perf_counter()
            filt.fetch_framed(filt.dispatch_framed(payload, offsets))
            best = max(best, len(lines) / (time.perf_counter() - t0))
        return best

    rate()  # warm every stage (re-guard probation, caches) once
    PROFILER.reset()
    trace.reset(0.0)  # hard off: no spans, no fold — the baseline
    off_lps = rate()
    trace.reset(None)
    PROFILER.reset()
    PROFILER.enable(1.0)  # sample=1: every span recorded AND folded
    on_lps = rate()
    ticks = PROFILER.tick()
    PROFILER.reset()
    trace.reset(None)
    overhead_pct = (100.0 * (off_lps - on_lps) / off_lps
                    if off_lps else 0.0)
    return {
        "k": k,
        "n_lines": n_lines,
        "repeats": repeats,
        "profiler_off_lps": round(off_lps, 1),
        "profiler_on_lps": round(on_lps, 1),
        "overhead_pct": round(overhead_pct, 3),
        "stages_folded": sorted((ticks or {}).get("stages", {})),
    }


def main() -> None:
    endpoints = [int(x) for x in env_read(
        "KLOGS_BENCH_FLEET_ENDPOINTS", DEFAULT_ENDPOINTS).split(",") if x]
    n_lines = int(env_read("KLOGS_BENCH_FLEET_LINES", str(DEFAULT_LINES)))
    batch_lines = int(env_read("KLOGS_BENCH_FLEET_BATCH",
                               str(DEFAULT_BATCH)))
    senders = int(env_read("KLOGS_BENCH_FLEET_SENDERS",
                           str(DEFAULT_SENDERS)))
    cap_lps = float(env_read("KLOGS_BENCH_FLEET_CAP_LPS",
                             str(DEFAULT_CAP_LPS)))
    k = int(env_read("KLOGS_BENCH_FLEET_K", str(DEFAULT_OVERHEAD_K)))
    overhead_lines = int(env_read("KLOGS_BENCH_FLEET_OVERHEAD_LINES",
                                  str(DEFAULT_OVERHEAD_LINES)))
    repeats = int(env_read("KLOGS_BENCH_REPEATS", "5"))

    # The headroom advertisement needs an envelope; each server gets
    # its own (possibly heterogeneous) device capacity pinned as the
    # constructor envelope in _drive_fleet — the env override would
    # flatten the heterogeneous row to one shared number. Refresh
    # capacity at prober cadence so a bench-length run actually sees
    # the advertisements diverge. (Writes are legal; only raw KLOGS_*
    # reads must flow through utils/env.)
    os.environ.pop("KLOGS_FLEET_CAPACITY_LPS", None)
    os.environ["KLOGS_FLEET_REFRESH_S"] = "0.5"
    # Span stream fully on: the per-stage attribution is the point.
    trace.reset(1.0)
    PROFILER.reset()
    PROFILER.enable(1.0)

    rows = []
    with tempfile.TemporaryDirectory(prefix="klogs-bench-fleet-") as tmp:
        # Rotate the corpus to gzip archives ONCE and replay the same
        # ArchiveSource-framed batches into every fleet size, so rows
        # differ only in the fleet.
        paths = _write_corpus(tmp, n_lines)
        batches = asyncio.run(_archive_batches(paths, batch_lines))
    for n in endpoints:
        row = asyncio.run(_drive_fleet([cap_lps] * n, batches,
                                       batch_lines, senders,
                                       bench.PATTERNS))
        rows.append(row)
        print(f"bench_fleet: {n} endpoint(s) -> {row['lps']:,.0f} l/s "
              f"bottleneck={row['bottleneck']}", file=sys.stderr)
    # The heterogeneous fleet: one full-rate device plus one at a
    # quarter rate. Capacity-weighted routing should steer admitted
    # share toward headroom, not split it 1/N.
    het = asyncio.run(_drive_fleet([cap_lps, cap_lps / 4.0], batches,
                                   batch_lines, senders,
                                   bench.PATTERNS))
    rows.append(het)
    shares = ", ".join(f"{pe['share']:.2f}" for pe in het["per_endpoint"])
    print(f"bench_fleet: heterogeneous [1x, 0.25x] -> "
          f"{het['lps']:,.0f} l/s shares=[{shares}]", file=sys.stderr)
    PROFILER.reset()
    trace.reset(None)

    overhead = measure_overhead(k, overhead_lines, repeats)
    print(f"bench_fleet: profiler overhead at K={k}: "
          f"{overhead['overhead_pct']:.2f}% "
          f"({overhead['profiler_off_lps']:,.0f} -> "
          f"{overhead['profiler_on_lps']:,.0f} l/s)", file=sys.stderr)

    import multiprocessing

    payload = {
        "metric": "sharded-collector lines/sec vs fleet size "
                  "(simulated filterd devices behind the real wire/"
                  "routing/capacity path), with per-stage utilization "
                  "attribution from the continuous profiler",
        "unit": "lines/sec",
        "corpus": "needle-finding synthetic pod logs, ~128B lines",
        "cpu_count": multiprocessing.cpu_count(),
        "rows": rows,
        "overhead": overhead,
    }
    out = env_read("KLOGS_BENCH_FLEET_OUT") or os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "BENCH_FLEET.json")
    with open(out, "w") as f:
        json.dump(payload, f, indent=1)
        f.write("\n")
    print(json.dumps({"rows": len(rows),
                      "overhead_pct": overhead["overhead_pct"],
                      "out": out}))


if __name__ == "__main__":
    main()
