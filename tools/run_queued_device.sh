#!/bin/sh
# Round-4 queued device measurements (BASELINE.md "Pending device
# measurements"), run in order with per-tool attach retries. The axon
# tunnel wedges transiently (attach hangs inside backend init), so each
# tool gets a hard per-attempt timeout and several attempts spread over
# time. Logs land next to this script's repo root as .{bench_r4,
# fused_ab,service_bench}.log; progress markers go to .queued_status.
set -u
cd "$(dirname "$0")/.."
status() { echo "$(date -u +%H:%M:%S) $*" >> .queued_status; }

status "start"
# 1. Headline bench (has its own attach-retry loop inside).
KLOGS_BENCH_DEVICE_TIMEOUT_S=5400 timeout 6000 python -u bench.py \
    > .bench_r4.log 2>&1
status "bench.py rc=$?"

# 2. Fused-groups A/B (attaches in-process; retry around it).
i=0
while [ $i -lt 8 ]; do
    i=$((i+1))
    timeout 900 python -u tools/bench_fused_ab.py >> .fused_ab.log 2>&1
    rc=$?
    status "bench_fused_ab attempt $i rc=$rc"
    [ $rc -eq 0 ] && break
    [ $rc -eq 1 ] && break   # divergence: hard fail, do not retry
    sleep 60
done

# 3. gRPC service bench on the TPU backend.
i=0
while [ $i -lt 5 ]; do
    i=$((i+1))
    timeout 900 python -u tools/bench_service.py --backend tpu \
        >> .service_bench.log 2>&1
    rc=$?
    status "bench_service attempt $i rc=$rc"
    [ $rc -eq 0 ] && break
    sleep 60
done
status "done"
