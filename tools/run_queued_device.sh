#!/bin/sh
# Round-4 queued device measurements (BASELINE.md "Pending device
# measurements"), gated on a successful tunnel probe. The axon tunnel
# wedges for long stretches (attach hangs inside backend init), so:
# probe cheaply in a loop; when an attach succeeds, run the whole queue
# back-to-back in that healthy window. Logs: .{bench_r4,fused_ab,
# service_bench}.log at the repo root; progress markers in
# .queued_status. Overall deadline ~6h from launch.
set -u
cd "$(dirname "$0")/.."
status() { echo "$(date -u +%H:%M:%S) $*" >> .queued_status; }

deadline=$(( $(date +%s) + 21600 ))
status "watchdog start (deadline +6h)"
bench_done=0; ab_done=0; svc_done=0

while [ "$(date +%s)" -lt "$deadline" ]; do
    if ! timeout 90 python -c "import jax; jax.devices()" 2>/dev/null; then
        sleep 75
        continue
    fi
    status "probe OK — tunnel healthy, running queue"
    if [ "$bench_done" -eq 0 ]; then
        KLOGS_BENCH_DEVICE_TIMEOUT_S=1500 timeout 1800 python -u bench.py \
            >> .bench_r4.log 2>&1 && bench_done=1
        status "bench.py rc=$? done=$bench_done"
    fi
    if [ "$ab_done" -eq 0 ]; then
        timeout 1800 python -u tools/bench_fused_ab.py >> .fused_ab.log 2>&1
        rc=$?
        [ $rc -eq 0 ] && ab_done=1
        [ $rc -eq 1 ] && ab_done=1  # divergence: hard fail, do not retry
        status "bench_fused_ab rc=$rc done=$ab_done"
    fi
    if [ "$svc_done" -eq 0 ]; then
        timeout 900 python -u tools/bench_service.py --backend tpu \
            >> .service_bench.log 2>&1 && svc_done=1
        status "bench_service rc=$? done=$svc_done"
    fi
    if [ "$bench_done" -eq 1 ] && [ "$ab_done" -eq 1 ] && [ "$svc_done" -eq 1 ]; then
        status "all done"
        exit 0
    fi
    sleep 75
done
status "deadline reached: bench=$bench_done ab=$ab_done svc=$svc_done"
