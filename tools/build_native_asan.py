"""Sanitizer build targets for the native extension (docs/NATIVE.md).

Two modes over the same harness:

- **ASan/UBSan** (default): compiles ``klogs_tpu/native/_hostops.c``
  with ``-fsanitize=address,undefined -fno-sanitize-recover=all`` and
  runs the native parity tests against THAT binary, so a buffer slip
  or UB in the C hot loops aborts the test run instead of corrupting
  memory quietly.
- **TSan** (``--tsan``): rebuilds with ``-fsanitize=thread`` and runs
  the *threaded* suites — the ``KLOGS_HOST_THREADS`` row-sliced
  group scan and the GIL-released sweep reentrancy tests — so the
  "disjoint verdict ranges, no races by construction" claim about the
  pthread workers is a dynamically tested invariant, not a comment.

This is the dynamic half of the native analysis tier (the static half
is the ``native-tier`` + ``abi-conformance`` passes in
``tools/analysis``); new kernels must land green under both modes.

Mechanics: the host ``python`` binary is NOT sanitized, so the
sanitizer runtime is LD_PRELOADed (``$CC -print-file-name=...``).
Under ASan leak detection is disabled (CPython's interned allocations
look like leaks at exit); under TSan ``halt_on_error=1`` turns the
first race report into a non-zero exit. Races are reported only for
accesses the instrumented .so makes — exactly the surface we own.
The sanitized .so is pinned via ``KLOGS_NATIVE_SO`` — the loader
raises if the pin fails to load, so a sanitizer run can never
silently green-light the pure-Python fallback.

Exit codes: 0 = built (and tests passed, unless --no-run-tests);
2 = SKIP (no sanitizer-capable compiler / runtime in this
environment — printed loudly, the tier-1 wrapper turns it into a
pytest skip); 1 = build or test failure.

Usage:
    python -m tools.build_native_asan [--tsan] [--no-run-tests]
                                      [--out PATH]
"""

import argparse
import os
import shutil
import subprocess
import sys
import sysconfig
import tempfile

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SRC = os.path.join(ROOT, "klogs_tpu", "native", "_hostops.c")
ASAN_FLAGS = ["-fsanitize=address,undefined", "-fno-sanitize-recover=all"]
TSAN_FLAGS = ["-fsanitize=thread"]
# The sweep + group-scan parity suites ride along so the GIL-released
# kernels (unaligned loads, masked tails, hash probes over untrusted
# offsets, the MultiDFA walk over an untrusted program blob) are
# exercised under ASan/UBSan in every tier-1 run; their `slow` loops
# are excluded to keep the gate fast.
TEST_FILES = ["tests/test_native.py", "tests/test_native_sweep.py",
              "tests/test_groupscan.py"]
# TSan mode runs the tests that actually take the multi-threaded
# paths, by node id: the row-sliced group scan drives the pthread
# worker pool against one shared MultiDFA program, and the sweep
# reentrancy tests overlap GIL-released kernel calls from Python
# threads over one shared blob. (test_threaded_rows_parity is marked
# slow for the plain gate, so node ids — not ``-m "not slow"`` — are
# the selection here; the genuinely minutes-long speedup benches stay
# out.)
TSAN_TEST_IDS = [
    "tests/test_groupscan.py::test_threaded_rows_parity",
    "tests/test_native_sweep.py::test_packed_tables_shared_across_threads",
    "tests/test_native_sweep.py::test_gil_released_during_sweep",
    # Slab pipeline: prefetch threads inside sweep_candidates while the
    # main thread confirms through group_scan — the exact production
    # overlap KLOGS_SWEEP_PIPELINE enables.
    "tests/test_native_sweep.py::test_sweep_pipeline_parity",
]


def _candidate_compilers() -> "list[str]":
    seen: "list[str]" = []
    for cc in (os.environ.get("CC"), "clang", "gcc", "cc"):
        if cc and cc not in seen and shutil.which(cc):
            seen.append(cc)
    return seen


def _supports_flags(cc: str, flags: "list[str]") -> bool:
    """Probe-compile an empty TU with the sanitizer flags."""
    with tempfile.TemporaryDirectory() as td:
        probe = os.path.join(td, "probe.c")
        with open(probe, "w") as f:
            f.write("int main(void) { return 0; }\n")
        res = subprocess.run(
            [cc, *flags, probe, "-o", os.path.join(td, "probe")],
            capture_output=True, timeout=60)
        return res.returncode == 0


def _find_runtime(cc: str, names: "list[str]") -> "str | None":
    for name in names:
        res = subprocess.run([cc, f"-print-file-name={name}"],
                             capture_output=True, text=True, timeout=30)
        path = res.stdout.strip()
        if res.returncode == 0 and path and path != name \
                and os.path.exists(path):
            return path
    return None


def _asan_runtime(cc: str) -> "str | None":
    """Path to the ASan runtime shared object for LD_PRELOAD: gcc
    ships libasan.so, clang libclang_rt.asan-<arch>.so."""
    import platform

    return _find_runtime(cc, [
        "libasan.so",
        f"libclang_rt.asan-{platform.machine()}.so",
        "libclang_rt.asan.so"])


def _tsan_runtime(cc: str) -> "str | None":
    import platform

    return _find_runtime(cc, [
        "libtsan.so",
        f"libclang_rt.tsan-{platform.machine()}.so",
        "libclang_rt.tsan.so"])


def _stdcxx_runtime(cc: str) -> "str | None":
    """libstdc++ must ride the SAME LD_PRELOAD: python itself doesn't
    link it, so the sanitizer's __cxa_throw interceptor would
    otherwise resolve its real_ pointer to NULL and abort the first
    time any bundled C++ extension (jaxlib's MLIR bindings) throws."""
    return _find_runtime(cc, ["libstdc++.so.6", "libstdc++.so",
                              "libc++.so.1", "libc++.so"])


def build(cc: str, out: str, flags: "list[str]") -> bool:
    include = sysconfig.get_paths()["include"]
    cmd = [cc, "-g", "-O1", "-fno-omit-frame-pointer", *flags,
           "-shared", "-fPIC", "-pthread", f"-I{include}", SRC,
           "-o", out]
    print(f"build: {' '.join(cmd)}")
    res = subprocess.run(cmd, capture_output=True, text=True, timeout=300)
    if res.returncode != 0:
        sys.stderr.write(res.stderr)
        return False
    return True


def run_tests(out: str, preload: str, tsan: bool) -> int:
    env = dict(os.environ)
    env["LD_PRELOAD"] = preload
    env["KLOGS_NATIVE_SO"] = out
    env.pop("KLOGS_NO_NATIVE", None)
    env.setdefault("JAX_PLATFORMS", "cpu")
    if tsan:
        # First data-race report fails the run; second_deadlock_stack
        # makes lock-inversion reports actionable.
        env["TSAN_OPTIONS"] = "halt_on_error=1 second_deadlock_stack=1"
        # The threaded tests pin their own KLOGS_HOST_THREADS via
        # monkeypatch; nothing to set here.
        cmd = [sys.executable, "-m", "pytest", *TSAN_TEST_IDS, "-q",
               "-p", "no:cacheprovider"]
    else:
        # CPython "leaks" its interned state at exit; halt_on_error
        # stays on for real findings via -fno-sanitize-recover.
        env["ASAN_OPTIONS"] = "detect_leaks=0"
        cmd = [sys.executable, "-m", "pytest", *TEST_FILES, "-q",
               "-m", "not slow", "-p", "no:cacheprovider"]
    print(f"test: LD_PRELOAD={preload!r} "
          f"KLOGS_NATIVE_SO={out} {' '.join(cmd)}")
    return subprocess.run(cmd, cwd=ROOT, env=env, timeout=600).returncode


def main(argv: "list[str] | None" = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tools.build_native_asan",
        description="sanitizer build + parity-test run for _hostops.c "
                    "(ASan/UBSan by default, ThreadSanitizer with "
                    "--tsan)")
    ap.add_argument("--tsan", action="store_true",
                    help="build with -fsanitize=thread and run the "
                         "threaded group-scan/sweep tests instead of "
                         "the full parity suite")
    ap.add_argument("--out", default=None,
                    help="output .so path (default: temp dir)")
    ap.add_argument("--no-run-tests", action="store_true",
                    help="build only")
    ns = ap.parse_args(argv)

    mode = "TSan" if ns.tsan else "ASan/UBSan"
    flags = TSAN_FLAGS if ns.tsan else ASAN_FLAGS
    if not os.path.exists(SRC):
        print(f"SKIP: {SRC} not found")
        return 2
    chosen = None
    for cc in _candidate_compilers():
        if _supports_flags(cc, flags):
            chosen = cc
            break
    if chosen is None:
        print(f"SKIP: no compiler supporting {' '.join(flags)} found "
              "(tried CC/clang/gcc/cc) — the sanitizer tier needs "
              "clang or gcc with the runtime libraries")
        return 2
    runtime = _tsan_runtime(chosen) if ns.tsan else _asan_runtime(chosen)
    if runtime is None:
        print(f"SKIP: {chosen} supports the flags but no {mode} "
              "runtime library was found to LD_PRELOAD")
        return 2
    stdcxx = _stdcxx_runtime(chosen)
    preload = f"{runtime} {stdcxx}" if stdcxx else runtime

    out = ns.out
    owned_dir = None
    if out is None:
        owned_dir = tempfile.mkdtemp(prefix="klogs-san-")
        suffix = "tsan" if ns.tsan else "asan"
        out = os.path.join(owned_dir, f"_hostops_{suffix}.so")
    try:
        if not build(chosen, out, flags):
            print(f"FAIL: {mode} build failed")
            return 1
        print(f"built {out} with {chosen}")
        if ns.no_run_tests:
            return 0
        rc = run_tests(out, preload, ns.tsan)
        if rc != 0:
            print(f"FAIL: native parity tests failed under {mode} "
                  f"(rc={rc})")
            return 1
        print(f"OK: native parity tests passed under {mode}")
        return 0
    finally:
        if owned_dir is not None:
            shutil.rmtree(owned_dir, ignore_errors=True)


if __name__ == "__main__":
    sys.exit(main())
