"""Sanitizer build target for the native extension (docs/NATIVE.md).

Compiles ``klogs_tpu/native/_hostops.c`` with
``-fsanitize=address,undefined -fno-sanitize-recover=all`` and runs the
existing native parity tests against THAT binary, so a buffer slip or
UB in the C hot loops aborts the test run instead of corrupting memory
quietly. This is the dynamic half of the native analysis tier (the
static half is the ``native-tier`` pass in ``tools/analysis``); the
SIMD sweep port (ROADMAP item 2) must land green under it.

Mechanics: the host ``python`` binary is NOT sanitized, so the ASan
runtime is LD_PRELOADed (``$CC -print-file-name=...``) and leak
detection is disabled (CPython's interned allocations look like leaks
at exit). The sanitized .so is pinned via ``KLOGS_NATIVE_SO`` — the
loader raises if the pin fails to load, so a sanitizer run can never
silently green-light the pure-Python fallback.

Exit codes: 0 = built (and tests passed, unless --no-run-tests);
2 = SKIP (no sanitizer-capable compiler / runtime in this
environment — printed loudly, the tier-1 wrapper turns it into a
pytest skip); 1 = build or test failure.

Usage:
    python -m tools.build_native_asan [--no-run-tests] [--out PATH]
"""

import argparse
import os
import shutil
import subprocess
import sys
import sysconfig
import tempfile

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SRC = os.path.join(ROOT, "klogs_tpu", "native", "_hostops.c")
SAN_FLAGS = ["-fsanitize=address,undefined", "-fno-sanitize-recover=all"]
# The sweep + group-scan parity suites ride along so the GIL-released
# kernels (unaligned loads, masked tails, hash probes over untrusted
# offsets, the MultiDFA walk over an untrusted program blob) are
# exercised under ASan/UBSan in every tier-1 run; their `slow` loops
# are excluded to keep the gate fast.
TEST_FILES = ["tests/test_native.py", "tests/test_native_sweep.py",
              "tests/test_groupscan.py"]


def _candidate_compilers() -> "list[str]":
    seen: "list[str]" = []
    for cc in (os.environ.get("CC"), "clang", "gcc", "cc"):
        if cc and cc not in seen and shutil.which(cc):
            seen.append(cc)
    return seen


def _supports_sanitizers(cc: str) -> bool:
    """Probe-compile an empty TU with the sanitizer flags."""
    with tempfile.TemporaryDirectory() as td:
        probe = os.path.join(td, "probe.c")
        with open(probe, "w") as f:
            f.write("int main(void) { return 0; }\n")
        res = subprocess.run(
            [cc, *SAN_FLAGS, probe, "-o", os.path.join(td, "probe")],
            capture_output=True, timeout=60)
        return res.returncode == 0


def _find_runtime(cc: str, names: "list[str]") -> "str | None":
    for name in names:
        res = subprocess.run([cc, f"-print-file-name={name}"],
                             capture_output=True, text=True, timeout=30)
        path = res.stdout.strip()
        if res.returncode == 0 and path and path != name \
                and os.path.exists(path):
            return path
    return None


def _asan_runtime(cc: str) -> "str | None":
    """Path to the ASan runtime shared object for LD_PRELOAD: gcc
    ships libasan.so, clang libclang_rt.asan-<arch>.so."""
    import platform

    return _find_runtime(cc, [
        "libasan.so",
        f"libclang_rt.asan-{platform.machine()}.so",
        "libclang_rt.asan.so"])


def _stdcxx_runtime(cc: str) -> "str | None":
    """libstdc++ must ride the SAME LD_PRELOAD: python itself doesn't
    link it, so ASan's __cxa_throw interceptor would otherwise resolve
    its real_ pointer to NULL and abort the first time any bundled C++
    extension (jaxlib's MLIR bindings) throws."""
    return _find_runtime(cc, ["libstdc++.so.6", "libstdc++.so",
                              "libc++.so.1", "libc++.so"])


def build(cc: str, out: str) -> bool:
    include = sysconfig.get_paths()["include"]
    cmd = [cc, "-g", "-O1", "-fno-omit-frame-pointer", *SAN_FLAGS,
           "-shared", "-fPIC", "-pthread", f"-I{include}", SRC,
           "-o", out]
    print(f"build: {' '.join(cmd)}")
    res = subprocess.run(cmd, capture_output=True, text=True, timeout=300)
    if res.returncode != 0:
        sys.stderr.write(res.stderr)
        return False
    return True


def run_tests(out: str, preload: str) -> int:
    env = dict(os.environ)
    env["LD_PRELOAD"] = preload
    env["KLOGS_NATIVE_SO"] = out
    env.pop("KLOGS_NO_NATIVE", None)
    env.setdefault("JAX_PLATFORMS", "cpu")
    # CPython "leaks" its interned state at exit; halt_on_error stays
    # on for real findings via -fno-sanitize-recover.
    env["ASAN_OPTIONS"] = "detect_leaks=0"
    cmd = [sys.executable, "-m", "pytest", *TEST_FILES, "-q",
           "-m", "not slow", "-p", "no:cacheprovider"]
    print(f"test: LD_PRELOAD={preload!r} "
          f"KLOGS_NATIVE_SO={out} {' '.join(cmd)}")
    return subprocess.run(cmd, cwd=ROOT, env=env, timeout=600).returncode


def main(argv: "list[str] | None" = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tools.build_native_asan",
        description="ASan/UBSan build + parity-test run for _hostops.c")
    ap.add_argument("--out", default=None,
                    help="output .so path (default: temp dir)")
    ap.add_argument("--no-run-tests", action="store_true",
                    help="build only")
    ns = ap.parse_args(argv)

    if not os.path.exists(SRC):
        print(f"SKIP: {SRC} not found")
        return 2
    chosen = None
    for cc in _candidate_compilers():
        if _supports_sanitizers(cc):
            chosen = cc
            break
    if chosen is None:
        print("SKIP: no compiler supporting -fsanitize=address,"
              "undefined found (tried CC/clang/gcc/cc) — the sanitizer "
              "tier needs clang or gcc with libasan/libubsan")
        return 2
    asan = _asan_runtime(chosen)
    if asan is None:
        print(f"SKIP: {chosen} supports the flags but no ASan runtime "
              "library was found to LD_PRELOAD")
        return 2
    stdcxx = _stdcxx_runtime(chosen)
    preload = f"{asan} {stdcxx}" if stdcxx else asan

    out = ns.out
    owned_dir = None
    if out is None:
        owned_dir = tempfile.mkdtemp(prefix="klogs-asan-")
        out = os.path.join(owned_dir, "_hostops_asan.so")
    try:
        if not build(chosen, out):
            print("FAIL: sanitizer build failed")
            return 1
        print(f"built {out} with {chosen}")
        if ns.no_run_tests:
            return 0
        rc = run_tests(out, preload)
        if rc != 0:
            print(f"FAIL: native parity tests failed under ASan/UBSan "
                  f"(rc={rc})")
            return 1
        print("OK: native parity tests passed under ASan/UBSan")
        return 0
    finally:
        if owned_dir is not None:
            shutil.rmtree(owned_dir, ignore_errors=True)


if __name__ == "__main__":
    sys.exit(main())
