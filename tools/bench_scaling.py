"""Pattern-count and line-width scaling at the ADOPTED kernel defaults
(tune.kernel_kwargs: mask_block=4 on hardware).

Refreshes BENCH_DEVICE.json's scaling_2026_07_29 rows, which were taken
on the plain chain: device cost should stay linear in pattern GROUPS
(grouped compilation) and byte throughput ~flat in width (VMEM tile cap
trades lanes for columns); this checks the restructured chain preserves
both properties. Methodology mirrors bench.py's pipelined measurement:
host-classified batch resident on device, N dispatches in flight, one
sync.
"""
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402


def main() -> None:
    import jax

    import bench as B
    from klogs_tpu.filters.tpu import pack_classify
    from klogs_tpu.ops import nfa
    from klogs_tpu.ops.pallas_nfa import match_cls_grouped_pallas
    from klogs_tpu.ops.tune import kernel_kwargs

    print("attached:", jax.devices()[0], flush=True)
    kw = kernel_kwargs(on_hardware=True)
    print("kernel kwargs:", kw, flush=True)
    N, NF = 524288, 32
    lines = [ln.rstrip(b"\n") for ln in B.make_lines(N)]
    out = {"date": time.strftime("%Y-%m-%d"), "kernel_kwargs": kw,
           "batch": N, "n_flight": NF, "patterns": [], "widths": []}

    def pipelined(dp, live, acc, dcls):
        run = lambda: match_cls_grouped_pallas(dp, live, acc, dcls, **kw)
        run().block_until_ready()
        best = 0.0
        for _ in range(3):
            t0 = time.perf_counter()
            outs = [run() for _ in range(NF)]
            outs[-1].block_until_ready()
            best = max(best, NF * dcls.shape[0] / (time.perf_counter() - t0))
        return best

    for k in (8, 16, 32, 64):
        pats = (B.PATTERNS * ((k // len(B.PATTERNS)) + 1))[:k] \
            if k > len(B.PATTERNS) else B.PATTERNS[:k]
        if k > len(B.PATTERNS):  # make repeats distinct patterns
            pats = B.PATTERNS + [p + r"x{0}" for p in B.PATTERNS[: k - 32]]
        dp, live, acc = nfa.compile_grouped(pats)
        table = np.asarray(dp.byte_class).astype(np.int8)
        cls = pack_classify(lines, 128, table, dp.begin_class,
                            dp.end_class, dp.pad_class)
        dcls = jax.device_put(cls)
        lps = pipelined(dp, live, acc, dcls)
        g = dp.follow.shape[0]
        out["patterns"].append({"k": k, "groups": g, "lps": round(lps, 1)})
        print(f"patterns {k:3d} ({g} groups): {lps:,.0f} lines/s", flush=True)

    dp, live, acc = nfa.compile_grouped(B.PATTERNS)
    table = np.asarray(dp.byte_class).astype(np.int8)
    for width in (128, 256, 512, 1024):
        wl = [(ln * ((width // len(ln)) + 1))[:width] for ln in lines[: N // (width // 128)]]
        cls = pack_classify(wl, width, table, dp.begin_class,
                            dp.end_class, dp.pad_class)
        dcls = jax.device_put(cls)
        lps = pipelined(dp, live, acc, dcls)
        mbs = lps * width / 1e6
        out["widths"].append({"width": width, "lps": round(lps, 1),
                              "mb_s": round(mbs, 1)})
        print(f"width {width:5d}B: {lps:,.0f} lines/s = {mbs:,.0f} MB/s",
              flush=True)

    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "BENCH_DEVICE.json")
    with open(path) as f:
        dev = json.load(f)
    dev["scaling_mask_block4"] = out
    with open(path, "w") as f:
        json.dump(dev, f, indent=1)
    print("wrote", path, flush=True)


if __name__ == "__main__":
    main()
