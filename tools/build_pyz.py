"""Build the single-file klogs.pyz zipapp (dist/klogs.pyz).

The reference ships an upx-compressed static Go binary
(/root/reference/.github/workflows/release.yaml:36-63) — install is
"download one file and run". The Python-ecosystem equivalent is a
zipapp: one file, runnable as ``python klogs.pyz ...`` (or directly
with the embedded shebang) on any machine with python3.10+ and the
library deps (numpy always; jax only for --backend=tpu; grpcio/msgpack
only for --remote; aiohttp only for real clusters — all imports are
lazy, so the artifact runs the fake/cpu paths with numpy alone). The
native C fast path compiles itself on first use into
~/.cache/klogs-tpu (klogs_tpu.native handles read-only zip packaging).

    python tools/build_pyz.py [outdir]
"""

import os
import py_compile
import shutil
import sys
import tempfile
import zipapp

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)

from klogs_tpu.utils.env import read as env_read  # noqa: E402

MAIN = """\
from klogs_tpu.cli import main

if __name__ == "__main__":
    main()
"""


def build(outdir: str) -> str:
    os.makedirs(outdir, exist_ok=True)
    out = os.path.join(outdir, "klogs.pyz")
    with tempfile.TemporaryDirectory() as stage:
        pkg_src = os.path.join(ROOT, "klogs_tpu")
        pkg_dst = os.path.join(stage, "klogs_tpu")
        shutil.copytree(
            pkg_src, pkg_dst,
            ignore=shutil.ignore_patterns("__pycache__", "*.so", "*.pyc"))
        with open(os.path.join(stage, "__main__.py"), "w") as f:
            f.write(MAIN)
        # Bake the release version into the artifact (the env override
        # only exists on the build machine; ≙ the reference's -ldflags
        # -X link-time stamp).
        ver = env_read("KLOGS_BUILD_VERSION")
        if ver:
            with open(os.path.join(pkg_dst, "version.py"), "a") as f:
                f.write(f"\nBUILD_VERSION = {ver!r}  # stamped at build\n")
        # Syntax-check everything we ship (a broken file inside a pyz
        # is much harder to diagnose than at build time). The .pyc
        # lands OUTSIDE the stage — default cfile would zip __pycache__
        # into the artifact, doubling it for bytecode zipapp never uses.
        with tempfile.TemporaryDirectory() as scratch:
            junk = os.path.join(scratch, "check.pyc")
            for dirpath, _, files in os.walk(stage):
                for name in files:
                    if name.endswith(".py"):
                        py_compile.compile(os.path.join(dirpath, name),
                                           cfile=junk, doraise=True)
        zipapp.create_archive(stage, out,
                              interpreter="/usr/bin/env python3",
                              compressed=True)
    return out


if __name__ == "__main__":
    path = build(sys.argv[1] if len(sys.argv) > 1 else
                 os.path.join(ROOT, "dist"))
    print(f"built {path} ({os.path.getsize(path)//1024} KB)")
