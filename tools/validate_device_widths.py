"""On-device validation: the adopted mask_block=4 default must compile
and agree with the host-regex oracle at EVERY production width bucket
(each bucket is a distinct Mosaic compile: T grows, tile shrinks)."""
import os
import random
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
random.seed(7)
from klogs_tpu.filters.cpu import RegexFilter
from klogs_tpu.filters.tpu import NFAEngineFilter

pats = ["ERROR", r"code=\d00", r"pod-\d+ crash", "timeout.*retry",
        r"^WARN", r"(fatal|panic):", r"lat=[0-9]{3,}ms", "needle"]
NEEDLES = ["ERROR", "code=700", "pod-42 crash", "timeout x y retry",
           "fatal:", "panic:", "lat=4567ms", "needle", "WARN lead"]
f = NFAEngineFilter(pats, kernel="pallas")
oracle = RegexFilter(pats)
for width in (100, 250, 500, 1000, 2000, 4000):
    lines = []
    for i in range(512):
        filler = "".join(random.choice("abcdefgh ")
                         for _ in range(width))
        if i % 3 == 0:
            n = random.choice(NEEDLES)
            if n.startswith("WARN"):
                body = n + filler
            else:
                pos = random.randrange(max(1, width - len(n)))
                body = filler[:pos] + n + filler[pos:]
        else:
            body = filler
        lines.append(body[:width].encode() + b"\n")
    t0 = time.perf_counter()
    got = f.match_lines(lines)
    dt = time.perf_counter() - t0
    want = oracle.match_lines(lines)
    assert got == want, f"DIVERGENCE at width {width}"
    assert sum(got) > 100, f"vacuous check at width {width}"
    print(f"width {width:5d}: ok ({sum(got)}/512 matched, {dt*1e3:.0f} ms)",
          flush=True)
print("all width buckets agree with the oracle under mask_block=4")
