"""One-attach-session device A/B: plain NFA kernel vs two-phase
(prefilter-gated) kernel vs candidate-mask alone, plus the (tile_b,
interleave) tune sweep — every configuration measured in the SAME
process on the SAME lines, so numbers are comparable and the prefilter
default can be decided on evidence (VERDICT r3 item 1).

Writes BENCH_DEVICE.json at the repo root:
  {"date": ..., "device": ..., "cpu_regex_lps": ...,
   "plain": {...}, "tune": [...], "gated": {...},
   "candidate_mask_only_lps": ..., "candidate_fraction": ...,
   "decision": "..."}

Method: pipelined rate (N dispatches in flight, one block) — the
tunnel's ~74 ms synchronous round trip would otherwise dominate (see
bench.py docstring). Each config reports the best of `repeats` runs.

Usage:  python tools/bench_device_ab.py          # full sweep
        KLOGS_AB_QUICK=1 python tools/...        # small batch smoke
"""

import json
import os
import sys
import time
from datetime import date

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from klogs_tpu.utils.env import read as env_read  # noqa: E402

import bench  # noqa: E402  (repo-root bench.py: PATTERNS, make_lines, cpu_lps)


def pipelined_lps(run, n_lines: int, repeats: int = 3, n_flight: int = 8) -> float:
    import numpy as np

    np.asarray(run())  # compile + warm
    best = 0.0
    for _ in range(repeats):
        t0 = time.perf_counter()
        outs = [run() for _ in range(n_flight)]
        outs[-1].block_until_ready()
        np.asarray(outs[-1])
        best = max(best, n_flight * n_lines / (time.perf_counter() - t0))
    return best


def main() -> None:
    quick = env_read("KLOGS_AB_QUICK") == "1"
    B = 4096 if quick else int(env_read("KLOGS_BENCH_DEVICE_BATCH", "32768"))
    repeats = 2 if quick else 3

    import jax
    import numpy as np

    dev = jax.devices()[0]
    print(f"attached: {dev.device_kind} ({jax.default_backend()})", flush=True)

    from klogs_tpu.filters.compiler.prefilter import compile_prefilter
    from klogs_tpu.filters.tpu import pack_lines
    from klogs_tpu.ops import nfa
    from klogs_tpu.ops.pallas_nfa import match_batch_grouped_pallas
    from klogs_tpu.ops.prefilter import candidate_mask, device_tables

    lines = bench.make_lines(B)
    bodies = [ln.rstrip(b"\n") for ln in lines]
    batch, lengths = pack_lines(bodies, 128)
    db, dl = jax.device_put(batch), jax.device_put(lengths)
    n = batch.shape[0]

    cpu = bench.cpu_lps(lines[: min(len(lines), 30000)], repeats)
    print(f"cpu_regex_lps: {cpu:,.0f}", flush=True)

    dp, live, acc = nfa.compile_grouped(bench.PATTERNS)
    pf = compile_prefilter(bench.PATTERNS)
    tables = device_tables(pf) if pf.usable else None

    out = {
        "date": date.today().isoformat(),
        "device": dev.device_kind,
        "batch": n,
        "line_width_bytes": 128,
        "n_patterns": len(bench.PATTERNS),
        "cpu_regex_lps": round(cpu, 1),
        "method": "pipelined, 8 in flight, best of %d" % repeats,
    }

    # --- 1. plain kernel, default config -------------------------------
    run_plain = lambda: match_batch_grouped_pallas(dp, live, acc, db, dl)
    plain_lps = pipelined_lps(run_plain, n, repeats)
    out["plain"] = {"tile_b": 4096, "interleave": 1,
                    "lps": round(plain_lps, 1),
                    "vs_cpu": round(plain_lps / cpu, 3)}
    print(f"plain default: {plain_lps:,.0f} lines/s "
          f"({plain_lps / cpu:.2f}x cpu)", flush=True)

    # --- 2. tune sweep (plain kernel) ----------------------------------
    sweep = []
    for tile in (1024, 2048, 4096, 8192):
        tile = min(tile, n)
        for il in (1, 2):
            if (tile % il) or any(r["tile_b"] == tile and r["interleave"] == il
                                  for r in sweep):
                continue
            try:
                lps = pipelined_lps(
                    lambda: match_batch_grouped_pallas(
                        dp, live, acc, db, dl, tile_b=tile, interleave=il),
                    n, repeats)
            except Exception as e:
                print(f"tile={tile} il={il} FAILED: {str(e)[:100]}", flush=True)
                continue
            sweep.append({"tile_b": tile, "interleave": il, "lps": round(lps, 1)})
            print(f"tile={tile} il={il}: {lps:,.0f} lines/s", flush=True)
    out["tune"] = sweep
    best = max(sweep, key=lambda r: r["lps"]) if sweep else out["plain"]
    out["best_plain"] = {**best, "vs_cpu": round(best["lps"] / cpu, 3)}

    # --- 3. candidate mask alone ---------------------------------------
    if tables is not None:
        cand = np.asarray(candidate_mask(tables, db, dl))
        frac = float(cand.mean())
        out["candidate_fraction"] = round(frac, 4)
        mask_lps = pipelined_lps(lambda: candidate_mask(tables, db, dl),
                                 n, repeats)
        out["candidate_mask_only_lps"] = round(mask_lps, 1)
        print(f"candidate mask alone: {mask_lps:,.0f} lines/s, "
              f"fraction {frac:.4f}", flush=True)

        # --- 4. gated kernel: default and best-plain config ------------
        def run_gated(tile, il):
            return pipelined_lps(
                lambda: match_batch_grouped_pallas(
                    dp, live, acc, db, dl, tile_b=tile, interleave=il,
                    prefilter_tables=tables),
                n, repeats)

        try:
            g_def = run_gated(4096, 1)
            out["gated"] = {"tile_b": 4096, "interleave": 1,
                            "lps": round(g_def, 1),
                            "vs_cpu": round(g_def / cpu, 3)}
            print(f"gated default: {g_def:,.0f} lines/s "
                  f"({g_def / cpu:.2f}x cpu)", flush=True)
        except Exception as e:
            out["gated"] = {"error": str(e)[:200]}
            print(f"gated default FAILED: {str(e)[:120]}", flush=True)
        if (best["tile_b"], best["interleave"]) != (4096, 1) and \
                "error" not in out.get("gated", {}):
            try:
                g_best = run_gated(best["tile_b"], best["interleave"])
                out["gated_best_tile"] = {
                    "tile_b": best["tile_b"], "interleave": best["interleave"],
                    "lps": round(g_best, 1), "vs_cpu": round(g_best / cpu, 3)}
                print(f"gated best-tile: {g_best:,.0f} lines/s", flush=True)
            except Exception as e:
                print(f"gated best-tile FAILED: {str(e)[:120]}", flush=True)

        # --- 5. smaller gated tile: skip granularity is the tile size,
        # so a smaller tile may win when candidates are sparse ----------
        for tile in (512, 1024):
            if tile >= n:
                continue
            try:
                g = run_gated(tile, 1)
                out[f"gated_tile{tile}"] = {"tile_b": tile, "interleave": 1,
                                            "lps": round(g, 1)}
                print(f"gated tile={tile}: {g:,.0f} lines/s", flush=True)
            except Exception as e:
                print(f"gated tile={tile} FAILED: {str(e)[:120]}", flush=True)
    else:
        out["candidate_fraction"] = None
        print("prefilter not usable for this pattern set", flush=True)

    # --- decision -------------------------------------------------------
    gated_all = [v["lps"] for k, v in out.items()
                 if k.startswith("gated") and isinstance(v, dict) and "lps" in v]
    best_gated = max(gated_all) if gated_all else 0.0
    if best_gated > out["best_plain"]["lps"] * 1.05:
        out["decision"] = ("prefilter ON: best gated %.0f > best plain %.0f "
                           "(+5%% margin)" % (best_gated, out["best_plain"]["lps"]))
    else:
        out["decision"] = ("prefilter OFF by default: best gated %.0f vs best "
                           "plain %.0f — gating overhead (LUT gathers + argsort "
                           "+ reorder) not paid back at this candidate fraction"
                           % (best_gated, out["best_plain"]["lps"]))
    print("DECISION:", out["decision"], flush=True)

    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "BENCH_DEVICE.json")
    with open(path, "w") as f:
        json.dump(out, f, indent=1)
    print(f"wrote {path}", flush=True)


if __name__ == "__main__":
    main()
