"""Kernel autotuning: pick (tile_b, interleave) by measurement.

The grouped kernel's best configuration depends on hardware details the
code cannot see (VMEM per core, MXU/VPU overlap behavior, dispatch
latency of the attach), so it is measured, not guessed: a short
pipelined sweep on the live device, cached per (automaton shape, batch
geometry, device kind) in ``~/.cache/klogs_tpu/tune.json``.

Hooked in two places:
- NFAEngineFilter reads KLOGS_TPU_TILE / KLOGS_TPU_INTERLEAVE /
  KLOGS_TPU_MASK_BLOCK / KLOGS_TPU_FUSED_GROUPS env overrides, else
  measured defaults. (The on-disk cache written here is consumed by
  operators/bench runs that call tune_grouped or load_cached
  explicitly; the hot path stays env-driven so a stale cache can never
  silently change production behavior.)
- bench.py / operators run ``tune_grouped`` explicitly (KLOGS_BENCH_TUNE=1).
"""

import json
import os
import time

CANDIDATE_TILES = (1024, 2048, 4096, 8192)
CANDIDATE_INTERLEAVE = (1, 2)
# Chain restructurings swept alongside (tile, interleave): mask_block=K
# precomputes K step masks off the serial chain; fused runs all groups
# in one grid cell with a shared one-hot. Both parity-tested; whether
# either wins is hardware-empirical (pallas_nfa.py docstrings).
CANDIDATE_VARIANTS = (
    {},  # plain
    {"mask_block": 4},
    {"mask_block": 8},
    {"fused": True},
)


def _cache_path() -> str:
    base = os.environ.get("XDG_CACHE_HOME",
                          os.path.join(os.path.expanduser("~"), ".cache"))
    return os.path.join(base, "klogs_tpu", "tune.json")


def _key(dp, batch_shape, device_kind: str) -> str:
    G = dp.follow.shape[0]
    return f"{device_kind}|G{G}|S{dp.n_states}|C{dp.n_classes}|B{batch_shape[0]}x{batch_shape[1]}"


def load_cached(dp, batch_shape, device_kind: str) -> dict | None:
    try:
        with open(_cache_path()) as f:
            return json.load(f).get(_key(dp, batch_shape, device_kind))
    except (OSError, ValueError):
        return None


def _store(dp, batch_shape, device_kind: str, cfg: dict) -> None:
    path = _cache_path()
    os.makedirs(os.path.dirname(path), exist_ok=True)
    try:
        with open(path) as f:
            all_cfg = json.load(f)
    except (OSError, ValueError):
        all_cfg = {}
    all_cfg[_key(dp, batch_shape, device_kind)] = cfg
    with open(path, "w") as f:
        json.dump(all_cfg, f, indent=1)


def tune_grouped(dp, live: int, acc: int, batch, lengths,
                 repeats: int = 3, n_flight: int = 6,
                 runner=None, quiet: bool = False, cls=None,
                 registry=None) -> dict:
    """Sweep the candidate grid on the live device; returns the winning
    {"tile_b", "interleave", "lines_per_s"} and caches it.

    ``runner(tile_b, interleave) -> lines_per_s`` is injectable for
    tests; the default measures the grouped kernel pipelined
    (N dispatches in flight, one sync — per-call blocking would measure
    the attach round trip, not the kernel). When ``cls`` (host-classified
    [B, T] i8 ids) is given, the hot-path entry match_cls_grouped_pallas
    is swept instead of the byte-consuming one.
    """
    import jax

    from klogs_tpu.ops.pallas_nfa import (
        match_batch_grouped_pallas,
        match_cls_grouped_pallas,
    )

    B = batch.shape[0] if cls is None else cls.shape[0]

    def default_runner(tile_b: int, interleave: int, **variant) -> float:
        # Non-divisor tiles are fine: the kernel wrapper pads the batch
        # up to a tile multiple internally.
        if cls is not None:
            run = lambda: match_cls_grouped_pallas(
                dp, live, acc, cls,
                tile_b=tile_b, interleave=interleave, **variant,
            )
        else:
            run = lambda: match_batch_grouped_pallas(
                dp, live, acc, batch, lengths,
                tile_b=tile_b, interleave=interleave, **variant,
            )
        run().block_until_ready()  # compile
        best = 0.0
        for _ in range(repeats):
            t0 = time.perf_counter()
            outs = [run() for _ in range(n_flight)]
            outs[-1].block_until_ready()
            best = max(best, n_flight * B / (time.perf_counter() - t0))
        return best

    runner = runner or default_runner
    # Injected test runners may predate the variant kwargs; detect by
    # signature instead of catching TypeError (which JAX also raises
    # for real kernel bugs — swallowing those would silently "measure"
    # only the plain config).
    import inspect

    params = inspect.signature(runner).parameters.values()
    runner_takes_variants = any(
        p.kind == inspect.Parameter.VAR_KEYWORD for p in params)
    results = []
    seen = set()
    for tile in (min(t, B) for t in CANDIDATE_TILES):
        for il in CANDIDATE_INTERLEAVE:
            if tile % il or tile // il < 8:
                continue
            for variant in CANDIDATE_VARIANTS:
                if variant and il != 1:
                    continue  # restructurings are interleave-exclusive
                if variant and not runner_takes_variants:
                    continue
                key = (tile, il, tuple(sorted(variant.items())))
                if key in seen:
                    continue
                seen.add(key)
                desc = " ".join(f"{k}={v}" for k, v in variant.items())
                try:
                    lps = runner(tile, il, **variant)
                except Exception as e:  # VMEM overflow / compile failure
                    if not quiet:
                        print(f"tune: tile={tile} interleave={il} {desc} "
                              f"failed: {str(e)[:80]}")
                    continue
                if lps > 0:
                    results.append({"tile_b": tile, "interleave": il,
                                    **variant,
                                    "lines_per_s": round(lps, 1)})
                    if not quiet:
                        print(f"tune: tile={tile} interleave={il} {desc}"
                              f" -> {lps:,.0f} lines/s")
    if not results:
        raise RuntimeError("kernel tuning failed for every candidate config")
    best = max(results, key=lambda r: r["lines_per_s"])
    # Sweep telemetry: into the caller's registry when one is threaded
    # through (a process serving a sidecar should scrape its own tune
    # events), else the process-global default for standalone
    # bench/operator runs.
    if registry is None:
        from klogs_tpu.obs import REGISTRY as registry

    registry.family("klogs_engine_tune_runs_total").inc()
    registry.family("klogs_engine_tune_best_lines_per_second").set(
        best["lines_per_s"])
    try:
        import jax

        device_kind = jax.devices()[0].device_kind
    except Exception:
        device_kind = "unknown"
    _store(dp, batch.shape if cls is None else cls.shape, device_kind, best)
    return best


def env_overrides() -> dict:
    """KLOGS_TPU_TILE / KLOGS_TPU_INTERLEAVE / KLOGS_TPU_FUSED_GROUPS /
    KLOGS_TPU_MASK_BLOCK, when set. Callers pass the result straight
    into match_cls_grouped_pallas / match_batch_grouped_pallas kwargs."""
    from klogs_tpu.utils.env import read as env_read

    out = {}
    if env_read("KLOGS_TPU_TILE"):
        out["tile_b"] = int(env_read("KLOGS_TPU_TILE"))
    if env_read("KLOGS_TPU_INTERLEAVE"):
        out["interleave"] = int(env_read("KLOGS_TPU_INTERLEAVE"))
    if env_read("KLOGS_TPU_FUSED_GROUPS") == "1":
        out["fused"] = True
    if env_read("KLOGS_TPU_MASK_BLOCK"):
        out["mask_block"] = int(env_read("KLOGS_TPU_MASK_BLOCK"))
    return out


# Measured hardware default (kernel-variant A/B 2026-07-31,
# OPERATING_POINT.json "fused_ab"): mask_block=4 pulls each block's four
# step masks (one-hot + char-mask matmul, state-independent work) off
# the serial chain, measuring 9.64M lines/s vs 8.42M for the plain chain
# at the 1M x 64-in-flight operating point on v5e (+13%; fused-groups
# ties plain, mask_block=8/16 fail Mosaic compile on real hardware).
HW_DEFAULT_MASK_BLOCK = 4


def chain_selection(on_hardware: bool,
                    allow_fused: bool = True) -> tuple[dict, bool, bool]:
    """THE chain-variant policy — every consumer (single-chip engine,
    mesh per-shard, bench) derives its kernel kwargs here so the rules
    live in one place. Returns ``(kw, chain_defaulted, dropped_fused)``:

    - ``kw``: env_overrides() plus the measured hardware default — on a
      real TPU backend, when the env picks no conflicting chain variant,
      mask_block=HW_DEFAULT_MASK_BLOCK. KLOGS_TPU_MASK_BLOCK=1 forces
      the plain chain; KLOGS_TPU_INTERLEAVE=1 restates the interleave
      default and does NOT suppress the mask_block default (only
      interleave>1 actually conflicts — pallas rejects the combo
      loudly). Interpret/CPU paths keep the plain chain (no hardware
      pipeline to win on, and hermetic tests should exercise the same
      default they can verify).
    - ``chain_defaulted``: the mask_block came from the DEFAULT, not the
      env — eligible for degrade-to-plain on compile/exec failure. An
      env-forced variant is never defaulted: the operator asked to
      measure exactly that kernel, so failures stay loud.
    - ``dropped_fused``: allow_fused=False (mesh per-shard compute,
      where one body backs both the plain and gated builds and fused
      has no gated sibling) removed an env-requested fused=True; the
      caller must WARN (silently measuring a different kernel corrupts
      pick-by-measurement). With fused dropped the chain is unpicked
      again, so the default re-applies."""
    env = env_overrides()
    kw = dict(env)
    dropped_fused = bool(not allow_fused and kw.pop("fused", False))
    picked_variant = ("mask_block" in kw or kw.get("fused")
                      or kw.get("interleave", 1) != 1)
    if on_hardware and not picked_variant:
        kw["mask_block"] = HW_DEFAULT_MASK_BLOCK
    chain_defaulted = (kw.get("mask_block", 1) > 1
                       and "mask_block" not in env)
    return kw, chain_defaulted, dropped_fused


def kernel_kwargs(on_hardware: bool) -> dict:
    """chain_selection()'s kwargs alone, for callers that manage their
    own variant sweep (bench tools)."""
    return chain_selection(on_hardware)[0]
