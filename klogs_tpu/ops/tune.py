"""Kernel autotuning: pick (tile_b, interleave) by measurement.

The grouped kernel's best configuration depends on hardware details the
code cannot see (VMEM per core, MXU/VPU overlap behavior, dispatch
latency of the attach), so it is measured, not guessed: a short
pipelined sweep on the live device, cached per (automaton shape, batch
geometry, device kind) in ``~/.cache/klogs_tpu/tune.json``.

Hooked in two places:
- NFAEngineFilter reads KLOGS_TPU_TILE / KLOGS_TPU_INTERLEAVE /
  KLOGS_TPU_MASK_BLOCK / KLOGS_TPU_FUSED_GROUPS env overrides, else
  measured defaults. (The on-disk cache written here is consumed by
  operators/bench runs that call tune_grouped or load_cached
  explicitly; the hot path stays env-driven so a stale cache can never
  silently change production behavior.)
- bench.py / operators run ``tune_grouped`` explicitly (KLOGS_BENCH_TUNE=1).
"""

import asyncio
import json
import os
import time
from typing import Any, Callable

CANDIDATE_TILES = (1024, 2048, 4096, 8192)
CANDIDATE_INTERLEAVE = (1, 2)
# Chain restructurings swept alongside (tile, interleave): mask_block=K
# precomputes K step masks off the serial chain; fused runs all groups
# in one grid cell with a shared one-hot. Both parity-tested; whether
# either wins is hardware-empirical (pallas_nfa.py docstrings).
CANDIDATE_VARIANTS = (
    {},  # plain
    {"mask_block": 4},
    {"mask_block": 8},
    {"fused": True},
)


def _cache_path() -> str:
    base = os.environ.get("XDG_CACHE_HOME",
                          os.path.join(os.path.expanduser("~"), ".cache"))
    return os.path.join(base, "klogs_tpu", "tune.json")


def _key(dp: Any, batch_shape: Any, device_kind: str) -> str:
    G = dp.follow.shape[0]
    return f"{device_kind}|G{G}|S{dp.n_states}|C{dp.n_classes}|B{batch_shape[0]}x{batch_shape[1]}"


def load_cached(dp: Any, batch_shape: Any,
                device_kind: str) -> "dict | None":
    try:
        with open(_cache_path()) as f:
            return json.load(f).get(_key(dp, batch_shape, device_kind))
    except (OSError, ValueError):
        return None


def _store(dp: Any, batch_shape: Any, device_kind: str,
           cfg: dict) -> None:
    path = _cache_path()
    os.makedirs(os.path.dirname(path), exist_ok=True)
    try:
        with open(path) as f:
            all_cfg = json.load(f)
    except (OSError, ValueError):
        all_cfg = {}
    all_cfg[_key(dp, batch_shape, device_kind)] = cfg
    with open(path, "w") as f:
        json.dump(all_cfg, f, indent=1)


def tune_grouped(dp: Any, live: int, acc: int, batch: Any, lengths: Any,
                 repeats: int = 3, n_flight: int = 6,
                 runner: "Callable[..., float] | None" = None,
                 quiet: bool = False, cls: Any = None,
                 registry: Any = None) -> dict:
    """Sweep the candidate grid on the live device; returns the winning
    {"tile_b", "interleave", "lines_per_s"} and caches it.

    ``runner(tile_b, interleave) -> lines_per_s`` is injectable for
    tests; the default measures the grouped kernel pipelined
    (N dispatches in flight, one sync — per-call blocking would measure
    the attach round trip, not the kernel). When ``cls`` (host-classified
    [B, T] i8 ids) is given, the hot-path entry match_cls_grouped_pallas
    is swept instead of the byte-consuming one.
    """
    import jax

    from klogs_tpu.ops.pallas_nfa import (
        match_batch_grouped_pallas,
        match_cls_grouped_pallas,
    )

    B = batch.shape[0] if cls is None else cls.shape[0]

    def default_runner(tile_b: int, interleave: int,
                       **variant: Any) -> float:
        # Non-divisor tiles are fine: the kernel wrapper pads the batch
        # up to a tile multiple internally.
        if cls is not None:
            run = lambda: match_cls_grouped_pallas(
                dp, live, acc, cls,
                tile_b=tile_b, interleave=interleave, **variant,
            )
        else:
            run = lambda: match_batch_grouped_pallas(
                dp, live, acc, batch, lengths,
                tile_b=tile_b, interleave=interleave, **variant,
            )
        run().block_until_ready()  # compile
        best = 0.0
        for _ in range(repeats):
            t0 = time.perf_counter()
            outs = [run() for _ in range(n_flight)]
            outs[-1].block_until_ready()
            best = max(best, n_flight * B / (time.perf_counter() - t0))
        return best

    runner = runner or default_runner
    # Injected test runners may predate the variant kwargs; detect by
    # signature instead of catching TypeError (which JAX also raises
    # for real kernel bugs — swallowing those would silently "measure"
    # only the plain config).
    import inspect

    params = inspect.signature(runner).parameters.values()
    runner_takes_variants = any(
        p.kind == inspect.Parameter.VAR_KEYWORD for p in params)
    results = []
    seen = set()
    for tile in (min(t, B) for t in CANDIDATE_TILES):
        for il in CANDIDATE_INTERLEAVE:
            if tile % il or tile // il < 8:
                continue
            for variant in CANDIDATE_VARIANTS:
                if variant and il != 1:
                    continue  # restructurings are interleave-exclusive
                if variant and not runner_takes_variants:
                    continue
                key = (tile, il, tuple(sorted(variant.items())))
                if key in seen:
                    continue
                seen.add(key)
                desc = " ".join(f"{k}={v}" for k, v in variant.items())
                try:
                    lps = runner(tile, il, **variant)
                except Exception as e:  # VMEM overflow / compile failure
                    if not quiet:
                        print(f"tune: tile={tile} interleave={il} {desc} "
                              f"failed: {str(e)[:80]}")
                    continue
                if lps > 0:
                    results.append({"tile_b": tile, "interleave": il,
                                    **variant,
                                    "lines_per_s": round(lps, 1)})
                    if not quiet:
                        print(f"tune: tile={tile} interleave={il} {desc}"
                              f" -> {lps:,.0f} lines/s")
    if not results:
        raise RuntimeError("kernel tuning failed for every candidate config")
    best = max(results, key=lambda r: r["lines_per_s"])
    # Sweep telemetry: into the caller's registry when one is threaded
    # through (a process serving a sidecar should scrape its own tune
    # events), else the process-global default for standalone
    # bench/operator runs.
    if registry is None:
        from klogs_tpu.obs import REGISTRY as registry

    registry.family("klogs_engine_tune_runs_total").inc()
    registry.family("klogs_engine_tune_best_lines_per_second").set(
        best["lines_per_s"])
    try:
        import jax

        device_kind = jax.devices()[0].device_kind
    except Exception:
        device_kind = "unknown"
    _store(dp, batch.shape if cls is None else cls.shape, device_kind, best)
    return best


def env_overrides() -> dict:
    """KLOGS_TPU_TILE / KLOGS_TPU_INTERLEAVE / KLOGS_TPU_FUSED_GROUPS /
    KLOGS_TPU_MASK_BLOCK, when set. Callers pass the result straight
    into match_cls_grouped_pallas / match_batch_grouped_pallas kwargs."""
    from klogs_tpu.utils.env import read as env_read

    out = {}
    if env_read("KLOGS_TPU_TILE"):
        out["tile_b"] = int(env_read("KLOGS_TPU_TILE"))
    if env_read("KLOGS_TPU_INTERLEAVE"):
        out["interleave"] = int(env_read("KLOGS_TPU_INTERLEAVE"))
    if env_read("KLOGS_TPU_FUSED_GROUPS") == "1":
        out["fused"] = True
    if env_read("KLOGS_TPU_MASK_BLOCK"):
        out["mask_block"] = int(env_read("KLOGS_TPU_MASK_BLOCK"))
    return out


# Measured hardware default (kernel-variant A/B 2026-07-31,
# OPERATING_POINT.json "fused_ab"): mask_block=4 pulls each block's four
# step masks (one-hot + char-mask matmul, state-independent work) off
# the serial chain, measuring 9.64M lines/s vs 8.42M for the plain chain
# at the 1M x 64-in-flight operating point on v5e (+13%; fused-groups
# ties plain, mask_block=8/16 fail Mosaic compile on real hardware).
HW_DEFAULT_MASK_BLOCK = 4


def chain_selection(on_hardware: bool,
                    allow_fused: bool = True) -> tuple[dict, bool, bool]:
    """THE chain-variant policy — every consumer (single-chip engine,
    mesh per-shard, bench) derives its kernel kwargs here so the rules
    live in one place. Returns ``(kw, chain_defaulted, dropped_fused)``:

    - ``kw``: env_overrides() plus the measured hardware default — on a
      real TPU backend, when the env picks no conflicting chain variant,
      mask_block=HW_DEFAULT_MASK_BLOCK. KLOGS_TPU_MASK_BLOCK=1 forces
      the plain chain; KLOGS_TPU_INTERLEAVE=1 restates the interleave
      default and does NOT suppress the mask_block default (only
      interleave>1 actually conflicts — pallas rejects the combo
      loudly). Interpret/CPU paths keep the plain chain (no hardware
      pipeline to win on, and hermetic tests should exercise the same
      default they can verify).
    - ``chain_defaulted``: the mask_block came from the DEFAULT, not the
      env — eligible for degrade-to-plain on compile/exec failure. An
      env-forced variant is never defaulted: the operator asked to
      measure exactly that kernel, so failures stay loud.
    - ``dropped_fused``: allow_fused=False (mesh per-shard compute,
      where one body backs both the plain and gated builds and fused
      has no gated sibling) removed an env-requested fused=True; the
      caller must WARN (silently measuring a different kernel corrupts
      pick-by-measurement). With fused dropped the chain is unpicked
      again, so the default re-applies."""
    env = env_overrides()
    kw = dict(env)
    dropped_fused = bool(not allow_fused and kw.pop("fused", False))
    picked_variant = ("mask_block" in kw or kw.get("fused")
                      or kw.get("interleave", 1) != 1)
    if on_hardware and not picked_variant:
        kw["mask_block"] = HW_DEFAULT_MASK_BLOCK
    chain_defaulted = (kw.get("mask_block", 1) > 1
                       and "mask_block" not in env)
    return kw, chain_defaulted, dropped_fused


def kernel_kwargs(on_hardware: bool) -> dict:
    """chain_selection()'s kwargs alone, for callers that manage their
    own variant sweep (bench tools)."""
    return chain_selection(on_hardware)[0]


# -- adaptive operating point (collector-side controller) --------------
#
# The kernel autotuner above picks a KERNEL config offline; the
# controller below adjusts the PIPELINE's operating point online —
# coalescer group sizing and device in-flight depth — from the live
# /profile signals (queue depth, in-flight occupancy, bottleneck).
# It is deliberately conservative: bounded multiplicative steps,
# consecutive-tick hysteresis with a cooldown after every move, and
# hard floor/ceiling anchored to the committed OPERATING_POINT.json
# surface. KLOGS_TUNE=off (the default) means the controller is never
# constructed — fixed-flag behavior, byte-identical.

DEFAULT_TUNE_INTERVAL_S = 5.0
DEFAULT_TUNE_STEP = 0.5  # fractional step: up = x(1+step), down = /(1+step)
_TUNE_UP_AFTER = 2    # consecutive pressure ticks before stepping up
_TUNE_DOWN_AFTER = 4  # consecutive idle ticks before stepping down
                      # (down > up: shedding capacity needs more proof)
_TUNE_COOLDOWN = 2    # quiet ticks after ANY step — the pipeline must
                      # show the new point's behavior before we judge it


def tune_mode() -> str:
    """KLOGS_TUNE: ``off`` (default; fixed flags, no controller built)
    or ``auto``. Anything else fails loudly — a typoed mode silently
    running fixed flags would be the worst kind of knob."""
    from klogs_tpu.utils.env import read as env_read

    raw = env_read("KLOGS_TUNE")
    mode = (raw or "off").strip().lower()
    if mode not in ("off", "auto"):
        raise ValueError(
            f"KLOGS_TUNE must be 'off' or 'auto', got {raw!r}")
    return mode


def operating_surface() -> "dict[str, tuple[int, int]]":
    """Measured (min, max) per controller parameter from the committed
    OPERATING_POINT.json batch x n_flight sweep — the hard envelope the
    controller may roam. Empty dict when the file is absent (a deployed
    package): bounds then collapse to the initial flag values, i.e. the
    controller can hold but never move."""
    path = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        os.pardir, "OPERATING_POINT.json")
    try:
        with open(path, encoding="utf-8") as f:
            doc = json.load(f)
    except (OSError, ValueError):
        return {}
    batches: "list[int]" = []
    flights: "list[int]" = []
    try:
        for entry in doc:
            for run in entry.get("runs", []):
                b, nf = run.get("batch"), run.get("n_flight")
                if isinstance(b, int) and not isinstance(b, bool):
                    batches.append(b)
                if isinstance(nf, int) and not isinstance(nf, bool):
                    flights.append(nf)
    except (TypeError, AttributeError):
        return {}
    out: "dict[str, tuple[int, int]]" = {}
    if batches:
        out["coalesce_lines"] = (min(batches), max(batches))
    if flights:
        out["max_in_flight"] = (min(flights), max(flights))
    return out


class AdaptiveController:
    """Close the loop between /profile and the pipeline's knobs.

    Decision policy per tick (one ``step_once`` on the live profile
    doc):

    - *pressure* — in-flight slots saturated with callers queued, or
      the coalescer backlog exceeding a full group: after
      ``_TUNE_UP_AFTER`` consecutive pressure ticks, step the binding
      parameter UP one bounded multiplicative step.
    - *idle* — in-flight occupancy under a quarter of depth with an
      empty coalescer: after ``_TUNE_DOWN_AFTER`` consecutive idle
      ticks, step back DOWN toward the flag values (latency recovery).
    - anything else resets both streaks; every applied step starts a
      ``_TUNE_COOLDOWN``-tick quiet period. Together these are the
      hysteresis: a signal oscillating tick-to-tick moves nothing.

    Bounds per parameter are ``[min(initial, surface_min),
    max(initial, surface_max)]`` from :func:`operating_surface` — the
    controller can roam the measured envelope and can always return to
    the operator's flags, but never invents an unmeasured regime.

    ``service`` duck-types ``coalesce_lines`` / ``max_in_flight``
    read properties and ``apply_tuning(coalesce_lines=, max_in_flight=)``
    (filters/async_service.py). Mutated fields (streaks, cooldown,
    current values) are only touched from ``run``'s single task —
    loop-confined, no lock.
    """

    PARAMS = ("coalesce_lines", "max_in_flight")

    def __init__(self, service: Any, *,
                 registry: Any = None,
                 profile_fn: "Callable[[], dict] | None" = None,
                 interval_s: "float | None" = None,
                 step: "float | None" = None,
                 surface: "dict[str, tuple[int, int]] | None" = None
                 ) -> None:
        from klogs_tpu.utils.env import positive_float

        self._service = service
        if profile_fn is None:
            from klogs_tpu.obs.profiler import PROFILER

            profile_fn = PROFILER.profile_doc
        self._profile_fn = profile_fn
        self._interval_s = (interval_s if interval_s is not None
                            else positive_float("KLOGS_TUNE_INTERVAL_S",
                                                DEFAULT_TUNE_INTERVAL_S))
        self._step = (step if step is not None
                      else positive_float("KLOGS_TUNE_STEP",
                                          DEFAULT_TUNE_STEP))
        self.values: "dict[str, int]" = {
            "coalesce_lines": int(service.coalesce_lines),
            "max_in_flight": int(service.max_in_flight),
        }
        surf = operating_surface() if surface is None else surface
        self.bounds: "dict[str, tuple[int, int]]" = {}
        for param, initial in self.values.items():
            lo, hi = surf.get(param, (initial, initial))
            self.bounds[param] = (min(initial, lo), max(initial, hi))
        self._press = 0
        self._idle = 0
        self._cooldown = 0
        self.steps_applied = 0  # for tests / soak assertions
        self._m_steps: Any = None
        self._m_value: Any = None
        if registry is not None:
            self._m_steps = registry.family("klogs_tune_steps_total")
            self._m_value = registry.family("klogs_tune_value")
            for param, value in self.values.items():
                self._m_value.labels(param=param).set(value)

    async def _apply(self, param: str, new: int,
                     direction: str) -> None:
        self.values[param] = new
        self._service.apply_tuning(**{param: new})
        self.steps_applied += 1
        self._press = 0
        self._idle = 0
        self._cooldown = _TUNE_COOLDOWN
        if self._m_steps is not None:
            self._m_steps.labels(param=param, direction=direction).inc()
        if self._m_value is not None:
            self._m_value.labels(param=param).set(new)
        from klogs_tpu.ui import term

        term.info("tune: %s %s -> %d (operating-point controller)",
                  param, direction, new)

    async def _step_up(self, param: str) -> bool:
        cur = self.values[param]
        hi = self.bounds[param][1]
        if cur >= hi:
            return False
        new = min(hi, max(cur + 1, int(cur * (1.0 + self._step))))
        await self._apply(param, new, "up")
        return True

    async def _step_down(self, param: str) -> bool:
        cur = self.values[param]
        lo = self.bounds[param][0]
        if cur <= lo:
            return False
        new = max(lo, min(cur - 1, int(cur / (1.0 + self._step))))
        await self._apply(param, new, "down")
        return True

    async def step_once(self, doc: dict
                        ) -> "tuple[str, str] | None":
        """One control decision from one profile snapshot. Returns the
        (param, direction) applied, or None (held). A pure state
        machine over the doc — directly testable without a pipeline —
        kept async so every mutation stays event-loop-confined (the
        lock-discipline contract for controller state)."""
        if not doc.get("enabled"):
            return None  # no signals, no opinion — hold the point
        samples = doc.get("samples") or {}

        def sample(name: str) -> float:
            v = samples.get(name)
            return float(v) if isinstance(v, (int, float)) else 0.0

        depth = sample("coalescer.queue_depth")
        pending = sample("coalescer.pending_lines")
        used = sample("device.in_flight_used")
        if self._cooldown > 0:
            self._cooldown -= 1
            return None
        flight = self.values["max_in_flight"]
        group = self.values["coalesce_lines"]
        # Pressure: the dispatch pipe is the wall (all slots busy AND
        # callers queued behind it), or groups overflow before the
        # kick (a full group's worth pending — bigger groups amortize
        # the per-dispatch fixed cost, the OPERATING_POINT.json fit).
        press_flight = used >= flight - 0.5 and depth > 0
        press_group = pending >= group
        idle = (used <= max(1.0, 0.25 * flight)
                and depth <= 0 and pending < 0.25 * group)
        if press_flight or press_group:
            self._press += 1
            self._idle = 0
        elif idle:
            self._idle += 1
            self._press = 0
        else:
            self._press = 0
            self._idle = 0
        if self._press >= _TUNE_UP_AFTER:
            if press_flight and await self._step_up("max_in_flight"):
                return ("max_in_flight", "up")
            if press_group and await self._step_up("coalesce_lines"):
                return ("coalesce_lines", "up")
            self._press = 0  # pinned at the ceiling: stop counting
            return None
        if self._idle >= _TUNE_DOWN_AFTER:
            # Unwind depth first (memory + queueing latency), group
            # size second (per-batch latency).
            if await self._step_down("max_in_flight"):
                return ("max_in_flight", "down")
            if await self._step_down("coalesce_lines"):
                return ("coalesce_lines", "down")
            self._idle = 0  # already at the floor
            return None
        return None

    async def run(self, stop: "asyncio.Event") -> None:
        """Tick loop (stop-aware poller idiom). The ``tune.step`` fault
        point wraps each decision: an armed fault skips that tick and
        MUST NOT kill the loop — a chaos script proves the pipeline
        keeps flowing at the held operating point."""
        from klogs_tpu.resilience import FAULTS
        from klogs_tpu.ui import term

        while not stop.is_set():
            try:
                await asyncio.wait_for(stop.wait(),
                                       timeout=self._interval_s)
            except asyncio.TimeoutError:
                pass
            if stop.is_set():
                return
            try:
                if FAULTS.active:
                    await FAULTS.fire("tune.step")
                await self.step_once(self._profile_fn())
            except asyncio.CancelledError:
                raise
            except Exception as e:  # noqa: BLE001
                # InjectedFault or a profile/apply surprise: hold the
                # current point, keep the loop alive.
                term.warning("tune: skipped a control tick (%s)", e)


def maybe_controller(service: Any, registry: Any = None
                     ) -> "AdaptiveController | None":
    """The app-side gate: None when KLOGS_TUNE=off (default — nothing
    is constructed, fixed-flag behavior byte-identical), None when the
    pipeline's filter service has no tuning surface (CPU batch path,
    remote tier), else a ready-to-run controller. Bad KLOGS_TUNE*
    values raise ValueError for the caller's friendly-fatal path."""
    if tune_mode() == "off":
        return None
    if getattr(service, "apply_tuning", None) is None:
        return None
    return AdaptiveController(service, registry=registry)
