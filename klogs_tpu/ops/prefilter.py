"""Device-side candidate masks for the two-phase filter.

Evaluates the compiled pair-CNF (filters/compiler/prefilter.py) on a
batch. Two granularities:

- ``candidate_matrix`` / ``candidate_matrix_from_cls`` — the [B, P]
  PER-PATTERN form (thousand-pattern mode): cell (b, p) False proves
  pattern p cannot match line b. ``group_candidates`` reduces it to
  per-(line, kernel-group) flags via the grouped program's
  pattern_group map, so the gated kernel skips (tile, group) cells,
  not just whole tiles.
- ``candidate_mask`` / ``candidate_mask_from_cls`` — the [B] any-
  pattern reduction that drives plain tile skipping (candidates are
  clustered to the front by a stable partition and dead tiles never
  run the scan loop).

Two formulations:

- ``candidate_mask`` — byte-domain: per adjacent byte pair, two
  256-entry LUT gathers and a bitwise AND, OR-reduced over positions.
  Simple, but TPU gathers serialize: the 2026-07-29 device A/B
  (BENCH_DEVICE.json) measured it at ~684k lines/s — nearly the full
  NFA kernel's cost, making gating a net loss.
- ``candidate_mask_from_cls`` — class-domain: the grouped program's
  shared byte classifier partitions bytes so that membership in any
  pattern byte-set (hence in any clause-pair side) is constant within a
  class. Slot hits become two small one-hot **matmuls** per position
  block ([B,TB,C] x [C,S] on the MXU, C ~ tens of classes, S = slot
  count) — no gathers — at ~1/10 the NFA kernel's MAC count. The input
  is the [B, T] class-id array the kernel wrapper already computes, so
  the byte->class gather is not paid twice.
"""

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from klogs_tpu.filters.compiler.prefilter import PrefilterProgram

# Position-block size for the chunked OR-fold: bounds the per-step
# intermediate to [B, PAIR_BLOCK, W] (byte path) / [B, PAIR_BLOCK, C]
# (class path) instead of materializing all L-1 pairs at once.
PAIR_BLOCK = 128


def device_tables(pf: PrefilterProgram):
    """(lut1 [256,W], lut2 [256,W], req [P,W]) as device uint32 arrays —
    a pytree suitable as a jit argument."""
    return (jnp.asarray(pf.lut1), jnp.asarray(pf.lut2), jnp.asarray(pf.req))


@jax.jit
def candidate_matrix(tables, batch: jax.Array,
                     lengths: jax.Array) -> jax.Array:
    """[B, L] u8 + [B] lengths -> [B, P] bool PER-PATTERN candidate
    matrix: True where the line satisfies pattern p's full clause
    requirement (necessary condition for a match of p; a False cell
    proves pattern p cannot match that line, so engines may skip that
    (line, pattern) scan). Device twin of the host oracle
    ``filters.compiler.prefilter.candidate_matrix_host``.

    The OR over pair positions folds in PAIR_BLOCK-sized chunks via
    lax.scan, so peak memory is [B, PAIR_BLOCK, W] regardless of L (a
    4096-byte bucket at B=32k would otherwise materialize a multi-GB
    [B, L-1, W] intermediate if XLA fails to fuse the reduce)."""
    lut1, lut2, req = tables
    B, L = batch.shape
    W = req.shape[1]
    P = req.shape[0]
    if L < 2:
        return jnp.broadcast_to(jnp.all(req == 0, axis=-1)[None, :],
                                (B, P))
    x = batch.astype(jnp.int32)
    a, b = x[:, :-1], x[:, 1:]
    pos = jnp.arange(L - 1, dtype=jnp.int32)
    valid = (pos[None, :] + 1) < lengths[:, None]
    n_pairs = L - 1
    pad = -n_pairs % PAIR_BLOCK
    if pad:
        a = jnp.pad(a, ((0, 0), (0, pad)))
        b = jnp.pad(b, ((0, 0), (0, pad)))
        valid = jnp.pad(valid, ((0, 0), (0, pad)))
    nb = a.shape[1] // PAIR_BLOCK
    # Scan axis leading: [nb, B, PAIR_BLOCK].
    a3 = a.reshape(B, nb, PAIR_BLOCK).swapaxes(0, 1)
    b3 = b.reshape(B, nb, PAIR_BLOCK).swapaxes(0, 1)
    v3 = valid.reshape(B, nb, PAIR_BLOCK).swapaxes(0, 1)

    def step(present, xs):
        ab, bb, vb = xs
        hits = lut1[ab] & lut2[bb]  # [B, PAIR_BLOCK, W]
        hits = jnp.where(vb[:, :, None], hits, jnp.uint32(0))
        blk = jax.lax.reduce(hits, np.uint32(0), jax.lax.bitwise_or, (1,))
        return present | blk, None

    present0 = jnp.zeros((B, W), dtype=jnp.uint32)
    present, _ = jax.lax.scan(step, present0, (a3, b3, v3))
    ok = (present[:, None, :] & req[None]) == req[None]  # [B, P, W]
    return jnp.all(ok, axis=-1)


@jax.jit
def candidate_mask(tables, batch: jax.Array, lengths: jax.Array) -> jax.Array:
    """[B, L] u8 + [B] lengths -> [B] bool: True when the line satisfies
    SOME pattern's full clause requirement (the any-pattern reduction
    of ``candidate_matrix`` — necessary condition for any match; False
    rows can never match and may be skipped)."""
    return candidate_matrix(tables, batch, lengths).any(axis=-1)


# ---------------------------------------------------------------------
# Class-domain formulation (the fast path).
# ---------------------------------------------------------------------


def class_tables(pf: PrefilterProgram, byte_class, n_classes: int,
                 slots_pad: int | None = None,
                 patterns_pad: int | None = None):
    """Re-express the byte LUTs over the grouped program's shared byte
    classes: (member1 [C, S] i8, member2 [C, S] i8, req_t [S, P] i8,
    req_count [P] i32) with S = slot count (W*32, optionally padded) and
    C = n_classes. Sentinel classes (BEGIN/END/PAD and padding) have no
    representative byte and get all-zero member rows, so pairs touching
    them never fire — no explicit validity mask needed downstream.

    Returns None when some byte class is NOT uniform w.r.t. the LUTs
    (cannot happen when both were compiled from the same parse, but the
    byte-LUT fallback stays correct if it ever does) — and when the
    program is not ``usable``: candidate_mask_from_cls treats a
    zero-requirement pattern column as shard padding and masks it out,
    so tables built from a program where a REAL pattern has no clauses
    would wrongly filter that pattern's matches. Production callers all
    check ``usable`` first; this guard makes misuse impossible."""
    if not pf.usable:
        return None
    byte_class = np.asarray(byte_class)
    lut1, lut2 = pf.lut1, pf.lut2
    W = lut1.shape[1]
    S = W * 32
    if slots_pad is not None:
        S = max(S, slots_pad)
    P = pf.req.shape[0]
    Pp = max(P, patterns_pad or 0)
    member1 = np.zeros((n_classes, S), dtype=np.int8)
    member2 = np.zeros((n_classes, S), dtype=np.int8)
    for c in range(n_classes):
        bs = np.nonzero(byte_class == c)[0]
        if len(bs) == 0:
            continue
        r1, r2 = lut1[bs[0]], lut2[bs[0]]
        if (lut1[bs] != r1).any() or (lut2[bs] != r2).any():
            return None  # class not LUT-uniform; caller falls back
        for w in range(W):
            for bit in range(32):
                s = w * 32 + bit
                one = np.uint32(1 << bit)
                member1[c, s] = 1 if (r1[w] & one) else 0
                member2[c, s] = 1 if (r2[w] & one) else 0
    req_t = np.zeros((S, Pp), dtype=np.int8)
    req_count = np.zeros((Pp,), dtype=np.int32)
    for p in range(P):
        for w in range(W):
            for bit in range(32):
                if pf.req[p, w] & np.uint32(1 << bit):
                    req_t[w * 32 + bit, p] = 1
                    req_count[p] += 1
    # Padded pattern columns keep req_count 0 => always "satisfied";
    # guard: a zero-requirement pattern makes gating pointless, which
    # compile_prefilter already reports via `usable` — padded columns
    # are only used for shard-uniform stacking where the real pattern
    # count masks them out via req_count == 0 rows being ignored by the
    # candidate OR only when ALL patterns are padded (never happens).
    return (jnp.asarray(member1), jnp.asarray(member2),
            jnp.asarray(req_t), jnp.asarray(req_count))


@jax.jit
def candidate_matrix_from_cls(tables, cls: jax.Array) -> jax.Array:
    """[B, T] class ids (classify_chunk output, sentinels included) ->
    [B, Pp] bool PER-PATTERN candidate matrix via MXU one-hot matmuls
    per position block (Pp = possibly padded pattern count; padded
    columns — req_count 0 — are always False, callers slice to the
    real pattern count). Device twin of
    ``filters.compiler.prefilter.candidate_matrix_host``.

    Pairs touching BEGIN/END/PAD columns self-suppress (all-zero member
    rows), so the full cls array — exactly what the kernel wrapper
    already computed — is passed as-is."""
    m1t, m2t, req_t, req_count = tables
    B, T = cls.shape
    C, S = m1t.shape
    if T < 2:
        # No adjacent pair can fire; every real pattern (>= 1 clause,
        # guaranteed by the class_tables usable gate) is ruled out.
        return jnp.zeros((B, req_count.shape[0]), dtype=bool)
    c1, c2 = cls[:, :-1], cls[:, 1:]
    n_pairs = T - 1
    pad = -n_pairs % PAIR_BLOCK
    if pad:
        # Pad with class C-1: grouped programs place pad_class last and
        # its member rows are zero; even if not, c2's matching pad rows
        # come from the same padding so only (pad,pad) pairs are added,
        # which fire nothing because sentinel rows are zero.
        c1 = jnp.pad(c1, ((0, 0), (0, pad)), constant_values=C - 1)
        c2 = jnp.pad(c2, ((0, 0), (0, pad)), constant_values=C - 1)
    nb = c1.shape[1] // PAIR_BLOCK
    c13 = c1.reshape(B, nb, PAIR_BLOCK).swapaxes(0, 1)
    c23 = c2.reshape(B, nb, PAIR_BLOCK).swapaxes(0, 1)

    def step(acc, xs):
        cb1, cb2 = xs  # [B, PAIR_BLOCK]
        oh1 = jax.nn.one_hot(cb1, C, dtype=jnp.int8)  # [B, TB, C]
        oh2 = jax.nn.one_hot(cb2, C, dtype=jnp.int8)
        m1 = jnp.einsum("btc,cs->bts", oh1, m1t,
                        preferred_element_type=jnp.int32).astype(jnp.int8)
        m2 = jnp.einsum("btc,cs->bts", oh2, m2t,
                        preferred_element_type=jnp.int32).astype(jnp.int8)
        # hit iff both sides fire at the same position: AND then OR over
        # the block, expressed as a multiply-accumulate contraction.
        blk = jnp.einsum("bts,bts->bs", m1, m2,
                         preferred_element_type=jnp.int32)
        return acc + blk, None

    acc0 = jnp.zeros((B, S), dtype=jnp.int32)
    acc, _ = jax.lax.scan(step, acc0, (c13, c23))
    hits = (acc > 0).astype(jnp.int8)  # [B, S]
    got = jnp.einsum("bs,sp->bp", hits, req_t,
                     preferred_element_type=jnp.int32)
    # Padded pattern columns have req_count 0 and would trivially pass;
    # they are masked out (a real pattern always has >= 1 slot when the
    # prefilter is usable).
    return (got >= req_count[None, :]) & (req_count[None, :] > 0)


@jax.jit
def candidate_mask_from_cls(tables, cls: jax.Array) -> jax.Array:
    """[B, T] class ids -> [B] bool: the any-pattern reduction of
    ``candidate_matrix_from_cls`` (padded columns never contribute)."""
    return candidate_matrix_from_cls(tables, cls).any(axis=1)


def pattern_group_onehot(pattern_group: "tuple[int, ...]",
                         n_groups: int) -> jax.Array:
    """[K, G] i8 one-hot of the grouped program's pattern -> group map
    (DeviceProgram.pattern_group) — the reduction table taking a
    per-pattern candidate matrix to per-(line, group) flags with one
    small matmul."""
    pg = np.asarray(pattern_group, dtype=np.int32)
    return jnp.asarray(
        (pg[:, None] == np.arange(n_groups)[None, :]).astype(np.int8))


@partial(jax.jit, static_argnames=("n_patterns",))
def group_candidates(matrix: jax.Array, onehot: jax.Array,
                     n_patterns: int) -> jax.Array:
    """[B, Pp] per-pattern candidate matrix + [K, G] group one-hot ->
    [B, G] bool: True where the line is a candidate for SOME pattern
    compiled into group g. ``n_patterns`` slices padded columns off
    before the reduction."""
    pm = matrix[:, :n_patterns].astype(jnp.int8)
    return jnp.einsum("bp,pg->bg", pm, onehot,
                      preferred_element_type=jnp.int32) > 0


@partial(jax.jit, static_argnames=("tile_b",))
def cluster_candidates(cand: jax.Array, tile_b: int):
    """Order lines candidates-first (stable) and mark live tiles.

    Returns (order [B] i32, inv [B] i32, tile_live [B//tile_b] i32):
    ``x[order]`` clusters candidates into the leading tiles,
    ``y[inv]`` undoes it, and tile_live[i] != 0 iff tile i holds at
    least one candidate.

    Implemented as a cumsum-based stable two-way partition (destination
    position = rank within own class) plus one scatter — a device
    argsort (radix, ~10 passes) measured as part of the gating overhead
    that sank the two-phase path in BENCH_DEVICE.json."""
    B = cand.shape[0]
    c = cand.astype(jnp.int32)
    n_cand = jnp.sum(c)
    pos = jnp.where(cand,
                    jnp.cumsum(c) - 1,
                    n_cand + jnp.cumsum(1 - c) - 1)  # [B] destination slot
    order = jnp.zeros((B,), dtype=jnp.int32).at[pos].set(
        jnp.arange(B, dtype=jnp.int32))
    n_tiles = B // tile_b
    tile_live = (
        (jnp.arange(n_tiles, dtype=jnp.int32) * tile_b) < n_cand
    ).astype(jnp.int32)
    return order, pos, tile_live
