"""Device-side candidate mask for the two-phase filter.

Evaluates the compiled pair-CNF (filters/compiler/prefilter.py) on a
packed byte batch: per adjacent byte pair, two 256-entry LUT lookups and
a bitwise AND; OR-reduce over positions; per pattern an all-bits check.
Pure elementwise/VPU work that XLA fuses — no matmuls — costing a small
fraction of one NFA kernel group pass. The resulting [B] bool mask
drives tile skipping in the Pallas kernel (candidates are clustered to
the front by a stable argsort and dead tiles never run the scan loop).
"""

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from klogs_tpu.filters.compiler.prefilter import PrefilterProgram


def device_tables(pf: PrefilterProgram):
    """(lut1 [256,W], lut2 [256,W], req [P,W]) as device uint32 arrays —
    a pytree suitable as a jit argument."""
    return (jnp.asarray(pf.lut1), jnp.asarray(pf.lut2), jnp.asarray(pf.req))


@jax.jit
def candidate_mask(tables, batch: jax.Array, lengths: jax.Array) -> jax.Array:
    """[B, L] u8 + [B] lengths -> [B] bool: True when the line satisfies
    some pattern's full clause requirement (necessary condition for any
    match; False rows can never match and may be skipped)."""
    lut1, lut2, req = tables
    x = batch.astype(jnp.int32)
    hits = lut1[x[:, :-1]] & lut2[x[:, 1:]]  # [B, L-1, W]
    # Pair (t, t+1) counts only when both bytes are inside the line.
    pos = jnp.arange(batch.shape[1] - 1, dtype=jnp.int32)
    valid = (pos[None, :] + 1) < lengths[:, None]
    hits = jnp.where(valid[:, :, None], hits, jnp.uint32(0))
    present = jax.lax.reduce(
        hits, np.uint32(0), jax.lax.bitwise_or, (1,)
    )  # [B, W]
    ok = (present[:, None, :] & req[None]) == req[None]  # [B, P, W]
    return jnp.all(ok, axis=-1).any(axis=-1)


@partial(jax.jit, static_argnames=("tile_b",))
def cluster_candidates(cand: jax.Array, tile_b: int):
    """Order lines candidates-first (stable) and mark live tiles.

    Returns (order [B] i32, inv [B] i32, tile_live [B//tile_b] i32):
    ``x[order]`` clusters candidates into the leading tiles,
    ``y[inv]`` undoes it, and tile_live[i] != 0 iff tile i holds at
    least one candidate."""
    order = jnp.argsort(jnp.logical_not(cand), stable=True)
    inv = jnp.argsort(order)
    n_cand = jnp.sum(cand.astype(jnp.int32))
    n_tiles = cand.shape[0] // tile_b
    tile_live = (
        (jnp.arange(n_tiles, dtype=jnp.int32) * tile_b) < n_cand
    ).astype(jnp.int32)
    return order, inv, tile_live
