"""Pallas TPU kernel for batch-NFA matching — the hot op.

Design, in order of what made it fast:

1. **VMEM residency.** The jnp/lax.scan path (klogs_tpu.ops.nfa) carries
   the [B, S] state vector through HBM every character step, making the
   filter HBM-bandwidth/latency-bound (measured ~74 ms per 32k x 128B
   batch on v5e). Here the state tile, transition table and class masks
   stay in VMEM for the whole position loop.
2. **Augmented automaton** (nfa.augment): inject and accept are folded
   into a `live` and an absorbing `acc` state, so the per-step update is
   just ``v' = (v @ F) & B[class]`` — two MXU matmuls and two VPU
   compares; no inject max, no accept reduction. "Matched" is row `acc`
   of the final state.
3. **int8 MXU.** 0/1 tables in int8 with int32 accumulation double MXU
   throughput vs bf16 and halve VMEM vs f32.
4. **Transposed layout.** Batch rides the 128-lane axis, states ride
   sublanes: the per-step class lookup is a sublane slice ``cls[t, :]``
   (Mosaic cannot dynamically slice the lane axis) and the one-hot class
   mask is an MXU matmul.

Per grid step (one lane-tile of TILE_B lines), all VMEM-resident:
    v = onehot(live)                       # [S, TILE_B] i8
    for t in 0..T-1:                       # static trip count
        c      = cls[t, :]                 # [1, TILE_B] sublane slice
        onehot = (iota_C == c)             # [C, TILE_B] VPU
        mask   = char_mask_T @ onehot      # [S, TILE_B] MXU (i8 -> i32)
        reach  = follow_T @ v              # [S, TILE_B] MXU (i8 -> i32)
        v      = (reach > 0) & (mask > 0)  # VPU, back to i8
    matched = v[acc, :]

Class ids are precomputed outside (nfa.classify_chunk + one extra pad
column so `acc` latches the final transition); that part is cheap,
elementwise, [B, T] i32 of traffic. Carry-in/out (v) keeps the long-line
chunk protocol (nfa.match_chunk) available on the kernel path.
"""

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from klogs_tpu.ops.nfa import DeviceProgram, classify_chunk

DEFAULT_TILE_B = 2048


def _kernel(cls_ref, char_mask_t_ref, follow_t_ref, v0_ref,
            out_ref, vout_ref, *, T: int, C: int, acc: int):
    TILE_B = cls_ref.shape[1]
    iota_c = jax.lax.broadcasted_iota(jnp.int32, (C, TILE_B), 0)

    def step(t, v):
        c = cls_ref[pl.ds(t, 1), :]  # [1, TILE_B] i32
        onehot = (iota_c == c).astype(jnp.int8)  # [C, TILE_B]
        mask = jnp.dot(char_mask_t_ref[:], onehot,
                       preferred_element_type=jnp.int32)  # [S, TILE_B]
        reach = jnp.dot(follow_t_ref[:], v,
                        preferred_element_type=jnp.int32)  # [S, TILE_B]
        return ((reach > 0) & (mask > 0)).astype(jnp.int8)

    v = jax.lax.fori_loop(0, T, step, v0_ref[:], unroll=False)
    out_ref[:] = v[acc : acc + 1, :]
    vout_ref[:] = v


@functools.partial(jax.jit, static_argnames=("acc", "first", "final",
                                             "tile_b", "interpret"))
def match_chunk_pallas(dp: DeviceProgram, acc: int,
                       chunk: jax.Array, rem: jax.Array,
                       v0: jax.Array,
                       first: bool = True, final: bool = True,
                       tile_b: int = DEFAULT_TILE_B, interpret: bool = False):
    """Kernel-path chunk matcher over an AUGMENTED program (nfa.augment,
    packed with dtype=jnp.int8). ``acc`` is the absorbing accept-state
    index; ``v0`` is the [B, S] i8 carry (from initial_state_kernel or a
    previous chunk). Returns (v [B, S] i8, matched [B] bool).

    Any batch size works: like the grouped sibling, B pads up to a tile
    multiple internally (pad rows carry a dead all-zero state and are
    sliced off before return), so long-line batches need not be
    tile-aligned."""
    B = chunk.shape[0]
    TILE_B = _cap_tile(tile_b, B, chunk.shape[1] + 2, dp.n_states,
                       cls_weight=8, state_weight=8)
    Bp = -(-B // TILE_B) * TILE_B
    if Bp != B:
        chunk = jnp.pad(chunk, ((0, Bp - B), (0, 0)))
        rem = jnp.pad(rem, (0, Bp - B))  # pad rows: already-ended lines
        v0 = jnp.pad(v0, ((0, Bp - B), (0, 0)))  # dead state: stays dead
    cls = classify_chunk(dp, chunk, rem, first=first, final=final)
    if final:
        # One pad step after END so `acc` latches the last transition.
        cls = jnp.concatenate(
            [cls, jnp.full((Bp, 1), dp.pad_class, dtype=jnp.int32)], axis=1
        )
    return _launch_chunk(dp, acc, cls, v0, B, TILE_B, final, interpret)


@functools.partial(jax.jit, static_argnames=("acc", "final", "tile_b",
                                             "interpret"))
def match_chunk_cls_pallas(dp: DeviceProgram, acc: int,
                           cls: jax.Array, v0: jax.Array,
                           final: bool = True,
                           tile_b: int = DEFAULT_TILE_B,
                           interpret: bool = False):
    """Chunk matcher over HOST-classified ids ([B, T] i8/i32 —
    classify_chunk_host layout, latch column included on final chunks):
    the long-line analog of match_cls_grouped_pallas, skipping the
    device-side classify gather (~85% of device time, BENCH_DEVICE.json).
    Returns (v [B, S] i8, matched [B] bool)."""
    B = cls.shape[0]
    TILE_B = _cap_tile(tile_b, B, cls.shape[1], dp.n_states, cls_weight=8, state_weight=8)
    Bp = -(-B // TILE_B) * TILE_B
    if Bp != B:
        cls = jnp.pad(cls, ((0, Bp - B), (0, 0)),
                      constant_values=dp.pad_class)
        v0 = jnp.pad(v0, ((0, Bp - B), (0, 0)))
    return _launch_chunk(dp, acc, cls.astype(jnp.int32), v0, B, TILE_B,
                         final, interpret)


def _launch_chunk(dp, acc, cls, v0, B, TILE_B, final, interpret):
    """Shared carried-state kernel launch over classified [Bp, T] i32."""
    Bp, T = cls.shape
    S, C = dp.n_states, dp.n_classes

    out, vout = pl.pallas_call(
        functools.partial(_kernel, T=T, C=C, acc=acc),
        grid=(Bp // TILE_B,),
        in_specs=[
            pl.BlockSpec((T, TILE_B), lambda i: (0, i),
                         memory_space=pltpu.VMEM),          # cls (transposed)
            pl.BlockSpec((S, C), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),          # char_mask^T
            pl.BlockSpec((S, S), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),          # follow^T
            pl.BlockSpec((S, TILE_B), lambda i: (0, i),
                         memory_space=pltpu.VMEM),          # v0^T
        ],
        out_specs=[
            pl.BlockSpec((1, TILE_B), lambda i: (0, i),
                         memory_space=pltpu.VMEM),          # matched row
            pl.BlockSpec((S, TILE_B), lambda i: (0, i),
                         memory_space=pltpu.VMEM),          # v carry-out
        ],
        out_shape=[
            jax.ShapeDtypeStruct((1, Bp), jnp.int8),
            jax.ShapeDtypeStruct((S, Bp), jnp.int8),
        ],
        interpret=interpret,
    )(cls.T, dp.char_mask.T, dp.follow.T, v0.T)

    matched = out[0, :B] > 0
    if final:
        matched = matched | jnp.asarray(dp.match_all)
    return vout.T[:B], matched


DEFAULT_TILE_B_GROUPED = 8192  # tune sweep 2026-07-29 (BENCH_DEVICE.json
# host_classify_rework.tune_cls): 5.62M lines/s vs 5.48M at 4096 on v5e,
# batch 131k; smaller batches are capped by min(tile_b, B) anyway.

# The per-grid-cell working set must fit the ~16MB scoped-VMEM limit:
# cls block [T, TILE_B] i32 plus the state tile (v i8 + reach i32 ≈ 5
# bytes x S per lane). Cap the tile so wide width-buckets / big-S
# augmented programs shrink the batch tile instead of overflowing VMEM —
# the non-gated hot path has no fallback, so an overflow would kill the
# run, not degrade it. (Budget measured: a 34MB scoped alloc was
# rejected with "limit 16.00M" on v5e; 12MB leaves room for tables and
# double-buffering.)
_VMEM_TILE_BUDGET = 12 << 20


def _pow2_floor(n: int) -> int:
    p = 1
    while p * 2 <= n:
        p *= 2
    return p


def _cap_tile(tile_b: int, B: int, T: int, S: int,
              cls_weight: int = 8, state_weight: int = 3) -> int:
    """Per-lane byte charges, calibrated against what Mosaic actually
    accepts/rejects on v5e. Both kernels double-buffer the [T, TILE] i32
    cls block (observed: 16.29M scoped alloc at T=515/TILE=4096, i.e.
    2 x 4 x T x TILE), hence cls_weight=8. The grouped kernel's state
    charge of 3 admits the 8192-lane T=131 config proven on hardware
    (5.62M lines/s, BENCH_DEVICE.json); the carried-state chunk kernel
    additionally carries v0/vout tiles, so it charges state_weight=8 —
    a 17MB scoped alloc was rejected at what lighter accounting
    predicted to fit."""
    per_lane = cls_weight * T + state_weight * S
    cap = max(8, _pow2_floor(_VMEM_TILE_BUDGET // per_lane))
    return max(1, min(tile_b, B, cap))


def _grouped_kernel(cls_ref, char_mask_t_ref, follow_t_ref, out_ref,
                    *, T: int, C: int, live: int, acc: int,
                    unroll: int = 1, interleave: int = 1,
                    mask_block: int = 1):
    g = pl.program_id(1)
    _grouped_kernel_body(g, cls_ref, char_mask_t_ref, follow_t_ref, out_ref,
                         T=T, C=C, live=live, acc=acc,
                         unroll=unroll, interleave=interleave,
                         mask_block=mask_block)


def _grouped_kernel_body(g, cls_ref, char_mask_t_ref, follow_t_ref, out_ref,
                         *, T: int, C: int, live: int, acc: int,
                         unroll: int = 1, interleave: int = 1,
                         mask_block: int = 1):
    """One (batch-tile, group) grid cell. The grid iterates groups
    innermost, so out_ref (indexed by tile only) stays VMEM-resident and
    accumulates the OR across groups. ``g`` is the group grid index,
    passed in so gated callers can read program_id outside a pl.when.

    ``interleave=2`` splits the lane tile into two independent halves
    advanced in the same loop body — two dependency chains let the
    scheduler overlap one half's MXU matmuls with the other's VPU
    compare/AND (the serial step chain is otherwise MXU-then-VPU with
    bubbles). Semantics identical; pick by measurement.

    ``mask_block=K`` restructures the scan into blocks of K steps: the
    K per-step masks (one-hot compare + char-mask matmul — data that
    does NOT depend on the state chain) are computed unrolled up front,
    then the K dependent chain steps (reach matmul + threshold-AND) run
    against the precomputed masks. The mask work is mutually
    independent, so the scheduler can pipeline its MXU matmuls
    back-to-back and overlap VPU one-hots with them, instead of
    serializing everything behind the state chain. Requires T padded to
    a K multiple (extra PAD steps are idempotent after the latch column:
    live/acc belong to every class and self-loop). Semantics identical;
    pick by measurement.
    """
    TILE_B = cls_ref.shape[1]
    S = follow_t_ref.shape[1]
    H = TILE_B // interleave

    if mask_block > 1:  # incompatible combos rejected in the launcher
        iota_c = jax.lax.broadcasted_iota(jnp.int32, (C, TILE_B), 0)

        def block(j, v):
            base = j * mask_block
            masks = []
            for k in range(mask_block):  # independent: pipelines on MXU
                c = cls_ref[pl.ds(base + k, 1), :].astype(jnp.int32)
                onehot = (iota_c == c).astype(jnp.int8)
                masks.append(
                    jnp.dot(char_mask_t_ref[0], onehot,
                            preferred_element_type=jnp.int32) > 0)
            for k in range(mask_block):  # the serial chain, 2 ops/step
                reach = jnp.dot(follow_t_ref[0], v,
                                preferred_element_type=jnp.int32)
                v = ((reach > 0) & masks[k]).astype(jnp.int8)
            return v

        v0 = (jax.lax.broadcasted_iota(jnp.int32, (S, TILE_B), 0)
              == live).astype(jnp.int8)
        v = jax.lax.fori_loop(0, T // mask_block, block, v0, unroll=unroll)
        matched = v[acc : acc + 1, :]
    else:
        def make_step(lo):
            iota_c = jax.lax.broadcasted_iota(jnp.int32, (C, H), 0)

            def half_step(t, v):
                c = cls_ref[pl.ds(t, 1), lo : lo + H].astype(jnp.int32)
                onehot = (iota_c == c).astype(jnp.int8)
                mask = jnp.dot(char_mask_t_ref[0], onehot,
                               preferred_element_type=jnp.int32)
                reach = jnp.dot(follow_t_ref[0], v,
                                preferred_element_type=jnp.int32)
                return ((reach > 0) & (mask > 0)).astype(jnp.int8)

            return half_step

        v0_half = [
            (jax.lax.broadcasted_iota(jnp.int32, (S, H), 0) == live
             ).astype(jnp.int8)
            for _ in range(interleave)
        ]
        steps = [make_step(i * H) for i in range(interleave)]

        def step(t, vs):
            return tuple(s(t, v) for s, v in zip(steps, vs))

        vs = jax.lax.fori_loop(0, T, step, tuple(v0_half), unroll=unroll)
        matched = jnp.concatenate([v[acc : acc + 1, :] for v in vs], axis=1)

    @pl.when(g == 0)
    def _():
        out_ref[:] = matched

    @pl.when(g > 0)
    def _():
        out_ref[:] = out_ref[:] | matched


def _check_fused_combo(fused, prefilter_tables, unroll, interleave,
                       mask_block=1, sweep_tables=None):
    """The fused kernel has no gated variant and a single dependency
    chain per group (no interleave/unroll). Silently running a
    DIFFERENT kernel than the caller asked to measure would corrupt the
    'pick by measurement' decision, so incompatible combos are loud."""
    if mask_block > 1 and interleave != 1:
        raise ValueError(
            "mask_block (KLOGS_TPU_MASK_BLOCK) and interleave "
            "(KLOGS_TPU_INTERLEAVE) are mutually exclusive chain "
            "restructurings; set at most one")
    if sweep_tables is not None and prefilter_tables is not None:
        raise ValueError(
            "sweep_tables (KLOGS_TPU_SWEEP) and prefilter_tables "
            "(KLOGS_TPU_PREFILTER) are mutually exclusive gates; the "
            "literal sweep subsumes the pair-CNF mask — set one")
    if not fused:
        return
    if prefilter_tables is not None:
        raise ValueError(
            "fused=True (KLOGS_TPU_FUSED_GROUPS) has no gated variant; "
            "drop the prefilter tables or unset KLOGS_TPU_PREFILTER")
    if sweep_tables is not None:
        raise ValueError(
            "fused=True (KLOGS_TPU_FUSED_GROUPS) has no gated variant; "
            "drop the sweep tables or unset KLOGS_TPU_SWEEP")
    if unroll != 1 or interleave != 1 or mask_block != 1:
        raise ValueError(
            "fused=True ignores unroll/interleave/mask_block; unset "
            "KLOGS_TPU_INTERLEAVE / KLOGS_TPU_MASK_BLOCK (or pass 1) "
            "when measuring the fused kernel")


def _grouped_kernel_fused(cls_ref, char_mask_all_ref, follow_t_ref, out_ref,
                          *, T: int, C: int, live: int, acc: int, G: int):
    """All G groups in ONE grid cell (grid iterates batch tiles only).

    Two savings over the per-group grid of _grouped_kernel:
    - the one-hot class expansion (iota==c over [C, TILE], pure VPU) is
      computed once per step instead of once per step PER GROUP;
    - the G mask matmuls collapse into one [G*S, C] @ [C, TILE] matmul,
      so the C-deep (usually 64 < 128) contraction is amortized over
      G*S output rows instead of padding the MXU per group.
    The reach matmuls stay per-group ([S,S] blocks are independent —
    stacking them block-diagonally would multiply FLOPs by G).
    Trade-off: the per-lane VMEM charge grows by ~G state tiles + the
    [G*S, TILE] mask block, shrinking the lane tile (see _cap_tile call
    in _launch_grouped); pick by measurement (KLOGS_TPU_FUSED_GROUPS=1).
    """
    TILE_B = cls_ref.shape[1]
    S = follow_t_ref.shape[2]
    iota_c = jax.lax.broadcasted_iota(jnp.int32, (C, TILE_B), 0)
    v0 = (jax.lax.broadcasted_iota(jnp.int32, (S, TILE_B), 0) == live
          ).astype(jnp.int8)

    def step(t, vs):
        c = cls_ref[pl.ds(t, 1), :].astype(jnp.int32)
        onehot = (iota_c == c).astype(jnp.int8)  # shared by all groups
        mask_all = jnp.dot(char_mask_all_ref[:], onehot,
                           preferred_element_type=jnp.int32)  # [G*S, TILE]
        out = []
        for g in range(G):
            reach = jnp.dot(follow_t_ref[g], vs[g],
                            preferred_element_type=jnp.int32)
            mask = mask_all[g * S : (g + 1) * S, :]
            out.append(((reach > 0) & (mask > 0)).astype(jnp.int8))
        return tuple(out)

    vs = jax.lax.fori_loop(0, T, step, tuple(v0 for _ in range(G)),
                           unroll=False)
    m = vs[0][acc : acc + 1, :]
    for g in range(1, G):
        m = m | vs[g][acc : acc + 1, :]
    out_ref[:] = m


def _grouped_kernel_gated(flags_ref, cls_ref, char_mask_t_ref, follow_t_ref,
                          out_ref, **kw):
    """(Tile, group)-skipping wrapper: flags_ref (scalar-prefetched,
    [n_tiles, G]) marks grid cells where the tile holds at least one
    candidate line FOR THAT GROUP's patterns. Dead cells never run the
    scan loop — the two-phase filter's payoff (compute scales with
    candidate work, not batch x groups). The out block is initialized
    at g == 0 either by the body's overwrite (live cell) or by an
    explicit zero write (dead cell), and live g > 0 cells OR into it,
    so any live/dead interleaving across the group axis accumulates
    correctly."""
    i = pl.program_id(0)
    g = pl.program_id(1)
    live_cell = flags_ref[i, g] > 0

    @pl.when(jnp.logical_not(live_cell) & (g == 0))
    def _():
        out_ref[:] = jnp.zeros_like(out_ref)

    @pl.when(live_cell)
    def _():
        _grouped_kernel_body(g, cls_ref, char_mask_t_ref, follow_t_ref,
                             out_ref, **kw)


@functools.partial(jax.jit, static_argnames=("live", "acc", "tile_b",
                                             "interpret", "unroll",
                                             "interleave", "fused",
                                             "mask_block", "return_stats"))
def match_batch_grouped_pallas(dp: DeviceProgram, live: int, acc: int,
                               batch: jax.Array, lengths: jax.Array,
                               tile_b: int = DEFAULT_TILE_B_GROUPED,
                               interpret: bool = False,
                               unroll: int = 1,
                               interleave: int = 1,
                               prefilter_tables=None,
                               fused: bool = False,
                               mask_block: int = 1,
                               sweep_tables=None,
                               return_stats: bool = False):
    """Full-line match over a compile_grouped program ([G, ...] leaves,
    shared byte classifier): [B, L] u8 + [B] -> [B] bool.

    Any batch size works: B is padded up to a multiple of the tile
    inside (zero-length pad rows can only hit via match_all, and they
    are sliced off before return), so callers — in particular MeshEngine
    shards whose local batch need not divide the tile — never trip a
    divisibility error.

    ``prefilter_tables`` enables the two-phase path: a cheap per-line
    candidate mask, a stable partition clustering candidates into the
    leading tiles, and a tile-skipping kernel — non-candidate tiles
    never run the scan loop. Necessary-condition semantics make the
    result identical to the plain path. Two table forms (both compiled
    from a USABLE PrefilterProgram for the same pattern set):

    - 4-tuple from ops.prefilter.class_tables: class-domain mask via
      MXU one-hot matmuls over the ALREADY-computed cls array (the fast
      form — no gathers).
    - 3-tuple from ops.prefilter.device_tables: byte-domain LUT-gather
      mask (fallback; measured ~NFA-kernel-cost on v5e, see
      BENCH_DEVICE.json).

    ``sweep_tables`` (an ops.sweep.SweepTables packed against THIS
    program's pattern_group map) enables the FUSED thousand-pattern
    path instead: the device literal sweep produces the per-(line,
    group) candidate mask right here on device and gates (tile, group)
    grid cells — frame -> sweep -> gated match in one dispatch, no
    host round-trip. Only this byte-consuming entry can fuse the sweep
    (the cls hot path never ships raw bytes to the device). With
    ``return_stats`` (and a gate active) returns (matched,
    (n_candidates, n_tiles_live, n_tiles)) like the cls entry."""
    B = batch.shape[0]
    _check_fused_combo(fused, prefilter_tables, unroll, interleave,
                       mask_block, sweep_tables)
    # +3: BEGIN, END, latch columns; then the mask_block T-padding the
    # launcher will add, so the VMEM budget sees the true cls width.
    T_cap = -(-(batch.shape[1] + 3) // mask_block) * mask_block
    TILE_B = _cap_tile(tile_b, B, T_cap, dp.n_states,
                       state_weight=_state_weight(fused, dp, mask_block))
    Bp = -(-B // TILE_B) * TILE_B
    if Bp != B:
        batch = jnp.pad(batch, ((0, Bp - B), (0, 0)))
        lengths = jnp.pad(lengths, (0, Bp - B))
    cls = classify_chunk(dp, batch, lengths, first=True, final=True)
    cls = jnp.concatenate(
        [cls, jnp.full((Bp, 1), dp.pad_class, dtype=jnp.int32)], axis=1
    )  # acc latch step
    cand_input = None
    if prefilter_tables is not None and len(prefilter_tables) != 4:
        cand_input = (batch, lengths)  # byte-LUT tables need raw bytes
    sweep_input = (batch, lengths) if sweep_tables is not None else None
    return _launch_grouped(dp, live, acc, cls, B, TILE_B,
                           interpret, unroll, interleave,
                           prefilter_tables, cand_input, fused=fused,
                           mask_block=mask_block,
                           sweep_tables=sweep_tables,
                           sweep_input=sweep_input,
                           return_stats=return_stats)


@functools.partial(jax.jit, static_argnames=("live", "acc", "tile_b",
                                             "interpret", "unroll",
                                             "interleave", "return_stats",
                                             "fused", "mask_block"))
def match_cls_grouped_pallas(dp: DeviceProgram, live: int, acc: int,
                             cls: jax.Array,
                             tile_b: int = DEFAULT_TILE_B_GROUPED,
                             interpret: bool = False,
                             unroll: int = 1,
                             interleave: int = 1,
                             prefilter_tables=None,
                             return_stats: bool = False,
                             fused: bool = False,
                             mask_block: int = 1):
    """Full-line match over HOST-classified int8 class ids: [B, T] i8
    (pack_classify layout: BEGIN, body classes, END, PAD latch columns)
    -> [B] bool. The single-chip hot path: the device-side byte->class
    gather (classify_chunk) measured as ~85% of hot-path device time
    (BENCH_DEVICE.json), so classification happens on the host — fused
    into the native packer — and the kernel consumes classes directly.

    ``prefilter_tables`` must be the class-domain 4-tuple
    (ops.prefilter.class_tables) when given. With ``return_stats`` (and
    gating active) returns (matched, (n_candidates, n_tiles_live,
    n_tiles)) — three device scalars fetched with the mask, feeding the
    --stats prefilter line."""
    B = cls.shape[0]
    _check_fused_combo(fused, prefilter_tables, unroll, interleave,
                       mask_block)
    # Fused per-lane charge: cls block + G state tiles (i8 v + i32
    # reach) + the shared [G*S, TILE] i32 mask block. The T charge
    # includes the mask_block padding the launcher will add. (An int8
    # cls block would cut its VMEM charge 4x and raise the lane-tile
    # cap, but Mosaic rejects the per-step dynamic single-row slice on
    # i8 memrefs — "index in dimension 0 must be a multiple of 8", the
    # i8 sublane-packing constraint — measured dead end, 2026-07-31.)
    T_cap = -(-cls.shape[1] // mask_block) * mask_block
    TILE_B = _cap_tile(tile_b, B, T_cap, dp.n_states,
                       state_weight=_state_weight(fused, dp, mask_block))
    Bp = -(-B // TILE_B) * TILE_B
    if Bp != B:
        # Pad rows are all-PAD: no state survives past step 0 except
        # live/acc self-loops, so they can only "match" via match_all —
        # and callers slice padded rows off anyway.
        cls = jnp.pad(cls, ((0, Bp - B), (0, 0)),
                      constant_values=dp.pad_class)
    return _launch_grouped(dp, live, acc, cls.astype(jnp.int32), B, TILE_B,
                           interpret, unroll, interleave,
                           prefilter_tables, None,
                           return_stats=return_stats, fused=fused,
                           mask_block=mask_block)


def _state_weight(fused: bool, dp, mask_block: int = 1) -> int:
    """Per-lane state-tile VMEM charge for _cap_tile (see its docstring
    for calibration). mask_block keeps K precomputed bool masks plus one
    i32 matmul transient resident alongside v/reach."""
    if fused:
        return 9 * dp.follow.shape[0]
    if mask_block > 1:
        return 3 + mask_block + 4
    return 3


def _launch_grouped(dp, live, acc, cls, B, TILE_B,
                    interpret, unroll, interleave,
                    prefilter_tables, cand_input,
                    return_stats: bool = False, fused: bool = False,
                    mask_block: int = 1,
                    sweep_tables=None, sweep_input=None):
    """Shared kernel launch over classified [Bp, T] i32 ids (padded to a
    TILE_B multiple); B is the real row count to slice back to."""
    if mask_block > 1 and cls.shape[1] % mask_block:
        # Extra PAD steps after the latch column are idempotent
        # (live/acc belong to every class and self-loop), so rounding T
        # up to a block multiple changes nothing semantically.
        extra = mask_block - cls.shape[1] % mask_block
        cls = jnp.concatenate(
            [cls, jnp.full((cls.shape[0], extra), dp.pad_class,
                           dtype=cls.dtype)], axis=1)
    Bp, T = cls.shape
    S, C = dp.n_states, dp.n_classes
    G = dp.follow.shape[0]

    # char_mask [G,C,S] -> [G,S,C]; follow [G,S,S] -> [G,S,S]^T per group.
    char_mask_t = jnp.swapaxes(dp.char_mask, 1, 2)
    follow_t = jnp.swapaxes(dp.follow, 1, 2)

    if fused:  # _check_fused_combo guaranteed prefilter_tables is None
        out = pl.pallas_call(
            functools.partial(_grouped_kernel_fused, T=T, C=C,
                              live=live, acc=acc, G=G),
            grid=(Bp // TILE_B,),
            in_specs=[
                pl.BlockSpec((T, TILE_B), lambda i: (0, i),
                             memory_space=pltpu.VMEM),      # cls (transposed)
                pl.BlockSpec((G * S, C), lambda i: (0, 0),
                             memory_space=pltpu.VMEM),      # char_mask^T stacked
                pl.BlockSpec((G, S, S), lambda i: (0, 0, 0),
                             memory_space=pltpu.VMEM),      # follow^T
            ],
            out_specs=pl.BlockSpec((1, TILE_B), lambda i: (0, i),
                                   memory_space=pltpu.VMEM),
            out_shape=jax.ShapeDtypeStruct((1, Bp), jnp.int8),
            interpret=interpret,
        )(cls.T, char_mask_t.reshape(G * S, C), follow_t)
        matched = (out[0, :B] > 0) | jnp.asarray(dp.match_all)
        return (matched, None) if return_stats else matched

    kern_kw = dict(T=T, C=C, live=live, acc=acc,
                   unroll=unroll, interleave=interleave,
                   mask_block=mask_block)
    if prefilter_tables is None and sweep_tables is None:
        out = pl.pallas_call(
            functools.partial(_grouped_kernel, **kern_kw),
            grid=(Bp // TILE_B, G),  # groups innermost: out block revisited
            in_specs=[
                pl.BlockSpec((T, TILE_B), lambda i, g: (0, i),
                             memory_space=pltpu.VMEM),      # cls (transposed)
                pl.BlockSpec((1, S, C), lambda i, g: (g, 0, 0),
                             memory_space=pltpu.VMEM),      # char_mask^T
                pl.BlockSpec((1, S, S), lambda i, g: (g, 0, 0),
                             memory_space=pltpu.VMEM),      # follow^T
            ],
            out_specs=pl.BlockSpec((1, TILE_B), lambda i, g: (0, i),
                                   memory_space=pltpu.VMEM),
            out_shape=jax.ShapeDtypeStruct((1, Bp), jnp.int8),
            interpret=interpret,
        )(cls.T, char_mask_t, follow_t)
        matched = (out[0, :B] > 0) | jnp.asarray(dp.match_all)
        return (matched, None) if return_stats else matched

    from klogs_tpu.ops.prefilter import (
        candidate_matrix,
        candidate_matrix_from_cls,
        cluster_candidates,
        group_candidates,
        pattern_group_onehot,
    )

    # One gated tail, two candidate sources (the launcher rejects both
    # gates at once in _check_fused_combo): the fused literal sweep
    # produces the exact per-(line, group) mask directly — its tables
    # were packed against this program's pattern_group map — while the
    # pair-CNF prefilter produces a per-(line, pattern) matrix reduced
    # to groups when the program carries a pattern_group map.
    if sweep_tables is not None:
        from klogs_tpu.ops.sweep import sweep_group_candidates

        gm = sweep_group_candidates(sweep_tables, *sweep_input)  # [Bp, G]
        if gm.shape[1] != G:
            raise ValueError(
                f"sweep tables target {gm.shape[1]} groups, grouped "
                f"program has {G} (pack with this program's "
                "pattern_group map)")
        cand = gm.any(axis=1)
    else:
        if len(prefilter_tables) == 4:  # class-domain tables (fast form)
            pm = candidate_matrix_from_cls(prefilter_tables, cls)  # [Bp, Pp]
        else:
            pm = candidate_matrix(prefilter_tables, *cand_input)  # [Bp, Pp]
        cand = pm.any(axis=1)
        gm = None
        if dp.pattern_group:
            # Thousand-pattern narrowing: gate per (tile, GROUP) — a
            # tile whose candidates all come from other groups'
            # patterns skips this group's scan loop entirely.
            onehot = pattern_group_onehot(dp.pattern_group, G)
            gm = group_candidates(pm, onehot, len(dp.pattern_group))
    order, inv, tile_live = cluster_candidates(cand, TILE_B)
    n_tiles = Bp // TILE_B
    if gm is not None:
        flags = (gm[order].reshape(n_tiles, TILE_B, G).any(axis=1)
                 .astype(jnp.int32))
    else:
        flags = jnp.broadcast_to(tile_live[:, None],
                                 (n_tiles, G)).astype(jnp.int32)
    cls = cls[order]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(n_tiles, G),
        in_specs=[
            pl.BlockSpec((T, TILE_B), lambda i, g, flags: (0, i)),
            pl.BlockSpec((1, S, C), lambda i, g, flags: (g, 0, 0)),
            pl.BlockSpec((1, S, S), lambda i, g, flags: (g, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, TILE_B), lambda i, g, flags: (0, i)),
    )
    out = pl.pallas_call(
        functools.partial(_grouped_kernel_gated, **kern_kw),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((1, Bp), jnp.int8),
        interpret=interpret,
    )(flags, cls.T, char_mask_t, follow_t)
    matched = (out[0] > 0)[inv][:B]
    matched = matched | jnp.asarray(dp.match_all)
    if return_stats:
        stats = (jnp.sum(cand.astype(jnp.int32)),
                 jnp.sum(tile_live),
                 jnp.asarray(tile_live.shape[0], jnp.int32))
        return matched, stats
    return matched


def initial_state_kernel(dp: DeviceProgram, live: int, batch_size: int):
    """[B, S] i8 one-hot on the `live` state — the augmented v0."""
    return jnp.tile(
        (jnp.arange(dp.n_states) == live).astype(jnp.int8)[None, :],
        (batch_size, 1),
    )


def match_batch_pallas(dp: DeviceProgram, acc: int, live: int,
                       batch: jax.Array, lengths: jax.Array,
                       tile_b: int = DEFAULT_TILE_B,
                       interpret: bool = False) -> jax.Array:
    """[B, L] u8 + [B] lengths -> [B] bool, via the VMEM-resident kernel.
    ``dp`` must be an augmented program (nfa.augment) packed as int8."""
    v0 = initial_state_kernel(dp, live, batch.shape[0])
    _, matched = match_chunk_pallas(
        dp, acc, batch, lengths, v0,
        first=True, final=True, tile_b=tile_b, interpret=interpret,
    )
    return matched
