"""Batch NFA matching in JAX — the TPU compute path.

This is the engine behind ``--backend=tpu`` (north star; the reference
has no counterpart — its write path is an unfiltered io.Copy,
/root/reference/cmd/root.go:359-374). The automaton comes from the
Glushkov compiler (klogs_tpu.filters.compiler.glushkov), whose defining
property makes the per-character update TPU-shaped:

    v' = ((v @ F) | inject) & B[class(c)]

- ``v @ F`` — state reachability as a 0/1 matmul on the MXU. States are
  padded to a multiple of 128 so the [B,S] x [S,S] product tiles cleanly
  onto the 128x128 systolic array.
- ``B[class(c)]`` — realized as a one-hot matmul ``onehot(c) @ B`` so the
  gather also rides the MXU instead of a scatter/gather unit.
- The scan over character positions is a ``lax.scan`` with static trip
  count — no data-dependent Python control flow under jit, per the XLA
  compilation model.

Everything here is pure and functional: a ``DeviceProgram`` (pytree of
arrays) plus jitted functions over it. Sharding/multi-chip lives in
klogs_tpu.parallel; this module is single-logical-device semantics.

Long lines (sequence-parallel analog, SURVEY.md §5 "long-context"): the
scan carries the state vector, so ``match_chunk`` exposes a carry-in /
carry-out API — a line longer than one tile is processed as consecutive
chunks with the NFA state vector carried across, the bit-parallel analog
of blockwise scanning.
"""

from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from klogs_tpu.filters.compiler.glushkov import NFAProgram

# TPU lane width: pad the state axis to a multiple of this so matmuls
# tile onto the MXU without remainder handling.
LANE = 128


@jax.tree_util.register_pytree_node_class
@dataclass
class DeviceProgram:
    """NFAProgram padded + packed as device arrays (a pytree).

    All float arrays hold exact 0.0/1.0 values; matmuls accumulate in
    f32 so counts up to S <= 4096 are exact.
    """

    char_mask: jax.Array  # [C, S] f32 — B table (one-hot matmul target)
    follow: jax.Array  # [S, S] f32 — F
    inject: jax.Array  # [S] f32
    accept: jax.Array  # [S] f32
    byte_class: jax.Array  # [256] i32
    begin_class: int
    end_class: int
    pad_class: int
    n_classes: int  # padded C
    n_states: int  # padded S
    match_all: bool
    # Grouped programs only: pattern index (input order) -> group id,
    # as a hashable tuple (static aux, not a leaf). Empty for
    # single-automaton programs. Lets the two-phase kernel path gate
    # per (tile, group), not just per tile (ops/pallas_nfa.py).
    pattern_group: tuple = ()

    def tree_flatten(self):
        leaves = (self.char_mask, self.follow, self.inject, self.accept,
                  self.byte_class)
        aux = (self.begin_class, self.end_class, self.pad_class,
               self.n_classes, self.n_states, self.match_all,
               self.pattern_group)
        return leaves, aux

    @classmethod
    def tree_unflatten(cls, aux, leaves):
        return cls(*leaves, *aux)


def _pad_to(n: int, m: int) -> int:
    return ((n + m - 1) // m) * m


def pack_program(prog: NFAProgram, dtype=jnp.float32) -> DeviceProgram:
    """Pad the compiler's dense arrays to MXU-friendly shapes.

    Padded states have all-zero rows/cols everywhere, so they can never
    activate; padded classes get all-zero char_mask rows (same kill
    semantics as the compiler's pad_class).
    """
    S = max(LANE, _pad_to(prog.n_states, LANE))
    C = _pad_to(prog.n_classes, 8)

    char_mask = np.zeros((C, S), dtype=np.float32)
    char_mask[: prog.n_classes, : prog.n_states] = prog.char_mask
    follow = np.zeros((S, S), dtype=np.float32)
    follow[: prog.n_states, : prog.n_states] = prog.follow
    inject = np.zeros(S, dtype=np.float32)
    inject[: prog.n_states] = prog.inject
    accept = np.zeros(S, dtype=np.float32)
    accept[: prog.n_states] = prog.accept

    return DeviceProgram(
        char_mask=jnp.asarray(char_mask, dtype=dtype),
        follow=jnp.asarray(follow, dtype=dtype),
        inject=jnp.asarray(inject, dtype=dtype),
        accept=jnp.asarray(accept, dtype=dtype),
        byte_class=jnp.asarray(prog.byte_class, dtype=jnp.int32),
        begin_class=prog.begin_class,
        end_class=prog.end_class,
        pad_class=prog.pad_class,
        n_classes=C,
        n_states=S,
        match_all=prog.match_all,
    )


def classify_chunk(dp: DeviceProgram, chunk: jax.Array, rem: jax.Array,
                   first: bool, final: bool) -> jax.Array:
    """bytes [B, L] u8 + remaining-lengths [B] -> class ids [B, T] i32.

    ``rem`` is each line's remaining byte count measured from this
    chunk's start: negative once a line has already ended (all pad),
    ``> L`` while it continues past this chunk. The END sentinel is
    emitted at chunk-local position ``rem`` when it falls inside this
    chunk — and when ``rem == L`` on a non-final chunk, END is deferred
    to the next chunk (where rem' == 0) so it is fed exactly once.
    Positions past END are pad_class, whose all-zero mask row kills
    every state while the sticky `matched` accumulator holds.
    ``first`` prepends the virtual BEGIN column.
    """
    B, L = chunk.shape
    body = dp.byte_class[chunk.astype(jnp.int32)]  # [B, L]
    if final:
        # Extra column so END can land at position L (rem == L).
        body = jnp.concatenate(
            [body, jnp.full((B, 1), dp.pad_class, dtype=jnp.int32)], axis=1
        )  # [B, L+1]
    # Non-final chunks get NO extra column: a trailing pad step would
    # kill the carried state mid-line, and rem == L defers END to the
    # next chunk (rem' == 0) anyway.
    pos = jnp.arange(body.shape[1], dtype=jnp.int32)[None, :]
    rem = rem.astype(jnp.int32)[:, None]
    body = jnp.where(pos < rem, body,
                     jnp.where(pos == rem, dp.end_class, dp.pad_class))
    if first:
        begin = jnp.full((B, 1), dp.begin_class, dtype=jnp.int32)
        body = jnp.concatenate([begin, body], axis=1)
    return body


def _scan_classes(dp: DeviceProgram, cls: jax.Array,
                  v0: jax.Array, matched0: jax.Array):
    """Core scan: cls [B, T] -> (v_final [B,S] f32, matched [B] bool)."""
    dtype = dp.follow.dtype

    def step(carry, c_t):
        v, matched = carry  # v: [B, S] dtype, matched: [B] bool
        reach = (jnp.dot(v, dp.follow, preferred_element_type=jnp.float32)
                 > 0.5).astype(dtype)
        active = jnp.maximum(reach, dp.inject[None, :])
        onehot = jax.nn.one_hot(c_t, dp.n_classes, dtype=dtype)  # [B, C]
        mask = jnp.dot(onehot, dp.char_mask,
                       preferred_element_type=jnp.float32)  # [B, S]
        v2 = (active * mask).astype(dtype)
        hit = jnp.dot(v2, dp.accept, preferred_element_type=jnp.float32) > 0.5
        return (v2, matched | hit), None

    (v, matched), _ = jax.lax.scan(step, (v0, matched0), cls.T)
    return v, matched


@jax.jit
def match_batch(dp: DeviceProgram, batch: jax.Array, lengths: jax.Array) -> jax.Array:
    """Full-line match: [B, L] u8 bytes + [B] lengths -> [B] bool keep-mask.

    Equivalent to `any(p.search(line) for p in patterns)` for the
    compiled pattern union (property-tested against the re oracle).
    """
    B = batch.shape[0]
    cls = classify_chunk(dp, batch, lengths, first=True, final=True)
    v0, matched0 = initial_state(dp, B)
    _, matched = _scan_classes(dp, cls, v0, matched0)
    return matched | jnp.asarray(dp.match_all)


@partial(jax.jit, static_argnames=("first", "final"))
def match_chunk(dp: DeviceProgram, chunk: jax.Array, rem: jax.Array,
                v0: jax.Array, matched0: jax.Array,
                first: bool, final: bool):
    """Carried-state matching for lines longer than one tile.

    ``chunk`` [B, L] holds bytes [k*L, (k+1)*L) of each line and ``rem``
    the line length minus k*L (see classify_chunk). Returns (v, matched)
    to thread into the next chunk; after the ``final`` chunk, ``matched``
    is the keep-mask (modulo the match_all shortcut).
    """
    cls = classify_chunk(dp, chunk, rem, first=first, final=final)
    v, matched = _scan_classes(dp, cls, v0, matched0)
    if final:
        matched = matched | jnp.asarray(dp.match_all)
    return v, matched


def initial_state(dp: DeviceProgram, batch_size: int):
    v0 = jnp.zeros((batch_size, dp.n_states), dtype=dp.follow.dtype)
    matched0 = jnp.zeros((batch_size,), dtype=bool)
    return v0, matched0


def augment(prog: NFAProgram) -> NFAProgram:
    """Fold inject+accept into the automaton via two extra states, so a
    matcher needs NO per-step inject/accept work — just v' = (v@F) & B[c]:

    - ``live`` (index n): always alive (member of every class, including
      pad); follow(live) = inject ∪ {live}. Starting from v0 = {live},
      the unanchored-search re-injection happens inside the matmul.
    - ``acc`` (index n+1): absorbing sink; follow(a) ∋ acc for every
      accepting a, follow(acc) = {acc}, member of every class including
      pad — once a match is seen it survives to the end of the scan, so
      "matched" is simply v_final[acc]. Requires one scan step AFTER the
      END sentinel to latch the last transition (matchers append one pad
      column).

    The result is still a valid NFAProgram (inject' = {live},
    accept' = {acc}), usable by any execution path.
    """
    n = prog.n_states
    live, acc = n, n + 1
    char_mask = np.zeros((prog.n_classes, n + 2), dtype=bool)
    char_mask[:, :n] = prog.char_mask
    char_mask[:, live] = True  # every class, including pad_class
    char_mask[:, acc] = True
    follow = np.zeros((n + 2, n + 2), dtype=bool)
    follow[:n, :n] = prog.follow
    follow[live, :n] = prog.inject
    follow[live, live] = True
    follow[:n, acc] = prog.accept
    follow[acc, acc] = True
    inject = np.zeros(n + 2, dtype=bool)
    inject[live] = True
    accept = np.zeros(n + 2, dtype=bool)
    accept[acc] = True
    return NFAProgram(
        n_states=n + 2,
        n_classes=prog.n_classes,
        byte_class=prog.byte_class,
        begin_class=prog.begin_class,
        end_class=prog.end_class,
        pad_class=prog.pad_class,
        char_mask=char_mask,
        follow=follow,
        inject=inject,
        accept=accept,
        match_all=prog.match_all,
        patterns=prog.patterns,
    )


# ---------------------------------------------------------------------
# Pattern-sharded stacking (the TP analog, SURVEY.md §2 "Mesh/sharding
# layer": shard K patterns over mesh axis `pattern`, lines over `data`).
# ---------------------------------------------------------------------


def stack_programs(progs: list[NFAProgram], dtype=jnp.float32) -> DeviceProgram:
    """Stack G per-group automata into one DeviceProgram with a leading
    group axis on every array leaf, suitable for vmap / sharding over a
    `pattern` mesh axis.

    The static class layout must be uniform across groups for the vmapped
    classify to be well-defined, so classes are re-laid out: byte classes
    keep their per-group ids in 0..n_byte-1, and BEGIN/END/PAD move to
    common slots at the top of the padded class range. char_mask rows are
    permuted to match; padded byte-class rows stay all-zero (their class
    ids never occur in any byte_class table).
    """
    max_byte = max(p.begin_class for p in progs)  # begin_class == n_byte_classes
    begin_c, end_c, pad_c = max_byte, max_byte + 1, max_byte + 2
    C = _pad_to(max_byte + 3, 8)
    S = max(LANE, _pad_to(max(p.n_states for p in progs), LANE))
    G = len(progs)

    char_mask = np.zeros((G, C, S), dtype=np.float32)
    follow = np.zeros((G, S, S), dtype=np.float32)
    inject = np.zeros((G, S), dtype=np.float32)
    accept = np.zeros((G, S), dtype=np.float32)
    byte_class = np.zeros((G, 256), dtype=np.int32)
    for g, p in enumerate(progs):
        n, nb = p.n_states, p.begin_class
        char_mask[g, :nb, :n] = p.char_mask[:nb]
        char_mask[g, begin_c, :n] = p.char_mask[p.begin_class]
        char_mask[g, end_c, :n] = p.char_mask[p.end_class]
        # pad_c row stays zero (kill-all), as in pack_program.
        follow[g, :n, :n] = p.follow
        inject[g, :n] = p.inject
        accept[g, :n] = p.accept
        byte_class[g] = p.byte_class

    return DeviceProgram(
        char_mask=jnp.asarray(char_mask, dtype=dtype),
        follow=jnp.asarray(follow, dtype=dtype),
        inject=jnp.asarray(inject, dtype=dtype),
        accept=jnp.asarray(accept, dtype=dtype),
        byte_class=jnp.asarray(byte_class, dtype=jnp.int32),
        begin_class=begin_c,
        end_class=end_c,
        pad_class=pad_c,
        n_classes=C,
        n_states=S,
        match_all=any(p.match_all for p in progs),
    )


def compile_grouped(patterns: list[str], ignore_case: bool = False,
                    max_positions: int = 126, dtype=jnp.int8,
                    n_groups: int | None = None,
                    states_pad: int | None = None,
                    classes_pad: int | None = None):
    """Compile K patterns into G small AUGMENTED automata with a SHARED
    byte classifier, stacked as [G, ...] arrays — the single-chip perf
    lever: MXU cost of the reachability matmul is quadratic in the state
    count, so G groups of <=126 positions (one 128x128 MXU tile each,
    live/acc included) beat one union automaton of G*126 states by ~G x.

    Returns (DeviceProgram with [G, ...] leaves and a shared [256]
    byte_class, live_index, acc_index). live/acc sit at S-2/S-1 and the
    BEGIN/END/PAD classes at C-3/C-2/C-1 in every group, so programs
    compiled with forced pads (``n_groups``/``states_pad``/``classes_pad``
    — used to make several pattern shards shape-uniform for stacking
    under shard_map) share all static metadata. Extra forced groups are
    all-dead (zero char_mask: can never match). Any-match over groups ==
    any-match over patterns.
    """
    from klogs_tpu.filters.compiler.glushkov import compile_patterns

    if not patterns:
        raise ValueError("compile_grouped needs at least one pattern")
    # Greedy first-fit-decreasing bin packing by position count
    # (tracking ORIGINAL pattern indices, so the program can report
    # which group each input pattern landed in — duplicates included).
    sized = [(compile_patterns([p], ignore_case=ignore_case).n_states, i)
             for i, p in enumerate(patterns)]
    sized.sort(key=lambda t: (-t[0], t[1]))
    bins: list[tuple[int, list[int]]] = []
    for n, pi in sized:
        for i, (load, ids) in enumerate(bins):
            if load + n <= max_positions:
                bins[i] = (load + n, ids + [pi])
                break
        else:
            bins.append((n, [pi]))
    pattern_group = [0] * len(patterns)
    for g, (_, ids) in enumerate(bins):
        for pi in ids:
            pattern_group[pi] = g
    progs = [compile_patterns([patterns[i] for i in ids],
                              ignore_case=ignore_case) for _, ids in bins]
    G = max(len(progs), n_groups or 0)

    # Shared byte classifier: bytes equivalent in EVERY group collapse.
    sig = np.stack([p.byte_class for p in progs], axis=1)  # [256, G']
    uniq, byte_class = np.unique(sig, axis=0, return_inverse=True)
    byte_class = byte_class.astype(np.int32)
    n_glob = uniq.shape[0]
    C = max(_pad_to(n_glob + 3, 8), classes_pad or 0)
    begin_c, end_c, pad_c = C - 3, C - 2, C - 1
    S = max(LANE, _pad_to(max(p.n_states for p in progs) + 2, LANE),
            states_pad or 0)
    live, acc = S - 2, S - 1

    char_mask = np.zeros((G, C, S), dtype=np.float32)
    follow = np.zeros((G, S, S), dtype=np.float32)
    inject = np.zeros((G, S), dtype=np.float32)
    accept = np.zeros((G, S), dtype=np.float32)
    for g, p in enumerate(progs):
        n = p.n_states
        # Byte classes: global class c has per-group local id uniq[c][g].
        char_mask[g, :n_glob, :n] = p.char_mask[uniq[:, g], :n]
        char_mask[g, begin_c, :n] = p.char_mask[p.begin_class, :n]
        char_mask[g, end_c, :n] = p.char_mask[p.end_class, :n]
        # live/acc are members of every class, including pad.
        char_mask[g, :, live] = 1.0
        char_mask[g, :, acc] = 1.0
        follow[g, :n, :n] = p.follow
        follow[g, live, :n] = p.inject  # live re-injects firstpos
        follow[g, live, live] = 1.0
        follow[g, :n, acc] = p.accept  # accepting -> absorbing acc
        follow[g, acc, acc] = 1.0
        inject[g, live] = 1.0
        accept[g, acc] = 1.0

    dp = DeviceProgram(
        char_mask=jnp.asarray(char_mask, dtype=dtype),
        follow=jnp.asarray(follow, dtype=dtype),
        inject=jnp.asarray(inject, dtype=dtype),
        accept=jnp.asarray(accept, dtype=dtype),
        byte_class=jnp.asarray(byte_class, dtype=jnp.int32),
        begin_class=begin_c,
        end_class=end_c,
        pad_class=pad_c,
        n_classes=C,
        n_states=S,
        match_all=any(p.match_all for p in progs),
        pattern_group=tuple(pattern_group),
    )
    return dp, live, acc


@jax.jit
def match_batch_grouped(dp: DeviceProgram, batch: jax.Array,
                        lengths: jax.Array) -> jax.Array:
    """Any-match across G stacked pattern groups: [G,...] program leaves,
    [B, L] bytes -> [B] bool.

    Written as a vmap over the group axis + an any-reduce; under
    sharding (program leaves on the `pattern` axis, batch on `data`)
    XLA lowers the reduce to an ICI all-reduce across pattern shards —
    collectives by annotation, not by hand (scaling-book recipe).
    """
    per_group = jax.vmap(match_batch, in_axes=(0, None, None))(dp, batch, lengths)
    return jnp.any(per_group, axis=0)
