"""Device-side literal sweep: factor-index narrowing on the TPU.

The host sweep (filters/compiler/index.py) narrows each line to its
candidate pattern groups at ~570k lines/s — an order of magnitude below
the device match pipeline — so in thousand-pattern mode the NARROWING
stage, not the match kernel, bounds throughput (ROADMAP item 2's open
half; the Hyperscan-FDR / GLoP literal-gating shape from PAPERS.md).
This module is the device twin: the same compiled factor tables
(FactorIndex.sweep_program), evaluated as a fixed sequence of
vectorized array passes over the packed ``[B, L]`` byte batch, so the
per-(line, group) candidate mask is produced ON DEVICE and can gate
the grouped Pallas NFA kernel in the same dispatch — frame -> sweep ->
gated match with no host round-trip (ops/pallas_nfa.py).

Stage structure (all dense — XLA needs static shapes, so there is no
survivor extraction; instead every stage is a cheap full-width pass
and the EXPENSIVE work is bounded by compile-time constants):

1. **Rolling codes via shifted slices.** The row is padded with 8 zero
   columns and the little-endian 4-byte code at every position is four
   shifted uint32 slices OR-ed together — no gather, pure VPU. The
   wide tier's chained key derives from the same array: the code 4
   positions ahead, Fibonacci-mixed in (one multiply + one xor).
2. **Exact two-tier hash probe.** Every position's key probes the
   tier's open-addressed table: ``max_probe`` UNROLLED gather+compare
   rounds into a cache/VMEM-resident table (searchsorted's log2 E
   dependent binary-search rounds measured ~8x slower on XLA CPU and
   lower the same way on TPU). The two tiers are what keep buckets
   shallow: minted rule families share a rarest 4-byte window, and a
   single-code table funnels them into one bucket whose depth the
   static walk pays at EVERY position (measured max bucket 137 at
   K=1024 single-tier vs 2 two-tier).
3. **Masked word verify.** A matched key selects a bucket of at most
   ``max_bucket`` entries (compile-time constant, typically 1-2). For
   each bucket slot, the candidate factor's bytes are compared as
   masked uint32 words against the SAME rolling-code array (window
   position minus the entry's rarity anchor gives the factor start;
   per-tier ceil(len/4) masked compares, zero-mask words are
   don't-care) together with the line-bounds check — EXACTLY the host
   sweep's verify, so the device mask equals the host mask bit for bit
   (property-tested in tests/test_sweep.py).
4. **Group-bitset accumulate.** Verified hits OR their factor's group
   bitset ([GW] uint32 lanes, 32 groups/lane) into a per-line
   accumulator; one unpack + the always-candidate mask yields the
   [B, G] bool candidate matrix.

Unlike the host sweep there is NO bloom stage: the dense exact probe
IS the gate here (equality beats a superset bloom at the same cost),
so the host's 64 KiB union bloom never ships to the device.

Exactness matters: the mask is a NECESSARY condition (a False cell
proves no pattern of that group matches the line), and host parity
makes the host sweep the oracle for the device path. Padded rows
(length 0) can never host a factor, so batch padding is safe.
"""

from dataclasses import dataclass
from typing import Any, Iterator

import jax
import jax.numpy as jnp
import numpy as np

from klogs_tpu.filters.compiler.index import (
    SweepProgram,
    SweepTier,
    pack_sweep_tier,
)

# Fibonacci multiply fold, shared with the host tables and the
# wide-tier key mix (filters/compiler/index.py _fold1): hash slot =
# high log2(H) bits of the wrapping 32-bit product.
_FIB = 2654435761


@jax.tree_util.register_pytree_node_class
@dataclass
class SweepTables:
    """SweepProgram as a device pytree. Array leaves carry the tables;
    ``n_groups`` and the static loop bounds are pytree AUX (they shape
    the unpack and bound the probe/verify loops), so mesh stacking
    requires them uniform across shards — ``stack_sweep_tables`` forces
    the maxima."""

    n_slot_key: Any   # [Hn] u32 narrow hash slots
    n_slot_eid: Any   # [Hn] i32, -1 = empty
    n_start: Any      # [En+1] i32 bucket starts
    n_fid: Any        # [NEn] i32
    n_anchor: Any     # [NEn] i32
    w_slot_key: Any   # [Hw] u32 wide hash slots
    w_slot_eid: Any   # [Hw] i32
    w_start: Any      # [Ew+1] i32
    w_fid: Any        # [NEw] i32
    w_anchor: Any     # [NEw] i32
    fac_len: Any      # [F] i32
    fac_words: Any    # [F, W] u32
    fac_wmask: Any    # [F, W] u32
    fac_groups: Any   # [F, GW] u32
    always_mask: Any  # [GW] u32
    n_groups: int
    n_bounds: "tuple[int, int, int]"  # narrow (max_probe, max_bucket, n_words)
    w_bounds: "tuple[int, int, int]"  # wide   (max_probe, max_bucket, n_words)

    def tree_flatten(self) -> "tuple[tuple, tuple]":
        leaves = (self.n_slot_key, self.n_slot_eid, self.n_start,
                  self.n_fid, self.n_anchor,
                  self.w_slot_key, self.w_slot_eid, self.w_start,
                  self.w_fid, self.w_anchor,
                  self.fac_len, self.fac_words, self.fac_wmask,
                  self.fac_groups, self.always_mask)
        return leaves, (self.n_groups, self.n_bounds, self.w_bounds)

    @classmethod
    def tree_unflatten(cls, aux: tuple, leaves: tuple) -> "SweepTables":
        return cls(*leaves, *aux)

    def leaf_iter(self) -> "Iterator[Any]":
        yield from self.tree_flatten()[0]


def _tier_leaves(t: SweepTier) -> "tuple[Any, ...]":
    return (jnp.asarray(t.slot_key), jnp.asarray(t.slot_eid),
            jnp.asarray(t.bucket_start), jnp.asarray(t.fid),
            jnp.asarray(t.anchor))


def device_sweep_tables(prog: SweepProgram) -> SweepTables:
    """Ship a packed SweepProgram to the device (jnp arrays)."""
    return SweepTables(
        *_tier_leaves(prog.narrow), *_tier_leaves(prog.wide),
        fac_len=jnp.asarray(prog.fac_len),
        fac_words=jnp.asarray(prog.fac_words),
        fac_wmask=jnp.asarray(prog.fac_wmask),
        fac_groups=jnp.asarray(prog.fac_groups),
        always_mask=jnp.asarray(prog.always_mask),
        n_groups=prog.n_groups,
        n_bounds=(prog.narrow.max_probe, prog.narrow.max_bucket,
                  prog.narrow.n_words),
        w_bounds=(prog.wide.max_probe, prog.wide.max_bucket,
                  prog.wide.n_words),
    )


def sweep_span_attrs(st: SweepTables) -> "dict[str, int]":
    """Bounded attribute set describing a sweep dispatch for the batch
    trace (obs.trace ``device.sweep`` spans): table shape, never table
    content. Host-side only — spans cannot live inside the jitted
    ``sweep_group_candidates`` (traced-purity), so the wrapping engine
    attaches these at the dispatch site."""
    return {
        "sweep_groups": int(st.n_groups),
        "sweep_factors": int(st.fac_len.shape[0]),
        "sweep_narrow_slots": int(st.n_slot_key.shape[-1]),
        "sweep_wide_slots": int(st.w_slot_key.shape[-1]),
    }


def stack_sweep_tables(progs: "list[SweepProgram]") -> SweepTables:
    """Shape-uniform [n_shards, ...] stack of per-shard SweepPrograms
    for shard_map (parallel/mesh.py): every array leaf is padded to the
    fleet maxima and the aux loop bounds are forced to the maxima too.
    Hash tables are REBUILT at the uniform power-of-two size (slot
    indices depend on the table size, so padding in place would break
    the probe), entry pads sit in zero-length buckets so they are never
    walked, and a shard whose bound is below the forced maximum reads
    only empty probe slots / empty bucket tails. Requires uniform
    n_groups — mesh shards are compiled with a forced group count
    already."""
    if not progs:
        raise ValueError("stack_sweep_tables needs at least one program")
    gs = {p.n_groups for p in progs}
    if len(gs) != 1:
        raise ValueError(f"shard sweep programs disagree on n_groups: {gs}")

    def pad1(a: np.ndarray, n: int, fill: int = 0) -> np.ndarray:
        out = np.full((n,) + a.shape[1:], fill, dtype=a.dtype)
        out[: len(a)] = a
        return out

    def stack_tier(
        tiers: "list[SweepTier]",
    ) -> "tuple[tuple[Any, ...], tuple[int, int, int]]":
        H = max(len(t.slot_key) for t in tiers)
        rebuilt = []
        for t in tiers:
            if len(t.slot_key) == H:
                rebuilt.append(t)
                continue
            entries = [(int(t.keys[e]), int(t.fid[i]), int(t.anchor[i]))
                       for e in range(len(t.keys))
                       for i in range(int(t.bucket_start[e]),
                                      int(t.bucket_start[e + 1]))]
            nt = pack_sweep_tier(entries, hash_size=H)
            nt.n_words = t.n_words
            rebuilt.append(nt)
        E = max(len(t.keys) for t in rebuilt)
        NE = max(len(t.fid) for t in rebuilt)
        leaves = (
            np.stack([pad1(t.slot_key, H) for t in rebuilt]),
            np.stack([pad1(t.slot_eid, H, -1) for t in rebuilt]),
            np.stack([np.concatenate(
                [t.bucket_start,
                 np.full(E - len(t.keys), t.bucket_start[-1],
                         dtype=t.bucket_start.dtype)])
                for t in rebuilt]),
            np.stack([pad1(t.fid, NE) for t in rebuilt]),
            np.stack([pad1(t.anchor, NE) for t in rebuilt]),
        )
        bounds = (max(t.max_probe for t in rebuilt),
                  max(t.max_bucket for t in rebuilt),
                  max(t.n_words for t in rebuilt))
        return tuple(jnp.asarray(x) for x in leaves), bounds

    n_leaves, n_bounds = stack_tier([p.narrow for p in progs])
    w_leaves, w_bounds = stack_tier([p.wide for p in progs])
    F = max(p.fac_len.shape[0] for p in progs)
    W = max(p.fac_words.shape[1] for p in progs)
    GW = max(p.fac_groups.shape[1] for p in progs)

    def pad2(a: np.ndarray, cols: int) -> np.ndarray:
        out = np.zeros((F, cols), dtype=a.dtype)
        out[: a.shape[0], : a.shape[1]] = a
        return out

    return SweepTables(
        *n_leaves, *w_leaves,
        fac_len=jnp.asarray(np.stack([pad1(p.fac_len, F)
                                      for p in progs])),
        fac_words=jnp.asarray(np.stack([pad2(p.fac_words, W)
                                        for p in progs])),
        fac_wmask=jnp.asarray(np.stack([pad2(p.fac_wmask, W)
                                        for p in progs])),
        fac_groups=jnp.asarray(np.stack([pad2(p.fac_groups, GW)
                                         for p in progs])),
        always_mask=jnp.asarray(np.stack([pad1(p.always_mask, GW)
                                          for p in progs])),
        n_groups=progs[0].n_groups,
        n_bounds=n_bounds, w_bounds=w_bounds,
    )


def _unpack_bits(packed: Any, n_groups: int) -> Any:
    """[..., GW] u32 bitset -> [..., n_groups] bool (static index
    arrays, so the lane/shift selects compile to gathers-by-constant)."""
    g = np.arange(n_groups)
    lane = g // 32
    shift = jnp.asarray((g % 32).astype(np.uint32))
    return ((packed[..., lane] >> shift) & jnp.uint32(1)) > 0


def _rolling_codes(batch: Any) -> Any:
    """[B, L] u8 -> [B, L+4] u32: the little-endian 4-byte code at
    every position (positions L..L+3 read zero pad only — present so
    the wide tier's +4 chained lookup stays in bounds)."""
    B, L = batch.shape
    xb = jnp.concatenate(
        [batch, jnp.zeros((B, 8), dtype=jnp.uint8)], axis=1)
    x32 = xb.astype(jnp.uint32)
    n = L + 4
    return (x32[:, :n]
            | (x32[:, 1 : n + 1] << jnp.uint32(8))
            | (x32[:, 2 : n + 2] << jnp.uint32(16))
            | (x32[:, 3 : n + 3] << jnp.uint32(24)))


def _probe_tier(keys_at: Any, roll: Any, slot_key: Any, slot_eid: Any,
                start: Any, fid: Any, anchor: Any,
                bounds: "tuple[int, int, int]", st: SweepTables,
                lens: Any, accw: "list[Any]") -> None:
    """One tier's dense hash probe + bounded bucket walk + masked word
    verify, OR-ing verified factors' group bitsets into ``accw``.
    ``keys_at`` is the per-position tier KEY array ([B, L]); ``roll``
    the shared rolling-code array ([B, L+4]) the verify compares
    against."""
    max_probe, max_bucket, n_words = bounds
    H = int(slot_key.shape[0])
    E = int(start.shape[0]) - 1
    if max_probe == 0 or E <= 0:
        return
    B, L = keys_at.shape
    bits = H.bit_length() - 1
    h = (keys_at * jnp.uint32(_FIB)) >> jnp.uint32(32 - bits)
    eid = jnp.full((B, L), -1, dtype=jnp.int32)
    for j in range(max_probe):
        s = ((h + jnp.uint32(j)) & jnp.uint32(H - 1)).astype(jnp.int32)
        m = (slot_key[s] == keys_at) & (slot_eid[s] >= 0)
        eid = jnp.where(m, slot_eid[s], eid)  # keys unique: <=1 match
    hit = eid >= 0
    eidc = jnp.clip(eid, 0, E - 1)
    b_lo = start[eidc]
    b_hi = start[eidc + 1]
    pos = jnp.arange(L, dtype=jnp.int32)[None, :]
    NE = int(fid.shape[0])
    GW = int(st.fac_groups.shape[-1])
    for j in range(max_bucket):
        e = b_lo + j
        in_bucket = hit & (e < b_hi)
        ec = jnp.clip(e, 0, NE - 1)
        f = fid[ec]
        flen = st.fac_len[f]
        begin = pos - anchor[ec]
        ver = in_bucket & (begin >= 0) & (begin + flen <= lens)
        bc = jnp.clip(begin, 0, L - 1)
        for w in range(n_words):
            cw = jnp.take_along_axis(
                roll, jnp.minimum(bc + 4 * w, L + 3), axis=1)
            ver = ver & ((cw & st.fac_wmask[..., w][f])
                         == st.fac_words[..., w][f])
        for g in range(GW):
            bits_g = jnp.where(ver, st.fac_groups[..., g][f],
                               jnp.uint32(0))  # [B, L]
            accw[g] = accw[g] | jax.lax.reduce(
                bits_g, np.uint32(0), jax.lax.bitwise_or, (1,))


@jax.jit
def sweep_group_candidates(st: SweepTables, batch: Any,
                           lengths: Any) -> Any:
    """[B, L] u8 rows + [B] lengths -> [B, G] bool candidate matrix:
    True where some guard factor of group g occurs INSIDE the line (or
    g is always-candidate). Device twin of the host
    ``FactorIndex.group_candidates`` — exact same survivors (module
    docstring), just packed rows instead of a framed payload."""
    B, L = batch.shape
    G = st.n_groups
    GW = int(st.fac_groups.shape[-1])
    always = jnp.broadcast_to(
        _unpack_bits(st.always_mask[None, :], G), (B, G))
    if L == 0 or (st.n_bounds[0] == 0 and st.w_bounds[0] == 0):
        return always
    roll = _rolling_codes(batch)          # [B, L+4]
    codes = roll[:, :L]
    lens = lengths.astype(jnp.int32)[:, None]
    accw = [jnp.zeros((B,), dtype=jnp.uint32) for _ in range(GW)]
    _probe_tier(codes, roll, st.n_slot_key, st.n_slot_eid, st.n_start,
                st.n_fid, st.n_anchor, st.n_bounds, st, lens, accw)
    # Wide tier key: Fibonacci mix of this code and the one 4 bytes
    # ahead — the chained half-window conjunction as ONE u32 key.
    wkey = (codes * jnp.uint32(_FIB)) ^ roll[:, 4 : L + 4]
    _probe_tier(wkey, roll, st.w_slot_key, st.w_slot_eid, st.w_start,
                st.w_fid, st.w_anchor, st.w_bounds, st, lens, accw)
    acc = jnp.stack(accw, axis=1)  # [B, GW]
    return _unpack_bits(acc, G) | always
