"""Sequence-parallel matching of ONE huge line (the long-context op).

The vector scan (ops/nfa, ops/pallas_nfa) is latency-bound on a single
line: T sequential steps, one tiny matmul each — a 1 MB line takes ~1.5 s
at ~1.5 us/step no matter how wide the machine is. This module removes
the sequential bottleneck with the classic linear-recurrence trick:

The AUGMENTED automaton (nfa.augment — inject folded into the `live`
self-loop, accept into the absorbing `acc` sink) makes the per-byte
update LINEAR over the boolean semiring:

    v_{t} = v_{t-1} @ A[c_t],   A[c][i,j] = Follow[i,j] AND B[c][j]

Matrix products are associative, so a tile of T0 bytes folds into one
transfer matrix M_tile = A[c_1] ... A[c_T0] by a log2(T0)-depth tree of
BATCHED [S,S]x[S,S] matmuls — T0-way parallel work the MXU eats whole —
and tiles compose across the line (and across DEVICES, each taking a
contiguous span, with one [S,S] matrix per device to gather: the
sequence-parallel layout SURVEY.md §5 notes as the scaling option).

Cost model, honestly: the matrix path does S x more multiply work per
byte than the vector scan (S^3 vs S^2 per step-ish), but it converts a
serial chain into parallel batched matmuls. For S=128 on v5e the vector
scan is ~us/byte (latency) while the tree is ~ns/byte (throughput) —
a ~100x single-line win, growing linearly with devices. Use it when one
line is huge; the batched vector kernel remains optimal when
parallelism already comes from many lines.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np

from klogs_tpu.ops.nfa import DeviceProgram

DEFAULT_TILE_T = 512


def _bmm_bool(a: jax.Array, b: jax.Array) -> jax.Array:
    """Batched boolean matrix product on int8 0/1 operands."""
    return (
        jnp.einsum("bij,bjk->bik", a, b, preferred_element_type=jnp.int32) > 0
    ).astype(jnp.int8)


@functools.partial(jax.jit, static_argnames=())
def tile_transfer_matrices(dp: DeviceProgram, cls: jax.Array) -> jax.Array:
    """classes [N, T0] -> transfer matrices [N, S, S] (one per tile),
    each the ordered product of its per-character step matrices, built
    by a log-depth pairwise tree so every level is one batched matmul.
    T0 must be a power of two (pad with pad_class: its step matrix is
    absorbing for live/acc and kills everything else, which is exactly
    the semantics of positions past the end of the line)."""
    N, T0 = cls.shape
    S = dp.n_states
    # A[c][i,j] = follow[i,j] & char_mask[c][j]
    a = dp.follow[None, :, :].astype(jnp.int8) * \
        dp.char_mask[cls.reshape(-1)][:, None, :].astype(jnp.int8)  # [N*T0,S,S]
    while a.shape[0] > N:
        a = _bmm_bool(a[0::2], a[1::2])
    return a


def _pad_pow2(n: int) -> int:
    p = 1
    while p < n:
        p *= 2
    return p


def classify_line(dp: DeviceProgram, line: bytes, tile_t: int) -> np.ndarray:
    """Class ids for one line incl. BEGIN/END/latch, padded to a
    multiple of tile_t (tile_t must be a power of two)."""
    body = np.frombuffer(line, dtype=np.uint8)
    cls = np.asarray(dp.byte_class)[body]
    full = np.concatenate([
        np.array([dp.begin_class], dtype=np.int32),
        cls.astype(np.int32),
        np.array([dp.end_class, dp.pad_class], dtype=np.int32),  # END + latch
    ])
    T = len(full)
    pad = -T % tile_t
    if pad:
        full = np.concatenate(
            [full, np.full(pad, dp.pad_class, dtype=np.int32)])
    return full


def match_line_scan(dp: DeviceProgram, live: int, acc: int, line: bytes,
                    tile_t: int = DEFAULT_TILE_T) -> bool:
    """Single-device sequence-parallel match of one line: per-tile
    transfer matrices by batched tree, then a cheap sequential
    vector-matrix fold across tiles (S^2 per tile_t bytes)."""
    assert tile_t & (tile_t - 1) == 0, "tile_t must be a power of two"
    cls = classify_line(dp, line, tile_t).reshape(-1, tile_t)
    mats = tile_transfer_matrices(dp, jnp.asarray(cls))  # [n_tiles, S, S]

    def fold(v, m):
        return (
            jnp.einsum("j,jk->k", v, m, preferred_element_type=jnp.int32) > 0
        ).astype(jnp.int8), None

    v0 = (jnp.arange(dp.n_states) == live).astype(jnp.int8)
    v, _ = jax.lax.scan(fold, v0, mats)
    return bool(np.asarray(v)[acc]) or dp.match_all


def match_line_sharded(dp: DeviceProgram, live: int, acc: int, line: bytes,
                       mesh=None, tile_t: int = DEFAULT_TILE_T) -> bool:
    """Sequence-parallel across DEVICES: the line's tiles shard over a
    1-D ``seq`` mesh axis; each device folds its contiguous span into
    one [S, S] transfer matrix, and the D per-device matrices compose
    after an all-gather — D-1 extra [S,S] matmuls total, the analog of
    a ring/all-to-all sequence-parallel step."""
    import jax.sharding as shd

    try:
        from jax import shard_map
    except ImportError:
        from jax.experimental.shard_map import shard_map

    if mesh is None:
        devs = np.asarray(jax.devices())
        mesh = shd.Mesh(devs, ("seq",))
    D = mesh.devices.size
    P = shd.PartitionSpec

    cls = classify_line(dp, line, tile_t)
    n_tiles = len(cls) // tile_t
    pad_tiles = -n_tiles % D
    if pad_tiles:
        cls = np.concatenate(
            [cls, np.full(pad_tiles * tile_t, dp.pad_class, dtype=np.int32)])
    cls = cls.reshape(-1, tile_t)

    def per_device(cls_local):
        mats = tile_transfer_matrices(dp, cls_local)  # [tiles/D, S, S]

        def fold(m_acc, m):
            return _bmm_bool(m_acc[None], m[None])[0], None

        eye = jnp.eye(dp.n_states, dtype=jnp.int8)
        m_dev, _ = jax.lax.scan(fold, eye, mats)  # [S, S]
        # One matrix per device; compose in device order.
        all_m = jax.lax.all_gather(m_dev, "seq")  # [D, S, S]

        def fold2(m_acc, m):
            return _bmm_bool(m_acc[None], m[None])[0], None

        m_total, _ = jax.lax.scan(fold2, eye, all_m)
        return m_total[None]  # [1, S, S] -> gathered to [D, S, S]

    specs = dict(mesh=mesh, in_specs=(P("seq"),), out_specs=P("seq"))
    try:
        fn = shard_map(per_device, check_vma=False, **specs)
    except TypeError:
        fn = shard_map(per_device, check_rep=False, **specs)
    m_total = np.asarray(jax.jit(fn)(jnp.asarray(cls)))[0]  # replicated
    v0 = np.zeros(dp.n_states, dtype=np.int64)
    v0[live] = 1
    return bool((v0 @ m_total)[acc] > 0) or dp.match_all
