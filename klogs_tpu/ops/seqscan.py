"""Sequence-parallel matching of ONE huge line (the long-context op).

The vector scan (ops/nfa, ops/pallas_nfa) is latency-bound on a single
line: T sequential steps, one tiny matmul each — a 1 MB line takes ~1.5 s
at ~1.5 us/step no matter how wide the machine is. This module removes
the sequential bottleneck with the classic linear-recurrence trick:

The AUGMENTED automaton (nfa.augment — inject folded into the `live`
self-loop, accept into the absorbing `acc` sink) makes the per-byte
update LINEAR over the boolean semiring:

    v_{t} = v_{t-1} @ A[c_t],   A[c][i,j] = Follow[i,j] AND B[c][j]

Matrix products are associative, so a tile of T0 bytes folds into one
transfer matrix M_tile = A[c_1] ... A[c_T0] by a log2(T0)-depth tree of
BATCHED [S,S]x[S,S] matmuls — T0-way parallel work the MXU eats whole —
and tiles compose across the line (and across DEVICES, each taking a
contiguous span, with one [S,S] matrix per device to gather: the
sequence-parallel layout SURVEY.md §5 notes as the scaling option).

Cost model, honestly: the matrix path does S x more multiply work per
byte than the vector scan (S^3 vs S^2 per step-ish), but it converts a
serial chain into parallel batched matmuls. For S=128 on v5e the vector
scan is ~us/byte (latency) while the tree is ~ns/byte (throughput) —
a ~100x single-line win, growing linearly with devices. Use it when one
line is huge; the batched vector kernel remains optimal when
parallelism already comes from many lines.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np

from klogs_tpu.ops.nfa import DeviceProgram

DEFAULT_TILE_T = 512

# Peak footprint of the tree's first level is N*T0 step matrices of S^2
# int8 each (>=16 KB per input byte at S=128). One jumbo line processed
# in a single call therefore OOMs the device — a ~1 MB line would want
# ~16 GB. Matching is instead CHUNKED: at most this many step-matrix
# bytes are materialized per tile_transfer_matrices call, and the
# resulting per-chunk matrices fold sequentially into the carry.
DEFAULT_STEP_BYTES_BUDGET = 128 << 20


def _tiles_per_chunk(tile_t: int, n_states: int,
                     budget: int = DEFAULT_STEP_BYTES_BUDGET) -> int:
    return max(1, budget // (tile_t * n_states * n_states))


def _bmm_bool(a: jax.Array, b: jax.Array) -> jax.Array:
    """Batched boolean matrix product on int8 0/1 operands."""
    return (
        jnp.einsum("bij,bjk->bik", a, b, preferred_element_type=jnp.int32) > 0
    ).astype(jnp.int8)


@functools.partial(jax.jit, static_argnames=())
def tile_transfer_matrices(dp: DeviceProgram, cls: jax.Array) -> jax.Array:
    """classes [N, T0] -> transfer matrices [N, S, S] (one per tile),
    each the ordered product of its per-character step matrices, built
    by a log-depth pairwise tree so every level is one batched matmul.
    T0 must be a power of two (pad with pad_class: its step matrix is
    absorbing for live/acc and kills everything else, which is exactly
    the semantics of positions past the end of the line).

    Materializes N*T0 step matrices — callers must bound N*T0 (see
    _tiles_per_chunk / DEFAULT_STEP_BYTES_BUDGET)."""
    N, T0 = cls.shape
    S = dp.n_states
    # A[c][i,j] = follow[i,j] & char_mask[c][j]
    a = dp.follow[None, :, :].astype(jnp.int8) * \
        dp.char_mask[cls.reshape(-1)][:, None, :].astype(jnp.int8)  # [N*T0,S,S]
    while a.shape[0] > N:
        a = _bmm_bool(a[0::2], a[1::2])
    return a


def _pad_pow2(n: int) -> int:
    p = 1
    while p < n:
        p *= 2
    return p


def _pow2_floor(n: int) -> int:
    p = 1
    while p * 2 <= n:
        p *= 2
    return p


def classify_line(dp: DeviceProgram, line: bytes, tile_t: int) -> np.ndarray:
    """Class ids for one line incl. BEGIN/END/latch, padded to a
    multiple of tile_t (tile_t must be a power of two)."""
    body = np.frombuffer(line, dtype=np.uint8)
    cls = np.asarray(dp.byte_class)[body]
    full = np.concatenate([
        np.array([dp.begin_class], dtype=np.int32),
        cls.astype(np.int32),
        np.array([dp.end_class, dp.pad_class], dtype=np.int32),  # END + latch
    ])
    T = len(full)
    pad = -T % tile_t
    if pad:
        full = np.concatenate(
            [full, np.full(pad, dp.pad_class, dtype=np.int32)])
    return full


@functools.partial(jax.jit, static_argnames=("live",))
def _scan_chunked(dp: DeviceProgram, cls3: jax.Array, live: int) -> jax.Array:
    """cls3 [n_chunks, tiles_per_chunk, tile_t] -> final state vector.
    The outer scan bounds peak memory to ONE chunk's step matrices."""

    def chunk_step(v, cls_chunk):
        mats = tile_transfer_matrices(dp, cls_chunk)  # [tpc, S, S]

        def fold(v, m):
            return (
                jnp.einsum("j,jk->k", v, m,
                           preferred_element_type=jnp.int32) > 0
            ).astype(jnp.int8), None

        v, _ = jax.lax.scan(fold, v, mats)
        return v, None

    v0 = (jnp.arange(dp.n_states) == live).astype(jnp.int8)
    v, _ = jax.lax.scan(chunk_step, v0, cls3)
    return v


def _chunk_classes(dp: DeviceProgram, cls: np.ndarray, tile_t: int,
                   tiles_per_chunk: int, round_to: int = 1) -> np.ndarray:
    """[n_tiles, tile_t] -> [n_chunks, tiles_per_chunk, tile_t], chunk
    count padded (with pad_class tiles, which are identity for live/acc)
    up to a power of two times ``round_to`` so the jit cache sees
    O(log line-length) distinct shapes, not one per length."""
    n_tiles = cls.shape[0]
    n_chunks = _pad_pow2(-(-n_tiles // tiles_per_chunk))
    n_chunks = -(-n_chunks // round_to) * round_to
    pad = n_chunks * tiles_per_chunk - n_tiles
    if pad:
        cls = np.concatenate(
            [cls, np.full((pad, tile_t), dp.pad_class, dtype=np.int32)])
    return cls.reshape(n_chunks, tiles_per_chunk, tile_t)


def match_line_scan(dp: DeviceProgram, live: int, acc: int, line: bytes,
                    tile_t: int = DEFAULT_TILE_T,
                    step_bytes_budget: int = DEFAULT_STEP_BYTES_BUDGET) -> bool:
    """Single-device sequence-parallel match of one line: per-tile
    transfer matrices by batched tree, then a cheap sequential
    vector-matrix fold across tiles (S^2 per tile_t bytes). Peak device
    memory is bounded by ``step_bytes_budget`` regardless of line size —
    tiles are processed in fixed-size chunks folded into the carry."""
    return match_lines_scan(dp, live, acc, [line], tile_t,
                            step_bytes_budget)[0]


@functools.partial(jax.jit, static_argnames=("live",))
def _scan_chunked_batch(dp: DeviceProgram, cls4: jax.Array,
                        live: int) -> jax.Array:
    """[N, n_chunks, tpc, tile_t] -> [N, S] final state vectors — N
    jumbo lines advancing together (vmap of the chunked fold)."""
    return jax.vmap(lambda c: _scan_chunked(dp, c, live))(cls4)


def match_lines_scan(dp: DeviceProgram, live: int, acc: int,
                     lines: list[bytes],
                     tile_t: int = DEFAULT_TILE_T,
                     step_bytes_budget: int = DEFAULT_STEP_BYTES_BUDGET,
                     ) -> list[bool]:
    """Batched sequence-parallel matching of N jumbo lines: lines are
    grouped by padded chunk-count (a power of two, so the jit cache
    sees O(log max-length) shapes — no recompilation per line) and each
    group runs as ONE vmapped device program. The step-matrix budget is
    split across the lines scanned together, keeping peak memory at
    ``step_bytes_budget`` for the whole call."""
    assert tile_t & (tile_t - 1) == 0, "tile_t must be a power of two"
    if not lines:
        return []
    S = dp.n_states
    # Every shape knob is quantized to a power of two — line count for
    # the budget split, tiles-per-chunk, group batch dim — so the jit
    # cache stays O(log^2), not one entry per concurrent-line count.
    per_line = max(step_bytes_budget // _pad_pow2(len(lines)),
                   tile_t * S * S)
    tpc = _pow2_floor(_tiles_per_chunk(tile_t, S, per_line))
    groups: dict[int, list[int]] = {}
    cls3s: list[np.ndarray] = []
    for i, line in enumerate(lines):
        cls = classify_line(dp, line, tile_t).reshape(-1, tile_t)
        cls3 = _chunk_classes(dp, cls, tile_t, tpc)
        cls3s.append(cls3)
        groups.setdefault(cls3.shape[0], []).append(i)
    out = [bool(dp.match_all)] * len(lines)
    # Peak memory = vmap-width x one chunk's step matrices; cap the
    # width so N concurrent jumbo lines can never multiply past the
    # budget (the batch dim is as real a memory axis as the chunk dim).
    max_n = max(1, _pow2_floor(
        step_bytes_budget // (tpc * tile_t * S * S)))
    for idxs in groups.values():
        for lo in range(0, len(idxs), max_n):
            sub = idxs[lo : lo + max_n]
            rows = [cls3s[i] for i in sub]
            # Pad the batch dim with all-PAD pseudo-lines (identity
            # folds, never match) up to a power of two.
            pad_n = _pad_pow2(len(rows)) - len(rows)
            if pad_n:
                rows.extend([np.full_like(rows[0], dp.pad_class)] * pad_n)
            stacked = jnp.asarray(np.stack(rows))
            v = np.asarray(_scan_chunked_batch(dp, stacked, live))
            for i, hit in zip(sub, v[:, acc]):
                out[i] = bool(hit) or dp.match_all
    return out


def _sharded_fn(mesh, n_states: int):
    """Build (once per mesh, via the jit cache on the returned callable)
    the shard_map'd per-device chunked fold."""
    import jax.sharding as shd

    try:
        from jax import shard_map
    except ImportError:
        from jax.experimental.shard_map import shard_map

    P = shd.PartitionSpec

    def per_device(dp, cls3_local):
        eye = jnp.eye(n_states, dtype=jnp.int8)

        def chunk_step(m_acc, cls_chunk):
            mats = tile_transfer_matrices(dp, cls_chunk)  # [tpc, S, S]

            def fold(m, m2):
                return _bmm_bool(m[None], m2[None])[0], None

            m, _ = jax.lax.scan(fold, m_acc, mats)
            return m, None

        # Chunked fold bounds peak memory to one chunk's step matrices.
        m_dev, _ = jax.lax.scan(chunk_step, eye, cls3_local)  # [S, S]
        # One matrix per device; compose in device order.
        all_m = jax.lax.all_gather(m_dev, "seq")  # [D, S, S]

        def fold2(m_acc, m):
            return _bmm_bool(m_acc[None], m[None])[0], None

        m_total, _ = jax.lax.scan(fold2, eye, all_m)
        return m_total[None]  # [1, S, S] -> gathered to [D, S, S]

    specs = dict(mesh=mesh,
                 in_specs=(P(), P("seq")),
                 out_specs=P("seq"))
    try:
        fn = shard_map(per_device, check_vma=False, **specs)
    except TypeError:
        fn = shard_map(per_device, check_rep=False, **specs)
    return jax.jit(fn)


# shard_map'd fold programs, keyed by (device ids, axis name, S) — NOT
# by the Mesh object: two Meshes over the same devices are functionally
# identical, and keying on the object would leak one jitted closure per
# ad-hoc Mesh. Bounded LRU so even pathological device-set churn cannot
# grow it without limit.
_SHARDED_CACHE: "dict" = {}
_SHARDED_CACHE_MAX = 8


def match_line_sharded(dp: DeviceProgram, live: int, acc: int, line: bytes,
                       mesh=None, tile_t: int = DEFAULT_TILE_T,
                       step_bytes_budget: int = DEFAULT_STEP_BYTES_BUDGET) -> bool:
    """Sequence-parallel across DEVICES: the line's tile-chunks shard
    over a 1-D ``seq`` mesh axis; each device folds its contiguous span
    into one [S, S] transfer matrix (chunk by chunk, so peak memory is
    bounded by ``step_bytes_budget`` per device), and the D per-device
    matrices compose after an all-gather — D-1 extra [S,S] matmuls
    total, the analog of a ring/all-to-all sequence-parallel step. The
    shard_map'd program is cached per (mesh, S); chunk counts are padded
    to powers of two so distinct line lengths reuse compilations."""
    import jax.sharding as shd

    if mesh is None:
        devs = np.asarray(jax.devices())
        mesh = shd.Mesh(devs, ("seq",))
    D = mesh.devices.size

    cls = classify_line(dp, line, tile_t).reshape(-1, tile_t)
    tpc = _tiles_per_chunk(tile_t, dp.n_states, step_bytes_budget)
    # Chunk count a power of two AND a multiple of D -> equal spans.
    cls3 = _chunk_classes(dp, cls, tile_t, tpc, round_to=D)

    key = (tuple(d.id for d in mesh.devices.flat), mesh.axis_names,
           dp.n_states)
    fn = _SHARDED_CACHE.pop(key, None)
    if fn is None:
        fn = _sharded_fn(mesh, dp.n_states)
    _SHARDED_CACHE[key] = fn  # re-insert: dict order gives LRU
    while len(_SHARDED_CACHE) > _SHARDED_CACHE_MAX:
        _SHARDED_CACHE.pop(next(iter(_SHARDED_CACHE)))
    m_total = np.asarray(fn(dp, jnp.asarray(cls3)))[0]  # replicated
    v0 = np.zeros(dp.n_states, dtype=np.int64)
    v0[live] = 1
    return bool((v0 @ m_total)[acc] > 0) or dp.match_all
