"""Policy core: retry with backoff+jitter, per-attempt deadlines, and a
three-state circuit breaker.

Loop-confinement: all of this runs on the one asyncio event loop the
pipeline shares (the goroutine analog), so no locks are needed — the
same discipline the fanout/coalescer layers follow. ``CircuitBreaker``
takes an injectable ``clock`` so state-machine tests never sleep.
"""

import asyncio
import random
import time
from dataclasses import dataclass
from typing import Awaitable, Callable, Optional

from klogs_tpu.cluster.backend import ClusterError


class Unavailable(ClusterError):
    """A policy-guarded call ultimately failed: retries exhausted or the
    breaker is open. Subclasses ClusterError so an un-degraded
    propagation still gets the CLI's one-friendly-line exit instead of
    a traceback; callers with a degrade path (``--on-filter-error``)
    catch THIS type."""


class BreakerOpen(Unavailable):
    """Fast-fail: the breaker is open, the call was never attempted."""


@dataclass(frozen=True)
class RetryPolicy:
    """Exponential backoff: attempt ``i`` (0-based) waits
    ``min(base_s * multiplier**i, max_s)``, spread by ``jitter``
    (uniform ±fraction, so a fleet of collectors retrying a shared
    apiserver doesn't thundering-herd on the same schedule).

    ``max_attempts`` counts ALL tries including the first; retries are
    ``max_attempts - 1``.
    """

    max_attempts: int = 5
    base_s: float = 0.5
    max_s: float = 10.0
    multiplier: float = 2.0
    jitter: float = 0.1

    def delay_s(self, attempt: int) -> float:
        d = min(self.base_s * self.multiplier ** attempt, self.max_s)
        if self.jitter:
            d *= 1.0 + self.jitter * (2.0 * random.random() - 1.0)
        return max(0.0, d)

    def retries_left(self, attempt: int) -> bool:
        """True when attempt ``attempt`` (0-based) may be followed by
        another."""
        return attempt + 1 < self.max_attempts

    async def wait(self, delay_s: float,
                   stop: "asyncio.Event | None" = None) -> bool:
        """Sleep ``delay_s``, stop-aware. Returns False when ``stop``
        fired during the wait — the caller must abort, not retry."""
        if stop is None:
            await asyncio.sleep(delay_s)
            return True
        try:
            await asyncio.wait_for(stop.wait(), timeout=delay_s)
            return False
        except asyncio.TimeoutError:
            return True

    async def sleep(self, attempt: int,
                    stop: "asyncio.Event | None" = None) -> bool:
        """Backoff before the retry following attempt ``attempt``."""
        return await self.wait(self.delay_s(attempt), stop)


class Deadline:
    """Per-attempt time budget. Construct one per attempt; pass
    ``remaining()`` to whatever transport timeout the call takes (gRPC
    ``timeout=``, aiohttp ``ClientTimeout``)."""

    def __init__(self, timeout_s: float,
                 clock: Callable[[], float] = time.monotonic):
        self.timeout_s = timeout_s
        self._clock = clock
        self._t0 = clock()

    def remaining(self) -> float:
        return max(0.0, self.timeout_s - (self._clock() - self._t0))

    @property
    def expired(self) -> bool:
        return self.remaining() <= 0.0


BREAKER_CLOSED = 0
BREAKER_OPEN = 1
BREAKER_HALF_OPEN = 2

_STATE_NAMES = {BREAKER_CLOSED: "closed", BREAKER_OPEN: "open",
                BREAKER_HALF_OPEN: "half-open"}


class CircuitBreaker:
    """Three-state breaker: ``failure_threshold`` CONSECUTIVE failures
    open it; while open, ``allow()`` is False (callers fast-fail with
    BreakerOpen instead of stacking doomed retries); after
    ``reset_timeout_s`` it half-opens and admits ``half_open_max``
    probe calls — one probe success closes it, one probe failure
    re-opens it for another full reset window.

    State is exported as ``klogs_breaker_state{breaker=name}``
    (0=closed, 1=open, 2=half-open) when a registry is bound.
    """

    def __init__(self, name: str = "rpc", failure_threshold: int = 5,
                 reset_timeout_s: float = 10.0, half_open_max: int = 1,
                 clock: Callable[[], float] = time.monotonic,
                 registry=None):
        self.name = name
        self.failure_threshold = failure_threshold
        self.reset_timeout_s = reset_timeout_s
        self.half_open_max = half_open_max
        self._clock = clock
        self._state = BREAKER_CLOSED
        self._failures = 0
        self._opened_at = 0.0
        self._probes_in_flight = 0
        self._gauge = None
        self.bind_registry(registry)

    def bind_registry(self, registry) -> None:
        if registry is not None:
            self._gauge = registry.family("klogs_breaker_state").labels(
                breaker=self.name)
            self._gauge.set(self._state)

    @property
    def state(self) -> int:
        self._maybe_half_open()
        return self._state

    @property
    def state_name(self) -> str:
        return _STATE_NAMES[self.state]

    def _set_state(self, state: int) -> None:
        prev, self._state = self._state, state
        if self._gauge is not None:
            self._gauge.set(state)
        if state == BREAKER_OPEN and prev != BREAKER_OPEN:
            # Flight recorder: a breaker opening IS the "where did my
            # batch go" moment — arm a dump carrying the batch whose
            # failure tripped it (obs.trace; no-op when tracing is
            # off). Also annotate whatever batch is in flight.
            from klogs_tpu.obs.trace import TRACER, flight_trigger

            TRACER.event("breaker.open", breaker=self.name)
            flight_trigger("breaker-open", breaker=self.name)

    def _maybe_half_open(self) -> None:
        if (self._state == BREAKER_OPEN
                and self._clock() - self._opened_at >= self.reset_timeout_s):
            self._set_state(BREAKER_HALF_OPEN)
            self._probes_in_flight = 0

    def allow(self) -> bool:
        """May a call proceed right now? Half-open admits at most
        ``half_open_max`` concurrent probes (and counts this admission
        as one)."""
        self._maybe_half_open()
        if self._state == BREAKER_CLOSED:
            return True
        if self._state == BREAKER_HALF_OPEN:
            if self._probes_in_flight < self.half_open_max:
                self._probes_in_flight += 1
                return True
            return False
        return False

    def release_probe(self) -> None:
        """Give back a half-open probe slot consumed by ``allow()``
        when the call ended in neither success nor a health-relevant
        failure (non-retryable error, cancellation). Without this the
        slot would leak and the breaker would fast-fail forever."""
        if self._state == BREAKER_HALF_OPEN and self._probes_in_flight > 0:
            self._probes_in_flight -= 1

    def record_success(self) -> None:
        self._failures = 0
        if self._state != BREAKER_CLOSED:
            self._set_state(BREAKER_CLOSED)
        self._probes_in_flight = 0

    def record_failure(self) -> None:
        if self._state == BREAKER_HALF_OPEN:
            # The probe failed: back to a full reset window.
            self._set_state(BREAKER_OPEN)
            self._opened_at = self._clock()
            self._probes_in_flight = 0
            return
        self._failures += 1
        if (self._state == BREAKER_CLOSED
                and self._failures >= self.failure_threshold):
            self._set_state(BREAKER_OPEN)
            self._opened_at = self._clock()


async def retry_call(
    fn: "Callable[[Optional[Deadline]], Awaitable]",
    *,
    policy: RetryPolicy,
    retryable: "Callable[[BaseException], bool]",
    site: str = "call",
    describe: "str | None" = None,
    breaker: "CircuitBreaker | None" = None,
    deadline_s: "float | None" = None,
    stop: "asyncio.Event | None" = None,
    fault_point: "str | None" = None,
    fault_target: "str | None" = None,
    registry=None,
) -> object:
    """Run ``await fn(deadline)`` under the unified policy.

    Per attempt: breaker gate (open → BreakerOpen immediately, no
    doomed backoff stack), armed-fault fire (so chaos scripts exercise
    the REAL retry path), a fresh ``Deadline`` when ``deadline_s`` is
    set. A ``retryable(exc)`` failure (InjectedFault always counts)
    records a breaker failure and backs off stop-aware; exhaustion
    raises ``Unavailable`` chaining the last cause. Non-retryable
    exceptions propagate untouched and do NOT trip the breaker (an
    INVALID_ARGUMENT is the caller's bug, not the callee's health).

    ``site`` labels ``klogs_retry_attempts_total`` (keep it bounded by
    deployment shape: kube/fanout, rpc@endpoint); ``describe``
    (default: site) is the human prefix on Unavailable messages and may
    name the target. ``fault_target`` is the endpoint identity handed
    to ``FAULTS.fire`` so ``point@endpoint`` chaos rules can hit
    exactly this call site's server.
    """
    from klogs_tpu.resilience.faults import FAULTS, InjectedFault

    describe = describe if describe is not None else site
    retries = None
    if registry is not None:
        retries = registry.family("klogs_retry_attempts_total").labels(
            site=site)
    attempt = 0
    while True:
        if breaker is not None and not breaker.allow():
            raise BreakerOpen(
                f"{describe}: circuit breaker {breaker.name!r} is open "
                f"(retry after ~{breaker.reset_timeout_s:.0f}s)")
        try:
            if fault_point is not None and FAULTS.active:
                await FAULTS.fire(fault_point, fault_target)
            result = await fn(
                Deadline(deadline_s) if deadline_s is not None else None)
        except Exception as e:  # noqa: BLE001 - classified below
            if not (isinstance(e, InjectedFault) or retryable(e)):
                # Not a health signal — but a half-open probe slot was
                # consumed by allow() and neither record_* will run, so
                # give it back or the breaker fast-fails forever.
                if breaker is not None:
                    breaker.release_probe()
                raise
            if breaker is not None:
                breaker.record_failure()
            if not policy.retries_left(attempt):
                raise Unavailable(
                    f"{describe}: {e} (after {attempt + 1} attempt"
                    f"{'s' if attempt else ''})") from e
            if retries is not None:
                retries.inc()
            if not await policy.sleep(attempt, stop):
                raise Unavailable(f"{describe}: stopped during retry "
                                  f"backoff ({e})") from e
            attempt += 1
            continue
        except BaseException:
            # Cancellation mid-probe: release the half-open slot too.
            if breaker is not None:
                breaker.release_probe()
            raise
        if breaker is not None:
            breaker.record_success()
        return result
