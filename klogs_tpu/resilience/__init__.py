"""klogs_tpu.resilience — unified failure-handling policy core.

The reference has no failure handling to inherit (SURVEY.md §5): a
single transient gRPC failure or apiserver 5xx killed a pipeline. This
package is the one implementation every layer converges on:

- ``RetryPolicy``: exponential backoff with jitter, stop-event-aware
  sleeps (a Ctrl-C during backoff aborts the wait, never the process).
- ``Deadline``: per-attempt time budget (feeds gRPC ``timeout=``).
- ``CircuitBreaker``: three-state (closed → open → half-open) fast-fail
  gate with timed half-open probes.
- ``retry_call``: the guarded-call combinator tying the three together,
  reporting through ``obs`` (``klogs_retry_attempts_total``,
  ``klogs_breaker_state``).
- ``FaultInjector`` / ``FAULTS``: the chaos layer — registered fault
  points (``rpc.match``, ``kube.list_pods``, ``kube.log_stream``,
  ``sink.write``) wrapping the same call sites the policies guard,
  scripted from tests (``FAULTS.arm``) or the ``KLOGS_FAULTS`` env spec
  (grammar in docs/RESILIENCE.md).

Call-site map: ``service/client.py`` (per-RPC deadline + retry on
UNAVAILABLE/DEADLINE_EXCEEDED + breaker), ``cluster/kube.py``
(transient 5xx/ClientError retry on list/discovery), ``runtime/
fanout.py`` (reconnect backoff), ``runtime/sink.py`` (fail-fast sink
errors), ``filters/sink.py`` (``--on-filter-error`` degrade routing).
"""

from klogs_tpu.resilience.faults import (
    FAULTS,
    KNOWN_POINTS,
    FaultInjector,
    FaultSpecError,
    InjectedFault,
)
from klogs_tpu.resilience.policy import (
    BREAKER_CLOSED,
    BREAKER_HALF_OPEN,
    BREAKER_OPEN,
    BreakerOpen,
    CircuitBreaker,
    Deadline,
    RetryPolicy,
    Unavailable,
    retry_call,
)

__all__ = [
    "BREAKER_CLOSED", "BREAKER_HALF_OPEN", "BREAKER_OPEN", "BreakerOpen",
    "CircuitBreaker", "Deadline", "FAULTS", "FaultInjector",
    "FaultSpecError", "InjectedFault", "KNOWN_POINTS", "RetryPolicy",
    "Unavailable", "retry_call",
]
