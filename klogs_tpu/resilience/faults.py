"""FaultInjector: the chaos layer over the policy-guarded call sites.

Fault points are names wrapping exactly the calls the resilience
policies guard, so an armed fault exercises the REAL recovery path
(retry loop, breaker, degrade routing) rather than a test double:

- ``rpc.match`` / ``rpc.hello`` — filterd RPC issue (service/client.py)
- ``kube.list_pods``            — pod list/discovery (cluster/kube.py,
                                  cluster/fake.py)
- ``kube.log_stream``           — log-stream open (cluster/kube.py,
                                  cluster/fake.py)
- ``sink.write``                — sink write (runtime/sink.py)
- ``source.open``               — non-kube source stream open
                                  (sources/replay.py, archive.py,
                                  socket.py; the kube path keeps its
                                  ``kube.*`` points)
- ``source.read``               — non-kube source chunk read (same
                                  sites; surfaces as SourceError so
                                  the fanout reconnect/degrade path
                                  runs for real)
- ``resolver.watch``            — one membership poll of the endpoint
                                  resolver (service/resolver.py); a
                                  fired fault exercises the
                                  keep-current-fleet path
- ``tune.step``                 — one adaptive-controller decision
                                  (ops/tune.py AdaptiveController); a
                                  fired fault must skip the tick, never
                                  kill the control loop

Arming: tests call ``FAULTS.arm(point, times=..., exc=..., delay_s=...)``
with whatever exception type the site really raises; operators/CI use
the ``KLOGS_FAULTS`` spec string (see ``FaultInjector.load_spec`` for
the grammar), whose ``error`` faults raise ``InjectedFault`` — every
guarded site classifies InjectedFault as a transient failure, so an
env-armed script always drives the retry path.

Endpoint targeting: a point may carry an ``@target`` qualifier
(``rpc.match@127.0.0.1:50051:error*``) so a chaos script against a
sharded filterd fleet can kill EXACTLY one server while its siblings
stay healthy. Call sites that know their endpoint pass it to
``fire(point, target)``; a targeted rule fires only for its endpoint,
an untargeted rule fires for every endpoint (the pre-shard behavior).

Zero-overhead when idle: sites guard with ``if FAULTS.active`` so a
production run never pays an awaitable hop per chunk. Each firing
counts into ``klogs_faults_injected_total{point=...}`` when a registry
is bound, so a chaos run's /metrics scrape shows exactly which faults
fired how often next to the recovery counters they provoked.
"""

import asyncio
import re
from dataclasses import dataclass
from typing import Callable

KNOWN_POINTS = frozenset({
    "rpc.match", "rpc.hello", "kube.list_pods", "kube.log_stream",
    "sink.write", "source.open", "source.read", "resolver.watch",
    "tune.step",
})


class InjectedFault(Exception):
    """Raised by env-spec ``error`` faults. Guarded call sites treat it
    as a transient failure of the wrapped operation."""


class FaultSpecError(ValueError):
    """Malformed KLOGS_FAULTS spec string."""


@dataclass
class _Rule:
    times: "int | None"  # remaining firings; None = forever
    exc: "Callable[[], BaseException] | None"
    delay_s: float = 0.0
    target: "str | None" = None  # endpoint qualifier; None = any


# One clause: point[@target]:action[*times]; action = error |
# error(msg) | delay(seconds). *N = N firings, bare * = every firing,
# absent = once. The target (an endpoint like host:port) may itself
# contain ':' — the non-greedy match plus the literal action
# alternatives keep the parse unambiguous.
_CLAUSE = re.compile(
    r"^(?P<point>[a-z_.]+)(?:@(?P<target>.+?))?:(?P<action>error|delay)"
    r"(?:\((?P<arg>[^)]*)\))?(?P<star>\*(?P<times>\d+)?)?$")


def _valid_target(target: str) -> bool:
    """Endpoint shape a target must take to ever match a fire() site:
    HOST:PORT or a unix socket path — the same rule service/shard.py's
    parse_endpoints enforces on --remote entries."""
    if target.startswith("unix:"):
        return len(target) > len("unix:")
    host, sep, port = target.rpartition(":")
    return bool(sep and host and port.isdigit() and 0 < int(port) < 65536)


class FaultInjector:
    def __init__(self) -> None:
        self._rules: "dict[str, list[_Rule]]" = {}
        self.counts: "dict[str, int]" = {}
        self._registry = None

    @property
    def active(self) -> bool:
        return bool(self._rules)

    def bind_registry(self, registry) -> None:
        """Point firing counters at this run's obs registry (or None to
        detach — registries are per-run, the injector is per-process)."""
        self._registry = registry

    def arm(self, point: str, *, times: "int | None" = 1,
            exc: "BaseException | Callable[[], BaseException] | None" = None,
            delay_s: float = 0.0, target: "str | None" = None) -> None:
        """Script ``point`` to misbehave on its next ``times`` firings
        (None = every firing). ``exc`` may be an exception instance
        (re-raised as that instance each firing) or a zero-arg factory;
        None with a delay = latency-only fault. ``target`` restricts
        the rule to one endpoint (only sites that pass their endpoint
        to ``fire`` can match a targeted rule)."""
        factory = None
        if exc is not None:
            factory = exc if callable(exc) else (lambda e=exc: e)
        self._rules.setdefault(point, []).append(
            _Rule(times=times, exc=factory, delay_s=delay_s, target=target))

    def clear(self) -> None:
        self._rules.clear()
        self.counts.clear()

    def armed_targets(self) -> "set[str]":
        """Endpoint qualifiers of currently-armed targeted rules — the
        sharded pipeline cross-checks them against the real --remote
        list so a well-formed but absent endpoint (one typoed digit)
        warns instead of silently scripting nothing."""
        return {r.target for rules in self._rules.values()
                for r in rules if r.target is not None}

    def load_spec(self, spec: str) -> None:
        """Parse a ``KLOGS_FAULTS`` spec and REPLACE the current script
        (the spec describes the whole scenario). Grammar, clauses
        separated by ``;`` or ``,``::

            point:error            raise InjectedFault once
            point:error(msg)*3     raise InjectedFault(msg), 3 firings
            point:delay(0.5)*      sleep 0.5s before EVERY firing
            point@host:port:error* ... only at ONE endpoint (sharded
                                   --remote fleets; sites that know
                                   their endpoint pass it to fire)

        Unknown points are rejected — a typoed point would otherwise be
        a chaos script that silently tests nothing.
        """
        rules: "dict[str, list[_Rule]]" = {}
        for raw in re.split(r"[;,]", spec):
            clause = raw.strip()
            if not clause:
                continue
            m = _CLAUSE.match(clause)
            if m is None:
                raise FaultSpecError(
                    f"bad fault clause {clause!r} (want "
                    "point[@endpoint]:error[(msg)][*N] or "
                    "point[@endpoint]:delay(seconds)[*N])")
            point = m.group("point")
            if point not in KNOWN_POINTS:
                raise FaultSpecError(
                    f"unknown fault point {point!r} (known: "
                    f"{', '.join(sorted(KNOWN_POINTS))})")
            target = m.group("target")
            if target is not None and not _valid_target(target):
                # Same rationale as unknown points: a malformed target
                # can never equal any endpoint passed to fire(), so the
                # clause would be a chaos script that silently tests
                # nothing. (A well-formed but absent endpoint is warned
                # about against the real --remote list at pipeline
                # build.)
                raise FaultSpecError(
                    f"bad fault target {target!r} in {clause!r} (want "
                    "HOST:PORT or unix:/path.sock)")
            if m.group("star") is None:
                times: "int | None" = 1
            elif m.group("times") is not None:
                times = int(m.group("times"))
            else:
                times = None  # bare '*': every firing
            arg = m.group("arg")
            if m.group("action") == "delay":
                try:
                    delay = float(arg) if arg else 0.0
                except ValueError as e:
                    raise FaultSpecError(
                        f"bad delay seconds in {clause!r}") from e
                rules.setdefault(point, []).append(
                    _Rule(times=times, exc=None, delay_s=delay,
                          target=target))
            else:
                msg = arg or f"injected fault at {point}"
                rules.setdefault(point, []).append(_Rule(
                    times=times, exc=(lambda m=msg: InjectedFault(m)),
                    target=target))
        self._rules = rules
        self.counts.clear()

    async def fire(self, point: str, target: "str | None" = None) -> None:
        """Apply the next armed rule for ``point`` (no-op when none):
        count it, apply the delay, raise the scripted exception.
        ``target`` is the firing site's endpoint identity (when it has
        one): targeted rules fire only when it matches; untargeted
        rules fire regardless."""
        rules = self._rules.get(point)
        if not rules:
            return
        for i, rule in enumerate(rules):
            if rule.target is None or rule.target == target:
                break
        else:
            return  # only rules scripted for OTHER endpoints remain
        if rule.times is not None:
            rule.times -= 1
            if rule.times <= 0:
                rules.pop(i)
                if not rules:
                    del self._rules[point]
        # Targeted firings count under their qualified name so a chaos
        # scrape shows exactly which endpoint took the hit (endpoints
        # are deployment shape — cardinality-safe).
        key = point if rule.target is None else f"{point}@{rule.target}"
        self.counts[key] = self.counts.get(key, 0) + 1
        if self._registry is not None:
            self._registry.family("klogs_faults_injected_total").labels(
                point=key).inc()
        if rule.delay_s:
            await asyncio.sleep(rule.delay_s)
        if rule.exc is not None:
            raise rule.exc()


# The process-wide injector every guarded site consults. Tests arm and
# clear it; app.run_async loads KLOGS_FAULTS into it at startup.
FAULTS = FaultInjector()
