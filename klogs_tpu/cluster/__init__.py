from klogs_tpu.cluster.backend import (
    ClusterBackend,
    ClusterError,
    LogStream,
    NamespaceNotFound,
    StreamError,
)
from klogs_tpu.cluster.types import ContainerInfo, LogOptions, PodInfo

__all__ = [
    "ClusterBackend",
    "ClusterError",
    "LogStream",
    "NamespaceNotFound",
    "StreamError",
    "ContainerInfo",
    "LogOptions",
    "PodInfo",
]
