"""Cluster data model.

Minimal projections of the Kubernetes objects klogs touches:
pods with ready-state + containers (cmd/root.go:126-164,240-262) and
the server-side log options (v1.PodLogOptions subset used at
cmd/root.go:201-221: SinceSeconds, TailLines, Follow, Container).
"""

from dataclasses import dataclass, field


@dataclass(frozen=True)
class ContainerInfo:
    name: str
    init: bool = False  # init containers gated behind -i (cmd/root.go:240-251)


@dataclass
class PodInfo:
    name: str
    namespace: str
    labels: dict[str, str] = field(default_factory=dict)
    ready: bool = True  # PodReady==True condition (cmd/root.go:137-143)
    containers: list[ContainerInfo] = field(default_factory=list)
    init_containers: list[ContainerInfo] = field(default_factory=list)


@dataclass
class LogOptions:
    """Server-side log options; the backend (kubelet analog) applies them."""

    since_seconds: int | None = None
    tail_lines: int | None = None
    follow: bool = False
    container: str = ""
    # kubectl-parity options absent from the reference (its getLopOpts,
    # cmd/root.go:201-221, maps only since/tail/follow): logs of the
    # PREVIOUS terminated container instance (PodLogOptions.Previous),
    # server-side RFC3339 line timestamps (PodLogOptions.Timestamps),
    # and an absolute RFC3339 lower bound (PodLogOptions.SinceTime).
    previous: bool = False
    timestamps: bool = False
    since_time: str | None = None


def match_label_selector(labels: dict[str, str], selector: str) -> bool:
    """Kubernetes equality-based label selector: "k=v,k2=v2" (also k==v, k!=v).

    The reference passes the -l value verbatim as ListOptions.LabelSelector
    (cmd/root.go:380-381); the apiserver implements the matching. The fake
    backend needs its own implementation of the equality subset.
    """
    for term_ in selector.split(","):
        term_ = term_.strip()
        if not term_:
            continue
        if "!=" in term_:
            k, v = term_.split("!=", 1)
            if labels.get(k.strip()) == v.strip():
                return False
        elif "==" in term_:
            k, v = term_.split("==", 1)
            if labels.get(k.strip()) != v.strip():
                return False
        elif "=" in term_:
            k, v = term_.split("=", 1)
            if labels.get(k.strip()) != v.strip():
                return False
        else:  # bare key: existence
            if term_.startswith("!"):
                if term_[1:].strip() in labels:
                    return False
            elif term_ not in labels:
                return False
    return True
