"""Real Kubernetes backend (stdlib REST client). Placeholder until the
transport lands; --cluster fake is fully functional."""

from klogs_tpu.cluster.backend import ClusterBackend
from klogs_tpu.ui import term


class KubeBackend(ClusterBackend):
    @classmethod
    def from_kubeconfig(cls, kubeconfig: str) -> "KubeBackend":
        term.fatal(
            "the real Kubernetes backend is not implemented yet in this build; "
            "use --cluster fake"
        )
        raise AssertionError("unreachable")
