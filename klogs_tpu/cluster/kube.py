"""Real Kubernetes backend over the apiserver REST API (aiohttp).

The data path mirrors the reference's client-go usage without client-go:
- namespace Get/List       (configNamespace/listNamespaces,
                            /root/reference/cmd/root.go:90-123)
- pod List + labelSelector (listAllPods/findPodByLabel,
                            cmd/root.go:126-164,377-397)
- pod log GET, chunked,    (GetLogs(...).Stream, cmd/root.go:322-325;
  follow/since/tail         option mapping per getLopOpts,
                            cmd/root.go:201-221)

Concurrency bound: the aiohttp connector limit plays the role of the
reference's rest config Burst = 100 (cmd/root.go:80).

Ready filtering (PodReady condition, cmd/root.go:137-143) happens here
so the app layer is backend-agnostic; FakeCluster implements the same
contract for hermetic tests.
"""

import asyncio
from typing import AsyncIterator

import aiohttp

from klogs_tpu.cluster.backend import (
    ClusterBackend,
    ClusterError,
    LogStream,
    StreamError,
)
from klogs_tpu.cluster.kubeconfig import ClusterCreds, KubeconfigError, load_creds
from klogs_tpu.cluster.types import ContainerInfo, LogOptions, PodInfo
from klogs_tpu.resilience import FAULTS, InjectedFault, RetryPolicy
from klogs_tpu.ui import term

BURST = 100  # ≙ rest config Burst (cmd/root.go:80)
CHUNK_BYTES = 64 * 1024

# Control-plane retry (resilience subsystem): transient apiserver
# weather — 5xx, dropped connections, connect timeouts — on the
# list/discovery GETs is retried with jittered backoff before the
# friendly ClusterError surfaces. Short budget: these gate interactive
# startup, so worst-case added latency stays under ~2s.
DEFAULT_RETRY = RetryPolicy(max_attempts=4, base_s=0.25, max_s=2.0,
                            jitter=0.1)


class _TransientHTTPError(Exception):
    """Internal: a 5xx the retry loop may still fix; never escapes
    _get_json (converted to ClusterError on exhaustion)."""


class KubeLogStream(LogStream):
    def __init__(self, resp: aiohttp.ClientResponse):
        self._resp = resp

    def __aiter__(self) -> AsyncIterator[bytes]:
        return self._chunks()

    async def _chunks(self) -> AsyncIterator[bytes]:
        try:
            async for chunk in self._resp.content.iter_chunked(CHUNK_BYTES):
                yield chunk
        except (aiohttp.ClientError, asyncio.TimeoutError) as e:
            # TimeoutError is not a ClientError subclass but is the
            # same mid-stream "connection went away" UX; the fanout
            # layer owns the reconnect policy either way.
            raise StreamError(
                f"log stream failed: {str(e) or 'read timed out'}") from e

    async def close(self) -> None:
        self._resp.close()


class KubeBackend(ClusterBackend):
    def __init__(self, creds: ClusterCreds,
                 retry: "RetryPolicy | None" = None, registry=None):
        self._creds = creds
        self._retry = retry if retry is not None else DEFAULT_RETRY
        self._retries_metric = None
        self.bind_registry(registry)
        # Auth is resolved PER REQUEST (not baked into session headers):
        # exec-plugin tokens rotate (~1h on GKE/EKS), and a --follow run
        # outliving its token would otherwise 401 until restart. The
        # provider caches until expiry, so the per-request call is a
        # dict lookup in the common case (client-go transport behavior,
        # /root/reference/cmd/root.go:76-86).
        self._session = aiohttp.ClientSession(
            base_url=creds.server,
            connector=aiohttp.TCPConnector(
                limit=BURST, ssl=creds.ssl_context
            ),
            # client-go honors HTTP(S)_PROXY/NO_PROXY; trust_env is
            # aiohttp's equivalent (also reads ~/.netrc, harmless here).
            trust_env=True,
        )

    async def _auth_headers(self, force_refresh: bool = False) -> dict:
        if self._creds.token_provider is None:
            token = self._creds.token
        else:
            # The exec helper is a blocking subprocess (up to 60s on a
            # cold cloud-auth path); running it on the event loop would
            # stall every stream, so it goes through a worker thread.
            # Cache hits return in microseconds either way.
            token = await asyncio.to_thread(
                self._creds.current_token, force_refresh)
        return {"Authorization": f"Bearer {token}"} if token else {}

    @classmethod
    def from_kubeconfig(cls, kubeconfig: str) -> "KubeBackend":
        try:
            return cls(load_creds(kubeconfig))
        except KubeconfigError as e:
            # ≙ pterm.Fatal on bad kubeconfig (cmd/root.go:78).
            term.fatal("%s", e)
            raise AssertionError("unreachable")

    def current_context(self) -> tuple[str, str]:
        return self._creds.context_name, self._creds.namespace

    def bind_registry(self, registry) -> None:
        """Late obs wiring (the backend exists before the per-run
        registry does): point the kube retry counter at this run."""
        if registry is not None:
            self._retries_metric = registry.family(
                "klogs_retry_attempts_total").labels(site="kube")

    async def _get_json(self, path: str, params: dict | None = None,
                        fault_point: "str | None" = None):
        """Control-plane GET. Transient failures (5xx, ClientError,
        connect timeout, injected faults) are retried under the shared
        RetryPolicy; what survives surfaces as ClusterError with a
        one-line human message (the app boundary prints it and exits 1,
        ≙ the reference's pterm panic, cmd/root.go:110,130) instead of a
        raw aiohttp traceback. The one-shot 401 token refresh (client-go
        transport parity) rides INSIDE the loop and consumes no retry
        budget."""
        attempt = 0
        refreshed = False  # the one-shot forced token refresh happened
        force = False      # force the provider on the NEXT header fetch
        while True:
            try:
                if fault_point is not None and FAULTS.active:
                    await FAULTS.fire(fault_point)
                async with self._session.get(
                    path, params=params or {},
                    headers=await self._auth_headers(force_refresh=force),
                ) as resp:
                    force = False
                    if resp.status == 404:
                        return None
                    if (resp.status == 401 and not refreshed
                            and self._creds.token_provider is not None):
                        # Token rejected before its cached expiry (e.g.
                        # revoked/rotated server-side): force the helper
                        # once and retry, like client-go's transport.
                        refreshed = True
                        force = True
                        continue
                    if resp.status in (401, 403):
                        word = ("Unauthorized" if resp.status == 401
                                else "Forbidden")
                        raise ClusterError(
                            f"{word} (HTTP {resp.status}) from "
                            f"{self._creds.server}{path} — check your "
                            f"kubeconfig credentials (context "
                            f"{self._creds.context_name!r})"
                        )
                    if resp.status >= 500:
                        # Transient apiserver weather (client-go retries
                        # these at the transport layer too).
                        body = (await resp.text())[:200]
                        raise _TransientHTTPError(
                            f"apiserver error HTTP {resp.status} on "
                            f"{path}: {body}")
                    if resp.status >= 400:
                        body = (await resp.text())[:200]
                        raise ClusterError(
                            f"apiserver error HTTP {resp.status} on {path}: "
                            f"{body}"
                        )
                    return await resp.json()
            except (aiohttp.ClientError, asyncio.TimeoutError,
                    InjectedFault, _TransientHTTPError) as e:
                # asyncio.TimeoutError: aiohttp's total-timeout is not a
                # ClientError subclass but is the same "can't reach it"
                # UX. InjectedFault: chaos scripts drive this exact
                # retry path (docs/RESILIENCE.md).
                if not self._retry.retries_left(attempt):
                    if isinstance(e, _TransientHTTPError):
                        raise ClusterError(
                            f"{e} (after {attempt + 1} attempts)") from e
                    raise ClusterError(
                        f"cannot reach apiserver {self._creds.server}: "
                        f"{str(e) or 'request timed out'} "
                        f"(after {attempt + 1} attempts)"
                    ) from e
                if self._retries_metric is not None:
                    self._retries_metric.inc()
                await self._retry.sleep(attempt)
                attempt += 1

    async def namespace_exists(self, namespace: str) -> bool:
        return await self._get_json(f"/api/v1/namespaces/{namespace}") is not None

    async def list_namespaces(self) -> list[str]:
        data = await self._get_json("/api/v1/namespaces")
        return [item["metadata"]["name"] for item in data.get("items", [])]

    async def list_pods(
        self, namespace: str, label_selector: str | None = None
    ) -> list[PodInfo]:
        params = {"labelSelector": label_selector} if label_selector else None
        data = await self._get_json(
            f"/api/v1/namespaces/{namespace}/pods", params,
            fault_point="kube.list_pods",
        )
        if data is None:
            return []
        return [_pod_info(item, namespace) for item in data.get("items", [])]

    async def endpoint_addresses(
        self, namespace: str, name: str
    ) -> "list[tuple[str, int | None]]":
        """Ready (ip, port) pairs from the named Endpoints object — the
        kube membership resolver's data source (service/resolver.py).
        Rides _get_json, so it inherits the shared RetryPolicy, the
        one-shot 401 token refresh, and the friendly ClusterError
        boundary. The ``resolver.watch`` fault point fires one layer
        up (resolver.py wraps every poll uniformly across kinds), so
        chaos scripts hit this path without double-counting. A missing
        Endpoints object resolves to an empty list (the service may
        not exist YET during a rollout — membership policy, including
        the refuse-to-empty guard, lives client-side in
        shard.apply_membership). Ports: one advertised port per subset
        is attached to its addresses; an ambiguous multi-port subset
        yields None (the --resolver spec must pin a port)."""
        data = await self._get_json(
            f"/api/v1/namespaces/{namespace}/endpoints/{name}")
        if data is None:
            return []
        out: "list[tuple[str, int | None]]" = []
        for subset in data.get("subsets") or []:
            ports = [p.get("port") for p in subset.get("ports") or []
                     if isinstance(p.get("port"), int)]
            port = ports[0] if len(ports) == 1 else None
            for addr in subset.get("addresses") or []:
                ip = addr.get("ip")
                if ip:
                    out.append((str(ip), port))
        return out

    async def open_log_stream(
        self, namespace: str, pod: str, opts: LogOptions
    ) -> LogStream:
        params: dict = {"container": opts.container}
        if opts.follow:
            params["follow"] = "true"
        if opts.since_seconds is not None:
            params["sinceSeconds"] = str(opts.since_seconds)
        if opts.tail_lines is not None:
            params["tailLines"] = str(opts.tail_lines)
        if opts.previous:
            params["previous"] = "true"
        if opts.timestamps:
            params["timestamps"] = "true"
        if opts.since_time is not None:
            params["sinceTime"] = opts.since_time
        try:
            if FAULTS.active:
                await FAULTS.fire("kube.log_stream")
            resp = None
            for attempt in (0, 1):
                resp = await self._session.get(
                    f"/api/v1/namespaces/{namespace}/pods/{pod}/log",
                    params=params,
                    headers=await self._auth_headers(force_refresh=attempt > 0),
                    timeout=aiohttp.ClientTimeout(total=None, sock_connect=30),
                )
                if (resp.status == 401 and attempt == 0
                        and self._creds.token_provider is not None):
                    # Mid-run token rotation: a reconnecting follow
                    # stream must not burn its backoff budget on 401s.
                    resp.close()
                    continue
                break
            if resp.status != 200:
                body = (await resp.text())[:300]
                resp.close()
                raise StreamError(
                    f"GET log for {pod}/{opts.container}: "
                    f"HTTP {resp.status}: {body}"
                )
        except (aiohttp.ClientError, asyncio.TimeoutError,
                InjectedFault) as e:
            # asyncio.TimeoutError: the sock_connect=30 bound above is
            # NOT a ClientError — before the resilience work a connect
            # timeout escaped as a raw traceback instead of the
            # StreamError the fanout reconnect policy handles.
            raise StreamError(
                f"open log stream {pod}/{opts.container}: "
                f"{str(e) or 'connect timed out'}") from e
        return KubeLogStream(resp)

    async def close(self) -> None:
        await self._session.close()


def _pod_info(item: dict, namespace: str) -> PodInfo:
    meta = item.get("metadata", {})
    spec = item.get("spec", {})
    status = item.get("status", {})
    ready = any(
        c.get("type") == "Ready" and c.get("status") == "True"
        for c in status.get("conditions", [])
    )  # ≙ PodReady scan (cmd/root.go:137-143)
    return PodInfo(
        name=meta.get("name", ""),
        namespace=namespace,
        labels=meta.get("labels", {}) or {},
        ready=ready,
        containers=[
            ContainerInfo(c["name"]) for c in spec.get("containers", [])
        ],
        init_containers=[
            ContainerInfo(c["name"], init=True)
            for c in spec.get("initContainers", [])
        ],
    )
