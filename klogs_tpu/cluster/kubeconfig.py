"""kubeconfig loading and TLS/auth resolution.

The subset klogs needs (configClient, /root/reference/cmd/root.go:69-87
and getCurrentNamespace, cmd/root.go:185-198): resolve the file(s)
($KUBECONFIG — a path LIST merged with client-go semantics, explicit
--kubeconfig, else ~/.kube/config), pick the current context, and
produce everything required to talk to its cluster: server URL, CA
trust, client-cert/token auth, and the context's default namespace.

Supported auth: client certificates (inline *-data or file paths),
bearer tokens (inline or tokenFile), and exec-plugin credential helpers
(the client-go mode GKE/EKS/AKS default kubeconfigs use, reference gets
it via clientcmd at cmd/root.go:76): the helper command runs
non-interactively, its ExecCredential JSON yields a token or client
cert, and the result is cached until its expirationTimestamp.

When NO kubeconfig file exists, credentials fall back to the in-cluster
service account (rest.InClusterConfig analog) — the deployment mode of
a collector running as a pod. A kubeconfig that exists but is malformed
stays a hard error, as in client-go.
"""

import base64
import json
import os
import ssl
import subprocess
import tempfile
from dataclasses import dataclass
from datetime import datetime, timezone

import yaml


class KubeconfigError(RuntimeError):
    pass


class KubeconfigMissing(KubeconfigError):
    """No kubeconfig file exists at any candidate path — the only case
    that falls through to in-cluster credentials (a file that exists
    but is malformed stays a hard error, as in client-go)."""


@dataclass
class ClusterCreds:
    context_name: str
    namespace: str
    server: str  # https://host:port
    ssl_context: ssl.SSLContext
    token: str | None  # Authorization: Bearer
    # Re-resolves the bearer token on demand (exec-plugin helpers cache
    # until expirationTimestamp, so calling per request is cheap and a
    # --follow run outliving the token picks up the rotation — client-go
    # behavior, /root/reference/cmd/root.go:76-86). None for static auth.
    token_provider: "callable | None" = None

    def current_token(self, force: bool = False) -> str | None:
        """The bearer token to use NOW. ``force`` bypasses the helper's
        expiry cache (after a 401 on a supposedly-fresh token)."""
        if self.token_provider is not None:
            try:
                tok = self.token_provider(force=force)
            except KubeconfigError as e:
                # Keep the last-known token (it may still work), but
                # surface the helper's real failure — a later 401 would
                # otherwise misdiagnose as "check your kubeconfig".
                from klogs_tpu.ui import term

                term.warning("credential helper failed: %s", e)
                return self.token
            if tok:
                self.token = tok  # last-known-good for helper hiccups
                return tok
        return self.token


def kubeconfig_paths() -> list[str]:
    """$KUBECONFIG as a pathsep-separated list (client-go semantics),
    else the single default ~/.kube/config."""
    env = os.environ.get("KUBECONFIG")
    if env:
        return [p for p in env.split(os.pathsep) if p]
    return [os.path.join(os.path.expanduser("~"), ".kube", "config")]


def _merge_configs(paths: list[str]) -> dict:
    """client-go merge (clientcmd.Load): per-name map entries and the
    current-context scalar each come from the FIRST file that defines
    them; later files never override. Missing files are skipped; a file
    that exists but fails to parse is an error; all-missing is an
    error."""
    merged: dict = {"clusters": [], "contexts": [], "users": [],
                    "current-context": ""}
    seen: dict[str, set] = {"clusters": set(), "contexts": set(),
                            "users": set()}
    loaded_any = False
    for path in paths:
        try:
            with open(path) as f:
                cfg = yaml.safe_load(f)
        except FileNotFoundError:
            continue
        except OSError as e:
            raise KubeconfigError(f"cannot read kubeconfig {path}: {e}") from e
        except yaml.YAMLError as e:
            raise KubeconfigError(f"kubeconfig {path} is not valid YAML: {e}") from e
        if cfg is None:
            # Empty file (or only comments): client-go treats it as an
            # empty config and proceeds with the rest of the list.
            loaded_any = True
            continue
        if not isinstance(cfg, dict):
            raise KubeconfigError(f"kubeconfig {path} is not a mapping")
        loaded_any = True
        for section in ("clusters", "contexts", "users"):
            for item in cfg.get(section) or []:
                name = item.get("name")
                if name and name not in seen[section]:
                    seen[section].add(name)
                    merged[section].append(item)
        if not merged["current-context"] and cfg.get("current-context"):
            merged["current-context"] = cfg["current-context"]
    if not loaded_any:
        raise KubeconfigMissing(
            f"no kubeconfig found at {os.pathsep.join(paths)}"
        )
    return merged


def _write_temp(data: bytes, label: str) -> str:
    fd, tmp = tempfile.mkstemp(prefix=f"klogs-{label}-")
    with os.fdopen(fd, "wb") as f:
        f.write(data)
    return tmp


def _materialize(inline_b64: str | None, path: str | None, label: str,
                 tmps: list | None = None) -> str | None:
    """Inline base64 data wins over file paths (kubectl precedence);
    inline data lands in a private temp file for ssl's file-based API.
    Temp paths are appended to ``tmps`` so the caller can delete them
    once ssl has read them (the ssl file APIs read eagerly) — inline key
    material must not linger in /tmp."""
    if inline_b64:
        tmp = _write_temp(base64.b64decode(inline_b64), label)
        if tmps is not None:
            tmps.append(tmp)
        return tmp
    return path


# ExecCredential cache: helper runs are slow (they often hit a cloud
# metadata/token endpoint), so results are reused until their
# expirationTimestamp. Keyed by the full exec spec.
_EXEC_CACHE: dict[str, tuple[datetime | None, dict]] = {}

_EXEC_API_VERSIONS = (
    "client.authentication.k8s.io/v1",
    "client.authentication.k8s.io/v1beta1",
    "client.authentication.k8s.io/v1alpha1",
)

_EXEC_TIMEOUT_S = 60


def _parse_rfc3339(ts: str) -> datetime:
    """Expiry timestamp parsing, erring toward re-running the helper:
    tz-naive values are assumed UTC (a naive/aware comparison would
    TypeError), and an unparseable value counts as already expired
    (caching a broken-expiry credential forever would serve stale
    tokens)."""
    try:
        dt = datetime.fromisoformat(ts.replace("Z", "+00:00"))
    except ValueError:
        return datetime.min.replace(tzinfo=timezone.utc)
    if dt.tzinfo is None:
        dt = dt.replace(tzinfo=timezone.utc)
    return dt


def exec_credential(spec: dict, force: bool = False) -> dict:
    """Run a kubeconfig exec credential helper and return the
    ExecCredential ``status`` dict (token and/or client cert). Results
    cache until status.expirationTimestamp (no expiry -> cached for the
    process lifetime, per client-go). ``force`` drops the cache entry
    first — used after the apiserver rejects a cached token (401) that
    the expiry said was still good. Never prompts: the helper runs with
    interactive=false."""
    key = json.dumps(spec, sort_keys=True, default=str)
    if force:
        _EXEC_CACHE.pop(key, None)
    hit = _EXEC_CACHE.get(key)
    if hit is not None:
        expiry, status = hit
        if expiry is None or datetime.now(timezone.utc) < expiry:
            return status

    command = spec.get("command")
    if not command:
        raise KubeconfigError("kubeconfig exec entry has no command")
    api_version = spec.get("apiVersion") or _EXEC_API_VERSIONS[1]
    if api_version not in _EXEC_API_VERSIONS:
        raise KubeconfigError(
            f"unsupported exec plugin apiVersion {api_version!r}")
    argv = [command] + list(spec.get("args") or [])
    env = dict(os.environ)
    for pair in spec.get("env") or []:
        if pair.get("name"):
            env[pair["name"]] = pair.get("value", "")
    env["KUBERNETES_EXEC_INFO"] = json.dumps({
        "apiVersion": api_version,
        "kind": "ExecCredential",
        "spec": {"interactive": False},
    })
    try:
        res = subprocess.run(argv, capture_output=True, text=True, env=env,
                             timeout=_EXEC_TIMEOUT_S)
    except FileNotFoundError as e:
        raise KubeconfigError(
            f"exec credential helper {command!r} not found: {e}") from e
    except subprocess.TimeoutExpired as e:
        raise KubeconfigError(
            f"exec credential helper {command!r} timed out") from e
    if res.returncode != 0:
        tail = (res.stderr or "").strip().splitlines()[-3:]
        raise KubeconfigError(
            f"exec credential helper {command!r} failed "
            f"(rc={res.returncode}): {' '.join(tail)}")
    try:
        cred = json.loads(res.stdout)
    except ValueError as e:
        raise KubeconfigError(
            f"exec credential helper {command!r} printed invalid JSON") from e
    status = cred.get("status") or {}
    if not (status.get("token")
            or (status.get("clientCertificateData")
                and status.get("clientKeyData"))):
        raise KubeconfigError(
            f"exec credential helper {command!r} returned neither a token "
            "nor a client certificate")
    expiry = (_parse_rfc3339(status["expirationTimestamp"])
              if status.get("expirationTimestamp") else None)
    _EXEC_CACHE[key] = (expiry, status)
    return status


# Kubelet-mounted service-account directory (rest.InClusterConfig).
SA_DIR = "/var/run/secrets/kubernetes.io/serviceaccount"


def in_cluster_creds() -> "ClusterCreds | None":
    """client-go rest.InClusterConfig analog: when running inside a pod,
    the kubelet mounts a service-account token + CA and the apiserver
    address is in the environment. Returns None when not in a pod.

    The token is re-read from the mounted file on every refresh: bound
    service-account tokens rotate (~1h) and the kubelet updates the
    file, so a long --follow survives rotation (client-go re-reads
    periodically for the same reason)."""
    # client-go ErrNotInCluster semantics: BOTH env vars must be
    # non-empty (a set-but-empty value means "not in a pod", never a
    # default port).
    host = os.environ.get("KUBERNETES_SERVICE_HOST")
    port = os.environ.get("KUBERNETES_SERVICE_PORT")
    token_path = os.path.join(SA_DIR, "token")
    ca_path = os.path.join(SA_DIR, "ca.crt")
    if not host or not port or not os.path.exists(token_path):
        return None
    if ":" in host and not host.startswith("["):
        host = f"[{host}]"  # IPv6 (client-go: net.JoinHostPort)
    try:
        ssl_ctx = (ssl.create_default_context(cafile=ca_path)
                   if os.path.exists(ca_path)
                   else ssl.create_default_context())
    except ssl.SSLError as e:
        # Keep the module's error contract: a corrupt mounted CA must
        # surface as the friendly fatal, not a raw traceback.
        raise KubeconfigError(
            f"in-cluster CA bundle {ca_path} is unusable: {e}") from e
    try:
        with open(os.path.join(SA_DIR, "namespace")) as f:
            namespace = f.read().strip() or "default"
    except OSError:
        namespace = "default"

    def provider(force: bool = False) -> "str | None":
        try:
            with open(token_path) as f:
                return f.read().strip() or None
        except OSError:
            return None

    return ClusterCreds(
        context_name="in-cluster",
        namespace=namespace,
        server=f"https://{host}:{port}",
        ssl_context=ssl_ctx,
        token=provider(),
        token_provider=provider,
    )


def load_creds(kubeconfig: str = "") -> ClusterCreds:
    if not kubeconfig:
        # client-go fallback order: kubeconfig file(s) first, then the
        # in-cluster service account when no file exists (the common
        # case for a collector running as a pod).
        try:
            return _file_creds(kubeconfig_paths())
        except KubeconfigMissing:
            creds = in_cluster_creds()
            if creds is not None:
                return creds
            raise
    return _file_creds([kubeconfig])


def _file_creds(paths: list[str]) -> ClusterCreds:
    cfg = _merge_configs(paths)
    path_desc = os.pathsep.join(paths)

    ctx_name = cfg.get("current-context") or ""
    contexts = {c["name"]: c.get("context", {}) for c in cfg.get("contexts", [])}
    if not ctx_name or ctx_name not in contexts:
        raise KubeconfigError(
            f"kubeconfig {path_desc} has no usable current-context ({ctx_name!r})"
        )
    ctx = contexts[ctx_name]
    namespace = ctx.get("namespace") or "default"

    clusters = {c["name"]: c.get("cluster", {}) for c in cfg.get("clusters", [])}
    users = {u["name"]: u.get("user", {}) for u in cfg.get("users", [])}
    cluster = clusters.get(ctx.get("cluster", ""))
    if cluster is None:
        raise KubeconfigError(f"context {ctx_name!r} names unknown cluster")
    user = users.get(ctx.get("user", ""), {})

    server = cluster.get("server")
    if not server:
        raise KubeconfigError(f"cluster for context {ctx_name!r} has no server")

    tmps: list[str] = []
    try:
        if cluster.get("insecure-skip-tls-verify"):
            ssl_ctx = ssl._create_unverified_context()
        else:
            ca = _materialize(cluster.get("certificate-authority-data"),
                              cluster.get("certificate-authority"), "ca", tmps)
            ssl_ctx = ssl.create_default_context(cafile=ca)

        cert = _materialize(user.get("client-certificate-data"),
                            user.get("client-certificate"), "cert", tmps)
        key = _materialize(user.get("client-key-data"),
                           user.get("client-key"), "key", tmps)
        if cert and key:
            ssl_ctx.load_cert_chain(cert, key)
    finally:
        for p in tmps:
            try:
                os.unlink(p)
            except OSError:
                pass

    token = user.get("token")
    token_provider = None
    if not token and user.get("tokenFile"):
        with open(user["tokenFile"]) as f:
            token = f.read().strip()
    if not token and not (cert and key) and user.get("exec"):
        status = exec_credential(user["exec"])
        token = status.get("token")
        if not token:
            # ExecCredential cert/key are PEM text, not base64.
            ec = _write_temp(status["clientCertificateData"].encode(),
                             "exec-cert")
            ek = _write_temp(status["clientKeyData"].encode(), "exec-key")
            try:
                ssl_ctx.load_cert_chain(ec, ek)
            finally:
                # load_cert_chain reads eagerly; the key material must
                # not linger in /tmp.
                for p in (ec, ek):
                    try:
                        os.unlink(p)
                    except OSError:
                        pass
        else:
            # Token-mode helper: re-run (cache honors expiry) so long
            # follows survive token rotation.
            spec = user["exec"]
            token_provider = (
                lambda force=False: exec_credential(spec, force=force)
                .get("token"))

    return ClusterCreds(
        context_name=ctx_name,
        namespace=namespace,
        server=server.rstrip("/"),
        ssl_context=ssl_ctx,
        token=token,
        token_provider=token_provider,
    )
