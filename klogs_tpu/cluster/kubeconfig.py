"""kubeconfig loading and TLS/auth resolution.

The subset klogs needs (configClient, /root/reference/cmd/root.go:69-87
and getCurrentNamespace, cmd/root.go:185-198): resolve the file
($KUBECONFIG, explicit --kubeconfig, else ~/.kube/config), pick the
current context, and produce everything required to talk to its
cluster: server URL, CA trust, client-cert/token auth, and the
context's default namespace.

Supported auth: client certificates (inline *-data or file paths) and
bearer tokens (inline or tokenFile). Exec-plugin credential helpers are
not supported in this build — a clear error tells the user to mint a
token instead.
"""

import base64
import os
import ssl
import tempfile
from dataclasses import dataclass

import yaml


class KubeconfigError(RuntimeError):
    pass


@dataclass
class ClusterCreds:
    context_name: str
    namespace: str
    server: str  # https://host:port
    ssl_context: ssl.SSLContext
    token: str | None  # Authorization: Bearer


def default_kubeconfig_path() -> str:
    env = os.environ.get("KUBECONFIG")
    if env:
        return env.split(os.pathsep)[0]
    return os.path.join(os.path.expanduser("~"), ".kube", "config")


def _materialize(inline_b64: str | None, path: str | None, label: str) -> str | None:
    """Inline base64 data wins over file paths (kubectl precedence);
    inline data lands in a private temp file for ssl's file-based API."""
    if inline_b64:
        fd, tmp = tempfile.mkstemp(prefix=f"klogs-{label}-")
        with os.fdopen(fd, "wb") as f:
            f.write(base64.b64decode(inline_b64))
        return tmp
    return path


def load_creds(kubeconfig: str = "") -> ClusterCreds:
    path = kubeconfig or default_kubeconfig_path()
    try:
        with open(path) as f:
            cfg = yaml.safe_load(f)
    except OSError as e:
        raise KubeconfigError(f"cannot read kubeconfig {path}: {e}") from e
    if not isinstance(cfg, dict):
        raise KubeconfigError(f"kubeconfig {path} is not a mapping")

    ctx_name = cfg.get("current-context") or ""
    contexts = {c["name"]: c.get("context", {}) for c in cfg.get("contexts", [])}
    if not ctx_name or ctx_name not in contexts:
        raise KubeconfigError(
            f"kubeconfig {path} has no usable current-context ({ctx_name!r})"
        )
    ctx = contexts[ctx_name]
    namespace = ctx.get("namespace") or "default"

    clusters = {c["name"]: c.get("cluster", {}) for c in cfg.get("clusters", [])}
    users = {u["name"]: u.get("user", {}) for u in cfg.get("users", [])}
    cluster = clusters.get(ctx.get("cluster", ""))
    if cluster is None:
        raise KubeconfigError(f"context {ctx_name!r} names unknown cluster")
    user = users.get(ctx.get("user", ""), {})

    server = cluster.get("server")
    if not server:
        raise KubeconfigError(f"cluster for context {ctx_name!r} has no server")

    if cluster.get("insecure-skip-tls-verify"):
        ssl_ctx = ssl._create_unverified_context()
    else:
        ca = _materialize(cluster.get("certificate-authority-data"),
                          cluster.get("certificate-authority"), "ca")
        ssl_ctx = ssl.create_default_context(cafile=ca)

    cert = _materialize(user.get("client-certificate-data"),
                        user.get("client-certificate"), "cert")
    key = _materialize(user.get("client-key-data"),
                       user.get("client-key"), "key")
    if cert and key:
        ssl_ctx.load_cert_chain(cert, key)

    token = user.get("token")
    if not token and user.get("tokenFile"):
        with open(user["tokenFile"]) as f:
            token = f.read().strip()
    if not token and not (cert and key) and user.get("exec"):
        raise KubeconfigError(
            "exec-plugin credential helpers are not supported; create a "
            "ServiceAccount token (kubectl create token ...) and put it in "
            "the kubeconfig user as `token:`"
        )

    return ClusterCreds(
        context_name=ctx_name,
        namespace=namespace,
        server=server.rstrip("/"),
        ssl_context=ssl_ctx,
        token=token,
    )
