"""FakeCluster: hermetic, deterministic in-memory cluster backend.

The reference has no test double at all — every cluster-touching path is
untested (SURVEY.md §4). This fake makes the whole pipeline testable and
benchmarkable without a cluster: synthetic namespaces/pods/containers,
deterministic log lines with timestamps, server-side since/tail/follow
semantics mirroring kubelet behavior, controllable stream chunking, and
fault injection (open failure, mid-stream cut, slow streams).
"""

import asyncio
import time
from dataclasses import dataclass, field
from typing import AsyncIterator, Callable

from klogs_tpu.cluster.backend import (
    ClusterBackend,
    ClusterError,
    LogStream,
    StreamError,
)
from klogs_tpu.cluster.types import (
    ContainerInfo,
    LogOptions,
    PodInfo,
    match_label_selector,
)
from klogs_tpu.resilience import FAULTS, InjectedFault

LEVELS = ("INFO", "DEBUG", "WARN", "ERROR")


def synthetic_line(pod: str, container: str, seq: int, ts: float) -> bytes:
    """One deterministic log line. Level cycles so a fixed fraction (1/4
    each) matches typical test patterns; a few structured fields give
    regexes something realistic to bite on."""
    level = LEVELS[seq % len(LEVELS)]
    tstr = time.strftime("%Y-%m-%dT%H:%M:%S", time.gmtime(ts))
    return (
        f"{tstr} {level} pod={pod} container={container} seq={seq} "
        f"latency={(seq * 7) % 500}ms code={200 + (seq % 5) * 100} "
        f"msg=\"request {'failed' if level == 'ERROR' else 'handled'} "
        f"path=/api/v{seq % 3}/items\"\n"
    ).encode()


@dataclass
class Faults:
    """Per-container fault injection for failure-path tests."""

    fail_open: bool = False  # raise StreamError from open_log_stream
    cut_after_lines: int | None = None  # clean EOF mid-history (premature end)
    error_after_lines: int | None = None  # raise StreamError mid-stream
    chunk_delay_s: float = 0.0  # slow stream


@dataclass
class FakeContainer:
    name: str
    init: bool = False
    # Historical lines as (unix_ts, line_bytes); ts ascending.
    lines: list[tuple[float, bytes]] = field(default_factory=list)
    # Follow-mode generation: new line every interval_s until closed.
    follow_interval_s: float = 0.01
    faults: Faults = field(default_factory=Faults)
    # Next sequence number for follow-mode generation.
    next_seq: int = 0
    # History of the PREVIOUS terminated instance (PodLogOptions.
    # Previous); empty = no previous instance, matching the apiserver's
    # 400 on `previous=true` for a never-restarted container.
    previous_lines: list[tuple[float, bytes]] = field(default_factory=list)


@dataclass
class FakePod:
    info: PodInfo
    containers: dict[str, FakeContainer] = field(default_factory=dict)


class FakeLogStream(LogStream):
    """Chunked byte stream over selected + live-generated lines.

    Chunk boundaries intentionally do NOT align with line boundaries
    (chunk_size split), matching HTTP chunked transfer from the kubelet
    (cmd/root.go:325) and exercising the line framer.
    """

    def __init__(
        self,
        container: FakeContainer,
        pod_name: str,
        opts: LogOptions,
        clock: Callable[[], float],
        chunk_size: int,
    ):
        self._c = container
        self._pod = pod_name
        self._opts = opts
        self._clock = clock
        self._chunk_size = chunk_size
        # Lazy: on Py3.10 asyncio primitives bind the loop alive at
        # construction, and streams may be built before the run loop.
        self._closed: "asyncio.Event | None" = None

    def _closed_ev(self) -> asyncio.Event:
        if self._closed is None:
            self._closed = asyncio.Event()
        return self._closed

    async def close(self) -> None:
        self._closed_ev().set()

    def _since_time_cutoff(self) -> float | None:
        """PodLogOptions.SinceTime as an epoch cutoff (RFC3339 input;
        validated tz-aware upstream)."""
        if self._opts.since_time is None:
            return None
        from datetime import datetime

        return datetime.fromisoformat(
            self._opts.since_time.replace("Z", "+00:00")).timestamp()

    def _stamp(self, ts: float, ln: bytes) -> bytes:
        """PodLogOptions.Timestamps: kubelet prefixes each line with an
        RFC3339Nano timestamp and one space."""
        if not self._opts.timestamps:
            return ln
        frac = int((ts % 1) * 1e9)
        stamp = time.strftime("%Y-%m-%dT%H:%M:%S", time.gmtime(ts))
        return f"{stamp}.{frac:09d}Z ".encode() + ln

    def _select_history(self) -> list[bytes]:
        # previous=true reads the terminated prior instance's history
        # (PodLogOptions.Previous); a previous stream never follows.
        lines = self._c.previous_lines if self._opts.previous else self._c.lines
        cutoff = self._since_time_cutoff()
        if cutoff is not None:
            lines = [(ts, ln) for ts, ln in lines if ts >= cutoff]
        if self._opts.since_seconds is not None:
            cutoff = self._clock() - self._opts.since_seconds
            lines = [(ts, ln) for ts, ln in lines if ts >= cutoff]
        if self._opts.tail_lines is not None and self._opts.tail_lines >= 0:
            lines = lines[len(lines) - min(self._opts.tail_lines, len(lines)):]
        return [self._stamp(ts, ln) for ts, ln in lines]

    async def _chunks(self) -> AsyncIterator[bytes]:
        f = self._c.faults
        emitted = 0
        buf = bytearray()

        async def flush_full():
            nonlocal buf
            while len(buf) >= self._chunk_size:
                chunk = bytes(buf[: self._chunk_size])
                del buf[: self._chunk_size]
                if f.chunk_delay_s:
                    await asyncio.sleep(f.chunk_delay_s)
                yield chunk

        for ln in self._select_history():
            if f.cut_after_lines is not None and emitted >= f.cut_after_lines:
                if buf:
                    yield bytes(buf)
                return  # clean EOF mid-stream (premature end)
            if f.error_after_lines is not None and emitted >= f.error_after_lines:
                if buf:
                    yield bytes(buf)
                raise StreamError(
                    f"stream read error for {self._pod}/{self._c.name}"
                )
            buf += ln
            emitted += 1
            async for chunk in flush_full():
                yield chunk
                if self._closed_ev().is_set():
                    return

        if buf:
            yield bytes(buf)
            buf.clear()

        if not self._opts.follow or self._opts.previous:
            return  # a terminated prior instance cannot produce new lines

        # Follow mode: generate lines until the stream is closed.
        while not self._closed_ev().is_set():
            try:
                await asyncio.wait_for(
                    self._closed_ev().wait(), timeout=self._c.follow_interval_s
                )
                return
            except asyncio.TimeoutError:
                pass
            if f.cut_after_lines is not None and emitted >= f.cut_after_lines:
                return
            if f.error_after_lines is not None and emitted >= f.error_after_lines:
                raise StreamError(
                    f"stream read error for {self._pod}/{self._c.name}"
                )
            seq = self._c.next_seq
            self._c.next_seq += 1
            now = self._clock()
            cutoff = self._since_time_cutoff()
            if cutoff is not None and now < cutoff:
                # kubelet applies the since bound to followed lines too
                # (reachable only via since_time: a future cutoff).
                continue
            line = self._stamp(now, synthetic_line(
                self._pod, self._c.name, seq, now))
            emitted += 1
            yield line

    def __aiter__(self) -> AsyncIterator[bytes]:
        return self._chunks()


class FakeCluster(ClusterBackend):
    def __init__(
        self,
        context_name: str = "fake-context",
        default_namespace: str = "default",
        clock: Callable[[], float] = time.time,
        chunk_size: int = 4096,
    ):
        self.context_name = context_name
        self.default_namespace = default_namespace
        self.clock = clock
        self.chunk_size = chunk_size
        # namespace -> pod name -> FakePod
        self.namespaces: dict[str, dict[str, FakePod]] = {}

    # ---- construction helpers -------------------------------------------

    def add_namespace(self, name: str) -> None:
        self.namespaces.setdefault(name, {})

    def add_pod(
        self,
        namespace: str,
        name: str,
        containers: list[str] | None = None,
        init_containers: list[str] | None = None,
        labels: dict[str, str] | None = None,
        ready: bool = True,
        lines_per_container: int = 0,
        follow_interval_s: float = 0.01,
        line_spacing_s: float = 1.0,
    ) -> FakePod:
        self.add_namespace(namespace)
        containers = containers if containers is not None else ["main"]
        init_containers = init_containers or []
        info = PodInfo(
            name=name,
            namespace=namespace,
            labels=dict(labels or {}),
            ready=ready,
            containers=[ContainerInfo(c) for c in containers],
            init_containers=[ContainerInfo(c, init=True) for c in init_containers],
        )
        pod = FakePod(info=info)
        now = self.clock()
        for cname in init_containers + containers:
            fc = FakeContainer(
                name=cname,
                init=cname in init_containers,
                follow_interval_s=follow_interval_s,
            )
            # Historical lines: spaced line_spacing_s apart, newest at ~now.
            n = lines_per_container
            for i in range(n):
                ts = now - (n - 1 - i) * line_spacing_s
                fc.lines.append((ts, synthetic_line(name, cname, i, ts)))
            fc.next_seq = n
            pod.containers[cname] = fc
        self.namespaces[namespace][name] = pod
        return pod

    @classmethod
    def synthetic(
        cls,
        n_pods: int,
        n_containers: int = 1,
        lines_per_container: int = 100,
        namespace: str = "default",
        n_not_ready: int = 0,
        labels_for: Callable[[int], dict[str, str]] | None = None,
        follow_interval_s: float = 0.01,
        **kw,
    ) -> "FakeCluster":
        """Deterministic synthetic cluster: pod-0000..pod-NNNN."""
        fc = cls(**kw)
        fc.add_namespace(namespace)
        for p in range(n_pods):
            labels = labels_for(p) if labels_for else {"app": f"app-{p % 4}"}
            fc.add_pod(
                namespace,
                f"pod-{p:04d}",
                containers=[f"c{c}" for c in range(n_containers)],
                labels=labels,
                ready=p >= n_not_ready,
                lines_per_container=lines_per_container,
                follow_interval_s=follow_interval_s,
            )
        return fc

    # ---- ClusterBackend -------------------------------------------------

    def current_context(self) -> tuple[str, str]:
        return self.context_name, self.default_namespace

    async def list_namespaces(self) -> list[str]:
        return sorted(self.namespaces)

    async def namespace_exists(self, namespace: str) -> bool:
        return namespace in self.namespaces

    async def list_pods(
        self, namespace: str, label_selector: str | None = None
    ) -> list[PodInfo]:
        # Chaos fault point: the same name KubeBackend fires, so a
        # KLOGS_FAULTS script behaves identically against the hermetic
        # backend (the fake has no retry layer of its own; injected
        # faults surface as the errors callers must tolerate).
        if FAULTS.active:
            try:
                await FAULTS.fire("kube.list_pods")
            except InjectedFault as e:
                raise ClusterError(f"list pods in {namespace!r}: {e}") from e
        pods = self.namespaces.get(namespace, {})
        out = []
        for pod in pods.values():
            if label_selector and not match_label_selector(
                pod.info.labels, label_selector
            ):
                continue
            out.append(pod.info)
        return out

    async def open_log_stream(
        self, namespace: str, pod: str, opts: LogOptions
    ) -> LogStream:
        if FAULTS.active:
            try:
                await FAULTS.fire("kube.log_stream")
            except InjectedFault as e:
                raise StreamError(
                    f"open log stream {pod}/{opts.container}: {e}") from e
        try:
            fp = self.namespaces[namespace][pod]
            fc = fp.containers[opts.container]
        except KeyError as e:
            raise StreamError(
                f"container {opts.container!r} of pod {pod!r} "
                f"in namespace {namespace!r} not found"
            ) from e
        if fc.faults.fail_open:
            raise StreamError(
                f"error getting logs for container {opts.container}: injected"
            )
        if opts.previous and not fc.previous_lines:
            # apiserver parity: 400 "previous terminated container ...
            # not found" for a container that never restarted.
            raise StreamError(
                f"previous terminated container {opts.container!r} in pod "
                f"{pod!r} not found"
            )
        return FakeLogStream(fc, pod, opts, self.clock, self.chunk_size)
