"""ClusterBackend: the seam between the CLI and any cluster.

The reference talks to the Kubernetes apiserver directly through a
package-global client-go Clientset (cmd/root.go:38,69-87) — untestable
without a cluster (SURVEY.md §4). This interface is the dependency-
injection point: the real REST-backed client and the hermetic
FakeCluster both implement it, so everything above (pod selection,
fan-out, filtering, sinks) is testable without any cluster.

All methods are async: the fan-out runtime is an asyncio event loop
(the goroutine analog, cmd/root.go:248-261).
"""

import abc

from klogs_tpu.cluster.types import LogOptions, PodInfo
from klogs_tpu.sources.base import SourceError, SourceStream


class ClusterError(Exception):
    """A cluster-access failure (apiserver error analog)."""


class NamespaceNotFound(ClusterError):
    pass


class StreamError(ClusterError, SourceError):
    """Opening or reading a log stream failed (cmd/root.go:326-329
    analog). Subclasses SourceError so the source-agnostic fanout
    layer handles kube stream failures and file/socket failures with
    one except clause."""


class LogStream(SourceStream):
    """One container's log stream: an async iterator of byte chunks.

    The analog of the reference's io.ReadCloser from GetLogs(...).Stream
    (cmd/root.go:322-325): raw chunked bytes, line boundaries not
    guaranteed to align with chunk boundaries. The iterator/close
    contract now lives on ``sources.base.SourceStream``; LogStream is
    the cluster-flavored alias every backend already implements.
    """


class ClusterBackend(abc.ABC):
    @abc.abstractmethod
    def current_context(self) -> tuple[str, str]:
        """Return (context_name, default_namespace) — getCurrentNamespace
        analog (cmd/root.go:185-198); default_namespace falls back to
        "default" when the context has none."""

    @abc.abstractmethod
    async def list_namespaces(self) -> list[str]:
        """All namespace names (cmd/root.go:106-115)."""

    @abc.abstractmethod
    async def namespace_exists(self, namespace: str) -> bool:
        """Namespaces().Get analog (cmd/root.go:96)."""

    @abc.abstractmethod
    async def list_pods(
        self, namespace: str, label_selector: str | None = None
    ) -> list[PodInfo]:
        """Pods(ns).List, optionally with a label selector
        (cmd/root.go:128,380-381). Returns all pods regardless of
        readiness; the Ready filter is applied by the caller, matching
        the reference's client-side filtering (cmd/root.go:137-143)."""

    @abc.abstractmethod
    async def open_log_stream(
        self, namespace: str, pod: str, opts: LogOptions
    ) -> LogStream:
        """GetLogs(pod, opts).Stream analog (cmd/root.go:322-325).

        ``opts.container`` must be set. since/tail/follow are applied
        server-side (by the backend), mirroring kubelet semantics.
        Raises StreamError on failure.
        """

    async def close(self) -> None:
        """Release any transport resources."""
