"""Top-level run orchestration.

Reference parity: rootCmd.Run (cmd/root.go:442-474) — splash, client +
namespace config, pod selection (label union vs interactive/all), log
fan-out, wait-or-keypress, final size table. Structured as testable
functions over an injected ClusterBackend instead of the reference's
package globals (cmd/root.go:36-49).
"""

import asyncio
import os
import signal
import threading
from typing import Iterable

from klogs_tpu.cli import Options
from klogs_tpu.cluster.backend import ClusterBackend
from klogs_tpu.cluster.types import LogOptions, PodInfo
from klogs_tpu.runtime.fanout import (
    FanoutRunner,
    StreamJob,
    plan_jobs,
    plan_source_jobs,
)
from klogs_tpu.ui import interactive, term, widgets
from klogs_tpu.utils.env import read as env_read
from klogs_tpu.utils import convert_bytes, parse_duration, split_log_file_name
from klogs_tpu.utils.duration import DurationError


def make_backend(opts: Options) -> ClusterBackend:
    if opts.cluster == "fake":
        from klogs_tpu.cluster.fake import FakeCluster

        n_pods = int(env_read("KLOGS_FAKE_PODS", "6"))
        n_containers = int(env_read("KLOGS_FAKE_CONTAINERS", "2"))
        n_lines = int(env_read("KLOGS_FAKE_LINES", "300"))
        fc = FakeCluster.synthetic(
            n_pods=n_pods, n_containers=n_containers, lines_per_container=n_lines
        )
        fc.add_namespace("kube-system")
        return fc

    from klogs_tpu.cluster.kube import KubeBackend

    return KubeBackend.from_kubeconfig(opts.kubeconfig)


async def resolve_namespace(
    backend: ClusterBackend, opts: Options,
    select_keys: Iterable[str] | None = None,
) -> str:
    """configNamespace analog (cmd/root.go:90-103): explicit -n, else the
    kubeconfig current-context namespace; verify existence; on miss, warn
    and fall into the interactive picker (selection not re-validated,
    SURVEY.md §3.4)."""
    namespace = opts.namespace
    if not namespace:
        context, namespace = backend.current_context()
        term.info("Using Context %s", term.green(context))
    if not await backend.namespace_exists(namespace):
        term.warning("Namespace %s not found", namespace)
        names = await backend.list_namespaces()
        namespace = interactive.interactive_select(
            names, "Select a Namespace", keys=select_keys
        )
    term.info("Using Namespace %s", term.green(namespace))
    return namespace


async def select_noninteractive(
    backend: ClusterBackend, namespace: str, opts: Options,
    quiet: bool = False,
) -> list[PodInfo]:
    """The re-runnable core of pod selection: label union
    (cmd/root.go:455-461) or all-Ready (cmd/root.go:137-143). Shared by
    the startup path and the --watch-new re-poll so both always select
    the same pod set; ``quiet`` suppresses the per-call chatter during
    polling."""
    if opts.labels:
        pods: list[PodInfo] = []
        for label in opts.labels:
            if not quiet:
                term.info("Getting Pods with label %s\n", term.green(label))
            found = await backend.list_pods(namespace, label_selector=label)
            if not found and not quiet:
                term.error(
                    "No pods found in namespace %s with label %s\n", namespace, label
                )
            # Union semantics, no dedup across labels (cmd/root.go:458-460).
            pods.extend(found)
        return pods
    all_pods = await backend.list_pods(namespace)
    return [p for p in all_pods if p.ready]  # cmd/root.go:137-143


async def select_pods(
    backend: ClusterBackend, namespace: str, opts: Options,
    select_keys: Iterable[str] | None = None,
) -> list[PodInfo]:
    """Pod selection: label union (cmd/root.go:455-461) or
    listAllPods with Ready filter + optional multiselect (cmd/root.go:126-164)."""
    if opts.labels:
        return await select_noninteractive(backend, namespace, opts)

    ready = await select_noninteractive(backend, namespace, opts)
    if not ready:
        term.error("No pods found in namespace %s", namespace)
        return []
    if not opts.all_pods:
        by_name = {p.name: p for p in ready}
        chosen = interactive.interactive_multiselect(
            [p.name for p in ready], "Select Pods to get logs", keys=select_keys
        )
        if not chosen:
            term.error("No pods selected")
            return []
        return [by_name[n] for n in chosen]
    return ready


def build_log_options(opts: Options) -> LogOptions:
    """getLopOpts analog (cmd/root.go:201-221), plus the kubectl-parity
    additions --previous/--timestamps (PodLogOptions.Previous/
    .Timestamps — server-side, like since/tail/follow)."""
    if opts.previous and opts.follow:
        # kubectl parity: "only one of follow or previous may be true".
        term.fatal("--previous is incompatible with -f/--follow "
                   "(a terminated instance cannot stream)")
    if opts.since and opts.since_time:
        term.fatal("at most one of -s/--since and --since-time may be "
                   "given (kubectl parity)")
    lo = LogOptions(follow=opts.follow, previous=opts.previous,
                    timestamps=opts.timestamps)
    if opts.since:
        try:
            lo.since_seconds = int(parse_duration(opts.since))
        except DurationError as e:
            term.fatal("%s", e)
    if opts.since_time:
        from datetime import datetime

        try:
            dt = datetime.fromisoformat(
                opts.since_time.replace("Z", "+00:00"))
            if dt.tzinfo is None:  # see cli.main: naive is not RFC3339
                raise ValueError("missing timezone offset")
        except ValueError:
            # Backstop for library callers; cli.main rejects earlier.
            term.fatal("invalid --since-time %r (want RFC3339 with a "
                       "timezone)", opts.since_time)
        lo.since_time = opts.since_time
    if opts.tail != -1:
        lo.tail_lines = opts.tail
    return lo


def print_plan(pods: list[PodInfo], jobs: list[StreamJob]) -> None:
    """The pod/container tree + counts (cmd/root.go:231-274)."""
    term.info(
        "Found %s Pod(s) %s Container(s)",
        term.green(str(len(pods))), term.green(str(len(jobs))),
    )
    jobs_by_pod: dict[str, list[StreamJob]] = {}
    for j in jobs:
        jobs_by_pod.setdefault(j.pod, []).append(j)
    for i, pod in enumerate(pods):
        children = [
            j.container + (term.gray(" [init]") if j.init else "")
            for j in jobs_by_pod.get(pod.name, [])
        ]
        widgets.render_tree(f"{pod.name} {term.blue(f'[Pod #{i + 1}]')}", children)
    term.info("Acquiring logs \U0001f680")


def print_log_size(log_files: list[str], log_path: str) -> None:
    """printLogSize analog (cmd/root.go:279-309)."""
    if not log_files:
        term.error("No logs saved")
        return
    term.info("Logs saved to %s", term.green(log_path))
    table = [["Pod", "Container", "Size"]]
    previous_pod = ""
    for path in log_files:
        try:
            size = os.stat(path).st_size
        except OSError:
            continue  # soft-skip, cmd/root.go:292-293
        pod, container = split_log_file_name(path)
        label = term.gray(pod) if pod == previous_pod else pod
        table.append([label, container, convert_bytes(size)])
        previous_pod = pod
    widgets.render_table(table)


def _print_backfill_summary(pipeline) -> None:
    """--backfill exit accounting (match/shed), printed whether or not
    --stats was given — a run-to-completion mode owes its verdict."""
    if pipeline is None:
        term.info("Backfill complete (no --match/--exclude: every line "
                  "written)")
        return
    s = pipeline.stats
    term.info(
        "Backfill complete: %s lines in, %s matched (%.2f%%), %s shed",
        f"{s.lines_in:,}", f"{s.lines_matched:,}", s.matched_pct(),
        f"{s.degraded_lines:,}")


async def _watch_for_quit(
    stop: asyncio.Event, message: str, done: "threading.Event",
    spinner: bool = True,
) -> None:
    """pressKeyToExit analog (cmd/root.go:399-421): open the controlling
    terminal (go-tty opens /dev/tty, not stdin), raw-mode key loop until
    q/Q under a spinner, then trigger explicit shutdown.

    Improvements over the reference: without a controlling terminal we
    warn and stop streaming rather than panicking (root.go:402-403), and
    the reader polls ``done`` so the thread exits (restoring the
    terminal) when the streams finish on their own. With ``-o
    stdout|both`` the spinner is replaced by one static line
    (``spinner=False``): a repainting spinner would garble the live
    log stream sharing the terminal."""
    loop = asyncio.get_running_loop()

    def read_q() -> None:
        import select
        import termios
        import tty

        with open("/dev/tty", "rb", buffering=0) as t:
            fd = t.fileno()
            old = termios.tcgetattr(fd)
            try:
                tty.setcbreak(fd)
                while not done.is_set():
                    r, _, _ = select.select([fd], [], [], 0.2)
                    if r and t.read(1) in (b"q", b"Q"):
                        return
            finally:
                termios.tcsetattr(fd, termios.TCSADRAIN, old)

    try:
        if spinner:
            async with widgets.Spinner(message):
                await loop.run_in_executor(None, read_q)
        else:
            term.info("%s", message)
            await loop.run_in_executor(None, read_q)
    except Exception as e:  # no controlling tty, termios failure
        term.warning("No controlling terminal for q-to-quit (%s); stopping", e)
    stop.set()


def make_inner_sink_factory(opts: Options):
    """``-o`` routing for where lines land (PARITY.md: additive beyond
    the reference, which only writes files): None = reference FileSink
    behavior; ``stdout`` = stern-style prefixed console stream;
    ``both`` = tee to file and console."""
    if opts.output == "files":
        if opts.format != "text":
            term.warning("--format %s only applies with -o stdout|both; "
                         "ignoring", opts.format)
        return None
    from klogs_tpu.runtime.sink import FileSink
    from klogs_tpu.runtime.stdout import (
        JsonStdoutSink,
        StdoutSink,
        TeeSink,
        compile_highlights,
    )

    if opts.format == "json":
        console = lambda job: JsonStdoutSink(job.pod, job.container)
    else:
        hl = compile_highlights(opts.match, opts.ignore_case)
        console = lambda job: StdoutSink(job.pod, job.container,
                                         highlight=hl)
    if opts.output == "stdout":
        return console
    return lambda job: TeeSink(FileSink(job.path), console(job))


def make_pipeline_for(opts: Options, registry=None):
    """The --match/--exclude filter pipeline (None = unfiltered
    reference path). ``registry`` (an obs.Registry) backs the stats
    when --metrics-port / --stats-json want them scrapable."""
    if not opts.match and not opts.exclude:
        return None
    import re as _re

    from klogs_tpu.filters.sink import make_pipeline

    from klogs_tpu.filters.compiler.parser import RegexSyntaxError

    try:
        return make_pipeline(opts.match, opts.backend, remote=opts.remote,
                             ignore_case=opts.ignore_case,
                             exclude=opts.exclude, registry=registry,
                             on_filter_error=opts.on_filter_error,
                             shard_mode=opts.shard_mode,
                             resolver=opts.resolver,
                             kubeconfig=opts.kubeconfig or None)
    except _re.error as e:
        term.fatal("invalid --match/--exclude pattern %r: %s", e.pattern, e)
    except RegexSyntaxError as e:
        # NFA-compiler rejections (unsupported constructs like
        # possessive quantifiers or backrefs) get the same friendly
        # exit as re syntax errors, not a traceback.
        term.fatal("unsupported --match/--exclude pattern: %s", e)
    except ImportError as e:
        term.fatal("--backend %s is unavailable: %s", opts.backend, e)


def _write_stats_json(path: str, registry, pipeline) -> None:
    """--stats-json: one-shot metrics dump at exit — the scrapeless
    option for batch (non-follow, non-server) runs. The full registry
    snapshot plus the --stats summary numbers, derived from the SAME
    metric objects a /metrics scrape reads."""
    import json

    from klogs_tpu.obs import snapshot
    from klogs_tpu.obs.profiler import refresh_process_metrics

    # Final process-gauge refresh so the dump carries exit-time
    # uptime/RSS, like a last scrape would.
    refresh_process_metrics(registry)
    doc: dict = {"metrics": snapshot(registry)}
    if pipeline is not None:
        s = pipeline.stats
        # p90 added next to the existing keys (additive only — the
        # key layout is a golden consumers parse).
        doc["summary"] = {
            "lines_in": s.lines_in,
            "lines_matched": s.lines_matched,
            "matched_pct": s.matched_pct(),
            "lines_per_sec": s.lines_per_sec(),
            "batches": s.batches,
            "batch_latency_p50_s": s.percentile_latency_s(50),
            "batch_latency_p90_s": s.percentile_latency_s(90),
            "batch_latency_p99_s": s.percentile_latency_s(99),
        }
        if s.has_service_latencies:
            doc["summary"].update({
                "queue_p50_s": s.percentile_queue_s(50),
                "queue_p90_s": s.percentile_queue_s(90),
                "queue_p99_s": s.percentile_queue_s(99),
                "device_p50_s": s.percentile_device_s(50),
                "device_p90_s": s.percentile_device_s(90),
                "device_p99_s": s.percentile_device_s(99),
            })
    try:
        with open(path, "w") as f:
            json.dump(doc, f, indent=1)
        term.info("Metrics dump written to %s", term.green(path))
    except OSError as e:
        term.error("cannot write --stats-json %s: %s", path, e)


async def run_async(
    opts: Options,
    backend: ClusterBackend | None = None,
    stop: asyncio.Event | None = None,
    select_keys: Iterable[str] | None = None,
) -> int:
    if opts.output != "files":
        # Console modes: log lines own stdout (stern-style); all UI
        # (splash, plan, warnings, prompts) moves to stderr so a piped
        # `klogs -o stdout | grep` sees only log lines and UI text can
        # never interleave into the byte stream.
        import sys as _sys

        term.set_ui_stream(_sys.stderr)
    try:
        return await _run_async_inner(opts, backend, stop, select_keys)
    finally:
        if opts.output != "files":
            term.set_ui_stream(None)


async def _run_async_inner(
    opts: Options,
    backend: ClusterBackend | None = None,
    stop: asyncio.Event | None = None,
    select_keys: Iterable[str] | None = None,
) -> int:
    widgets.splash_screen()
    # Chaos layer: a KLOGS_FAULTS spec scripts the registered fault
    # points for this run (grammar in docs/RESILIENCE.md). Loud when
    # armed — nobody should discover a forgotten fault spec from
    # mystery retries in production.
    from klogs_tpu.resilience import FAULTS, FaultSpecError

    fault_spec = env_read("KLOGS_FAULTS")
    if fault_spec:
        try:
            FAULTS.load_spec(fault_spec)
        except FaultSpecError as e:
            term.fatal("invalid KLOGS_FAULTS: %s", e)
        term.warning("Fault injection ACTIVE (KLOGS_FAULTS=%s)", fault_spec)
    # --source/--backfill: a non-kube Source replaces the cluster
    # backend wholesale — no namespace resolution, no pod selection,
    # no kube client. cli.main validates the spec; this is the
    # library-caller backstop.
    from klogs_tpu.sources import SourceError, make_source

    try:
        source = make_source(opts)
    except SourceError as e:
        term.fatal("%s", e)
    if source is None:
        backend = backend or make_backend(opts)
    profiling = False
    if opts.profile:
        # Optional tracing hook (SURVEY.md §5: the reference has none;
        # the TPU build adds jax profiler capture for the filter path).
        try:
            import jax.profiler
        except ImportError as e:
            term.fatal("--profile requires jax: %s", e)
        jax.profiler.start_trace(opts.profile)
        profiling = True
        term.info("Profiling to %s", term.green(opts.profile))
    try:
        container_re = exclude_container_re = None
        log_opts = build_log_options(opts)
        if source is not None:
            namespace = "local"
            pods: list[PodInfo] = []
            await source.start()
            refs = await source.discover()
            jobs = plan_source_jobs(refs, opts.log_path)
            log_files = [j.path for j in jobs]
            mode = "backfilling" if opts.backfill else "streaming"
            term.info("Found %s %s stream(s), %s",
                      term.green(str(len(jobs))), source.kind, mode)
            for j in jobs[:12]:
                term.info("  %s", j.pod)
            if len(jobs) > 12:
                term.info("  … and %d more", len(jobs) - 12)
        else:
            namespace = await resolve_namespace(backend, opts, select_keys)
            pods = await select_pods(backend, namespace, opts, select_keys)
            import re as _re

            # Backstop for library callers; cli.main rejects earlier.
            if opts.container:
                try:
                    container_re = _re.compile(opts.container)
                except _re.error as e:
                    term.fatal("invalid -c/--container pattern %r: %s",
                               opts.container, e)
            if opts.exclude_container:
                try:
                    exclude_container_re = _re.compile(opts.exclude_container)
                except _re.error as e:
                    term.fatal("invalid -E/--exclude-container pattern "
                               "%r: %s", opts.exclude_container, e)
            jobs = plan_jobs(pods, opts.log_path, opts.init_containers,
                             container_re=container_re,
                             exclude_container_re=exclude_container_re)
            log_files = [j.path for j in jobs]
            if (container_re is not None
                    or exclude_container_re is not None) \
                    and pods and not jobs:
                # A filter miss must be distinguishable from an empty
                # cluster (≙ the empty-label-result error that continues,
                # cmd/root.go:392-394).
                term.error("No containers left after -c/-E filtering in "
                           "%d selected pod(s)", len(pods))
            if jobs:
                if container_re is not None \
                        or exclude_container_re is not None:
                    # With -c/-E active, pods whose containers were all
                    # filtered out contribute no streams — counting or
                    # rendering them would misstate the plan.
                    streaming = {j.pod for j in jobs}
                    print_plan([p for p in pods if p.name in streaming],
                               jobs)
                else:
                    print_plan(pods, jobs)
        if opts.timestamps and (opts.match or opts.exclude):
            # grep-parity semantics: the server-side stamp is part of
            # the line the filter sees (as it would be for kubectl
            # --timestamps | grep). Say so once — a ^-anchored pattern
            # silently matching nothing is a support ticket.
            term.info("note: --timestamps prefixes are part of the line "
                      "--match/--exclude see (anchor accordingly)")

        # Observability (opt-in): one registry backs the pipeline
        # stats, the fan-out instrumentation, and — with
        # --metrics-port — a live /metrics + /healthz HTTP sidecar.
        # Per-RUN (not the process-global obs.REGISTRY): a second
        # run_async in the same process must not inherit the first
        # run's counters into its summary/dump.
        obs_registry = None
        metrics_srv = None
        if opts.metrics_port is not None or opts.stats_json is not None:
            from klogs_tpu import obs

            obs_registry = obs.Registry()
            obs.register_all(obs_registry)
            from klogs_tpu.version import BUILD_VERSION as _ver

            obs_registry.family("klogs_build_info").labels(
                version=_ver).set(1)
        # Tracing (opt-in): --trace-json turns head sampling fully on
        # (unless KLOGS_TRACE_SAMPLE pins a rate) and appends every
        # finished span to the file; with KLOGS_TRACE_SAMPLE alone the
        # spans still feed /traces (--metrics-port sidecar) and the
        # degrade flight recorder. Trace counters ride the run
        # registry when one exists.
        from klogs_tpu.obs import trace as _trace

        if opts.trace_json is not None:
            _trace.TRACER.enable_default()
            _trace.TRACER.set_json_path(opts.trace_json)
        if obs_registry is not None:
            _trace.TRACER.bind_registry(obs_registry)
            _trace.RECORDER.bind_registry(obs_registry)
        # Continuous utilization profiling (opt-in): --profile-json
        # appends one snapshot per tick; KLOGS_PROFILE_SAMPLE alone
        # also enables it (feeding /profile on --metrics-port without
        # a file sink). KLOGS_PROFILE_SAMPLE=0 is the kill switch even
        # against the explicit flag.
        from klogs_tpu.obs.profiler import PROFILER

        PROFILER.maybe_enable()
        if opts.profile_json is not None and PROFILER.enable():
            PROFILER.set_json_path(opts.profile_json)
        if PROFILER.enabled and obs_registry is not None:
            PROFILER.bind_registry(obs_registry)
        prof_stop: asyncio.Event | None = None
        prof_task: asyncio.Task | None = None
        tune_stop: asyncio.Event | None = None
        tune_task: asyncio.Task | None = None
        # Resilience observability rides the same per-run registry:
        # fault firings, kube retry attempts (the backend exists before
        # the registry, hence the late bind), breaker state (bound in
        # the remote client via make_pipeline's registry).
        FAULTS.bind_registry(obs_registry)
        backend_bind = getattr(backend, "bind_registry", None)
        if backend_bind is not None and obs_registry is not None:
            backend_bind(obs_registry)
        if source is not None and obs_registry is not None:
            source.bind_registry(obs_registry)

        pipeline = make_pipeline_for(opts, registry=obs_registry)
        inner_factory = make_inner_sink_factory(opts)
        try:
            if PROFILER.enabled:
                # Started inside this try so the finally below always
                # reaps the ticker (a fatal during pipeline start must
                # not leak the task into loop teardown).
                prof_stop = asyncio.Event()
                prof_task = asyncio.create_task(
                    PROFILER.run_ticker(prof_stop))
            if pipeline is not None:
                await pipeline.start()  # remote: verify patterns up front
                pipeline.inner_factory = inner_factory
                # KLOGS_TUNE=auto: the adaptive operating-point
                # controller (ops/tune.py) drives the coalescer/
                # in-flight knobs from live /profile signals. Off by
                # default — nothing is even constructed, so fixed-flag
                # behavior stays byte-identical.
                from klogs_tpu.ops.tune import maybe_controller

                try:
                    ctrl = maybe_controller(pipeline.service,
                                            registry=obs_registry)
                except ValueError as e:
                    term.fatal("%s", e)
                if ctrl is not None:
                    if not PROFILER.enabled and not PROFILER.enable():
                        term.warning(
                            "KLOGS_TUNE=auto needs profiler signals but "
                            "KLOGS_PROFILE_SAMPLE=0 disables them; the "
                            "controller will hold the fixed flags")
                    elif prof_task is None:
                        # Tuning enabled the profiler itself: it still
                        # needs the ticker for live samples.
                        if obs_registry is not None:
                            PROFILER.bind_registry(obs_registry)
                        prof_stop = asyncio.Event()
                        prof_task = asyncio.create_task(
                            PROFILER.run_ticker(prof_stop))
                    tune_stop = asyncio.Event()
                    tune_task = asyncio.create_task(ctrl.run(tune_stop))
            runner = FanoutRunner(
                backend, namespace, log_opts,
                sink_factory=(pipeline.sink_factory if pipeline
                              else inner_factory),
                create_files=opts.output != "stdout",
                registry=obs_registry,
                source=source,
            )
            if opts.metrics_port is not None:
                from klogs_tpu import obs

                health = obs.Health()
                # The collector has no cold-start compile gate of its
                # own (the engine warms on first batch; a --remote
                # engine warms in filterd): it is ready once streaming
                # is set up.
                health.set_ready()
                health.add_live_check("runner",
                                      lambda: not runner._stopping)
                metrics_srv = obs.MetricsHTTPServer(
                    obs_registry, health=health, port=opts.metrics_port)
                try:
                    bound_metrics = await metrics_srv.start()
                except OSError as e:
                    # Friendly one-liner like every other bad-flag
                    # path, not a traceback out of asyncio.run.
                    term.fatal("cannot bind --metrics-port %s: %s",
                               opts.metrics_port, e)
                term.info("Metrics on %s",
                          term.green(f"http://127.0.0.1:{bound_metrics}"
                                     "/metrics"))
            # --watch-new: stern-style dynamic discovery. Only a
            # NON-interactive selection can be re-planned (the user's
            # one-off multiselect cannot); re-run the same -a/-l
            # selection and let the runner diff.
            plan_new = None
            if source is not None:
                if opts.follow:
                    # Sources re-discover for free (glob expansion, new
                    # socket connections): follow mode always watches.
                    _src = source

                    async def plan_new() -> list[StreamJob]:
                        return plan_source_jobs(await _src.discover(),
                                                opts.log_path)
                if opts.watch_new and not opts.follow:
                    term.warning("--watch-new only applies with -f; "
                                 "ignoring")
            elif opts.watch_new and opts.follow:
                if opts.all_pods or opts.labels:
                    async def plan_new() -> list[StreamJob]:
                        pods = await select_noninteractive(
                            backend, namespace, opts, quiet=True)
                        return plan_jobs(
                            pods, opts.log_path, opts.init_containers,
                            container_re=container_re,
                            exclude_container_re=exclude_container_re)
                else:
                    term.warning(
                        "--watch-new needs -a or -l (an interactive pod "
                        "pick cannot be re-run); ignoring")
            elif opts.watch_new:
                term.warning("--watch-new only applies with -f; ignoring")
            # With discovery active, an EMPTY initial selection still
            # waits (the point of starting the watch before deploying).
            interrupted = False
            if opts.follow and (jobs or plan_new is not None):
                own_stop = stop is None
                if own_stop:
                    stop = asyncio.Event()
                # The flusher gets the stop event so an
                # --on-filter-error=abort escalation from an idle
                # stream's stale flush tears the run down instead of
                # dying silently in a background task.
                flusher = (
                    asyncio.create_task(pipeline.run_deadline_flusher(stop))
                    if pipeline is not None else None
                )
                sigint_installed = False
                if own_stop:
                    # Ctrl-C parity+: the reference exits with streams
                    # still running and buffers unflushed (SURVEY §3.3
                    # quirk class). First SIGINT = graceful stop (same
                    # teardown as q: close streams, flush every sink,
                    # render the size table) but still exit 130 like
                    # kubectl; second SIGINT = give up immediately.
                    loop = asyncio.get_running_loop()

                    def on_sigint() -> None:
                        nonlocal interrupted
                        if interrupted:
                            # Force quit must NOT re-enter the event
                            # loop (a raised KeyboardInterrupt funnels
                            # through asyncio.run's cleanup, which can
                            # block on the very await that wedged the
                            # graceful path — e.g. backend.close on a
                            # dead tunnel). Die by signal, like the
                            # default handler would.
                            signal.signal(signal.SIGINT, signal.SIG_DFL)
                            os.kill(os.getpid(), signal.SIGINT)
                            return
                        interrupted = True
                        term.warning(
                            "Interrupt: stopping streams (Ctrl-C again "
                            "to force quit)")
                        stop.set()

                    try:
                        loop.add_signal_handler(signal.SIGINT, on_sigint)
                        sigint_installed = True
                    except (NotImplementedError, RuntimeError):
                        pass  # non-main thread / platform without support
                    watcher_done = threading.Event()
                    if opts.output == "stdout":
                        quit_msg = (f"Press {term.green('q')} to stop "
                                    "streaming logs")
                    else:
                        quit_msg = (f"Press {term.green('q')} to stop "
                                    "streaming logs in "
                                    f"{term.green(opts.log_path)}")
                    watcher = asyncio.create_task(
                        _watch_for_quit(stop, quit_msg, watcher_done,
                                        spinner=opts.output == "files")
                    )
                else:
                    watcher = watcher_done = None
                try:
                    interval = 5.0
                    if plan_new is not None:  # knob is irrelevant otherwise
                        raw = env_read("KLOGS_WATCH_INTERVAL_S", "5")
                        try:
                            # Floor of 0.2s: a zero/negative value would
                            # busy-poll the apiserver all session.
                            interval = max(0.2, float(raw))
                        except ValueError:
                            term.fatal(
                                "KLOGS_WATCH_INTERVAL_S must be a number, "
                                "got %r", raw)
                    results = await runner.run(
                        jobs, stop=stop, plan_new=plan_new,
                        discover_interval_s=interval)
                    # Late-discovered streams must appear in the size
                    # table too.
                    log_files = [r.job.path for r in results]
                finally:
                    if sigint_installed:
                        asyncio.get_running_loop().remove_signal_handler(
                            signal.SIGINT)
                    if watcher is not None:
                        # Unblock the /dev/tty reader thread so the
                        # terminal is restored and the process can exit.
                        watcher_done.set()
                        await watcher
                    if flusher is not None:
                        flusher.cancel()
                        try:
                            await flusher
                        except asyncio.CancelledError:
                            pass
            else:
                await runner.run(jobs)
                if opts.backfill:
                    # Run-to-completion contract: always account for
                    # what was matched vs shed, --stats or not.
                    _print_backfill_summary(pipeline)

            if opts.output != "stdout":
                # No files exist in stdout-only mode; the size table
                # (cmd/root.go:279-309) only describes written files.
                print_log_size(log_files, opts.log_path)
            if pipeline is not None and opts.stats:
                pipeline.print_summary()
            if opts.stats_json is not None:
                if pipeline is not None:
                    # Sharded remote tier: pull each endpoint's final
                    # capacity advertisement so the dump carries the
                    # fleet's offered/admitted totals (a short batch
                    # run ends before the prober's refresh cadence).
                    refresh = getattr(pipeline.service,
                                      "refresh_capacity", None)
                    if refresh is not None:
                        await refresh()
                _write_stats_json(opts.stats_json, obs_registry, pipeline)
            # Interrupted-but-graceful: everything is flushed and
            # reported, yet scripts still see the conventional 130.
            return 130 if interrupted else 0
        finally:
            # Close inside the loop even on error/Ctrl-C paths — an
            # unawaited grpc channel or in-flight batch task would be
            # destroyed pending at loop teardown.
            if tune_task is not None:
                if tune_stop is not None:
                    tune_stop.set()
                try:
                    await tune_task
                except (asyncio.CancelledError, Exception):  # noqa: BLE001
                    pass
            if prof_task is not None:
                # run_ticker's final tick completes the JSONL stream
                # before the task returns.
                if prof_stop is not None:
                    prof_stop.set()
                try:
                    await prof_task
                except (asyncio.CancelledError, Exception):  # noqa: BLE001
                    pass
                PROFILER.set_json_path(None)
            if metrics_srv is not None:
                await metrics_srv.stop()
            if pipeline is not None:
                await pipeline.aclose()
            # A degrade trigger armed near the end of the run may have
            # no further root span to ride — write it now, and stop
            # appending spans to this run's --trace-json file.
            _trace.RECORDER.flush()
            if opts.trace_json is not None:
                _trace.TRACER.set_json_path(None)
    finally:
        if profiling:
            import jax.profiler

            try:
                jax.profiler.stop_trace()
            except Exception as e:
                # Trace serialization failure must not skip backend
                # cleanup or mask an in-flight exception.
                term.warning("Failed to write profiler trace: %s", e)
        if backend is not None:
            await backend.close()
        if source is not None:
            await source.close()


def run(opts: Options) -> int:
    return asyncio.run(run_async(opts))
