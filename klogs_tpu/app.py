"""Top-level run orchestration (analog of rootCmd.Run, cmd/root.go:442-474).

Placeholder until the fan-out runtime lands; fails cleanly instead of
tracebacking.
"""

from klogs_tpu.cli import Options
from klogs_tpu.ui import term


def run(opts: Options) -> int:
    term.fatal("log acquisition is not implemented yet in this build")
    raise AssertionError("unreachable")  # fatal() always raises
