"""Human-readable byte sizes.

Reference parity: ``convertBytes`` (cmd/root.go:423-434) — zero renders
red "0 B"; below 1 KiB exact bytes; otherwise integer *floor* division
to KB / MB (1.5 KB renders "1 KB", cmd/root_test.go:20-23). The
reference never renders GB; MB is the terminal unit.
"""

from klogs_tpu.ui.term import red


def convert_bytes(n: int, *, color: bool = True) -> str:
    if n == 0:
        return red("0 B") if color else "0 B"
    if n < 1024:
        return f"{n} B"
    if n < 1024 * 1024:
        return f"{n // 1024} KB"
    return f"{n // 1024 // 1024} MB"
