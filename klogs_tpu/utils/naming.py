"""Log file naming and default paths.

Reference parity: file name ``<pod>__<container>.log`` with separator
"__" (cmd/root.go:51-53,341-342); default log path
``logs/<YYYY-MM-DDTHH-MM>`` computed once at startup (cmd/root.go:47);
the size table parses names back via the separator (cmd/root.go:295-296).
"""

import os
import time

FILE_NAME_SEPARATOR = "__"


def default_log_path(now: float | None = None) -> str:
    t = time.localtime(now if now is not None else time.time())
    return os.path.join("logs", time.strftime("%Y-%m-%dT%H-%M", t))


def log_file_name(pod: str, container: str) -> str:
    return f"{pod}{FILE_NAME_SEPARATOR}{container}.log"


def split_log_file_name(file_name: str) -> tuple[str, str]:
    """Invert log_file_name: basename -> (pod, container)."""
    base = os.path.basename(file_name)
    parts = base.split(FILE_NAME_SEPARATOR)
    if len(parts) < 2:
        raise ValueError(f"not a klogs log file name: {base!r}")
    pod, container = parts[0], parts[1]
    container = container.removesuffix(".log")
    return pod, container
