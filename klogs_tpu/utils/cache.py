"""The one cache-directory resolver (native .so builds, DFA tables):
XDG_CACHE_HOME else ~/.cache, under a klogs-tpu namespace. A single
helper so a future relocation (KLOGS_CACHE_DIR, containerized HOME)
cannot leave the two caches in different places."""

import os


def cache_dir() -> str:
    base = os.environ.get("XDG_CACHE_HOME",
                          os.path.join(os.path.expanduser("~"), ".cache"))
    return os.path.join(base, "klogs-tpu")
