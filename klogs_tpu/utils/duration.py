"""Go-style duration parsing.

Reference parity: ``--since`` is parsed with Go's ``time.ParseDuration``
(cmd/root.go:206) which accepts decimal numbers with optional fraction
and a unit suffix, concatenated: "300ms", "-1.5h", "2h45m". Valid units:
ns, us (µs/μs), ms, s, m, h. A bare number with no unit is an error, as
is an empty string.
"""

import re

_UNITS = {
    "ns": 1e-9,
    "us": 1e-6,
    "µs": 1e-6,
    "μs": 1e-6,
    "ms": 1e-3,
    "s": 1.0,
    "m": 60.0,
    "h": 3600.0,
}

_TOKEN = re.compile(r"(\d+(?:\.\d*)?|\.\d+)(ns|us|µs|μs|ms|s|m|h)")


class DurationError(ValueError):
    pass


def parse_duration(text: str) -> float:
    """Parse a Go duration string into seconds (float)."""
    s = text
    if not s:
        raise DurationError(f"time: invalid duration {text!r}")
    sign = 1.0
    if s[0] in "+-":
        sign = -1.0 if s[0] == "-" else 1.0
        s = s[1:]
    if not s:  # bare "+" / "-" is invalid, like Go
        raise DurationError(f"time: invalid duration {text!r}")
    if s == "0":
        return 0.0
    total = 0.0
    pos = 0
    while pos < len(s):
        m = _TOKEN.match(s, pos)
        if not m:
            raise DurationError(f"time: invalid duration {text!r}")
        total += float(m.group(1)) * _UNITS[m.group(2)]
        pos = m.end()
    return sign * total
