from klogs_tpu.utils.bytesize import convert_bytes
from klogs_tpu.utils.duration import parse_duration
from klogs_tpu.utils.naming import (
    FILE_NAME_SEPARATOR,
    default_log_path,
    log_file_name,
    split_log_file_name,
)

__all__ = [
    "convert_bytes",
    "parse_duration",
    "FILE_NAME_SEPARATOR",
    "default_log_path",
    "log_file_name",
    "split_log_file_name",
]
