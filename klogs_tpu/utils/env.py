"""Shared access point for every ``KLOGS_*`` environment knob.

The fleet grew ~40 env knobs across five subsystems, and the hardest
review findings of PRs 5-10 were knob-parsing bugs: ``KLOGS_HEDGE_S=
nan`` flowing into ``asyncio.wait(timeout=nan)``, a negative DFA cache
cap evicting every table on every write, zero timeouts DEADLINE-
EXCEEDing every RPC with an error that never named the variable. The
fix each time was the same — validate at the read site, loudly, naming
the knob — so the read sites now share ONE module. ``tools/analysis``'s
``env-discipline`` pass enforces the funnel: a raw ``os.environ[...]``
/ ``os.getenv`` read of a ``KLOGS_*`` key anywhere else in the tree is
a finding, and every knob read here must appear in the README env
table (both directions).

Three validation dialects exist on purpose (callers pick per knob):

- **raise** (:func:`positive_float`, :func:`nonneg_float`): a bad
  value crashes naming the variable. For knobs where silently running
  with a default hides real regressions (timeouts, degrade
  thresholds).
- **warn-and-default** (:func:`warn_positive_int`,
  :func:`warn_nonneg_float`): a bad value prints one stderr notice and
  keeps the default. For server-side knobs where a typo must not kill
  a multi-tenant daemon at import time.
- **passthrough** (:func:`read` / :func:`is_set`): string knobs (file
  paths, mode selectors, fault scripts) whose validation is inherently
  site-specific; the site keeps its logic but the read still flows
  through here so the discipline pass can see it.
"""

from __future__ import annotations

import math
import os


def read(name: str, default: "str | None" = None) -> "str | None":
    """THE raw environment read. Every KLOGS_* knob in the tree flows
    through this module; see the module docstring for why."""
    return os.environ.get(name, default)


def is_set(name: str) -> bool:
    """Whether the knob is present at all (some knobs distinguish
    'unset' from any value — e.g. KLOGS_TRACE_SAMPLE=0 vs absent)."""
    return os.environ.get(name) is not None


def positive_float(name: str, default: float,
                   exc: type = ValueError) -> float:
    """Strict positive finite float; zero/negative/nan/inf/garbage
    raises ``exc`` naming the variable (a bad knob must not surface as
    a mystery timeout downstream). nan compares False against
    everything and inf is no deadline at all — both are garbage for a
    knob documented as a positive number of seconds."""
    raw = read(name)
    if raw is None:
        return default
    try:
        value = float(raw)
        if not math.isfinite(value) or value <= 0:
            raise ValueError("must be positive and finite")
    except ValueError as e:
        raise exc(
            f"{name} must be a positive number, got {raw!r}") from e
    return value


def nonneg_float(name: str, default: float) -> float:
    """Strict non-negative finite float; malformed values raise
    (silent misconfiguration of a degrade knob hides real
    regressions)."""
    raw = read(name)
    if raw is None:
        return default
    try:
        v = float(raw)
    except ValueError:
        raise ValueError(f"{name}={raw!r}: expected a number") from None
    if not math.isfinite(v) or v < 0:
        raise ValueError(f"{name}={raw!r}: expected a finite value >= 0")
    return v


def warn_positive_int(name: str, default: int) -> int:
    """Positive-int knob; malformed values warn and fall back rather
    than crashing module import with a bare ValueError."""
    raw = read(name)
    if raw is None:
        return default
    try:
        val = int(raw)
    except ValueError:
        val = 0
    if val < 1:
        import sys

        print(f"klogs: ignoring invalid {name}={raw!r} (want a positive "
              f"integer); using {default}", file=sys.stderr)
        return default
    return val


def warn_nonneg_float(name: str, default: float) -> float:
    """Non-negative float knob (0 commonly means 'disabled'); a bad
    value degrades to the default loudly instead of killing the
    server."""
    raw = read(name)
    if raw is None:
        return default
    try:
        val = float(raw)
        if not math.isfinite(val) or val < 0:
            raise ValueError
    except ValueError:
        import sys

        print(f"klogs: ignoring invalid {name}={raw!r} (want a "
              f"non-negative number); using {default}", file=sys.stderr)
        return default
    return val
