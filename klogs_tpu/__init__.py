"""klogs-tpu: a TPU-native log acquisition and filtering framework.

A ground-up rebuild of the capabilities of rogosprojects/klogs
(reference: /root/reference, a Go CLI that fans per-container Kubernetes
log streams out to files; cmd/root.go:436-497) re-designed TPU-first:

- the CLI / pod-discovery / fan-out / file-sink surface of klogs is kept
  behaviorally identical (flags, naming, UX; see ``klogs_tpu.cli``),
- a new ``--match <regex>`` line-filter stage is added whose hot path is
  a bit-parallel batch-NFA evaluated on TPU via JAX/Pallas under
  ``shard_map`` over a device mesh (see ``klogs_tpu.filters`` and
  ``klogs_tpu.ops``).

Layer map (mirrors SURVEY.md §1):
  L1 CLI            klogs_tpu.cli
  L2 terminal UI    klogs_tpu.ui
  L3 cluster access klogs_tpu.cluster  (real REST client + hermetic fake)
  L4 log streams    klogs_tpu.cluster.backend.LogStream
  L4.5 filtering    klogs_tpu.filters (LineBatcher, LogFilter, NFA, TPU)
  L5 concurrency    klogs_tpu.runtime (asyncio fan-out)
  L6 sink           klogs_tpu.runtime.sink
  mesh/collectives  klogs_tpu.parallel
"""

from klogs_tpu.version import BUILD_VERSION

__version__ = BUILD_VERSION

__all__ = ["BUILD_VERSION", "__version__"]
