"""Multi-host initialization for the filter mesh.

Single-host multi-chip needs nothing: ``jax.devices()`` sees every local
chip and MeshEngine builds its (data, pattern) mesh over them, with the
pattern-OR collective riding ICI.

Multi-host (e.g. v5e-16+ pods, or a filterd fleet spanning hosts) uses
jax's standard distributed runtime over DCN: every process calls
``initialize()`` before first jax use, after which ``jax.devices()``
is the GLOBAL device list and the same MeshEngine code shards over all
hosts — collectives ride ICI within a slice and DCN across hosts, laid
out by XLA from the mesh axes (scaling-book recipe; nothing here is
host-count-aware).

The reference is strictly single-process (one Go binary, SURVEY.md §2);
this is the subsystem its design never needed but the TPU architecture
makes first-class.

Environment-driven (the TPU runtime populates these on Cloud TPU pods;
set them manually elsewhere):
  KLOGS_COORDINATOR   host:port of process 0 (else jax defaults apply)
  KLOGS_NUM_PROCESSES total process count
  KLOGS_PROCESS_ID    this process's index

CPU fleets: cross-process collectives ride jax's gloo backend (the
default `jax_cpu_collectives_implementation`). The platform must be
pinned (JAX_PLATFORMS=cpu) BEFORE first backend init — an ambient
accelerator plugin that doesn't support multi-process leaves
process_count() at 1 after an apparently-successful handshake
(observed with the axon TPU tunnel plugin; root-caused 2026-07-31).
Validated live by tests/test_distributed.py's two-controller run.
"""

import jax

from klogs_tpu.utils.env import read as _env_read


def initialize(coordinator: str | None = None,
               num_processes: int | None = None,
               process_id: int | None = None) -> None:
    """Idempotent jax.distributed bring-up. No-ops when the environment
    describes a single process."""
    coordinator = coordinator or _env_read("KLOGS_COORDINATOR")
    num_processes = num_processes or _int_env("KLOGS_NUM_PROCESSES")
    process_id = process_id if process_id is not None else _int_env("KLOGS_PROCESS_ID")
    if num_processes in (None, 1):
        return
    jax.distributed.initialize(
        coordinator_address=coordinator,
        num_processes=num_processes,
        process_id=process_id,
    )


def _int_env(name: str) -> int | None:
    v = _env_read(name)
    return int(v) if v else None
