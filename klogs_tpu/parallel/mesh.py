"""Device-mesh execution of the batch-NFA filter.

SPMD layout (SURVEY.md §2 "Mesh/sharding layer", §5 "Distributed
communication backend"): a 2-D ``Mesh`` with axes

- ``data``    — lines (DP): the [B, L] byte batch is row-sharded.
- ``pattern`` — pattern groups (the TP analog): the K patterns are
  split into G groups, each compiled to its own automaton; the stacked
  [G, ...] program arrays are sharded one group per mesh column.

The per-line any-match reduce across pattern shards is expressed as a
plain ``jnp.any`` over the group axis; GSPMD lowers it to an all-reduce
over ICI. No hand-written collectives — shardings are annotated and XLA
inserts the comms (the reference's only comm stack is REST to the
apiserver, cmd/root.go:322-325; this is its on-mesh equivalent).

Multi-host: under ``jax.distributed`` the same Mesh spans hosts over
DCN transparently; nothing here is host-count-aware.
"""

import math

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from klogs_tpu.filters.compiler.glushkov import compile_patterns
from klogs_tpu.ops import nfa


def choose_grid(n_devices: int, n_patterns: int) -> tuple[int, int]:
    """(data, pattern) mesh shape: give the pattern axis at most as many
    shards as there are patterns, keep it a divisor of the device count,
    and spend the rest on data parallelism. Batch is the throughput axis,
    so data gets the benefit of the doubt on ties."""
    g = 1
    for cand in range(min(n_devices, n_patterns), 0, -1):
        if n_devices % cand == 0:
            g = cand
            break
    d = n_devices // g
    # Prefer data-major splits: if the pattern axis ended up bigger than
    # data for a small pattern count, rebalance toward data.
    while g >= 2 * d and g % 2 == 0:
        g //= 2
        d *= 2
    return d, g


def split_patterns(patterns: list[str], g: int) -> list[list[str]]:
    """Round-robin so group automaton sizes stay balanced."""
    groups = [patterns[i::g] for i in range(g)]
    return [grp for grp in groups if grp]


class MeshEngine:
    """Pattern-sharded, data-parallel match engine over a jax Mesh.

    Drop-in ``engine`` for NFAEngineFilter: exposes match_batch over
    numpy arrays, returning a device mask handle.

    Two SPMD implementations with identical semantics:

    - ``impl="gspmd"`` (default): sharding annotations on a vmapped
      any-match; XLA's partitioner inserts the cross-shard all-reduce.
    - ``impl="shard_map"``: per-shard code with an EXPLICIT collective —
      each pattern shard evaluates its own automaton on its data rows,
      then ``jax.lax.pmax`` ORs the bitmask across the ``pattern`` axis
      over ICI. Same collective XLA would insert, written out so the
      comm pattern is visible/auditable (SURVEY.md §5 "Distributed
      communication backend").
    - ``impl="pallas"`` (and ``"pallas_interpret"`` for hermetic tests):
      shard_map with the grouped Pallas kernel as the per-shard compute —
      the production multi-chip hot path (VMEM-resident kernel per chip,
      pmax OR across pattern shards over ICI). Pattern groups are
      bin-packed per shard via compile_grouped.
    """

    def __init__(self, patterns: list[str], ignore_case: bool = False,
                 devices=None, grid: tuple[int, int] | None = None,
                 impl: str = "gspmd"):
        devices = devices if devices is not None else jax.devices()
        if grid is None:
            grid = choose_grid(len(devices), len(patterns))
        d, g = grid
        if d * g != len(devices):
            raise ValueError(f"grid {grid} != device count {len(devices)}")
        groups = split_patterns(patterns, g)
        # If fewer pattern groups than shards, replicate the last: a
        # duplicate group changes nothing under any-match.
        while len(groups) < grid[1]:
            groups.append(groups[-1])
        self.grid = (d, grid[1])
        self.mesh = Mesh(np.asarray(devices).reshape(self.grid), ("data", "pattern"))
        # Under jax.distributed the mesh spans processes: host numpy
        # can no longer be handed to jit/device_put directly — every
        # process holds the SAME full array and materializes only its
        # addressable shards (make_array_from_callback; the
        # replicated-input SPMD recipe). Single-process keeps the
        # zero-copy direct path.
        self._multiprocess = jax.process_count() > 1
        if impl in ("pallas", "pallas_interpret"):
            self._init_pallas(groups, ignore_case, impl)
            return
        progs = [compile_patterns(grp, ignore_case=ignore_case) for grp in groups]
        self.dp = nfa.stack_programs(progs)
        self.match_all = self.dp.match_all

        prog_sharding = jax.tree_util.tree_map(
            lambda _: NamedSharding(self.mesh, P("pattern")), self.dp
        )
        if self._multiprocess:
            self.dp = jax.tree_util.tree_map(self._global_leaf, self.dp,
                                             prog_sharding)
        else:
            self.dp = jax.device_put(self.dp, prog_sharding)
        if impl == "gspmd":
            self._fn = jax.jit(
                nfa.match_batch_grouped,
                in_shardings=(
                    prog_sharding,
                    NamedSharding(self.mesh, P("data", None)),
                    NamedSharding(self.mesh, P("data")),
                ),
                out_shardings=NamedSharding(self.mesh, P("data")),
            )
        elif impl == "shard_map":
            try:
                from jax import shard_map  # jax >= 0.8
            except ImportError:
                from jax.experimental.shard_map import shard_map

            def per_shard(dp_shard, batch_local, lengths_local):
                # dp leaves arrive with a leading local group axis of 1.
                local = jax.tree_util.tree_map(lambda x: x[0], dp_shard)
                matched = nfa.match_batch(local, batch_local, lengths_local)
                # OR across pattern shards = max of 0/1 over the axis;
                # rides ICI when the mesh spans chips.
                return jax.lax.pmax(matched.astype(jnp.int32), "pattern") > 0

            specs = dict(
                mesh=self.mesh,
                in_specs=(
                    jax.tree_util.tree_map(lambda _: P("pattern"), self.dp),
                    P("data", None),
                    P("data"),
                ),
                out_specs=P("data"),
            )
            # The scan carry is zeros-initialized inside match_batch,
            # which the varying-manual-axes checker flags as
            # unvarying-meets-varying; the pmax above establishes the
            # replication the out_spec needs, so the check is safely
            # off. (Knob renamed check_rep -> check_vma in jax 0.8.)
            try:
                smapped = shard_map(per_shard, check_vma=False, **specs)
            except TypeError:
                smapped = shard_map(per_shard, check_rep=False, **specs)
            self._fn = jax.jit(smapped)
        else:
            raise ValueError(f"unknown impl {impl!r}")
        self.impl = impl

    def _init_pallas(self, groups: list[list[str]], ignore_case: bool,
                     impl: str) -> None:
        """shard_map with the grouped Pallas kernel as per-shard compute
        — the production multi-chip hot path, running the SAME
        architecture as single-chip: host-side fused pack+classify (the
        device classify gather measured as ~85% of device time,
        BENCH_DEVICE.json), int8 class ids sharded over `data`, kernel
        consuming classes directly, pmax OR across `pattern` shards.

        Shards must be shape-uniform, so each shard's pattern set
        compiles twice: once to learn its natural (G, S, C), then with
        forced pads to the maxima (dead filler groups can never match).
        Because every shard must classify a line identically for ONE
        host-side cls array to serve all pattern shards, the per-shard
        classifiers are refined into a GLOBAL one (unique rows of the
        stacked byte->class signatures) and each shard's char_mask rows
        are re-laid-out onto the global classes.

        KLOGS_TPU_PREFILTER=1 additionally stacks per-shard class-domain
        prefilter tables so each shard tile-skips on its own patterns'
        candidate mask (all-or-nothing across shards, matching the
        single-chip usability rule)."""
        import dataclasses

        from klogs_tpu.ops.nfa import _pad_to
        from klogs_tpu.utils.env import read as env_read
        from klogs_tpu.ops.pallas_nfa import (
            match_batch_grouped_pallas,
            match_cls_grouped_pallas,
        )

        probe = [nfa.compile_grouped(ps, ignore_case=ignore_case)[0]
                 for ps in groups]
        G = max(p.follow.shape[0] for p in probe)
        S = max(p.n_states for p in probe)
        # No classes_pad: the whole class axis (char_mask rows,
        # byte_class, sentinels, n_classes) is rebuilt onto the global
        # classifier below, so only group/state shapes need forcing.
        dps = [nfa.compile_grouped(ps, ignore_case=ignore_case,
                                   n_groups=G, states_pad=S)[0]
               for ps in groups]
        live, acc = S - 2, S - 1

        # Global classifier: bytes equivalent in EVERY shard collapse.
        sig = np.stack([np.asarray(d.byte_class) for d in dps], axis=1)
        uniq, glob = np.unique(sig, axis=0, return_inverse=True)
        n_glob = uniq.shape[0]
        C = _pad_to(n_glob + 3, 8)
        begin_c, end_c, pad_c = C - 3, C - 2, C - 1
        redps = []
        for k, d in enumerate(dps):
            cm = np.asarray(d.char_mask)  # [G, C_loc, S]
            ncm = np.zeros((G, C, S), dtype=cm.dtype)
            ncm[:, :n_glob, :] = cm[:, uniq[:, k], :]
            ncm[:, begin_c] = cm[:, d.begin_class]
            ncm[:, end_c] = cm[:, d.end_class]
            ncm[:, pad_c] = cm[:, d.pad_class]
            redps.append(dataclasses.replace(
                d,
                char_mask=jnp.asarray(ncm),
                byte_class=jnp.asarray(glob.astype(np.int32)),
                begin_class=begin_c, end_class=end_c, pad_class=pad_c,
                n_classes=C,
                # match_all is pytree AUX and may differ across shards;
                # stacking requires identical aux, so force the any()
                # verdict uniformly — the OR across shards is what the
                # engine computes anyway. pattern_group (also aux)
                # differs per shard too and only feeds the single-chip
                # per-(tile, group) gate; the mesh path gates per tile,
                # so clear it uniformly.
                match_all=any(x.match_all for x in dps),
                pattern_group=(),
            ))
        stacked = jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *redps)
        if self._multiprocess:
            shardings = jax.tree_util.tree_map(
                lambda _: NamedSharding(self.mesh, P("pattern")), stacked)
            stacked = jax.tree_util.tree_map(self._global_leaf, stacked,
                                             shardings)
        self.dp = stacked
        self.match_all = stacked.match_all
        self.cls_table = glob.astype(np.int8) if C <= 127 else None
        self._glob = glob.astype(np.int32)
        self.begin_class, self.end_class, self.pad_class = begin_c, end_c, pad_c
        interpret = impl == "pallas_interpret"

        pf_stacked = None
        if env_read("KLOGS_TPU_PREFILTER", "0") == "1" \
                and self.cls_table is not None:
            pf_stacked = self._stack_prefilters(groups, ignore_case, glob, C)

        # Device literal sweep (thousand-pattern fused path): per-shard
        # sweep tables stacked shape-uniform, gating each shard's
        # (tile, group) grid cells on ITS patterns' factor-index
        # candidate mask. The sweep-vs-prefilter precedence is the ONE
        # shared rule (cpu.device_gate_choice, same as tpu._init_sweep):
        # auto K threshold + real accelerator, explicit prefilter
        # opt-in beats auto sweep, forced sweep supersedes — and a
        # working prefilter is only discarded after the tables built.
        sweep_stacked = None
        n_patterns = sum(len(ps) for ps in groups)
        from klogs_tpu.filters.cpu import (
            device_gate_choice,
            note_sweep_supersedes,
        )

        if device_gate_choice(n_patterns,
                              have_prefilter=pf_stacked is not None,
                              interpret=interpret) == "sweep":
            sweep_stacked = self._stack_sweeps(groups, ignore_case, dps, G)
            if sweep_stacked is not None and pf_stacked is not None:
                note_sweep_supersedes(mesh=True)
                pf_stacked = None

        # Same chain-variant policy as the single-chip hot path
        # (tune.chain_selection: measured default mask_block=4 on
        # hardware, env-overridable), minus `fused` — it has no gated
        # sibling while this one per_shard body backs both the plain and
        # gated builds, so chain_selection drops it and we warn.
        from klogs_tpu.ops.tune import chain_selection

        vkw, self._chain_defaulted, dropped_fused = chain_selection(
            not interpret, allow_fused=False)
        if dropped_fused:
            from klogs_tpu.ui import term

            term.warning(
                "KLOGS_TPU_FUSED_GROUPS=1 has no mesh per-shard variant; "
                "using the default chain instead")
        # tile_b is a cap; the kernel wrapper pads any local batch up
        # to a tile multiple, so non-power-of-two shard sizes work.
        vkw.setdefault("tile_b", 2048)
        self._vkw = vkw

        try:
            from jax import shard_map
        except ImportError:
            from jax.experimental.shard_map import shard_map

        def build(with_pf: bool, vkw=vkw):
            def per_shard(dp_shard, cls_local, *pf_shard):
                local = jax.tree_util.tree_map(lambda x: x[0], dp_shard)
                pf = tuple(x[0] for x in pf_shard) if pf_shard else None
                matched = match_cls_grouped_pallas(
                    local, live, acc, cls_local,
                    interpret=interpret,
                    prefilter_tables=pf, **vkw,
                )
                return jax.lax.pmax(matched.astype(jnp.int32), "pattern") > 0

            in_specs = [
                jax.tree_util.tree_map(lambda _: P("pattern"), stacked),
                P("data", None),
            ]
            if with_pf:
                in_specs.extend(P("pattern") for _ in pf_stacked)
            specs = dict(mesh=self.mesh, in_specs=tuple(in_specs),
                         out_specs=P("data"))
            try:
                smapped = shard_map(per_shard, check_vma=False, **specs)
            except TypeError:
                smapped = shard_map(per_shard, check_rep=False, **specs)
            if with_pf:
                return jax.jit(
                    lambda dp, cls, pf=pf_stacked: smapped(dp, cls, *pf))
            return jax.jit(smapped)

        self._build = build

        def build_sweep(vkw=vkw):
            def per_shard(dp_shard, batch_local, lengths_local,
                          sweep_shard):
                local = jax.tree_util.tree_map(lambda x: x[0], dp_shard)
                st = jax.tree_util.tree_map(lambda x: x[0], sweep_shard)
                matched = match_batch_grouped_pallas(
                    local, live, acc, batch_local, lengths_local,
                    interpret=interpret, sweep_tables=st, **vkw)
                return jax.lax.pmax(matched.astype(jnp.int32),
                                    "pattern") > 0

            specs = dict(
                mesh=self.mesh,
                in_specs=(
                    jax.tree_util.tree_map(lambda _: P("pattern"),
                                           stacked),
                    P("data", None),
                    P("data"),
                    jax.tree_util.tree_map(lambda _: P("pattern"),
                                           sweep_stacked),
                ),
                out_specs=P("data"),
            )
            try:
                smapped = shard_map(per_shard, check_vma=False, **specs)
            except TypeError:
                smapped = shard_map(per_shard, check_rep=False, **specs)
            return jax.jit(
                lambda dp, batch, lengths, st=sweep_stacked:
                smapped(dp, batch, lengths, st))

        # The plain fn always exists: it is both the default path and
        # the degrade target when the opt-in gated kernel fails (same
        # contract as the single-chip fetch-time fallback).
        self._fn = build(False)
        self._fn_gated = build(True) if pf_stacked is not None else None
        # Byte-consuming fused path: match_batch routes through it when
        # built (frame -> sweep -> gated match per shard, one device
        # dispatch); match_cls cannot (no bytes to sweep).
        self._fn_sweep = (build_sweep() if sweep_stacked is not None
                          else None)
        self.impl = impl

    def disable_prefilter(self) -> None:
        """Degrade to the plain kernel (e.g. after a gated-kernel
        compile/execution failure surfaced at fetch)."""
        self._fn_gated = None

    @property
    def gated(self) -> bool:
        return getattr(self, "_fn_gated", None) is not None

    def disable_sweep(self) -> None:
        """Degrade the fused sweep path to host-classify + plain kernel
        (e.g. after a sweep-kernel failure surfaced at fetch)."""
        self._fn_sweep = None

    @property
    def swept(self) -> bool:
        return getattr(self, "_fn_sweep", None) is not None

    @staticmethod
    def _stack_sweeps(groups, ignore_case, dps, G):
        """Per-shard device-sweep tables over each shard's OWN pattern
        set, retargeted to its grouped program's pattern_group map (the
        forced-uniform G makes always/group bitsets shape-uniform), and
        stacked [n_shards, ...] via ops.sweep.stack_sweep_tables.
        Returns None (sweep off everywhere) when any shard's tables
        fail to build — shard_map runs one program."""
        from klogs_tpu.filters.compiler.groups import analyze, plan_groups
        from klogs_tpu.filters.compiler.index import FactorIndex
        from klogs_tpu.ops.sweep import stack_sweep_tables

        progs = []
        try:
            for ps, dp in zip(groups, dps):
                infos = analyze(ps, ignore_case=ignore_case)
                index = FactorIndex(infos, plan_groups(infos))
                progs.append(index.sweep_program(
                    group_of=np.asarray(dp.pattern_group,
                                        dtype=np.int32),
                    n_groups=G))
            return stack_sweep_tables(progs)
        except Exception as e:
            from klogs_tpu.ui import term

            term.warning(
                "mesh device sweep unavailable (%s: %s); running the "
                "plain kernel", type(e).__name__, e)
            return None

    @staticmethod
    def _stack_prefilters(groups, ignore_case, glob, C):
        """Per-shard class-domain prefilter tables over the GLOBAL
        classifier, padded shape-uniform and stacked [n_shards, ...].
        Returns None (gating off everywhere) unless every shard's
        pattern set is usable — a shard that cannot gate must still
        scan all its tiles, and shard_map runs one program."""
        from klogs_tpu.filters.compiler.prefilter import compile_prefilter
        from klogs_tpu.ops.prefilter import class_tables

        pfs = [compile_prefilter(ps, ignore_case=ignore_case)
               for ps in groups]
        if not all(pf.usable for pf in pfs):
            return None
        slots = max(pf.lut1.shape[1] * 32 for pf in pfs)
        pats = max(pf.req.shape[0] for pf in pfs)
        tabs = [class_tables(pf, glob, C, slots_pad=slots,
                             patterns_pad=pats) for pf in pfs]
        if any(t is None for t in tabs):
            return None
        return tuple(jnp.stack(xs) for xs in zip(*tabs))

    @property
    def data_parallelism(self) -> int:
        return self.grid[0]

    def _global_leaf(self, arr, sharding):
        """Full host array -> global jax.Array under a multi-process
        mesh (this process materializes its addressable shards)."""
        arr = np.asarray(arr)
        return jax.make_array_from_callback(
            arr.shape, sharding, lambda idx: arr[idx])

    def _place_data(self, arr: np.ndarray, spec):
        """Batch-input placement: direct (jit shards it) in one
        process, global-Array construction across processes."""
        if not self._multiprocess:
            return arr
        return self._global_leaf(arr, NamedSharding(self.mesh, spec))

    def match_batch(self, batch: np.ndarray, lengths: np.ndarray):
        """[B, L] u8 + [B] i32 -> [>=B] bool mask, returned as a DEVICE
        array (padded rows at the tail; callers slice after np.asarray —
        keeps dispatch non-blocking for the async pipeline). B is padded
        up to a multiple of the data axis so every shard gets equal rows.

        The pallas impls consume class ids, so this entry classifies on
        the host (vectorized numpy over the global table) and routes to
        match_cls — same verdicts, one extra host pass; filters that can
        produce cls directly (pack_classify) should call match_cls."""
        if self.impl in ("pallas", "pallas_interpret"):
            if self.swept:
                try:
                    return self._match_batch_swept(batch, lengths)
                except Exception as e:
                    # Fused-sweep compile/dispatch trouble degrades to
                    # the classify path, not a dead stream (same
                    # contract as the gated kernel).
                    from klogs_tpu.ui import term

                    term.warning(
                        "mesh fused sweep kernel unavailable (%s); "
                        "falling back to host classify + plain NFA",
                        str(e)[:120])
                    self.disable_sweep()
            from klogs_tpu.filters.tpu import classify_batch

            cls = classify_batch(batch, lengths, self._glob,
                                 self.begin_class, self.end_class,
                                 self.pad_class)
            return self.match_cls(cls)
        B = batch.shape[0]
        d = self.grid[0]
        Bp = math.ceil(B / d) * d
        if Bp != B:
            batch = np.concatenate(
                [batch, np.zeros((Bp - B, batch.shape[1]), dtype=batch.dtype)]
            )
            lengths = np.concatenate(
                [lengths, np.zeros((Bp - B,), dtype=lengths.dtype)]
            )
        return self._fn(self.dp, self._place_data(batch, P("data", None)),
                        self._place_data(lengths, P("data")))

    def _match_batch_swept(self, batch: np.ndarray, lengths: np.ndarray):
        """Fused byte path: [B, L] u8 + [B] i32 -> [>=B] bool device
        mask via frame -> device sweep -> gated match per shard (one
        dispatch, no host classify). Rows pad to a data-axis multiple;
        zero-length pad rows can never host a factor or match."""
        B = batch.shape[0]
        d = self.grid[0]
        Bp = math.ceil(B / d) * d
        if Bp != B:
            batch = np.concatenate(
                [batch, np.zeros((Bp - B, batch.shape[1]),
                                 dtype=batch.dtype)])
            lengths = np.concatenate(
                [lengths, np.zeros((Bp - B,), dtype=lengths.dtype)])
        from klogs_tpu.obs import trace

        with trace.TRACER.span("mesh.dispatch", impl=self.impl,
                               rows=Bp, swept=True,
                               grid=f"{self.grid[0]}x{self.grid[1]}"):
            return self._fn_sweep(
                self.dp, self._place_data(batch, P("data", None)),
                self._place_data(np.ascontiguousarray(lengths,
                                                      dtype=np.int32),
                                 P("data")))

    def match_cls(self, cls: np.ndarray, plain: bool = False):
        """Hot-path entry for pallas impls: [B, T] int8/int32 class ids
        (pack_classify layout) -> [>=B] bool device mask. Rows are
        padded (all-PAD: cannot match) to a data-axis multiple. The
        gated fn is used when built (KLOGS_TPU_PREFILTER=1) unless
        ``plain`` forces the fallback."""
        B = cls.shape[0]
        d = self.grid[0]
        Bp = math.ceil(B / d) * d
        if Bp != B:
            cls = np.concatenate(
                [cls, np.full((Bp - B, cls.shape[1]), self.pad_class,
                              dtype=cls.dtype)]
            )
        use_gated = not plain and self.gated
        fn = self._fn_gated if use_gated else self._fn
        cls = self._place_data(cls, P("data", None))
        from klogs_tpu.obs import trace

        try:
            with trace.TRACER.span("mesh.dispatch", impl=self.impl,
                                   rows=Bp, gated=use_gated,
                                   grid=f"{self.grid[0]}x{self.grid[1]}"):
                return fn(self.dp, cls)
        except Exception as e:
            # Chain-variant compile fragility is a known failure mode
            # (mask_block=8/16 fail Mosaic on v5e). A DEFAULTED variant
            # failing on the PLAIN fn degrades to the plain chain
            # instead of killing the run. A gated-fn failure is NOT
            # attributed to the chain (the prefilter machinery is the
            # other suspect) — it propagates to the caller's
            # disable-prefilter retry, whose plain rerun comes back
            # through here and exercises this degrade if the chain
            # really is at fault. An env-forced variant stays loud —
            # the operator asked to measure exactly that kernel.
            if use_gated or not getattr(self, "_chain_defaulted", False):
                raise
            from klogs_tpu.ui import term

            term.warning(
                "default mask_block=%d chain failed on this backend (%s); "
                "rebuilding with the plain chain",
                self._vkw.get("mask_block"), str(e)[:120])
            self.degrade_chain()
            return self._fn(self.dp, cls)

    def degrade_chain(self) -> None:
        """Rebuild both fns on the plain serial chain (mask_block=1) —
        the degrade target after a defaulted-chain-variant failure
        (sync, via match_cls; or async at fetch, via the filter's retry
        closure)."""
        self._chain_defaulted = False
        self._vkw = dict(self._vkw, mask_block=1)
        self._fn = self._build(False, self._vkw)
        if self.gated:
            self._fn_gated = self._build(True, self._vkw)

    def close(self) -> None:
        pass
