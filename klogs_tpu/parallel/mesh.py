"""Device-mesh execution of the batch-NFA filter.

SPMD layout (SURVEY.md §2 "Mesh/sharding layer", §5 "Distributed
communication backend"): a 2-D ``Mesh`` with axes

- ``data``    — lines (DP): the [B, L] byte batch is row-sharded.
- ``pattern`` — pattern groups (the TP analog): the K patterns are
  split into G groups, each compiled to its own automaton; the stacked
  [G, ...] program arrays are sharded one group per mesh column.

The per-line any-match reduce across pattern shards is expressed as a
plain ``jnp.any`` over the group axis; GSPMD lowers it to an all-reduce
over ICI. No hand-written collectives — shardings are annotated and XLA
inserts the comms (the reference's only comm stack is REST to the
apiserver, cmd/root.go:322-325; this is its on-mesh equivalent).

Multi-host: under ``jax.distributed`` the same Mesh spans hosts over
DCN transparently; nothing here is host-count-aware.
"""

import math

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from klogs_tpu.filters.compiler.glushkov import compile_patterns
from klogs_tpu.ops import nfa


def choose_grid(n_devices: int, n_patterns: int) -> tuple[int, int]:
    """(data, pattern) mesh shape: give the pattern axis at most as many
    shards as there are patterns, keep it a divisor of the device count,
    and spend the rest on data parallelism. Batch is the throughput axis,
    so data gets the benefit of the doubt on ties."""
    g = 1
    for cand in range(min(n_devices, n_patterns), 0, -1):
        if n_devices % cand == 0:
            g = cand
            break
    d = n_devices // g
    # Prefer data-major splits: if the pattern axis ended up bigger than
    # data for a small pattern count, rebalance toward data.
    while g >= 2 * d and g % 2 == 0:
        g //= 2
        d *= 2
    return d, g


def split_patterns(patterns: list[str], g: int) -> list[list[str]]:
    """Round-robin so group automaton sizes stay balanced."""
    groups = [patterns[i::g] for i in range(g)]
    return [grp for grp in groups if grp]


class MeshEngine:
    """Pattern-sharded, data-parallel match engine over a jax Mesh.

    Drop-in ``engine`` for NFAEngineFilter: exposes match_batch over
    numpy arrays, returning a device mask handle.

    Two SPMD implementations with identical semantics:

    - ``impl="gspmd"`` (default): sharding annotations on a vmapped
      any-match; XLA's partitioner inserts the cross-shard all-reduce.
    - ``impl="shard_map"``: per-shard code with an EXPLICIT collective —
      each pattern shard evaluates its own automaton on its data rows,
      then ``jax.lax.pmax`` ORs the bitmask across the ``pattern`` axis
      over ICI. Same collective XLA would insert, written out so the
      comm pattern is visible/auditable (SURVEY.md §5 "Distributed
      communication backend").
    - ``impl="pallas"`` (and ``"pallas_interpret"`` for hermetic tests):
      shard_map with the grouped Pallas kernel as the per-shard compute —
      the production multi-chip hot path (VMEM-resident kernel per chip,
      pmax OR across pattern shards over ICI). Pattern groups are
      bin-packed per shard via compile_grouped.
    """

    def __init__(self, patterns: list[str], ignore_case: bool = False,
                 devices=None, grid: tuple[int, int] | None = None,
                 impl: str = "gspmd"):
        devices = devices if devices is not None else jax.devices()
        if grid is None:
            grid = choose_grid(len(devices), len(patterns))
        d, g = grid
        if d * g != len(devices):
            raise ValueError(f"grid {grid} != device count {len(devices)}")
        groups = split_patterns(patterns, g)
        # If fewer pattern groups than shards, replicate the last: a
        # duplicate group changes nothing under any-match.
        while len(groups) < grid[1]:
            groups.append(groups[-1])
        self.grid = (d, grid[1])
        self.mesh = Mesh(np.asarray(devices).reshape(self.grid), ("data", "pattern"))
        if impl in ("pallas", "pallas_interpret"):
            self._init_pallas(groups, ignore_case, impl)
            return
        progs = [compile_patterns(grp, ignore_case=ignore_case) for grp in groups]
        self.dp = nfa.stack_programs(progs)
        self.match_all = self.dp.match_all

        prog_sharding = jax.tree_util.tree_map(
            lambda _: NamedSharding(self.mesh, P("pattern")), self.dp
        )
        self.dp = jax.device_put(self.dp, prog_sharding)
        if impl == "gspmd":
            self._fn = jax.jit(
                nfa.match_batch_grouped,
                in_shardings=(
                    prog_sharding,
                    NamedSharding(self.mesh, P("data", None)),
                    NamedSharding(self.mesh, P("data")),
                ),
                out_shardings=NamedSharding(self.mesh, P("data")),
            )
        elif impl == "shard_map":
            try:
                from jax import shard_map  # jax >= 0.8
            except ImportError:
                from jax.experimental.shard_map import shard_map

            def per_shard(dp_shard, batch_local, lengths_local):
                # dp leaves arrive with a leading local group axis of 1.
                local = jax.tree_util.tree_map(lambda x: x[0], dp_shard)
                matched = nfa.match_batch(local, batch_local, lengths_local)
                # OR across pattern shards = max of 0/1 over the axis;
                # rides ICI when the mesh spans chips.
                return jax.lax.pmax(matched.astype(jnp.int32), "pattern") > 0

            specs = dict(
                mesh=self.mesh,
                in_specs=(
                    jax.tree_util.tree_map(lambda _: P("pattern"), self.dp),
                    P("data", None),
                    P("data"),
                ),
                out_specs=P("data"),
            )
            # The scan carry is zeros-initialized inside match_batch,
            # which the varying-manual-axes checker flags as
            # unvarying-meets-varying; the pmax above establishes the
            # replication the out_spec needs, so the check is safely
            # off. (Knob renamed check_rep -> check_vma in jax 0.8.)
            try:
                smapped = shard_map(per_shard, check_vma=False, **specs)
            except TypeError:
                smapped = shard_map(per_shard, check_rep=False, **specs)
            self._fn = jax.jit(smapped)
        else:
            raise ValueError(f"unknown impl {impl!r}")
        self.impl = impl

    def _init_pallas(self, groups: list[list[str]], ignore_case: bool,
                     impl: str) -> None:
        """shard_map with the grouped Pallas kernel as per-shard compute
        — the production multi-chip hot path. Shards must be
        shape-uniform, so each shard's pattern set compiles twice: once
        to learn its natural (G, S, C), then with forced pads to the
        maxima (dead filler groups can never match)."""
        from klogs_tpu.ops.pallas_nfa import match_batch_grouped_pallas

        probe = [nfa.compile_grouped(ps, ignore_case=ignore_case)[0]
                 for ps in groups]
        G = max(p.follow.shape[0] for p in probe)
        S = max(p.n_states for p in probe)
        C = max(p.n_classes for p in probe)
        dps = [nfa.compile_grouped(ps, ignore_case=ignore_case,
                                   n_groups=G, states_pad=S, classes_pad=C)[0]
               for ps in groups]
        live, acc = S - 2, S - 1
        # match_all is pytree AUX data and may differ across shards (a
        # nullable pattern in one group only); tree_map stacking requires
        # identical aux, so force the any() verdict uniformly — the OR
        # across shards is what the engine computes anyway.
        import dataclasses

        any_match_all = any(d.match_all for d in dps)
        dps = [dataclasses.replace(d, match_all=any_match_all) for d in dps]
        stacked = jax.tree_util.tree_map(
            lambda *xs: jnp.stack(xs), *dps
        )  # leaves [n_shards, ...]; aux uniform by construction
        self.dp = stacked
        self.match_all = stacked.match_all
        interpret = impl == "pallas_interpret"

        def per_shard(dp_shard, batch_local, lengths_local):
            local = jax.tree_util.tree_map(lambda x: x[0], dp_shard)
            # tile_b is a cap; the kernel wrapper pads any local batch up
            # to a tile multiple, so non-power-of-two shard sizes work.
            matched = match_batch_grouped_pallas(
                local, live, acc, batch_local, lengths_local,
                tile_b=2048, interpret=interpret,
            )
            return jax.lax.pmax(matched.astype(jnp.int32), "pattern") > 0

        try:
            from jax import shard_map
        except ImportError:
            from jax.experimental.shard_map import shard_map

        specs = dict(
            mesh=self.mesh,
            in_specs=(
                jax.tree_util.tree_map(lambda _: P("pattern"), stacked),
                P("data", None),
                P("data"),
            ),
            out_specs=P("data"),
        )
        try:
            smapped = shard_map(per_shard, check_vma=False, **specs)
        except TypeError:
            smapped = shard_map(per_shard, check_rep=False, **specs)
        self._fn = jax.jit(smapped)
        self.impl = impl

    @property
    def data_parallelism(self) -> int:
        return self.grid[0]

    def match_batch(self, batch: np.ndarray, lengths: np.ndarray):
        """[B, L] u8 + [B] i32 -> [>=B] bool mask, returned as a DEVICE
        array (padded rows at the tail; callers slice after np.asarray —
        keeps dispatch non-blocking for the async pipeline). B is padded
        up to a multiple of the data axis so every shard gets equal rows."""
        B = batch.shape[0]
        d = self.grid[0]
        Bp = math.ceil(B / d) * d
        if Bp != B:
            batch = np.concatenate(
                [batch, np.zeros((Bp - B, batch.shape[1]), dtype=batch.dtype)]
            )
            lengths = np.concatenate(
                [lengths, np.zeros((Bp - B,), dtype=lengths.dtype)]
            )
        return self._fn(self.dp, batch, lengths)

    def close(self) -> None:
        pass
