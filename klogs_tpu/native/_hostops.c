/* _hostops — native host-side hot loop for klogs_tpu.
 *
 * The TPU engine consumes fixed-width [batch, width] uint8 tensors; the
 * pure-Python packer (one numpy frombuffer+copy per line) caps the host
 * path well below device rate. This module does the pack in one C pass.
 *
 * The reference's only native aspect is being a compiled Go binary
 * (SURVEY.md section 2); its host hot loop is io.Copy
 * (/root/reference/cmd/root.go:359-374). This is the equivalent
 * native layer for the batched-filter design.
 *
 * Exposed functions (GIL-holding except pack_classify's optional
 * KLOGS_HOST_THREADS row-parallel phase; no numpy C-API dependency —
 * callers wrap the returned buffers with np.frombuffer):
 *
 *   pack_lines(lines: list[bytes], width: int, rows: int)
 *       -> (buffer: bytes, lengths: bytes holding int32[rows])
 *     Zero-padded row-major [rows, width] pack; rows >= len(lines), the
 *     excess rows are zero (empty lines). A line longer than width is
 *     truncated (callers route long lines to the chunked path first).
 *
 *   count_keep_bytes(lines: list[bytes], mask: bytes) -> int
 *   join_kept(lines: list[bytes], mask: bytes) -> bytes
 *     Gather of mask-selected lines into one contiguous write buffer.
 */

#define PY_SSIZE_T_CLEAN
#include <Python.h>
#include <string.h>
#include <stdint.h>

/* Pair-LUT classification: one 64K-entry uint16 table maps two input
 * bytes to two class bytes per lookup — measured 3.65 GB/s vs 2.43 GB/s
 * for the per-byte 256-entry loop on the bench host (tools microbench,
 * 2026-07-30); the per-byte table stays for odd tails. Built lazily and
 * cached against the 256-byte source table (one filter process uses one
 * classifier; a memcmp guards pattern-set changes). GIL held throughout
 * this module, so the static cache needs no locking. The build is
 * endian-agnostic: index and entry are composed through memcpy exactly
 * like the hot loop reads/writes them. */
static uint8_t pair_src[256];
static uint16_t pair_tab[65536];
static int pair_valid = 0;

static const uint16_t *
get_pair_tab(const int8_t *tab)
{
    if (!pair_valid || memcmp(pair_src, tab, 256) != 0) {
        for (int a = 0; a < 256; a++) {
            for (int b = 0; b < 256; b++) {
                uint8_t pr[2] = {(uint8_t)a, (uint8_t)b};
                uint8_t cr[2] = {(uint8_t)tab[a], (uint8_t)tab[b]};
                uint16_t w, c;
                memcpy(&w, pr, 2);
                memcpy(&c, cr, 2);
                pair_tab[w] = c;
            }
        }
        memcpy(pair_src, tab, 256);
        pair_valid = 1;
    }
    return pair_tab;
}

/* Classify `len` bytes from src into dst via the pair LUT. */
static inline void
classify_span(int8_t *dst, const uint8_t *src, Py_ssize_t len,
              const int8_t *tab, const uint16_t *ptab)
{
    Py_ssize_t j = 0;
    for (; j + 2 <= len; j += 2) {
        uint16_t w, c;
        memcpy(&w, src + j, 2);
        c = ptab[w];
        memcpy(dst + j, &c, 2);
    }
    if (j < len)
        dst[j] = tab[src[j]];
}

/* Optional row-parallel execution of the pack_classify body.
 *
 * KLOGS_HOST_THREADS=N (N>1) splits the row loop across N pthreads with
 * the GIL RELEASED — the per-row work below is pure C over buffers whose
 * line pointers/lengths were snapshotted under the GIL (PyBytes are
 * immutable, and the caller's list holds the references alive for the
 * duration of the call). On the single-core bench host this cannot be
 * measured (nproc=1); it exists for production TPU hosts, where dozens
 * of cores feed one device and the single-threaded packer (9.4M
 * lines/s here) would otherwise be the sustained-rate bound against a
 * faster-than-tunnel device link. Default (unset / 1) takes the
 * original GIL-holding single-pass path, byte-for-byte identical
 * output (covered by tests/test_native.py parity over both settings).
 */
#include <pthread.h>

typedef struct {
    const char **ptrs;          /* [rows] line pointers (NULL past n) */
    const Py_ssize_t *lens;     /* [rows] clamped line lengths */
    int8_t *out;
    int32_t *lengths;
    Py_ssize_t T;
    const int8_t *tab;
    const uint16_t *ptab;
    int begin_c, end_c, pad_c;
    Py_ssize_t lo, hi;          /* row range for this worker */
} pack_job;

static void
pack_rows(const pack_job *job)
{
    const Py_ssize_t T = job->T;
    for (Py_ssize_t i = job->lo; i < job->hi; i++) {
        int8_t *row = job->out + i * T;
        Py_ssize_t len = job->lens[i];
        if (len > 0)
            classify_span(row + 1, (const uint8_t *)job->ptrs[i], len,
                          job->tab, job->ptab);
        row[0] = (int8_t)job->begin_c;
        row[1 + len] = (int8_t)job->end_c;
        memset(row + 2 + len, (int8_t)job->pad_c, T - 2 - len);
        job->lengths[i] = (int32_t)len;
    }
}

static void *
pack_worker(void *arg)
{
    pack_rows((const pack_job *)arg);
    return NULL;
}

static int
host_threads(void)
{
    const char *s = getenv("KLOGS_HOST_THREADS");
    if (!s)
        return 1;
    int n = atoi(s);
    return n < 1 ? 1 : (n > 64 ? 64 : n);
}

/* THE one spawn/join/inline-fallback loop for row-parallel work
 * (pack_classify, pack_classify_framed, dfa_scan all dispatch through
 * here — the failure-handling rules live in exactly one place):
 * jobs[0..count) are pre-sliced clones; the LAST live slice runs
 * inline on this thread, a failed pthread_create degrades that slice
 * to inline execution, and every spawned worker is joined before
 * return. Call with the GIL released; job structs must reference no
 * Python objects. */
/* Clone *proto into jobs[0..count) slices covering [0, rows) in
 * contiguous ranges of ceil(rows/nthreads) rounded up to `align` rows
 * (lane-aligned splits keep interleaved loops on full groups except at
 * each slice's own tail); writes the bounds through the lo/hi field
 * offsets so pack_job and dfa_job share one slicer. Returns the live
 * slice count. */
#include <stddef.h>

static int
slice_jobs(char *jobs, size_t jsz, const void *proto, Py_ssize_t rows,
           int nthreads, Py_ssize_t align, size_t lo_off, size_t hi_off)
{
    Py_ssize_t per = (rows + nthreads - 1) / nthreads;
    per = (per + align - 1) / align * align;
    if (per < 1)
        per = 1;
    int count = 0;
    for (int t = 0; t < nthreads; t++) {
        Py_ssize_t lo = (Py_ssize_t)t * per;
        Py_ssize_t hi = lo + per < rows ? lo + per : rows;
        if (lo >= hi)
            break;
        char *j = jobs + (size_t)count * jsz;
        memcpy(j, proto, jsz);
        *(Py_ssize_t *)(j + lo_off) = lo;
        *(Py_ssize_t *)(j + hi_off) = hi;
        count++;
    }
    return count;
}

static void
pack_rows_run(void *arg)
{
    pack_rows((const pack_job *)arg);
}

static void
dispatch_row_jobs(char *jobs, size_t jsz, int count,
                  void *(*worker)(void *), void (*run)(void *))
{
    pthread_t tids[64];
    int started = 0;
    for (int t = 0; t < count; t++) {
        void *j = jobs + (size_t)t * jsz;
        if (t == count - 1) {
            run(j);
            break;
        }
        if (pthread_create(&tids[started], NULL, worker, j) != 0) {
            run(j);
            continue;
        }
        started++;
    }
    for (int t = 0; t < started; t++)
        pthread_join(tids[t], NULL);
}

static PyObject *
pack_lines(PyObject *self, PyObject *args)
{
    PyObject *list;
    Py_ssize_t width, rows;
    if (!PyArg_ParseTuple(args, "O!nn", &PyList_Type, &list, &width, &rows))
        return NULL;
    Py_ssize_t n = PyList_GET_SIZE(list);
    if (rows < n)
        rows = n;
    if (width <= 0) {
        PyErr_SetString(PyExc_ValueError, "width must be positive");
        return NULL;
    }

    PyObject *buf = PyBytes_FromStringAndSize(NULL, rows * width);
    PyObject *lens = PyBytes_FromStringAndSize(NULL, rows * 4);
    if (!buf || !lens) {
        Py_XDECREF(buf);
        Py_XDECREF(lens);
        return NULL;
    }
    char *out = PyBytes_AS_STRING(buf);
    int32_t *lengths = (int32_t *)PyBytes_AS_STRING(lens);
    memset(out, 0, rows * width);
    memset(lengths, 0, rows * 4);

    for (Py_ssize_t i = 0; i < n; i++) {
        PyObject *item = PyList_GET_ITEM(list, i);
        char *p;
        Py_ssize_t len;
        if (PyBytes_AsStringAndSize(item, &p, &len) < 0) {
            Py_DECREF(buf);
            Py_DECREF(lens);
            return NULL;
        }
        Py_ssize_t c = len < width ? len : width;
        memcpy(out + i * width, p, c);
        lengths[i] = (int32_t)c;
    }
    return Py_BuildValue("(NN)", buf, lens);
}

/* pack_classify(lines, width, rows, table[256] bytes, begin, end, pad)
 *   -> (cls: bytes holding int8[rows, width+3], lengths: int32[rows])
 *
 * Fused pack + byte->class classification with the sentinel layout the
 * grouped Pallas kernel consumes directly (klogs_tpu/ops/pallas_nfa.py):
 *   col 0            BEGIN
 *   cols 1..len      table[byte]
 *   col len+1        END
 *   cols len+2..     PAD (includes the accept-latch step)
 * Device-side classify_chunk (a [B,T] gather) measured as ~85% of the
 * single-chip hot-path device time (BENCH_DEVICE.json "host_classify"
 * probe, 2026-07-29); one host pass removes it entirely. Excess rows
 * (rows > len(lines)) are packed as empty lines (BEGIN,END,PAD...).
 */
static PyObject *
pack_classify(PyObject *self, PyObject *args)
{
    PyObject *list;
    Py_ssize_t width, rows;
    Py_buffer table;
    int begin_c, end_c, pad_c;
    if (!PyArg_ParseTuple(args, "O!nny*iii", &PyList_Type, &list, &width,
                          &rows, &table, &begin_c, &end_c, &pad_c))
        return NULL;
    if (table.len < 256) {
        PyBuffer_Release(&table);
        PyErr_SetString(PyExc_ValueError, "class table must have 256 entries");
        return NULL;
    }
    Py_ssize_t n = PyList_GET_SIZE(list);
    if (rows < n)
        rows = n;
    if (width <= 0) {
        PyBuffer_Release(&table);
        PyErr_SetString(PyExc_ValueError, "width must be positive");
        return NULL;
    }
    const Py_ssize_t T = width + 3;
    PyObject *buf = PyBytes_FromStringAndSize(NULL, rows * T);
    PyObject *lens = PyBytes_FromStringAndSize(NULL, rows * 4);
    if (!buf || !lens) {
        PyBuffer_Release(&table);
        Py_XDECREF(buf);
        Py_XDECREF(lens);
        return NULL;
    }
    const int8_t *tab = (const int8_t *)table.buf;
    const uint16_t *ptab = get_pair_tab(tab);
    int8_t *out = (int8_t *)PyBytes_AS_STRING(buf);
    int32_t *lengths = (int32_t *)PyBytes_AS_STRING(lens);
    int nthreads = host_threads();

    if (nthreads <= 1 || rows < 4096) {
        /* Default path: one fused pass, zero scratch allocations (the
         * measured 9.4M lines/s loop). Also the degrade target when the
         * threaded path's snapshots can't be allocated. No up-front
         * whole-buffer memset: each row writes BEGIN + body + END and
         * pads only its own tail — for near-full rows (the common
         * bucket) that is a handful of bytes instead of touching the
         * 30+ MB buffer twice. */
fused:
        for (Py_ssize_t i = 0; i < rows; i++) {
            int8_t *row = out + i * T;
            Py_ssize_t len = 0;
            if (i < n) {
                PyObject *item = PyList_GET_ITEM(list, i);
                char *p;
                if (PyBytes_AsStringAndSize(item, &p, &len) < 0) {
                    PyBuffer_Release(&table);
                    Py_DECREF(buf);
                    Py_DECREF(lens);
                    return NULL;
                }
                if (len > width)
                    len = width;
                classify_span(row + 1, (const uint8_t *)p, len, tab, ptab);
            }
            row[0] = (int8_t)begin_c;
            row[1 + len] = (int8_t)end_c;
            memset(row + 2 + len, (int8_t)pad_c, T - 2 - len);
            lengths[i] = (int32_t)len;
        }
        PyBuffer_Release(&table);
        return Py_BuildValue("(NN)", buf, lens);
    }

    /* Threaded path (KLOGS_HOST_THREADS>1): snapshot line pointers/
     * lengths under the GIL, then run the row loop GIL-free across
     * pthreads. Requirements, all enforced below — failure of any
     * allocation degrades to the fused path above via `goto fused`:
     * (a) workers must never read the shared static pair-LUT cache
     *     (another Python thread could call in with a different
     *     classifier and rebuild it mid-read) -> call-local copies;
     * (b) the caller's list can be mutated with the GIL released, so
     *     each item is incref'd for the window and the owned pointers
     *     are recorded in their own array (NOT re-read from the list
     *     at cleanup: by then the list may hold different objects). */
    const char **ptrs = PyMem_Malloc(rows * sizeof(char *));
    Py_ssize_t *lenv = PyMem_Malloc(rows * sizeof(Py_ssize_t));
    PyObject **objs = n > 0 ? PyMem_Malloc(n * sizeof(PyObject *)) : NULL;
    int8_t *tab_copy = PyMem_Malloc(256);
    uint16_t *ptab_copy = PyMem_Malloc(65536 * sizeof(uint16_t));
    if (!ptrs || !lenv || (n > 0 && !objs) || !tab_copy || !ptab_copy) {
        PyMem_Free(ptrs);
        PyMem_Free(lenv);
        PyMem_Free(objs);
        PyMem_Free(tab_copy);
        PyMem_Free(ptab_copy);
        nthreads = 1;
        goto fused;
    }
    memcpy(tab_copy, tab, 256);
    memcpy(ptab_copy, ptab, 65536 * sizeof(uint16_t));

    Py_ssize_t held = 0;
    for (Py_ssize_t i = 0; i < rows; i++) {
        ptrs[i] = NULL;
        lenv[i] = 0;
        if (i < n) {
            PyObject *item = PyList_GET_ITEM(list, i);
            char *p;
            Py_ssize_t len;
            if (PyBytes_AsStringAndSize(item, &p, &len) < 0) {
                for (Py_ssize_t k = 0; k < held; k++)
                    Py_DECREF(objs[k]);
                PyMem_Free(ptrs);
                PyMem_Free(lenv);
                PyMem_Free(objs);
                PyMem_Free(tab_copy);
                PyMem_Free(ptab_copy);
                PyBuffer_Release(&table);
                Py_DECREF(buf);
                Py_DECREF(lens);
                return NULL;
            }
            Py_INCREF(item);
            objs[held++] = item;
            ptrs[i] = p;
            lenv[i] = len > width ? width : len;
        }
    }

    {
        pack_job job = {ptrs, lenv, out, lengths, T, tab_copy, ptab_copy,
                        begin_c, end_c, pad_c, 0, rows};
        pack_job jobs[64];
        int count = slice_jobs((char *)jobs, sizeof(pack_job), &job,
                               rows, nthreads, 1,
                               offsetof(pack_job, lo),
                               offsetof(pack_job, hi));
        Py_BEGIN_ALLOW_THREADS
        dispatch_row_jobs((char *)jobs, sizeof(pack_job), count,
                          pack_worker, pack_rows_run);
        Py_END_ALLOW_THREADS
    }
    for (Py_ssize_t k = 0; k < held; k++)
        Py_DECREF(objs[k]);
    PyMem_Free(ptrs);
    PyMem_Free(lenv);
    PyMem_Free(objs);
    PyMem_Free(tab_copy);
    PyMem_Free(ptab_copy);
    PyBuffer_Release(&table);
    return Py_BuildValue("(NN)", buf, lens);
}

/* classify_chunk(data[B*L] bytes, B, L, rem int32[B] bytes, table[256]
 * bytes, begin, end, pad, first, final)
 *   -> bytes holding int8[B, T], the carried-state chunk layout of
 * klogs_tpu.filters.tpu.classify_chunk_host (BEGIN column when first;
 * END at chunk-local position rem when it falls inside this chunk's
 * window — the final chunk gets an extra column so END can land at L —
 * plus the accept-latch PAD column when final). One C pass instead of
 * several numpy passes over multi-MB chunk batches. */
static PyObject *
classify_chunk_c(PyObject *self, PyObject *args)
{
    Py_buffer data, rembuf, table;
    Py_ssize_t B, L;
    int begin_c, end_c, pad_c, first, final;
    if (!PyArg_ParseTuple(args, "y*nny*y*iiiii", &data, &B, &L, &rembuf,
                          &table, &begin_c, &end_c, &pad_c, &first, &final))
        return NULL;
    if (B < 0 || L <= 0 || data.len < B * L || rembuf.len < B * 4
        || table.len < 256) {
        PyBuffer_Release(&data);
        PyBuffer_Release(&rembuf);
        PyBuffer_Release(&table);
        PyErr_SetString(PyExc_ValueError, "classify_chunk: bad buffer sizes");
        return NULL;
    }
    const Py_ssize_t off = first ? 1 : 0;
    const Py_ssize_t Lb = L + (final ? 1 : 0);
    const Py_ssize_t T = off + Lb + (final ? 1 : 0);
    PyObject *buf = PyBytes_FromStringAndSize(NULL, B * T);
    if (!buf) {
        PyBuffer_Release(&data);
        PyBuffer_Release(&rembuf);
        PyBuffer_Release(&table);
        return NULL;
    }
    const uint8_t *src0 = (const uint8_t *)data.buf;
    const int32_t *remv = (const int32_t *)rembuf.buf;
    const int8_t *tab = (const int8_t *)table.buf;
    const uint16_t *ptab = get_pair_tab(tab);
    int8_t *out = (int8_t *)PyBytes_AS_STRING(buf);
    for (Py_ssize_t i = 0; i < B; i++) {
        int8_t *row = out + i * T;
        const uint8_t *src = src0 + i * L;
        int32_t rem = remv[i];
        Py_ssize_t n = rem < 0 ? 0 : (rem > L ? L : (Py_ssize_t)rem);
        if (first)
            row[0] = (int8_t)begin_c;
        classify_span(row + off, src, n, tab, ptab);
        memset(row + off + n, (int8_t)pad_c, T - off - n);
        if (rem >= 0 && rem < Lb)
            row[off + rem] = (int8_t)end_c;
    }
    PyBuffer_Release(&data);
    PyBuffer_Release(&rembuf);
    PyBuffer_Release(&table);
    return buf;
}

/* frame_lines(lines: list[bytes], strip_nl) -> (payload, offsets, raw_total)
 *
 * Contiguous "framed batch" builder: payload = concatenation of the
 * lines (trailing '\n' runs stripped when strip_nl, matching the
 * engine's rstrip(b"\n") parity rule), offsets = int32[n+1] exclusive
 * prefix sums, raw_total = sum of UNstripped lengths (the stats
 * bytes-in figure). One C pass; this is the collector-side cost of the
 * framed wire/service path, replacing per-line msgpack objects. */
static PyObject *
frame_lines(PyObject *self, PyObject *args)
{
    PyObject *list;
    int strip_nl;
    if (!PyArg_ParseTuple(args, "O!i", &PyList_Type, &list, &strip_nl))
        return NULL;
    Py_ssize_t n = PyList_GET_SIZE(list);
    Py_ssize_t total = 0, raw = 0;
    for (Py_ssize_t i = 0; i < n; i++) {
        PyObject *item = PyList_GET_ITEM(list, i);
        char *p;
        Py_ssize_t len;
        if (PyBytes_AsStringAndSize(item, &p, &len) < 0)
            return NULL;
        raw += len;
        if (strip_nl)
            while (len > 0 && p[len - 1] == '\n')
                len--;
        total += len;
    }
    if (total > INT32_MAX) {
        PyErr_SetString(PyExc_OverflowError,
                        "framed batch exceeds int32 offsets");
        return NULL;
    }
    PyObject *payload = PyBytes_FromStringAndSize(NULL, total);
    PyObject *offs = PyBytes_FromStringAndSize(NULL, (n + 1) * 4);
    if (!payload || !offs) {
        Py_XDECREF(payload);
        Py_XDECREF(offs);
        return NULL;
    }
    char *out = PyBytes_AS_STRING(payload);
    int32_t *ov = (int32_t *)PyBytes_AS_STRING(offs);
    Py_ssize_t pos = 0;
    ov[0] = 0;
    for (Py_ssize_t i = 0; i < n; i++) {
        PyObject *item = PyList_GET_ITEM(list, i);
        char *p = PyBytes_AS_STRING(item);
        Py_ssize_t len = PyBytes_GET_SIZE(item);
        if (strip_nl)
            while (len > 0 && p[len - 1] == '\n')
                len--;
        memcpy(out + pos, p, len);
        pos += len;
        ov[i + 1] = (int32_t)pos;
    }
    return Py_BuildValue("(NNn)", payload, offs, raw);
}

/* split_frame(payload, offsets, n) -> list[bytes]
 * Inverse of frame_lines (fallback bridge for engines without a framed
 * fast path): one PyBytes per span. */
static PyObject *
split_frame(PyObject *self, PyObject *args)
{
    Py_buffer payload, offs;
    Py_ssize_t n;
    if (!PyArg_ParseTuple(args, "y*y*n", &payload, &offs, &n))
        return NULL;
    if (n < 0 || offs.len < (n + 1) * 4) {
        PyBuffer_Release(&payload);
        PyBuffer_Release(&offs);
        PyErr_SetString(PyExc_ValueError, "split_frame: bad offsets size");
        return NULL;
    }
    const int32_t *ov = (const int32_t *)offs.buf;
    const char *src = (const char *)payload.buf;
    PyObject *list = PyList_New(n);
    if (!list)
        goto fail;
    for (Py_ssize_t i = 0; i < n; i++) {
        int32_t lo = ov[i], hi = ov[i + 1];
        if (lo < 0 || hi < lo || hi > payload.len) {
            Py_DECREF(list);
            list = NULL;
            PyErr_SetString(PyExc_ValueError,
                            "split_frame: offsets out of range");
            goto fail;
        }
        PyObject *b = PyBytes_FromStringAndSize(src + lo, hi - lo);
        if (!b) {
            Py_DECREF(list);
            list = NULL;
            goto fail;
        }
        PyList_SET_ITEM(list, i, b);
    }
fail:
    PyBuffer_Release(&payload);
    PyBuffer_Release(&offs);
    return list;
}

/* pack_classify_framed(payload, offsets, n, sel, width, rows, table,
 *                      begin, end, pad) -> (cls bytes, lens bytes)
 *
 * Framed-batch variant of pack_classify: line i is
 * payload[offsets[i]:offsets[i+1]] (trailing '\n' runs stripped,
 * idempotent with frame_lines' stripping). ``sel`` selects a row
 * subset as int32 indices (width-bucketing), or None for all n rows in
 * order. No per-line PyObject is ever created — this is the server-side
 * hot path of the framed service protocol. Reuses the pair-LUT
 * classifier and the KLOGS_HOST_THREADS row-parallel worker pool; the
 * GIL is released for the whole row loop even single-threaded (the
 * asyncio event loop keeps serving while a jumbo batch packs). */
static PyObject *
pack_classify_framed(PyObject *self, PyObject *args)
{
    Py_buffer payload, offs, table;
    PyObject *selobj;
    Py_ssize_t n, width, rows;
    int begin_c, end_c, pad_c;
    if (!PyArg_ParseTuple(args, "y*y*nOnny*iii", &payload, &offs, &n,
                          &selobj, &width, &rows, &table,
                          &begin_c, &end_c, &pad_c))
        return NULL;
    Py_buffer sel = {0};
    int have_sel = 0;
    if (selobj != Py_None) {
        if (PyObject_GetBuffer(selobj, &sel, PyBUF_SIMPLE) < 0) {
            PyBuffer_Release(&payload);
            PyBuffer_Release(&offs);
            PyBuffer_Release(&table);
            return NULL;
        }
        have_sel = 1;
        n = sel.len / 4;  /* row count = selected count */
    }
    const Py_ssize_t nspans = have_sel ? (offs.len / 4) - 1 : n;
    if (n < 0 || width <= 0 || table.len < 256
        || offs.len < (nspans + 1) * 4) {
        if (have_sel)
            PyBuffer_Release(&sel);
        PyBuffer_Release(&payload);
        PyBuffer_Release(&offs);
        PyBuffer_Release(&table);
        PyErr_SetString(PyExc_ValueError,
                        "pack_classify_framed: bad sizes");
        return NULL;
    }
    if (rows < n)
        rows = n;
    const Py_ssize_t T = width + 3;
    PyObject *buf = PyBytes_FromStringAndSize(NULL, rows * T);
    PyObject *lens = PyBytes_FromStringAndSize(NULL, rows * 4);
    const char **ptrs = PyMem_Malloc(rows * sizeof(char *));
    Py_ssize_t *lenv = PyMem_Malloc(rows * sizeof(Py_ssize_t));
    if (!buf || !lens || !ptrs || !lenv) {
        if (have_sel)
            PyBuffer_Release(&sel);
        PyBuffer_Release(&payload);
        PyBuffer_Release(&offs);
        PyBuffer_Release(&table);
        Py_XDECREF(buf);
        Py_XDECREF(lens);
        PyMem_Free(ptrs);
        PyMem_Free(lenv);
        return NULL;
    }
    const int32_t *ov = (const int32_t *)offs.buf;
    const int32_t *sv = have_sel ? (const int32_t *)sel.buf : NULL;
    const char *src = (const char *)payload.buf;
    for (Py_ssize_t i = 0; i < rows; i++) {
        ptrs[i] = NULL;
        lenv[i] = 0;
        if (i >= n)
            continue;
        Py_ssize_t r = have_sel ? (Py_ssize_t)sv[i] : i;
        if (r < 0 || r >= nspans)
            goto bad_span;
        int32_t lo = ov[r], hi = ov[r + 1];
        if (lo < 0 || hi < lo || hi > payload.len)
            goto bad_span;
        Py_ssize_t len = hi - lo;
        while (len > 0 && src[lo + len - 1] == '\n')
            len--;
        ptrs[i] = src + lo;
        lenv[i] = len > width ? width : len;
    }

    {
        const int8_t *tab = (const int8_t *)table.buf;
        const uint16_t *ptab = get_pair_tab(tab);
        pack_job job = {ptrs, lenv, (int8_t *)PyBytes_AS_STRING(buf),
                        (int32_t *)PyBytes_AS_STRING(lens), T, tab, ptab,
                        begin_c, end_c, pad_c, 0, rows};
        int nthreads = host_threads();
        /* EVERY branch below releases the GIL, so the static pair-LUT
         * cache could be rebuilt under us by another Python thread
         * packing with a different classifier — copy it call-locally
         * ONCE here (one block, not one per branch: code-review r5);
         * on alloc failure run GIL-HELD on the statics. */
        int8_t *tab_copy = PyMem_Malloc(256);
        uint16_t *ptab_copy = PyMem_Malloc(65536 * sizeof(uint16_t));
        if (!tab_copy || !ptab_copy) {
            PyMem_Free(tab_copy);
            PyMem_Free(ptab_copy);
            pack_rows(&job);
        } else {
            memcpy(tab_copy, tab, 256);
            memcpy(ptab_copy, ptab, 65536 * sizeof(uint16_t));
            job.tab = tab_copy;
            job.ptab = ptab_copy;
            if (nthreads <= 1 || rows < 4096) {
                Py_BEGIN_ALLOW_THREADS
                pack_rows(&job);
                Py_END_ALLOW_THREADS
            } else {
                pack_job jobs[64];
                int count = slice_jobs((char *)jobs, sizeof(pack_job),
                                       &job, rows, nthreads, 1,
                                       offsetof(pack_job, lo),
                                       offsetof(pack_job, hi));
                Py_BEGIN_ALLOW_THREADS
                dispatch_row_jobs((char *)jobs, sizeof(pack_job), count,
                                  pack_worker, pack_rows_run);
                Py_END_ALLOW_THREADS
            }
            PyMem_Free(tab_copy);
            PyMem_Free(ptab_copy);
        }
    }
    PyMem_Free(ptrs);
    PyMem_Free(lenv);
    if (have_sel)
        PyBuffer_Release(&sel);
    PyBuffer_Release(&payload);
    PyBuffer_Release(&offs);
    PyBuffer_Release(&table);
    return Py_BuildValue("(NN)", buf, lens);

bad_span:
    PyMem_Free(ptrs);
    PyMem_Free(lenv);
    if (have_sel)
        PyBuffer_Release(&sel);
    PyBuffer_Release(&payload);
    PyBuffer_Release(&offs);
    PyBuffer_Release(&table);
    Py_DECREF(buf);
    Py_DECREF(lens);
    PyErr_SetString(PyExc_ValueError,
                    "pack_classify_framed: offsets/sel out of range");
    return NULL;
}

/* dfa_scan(payload, offsets, n, table, n_classes, accept, byte_class,
 *          start, end_class) -> mask bytes[n]
 *
 * Flat-table DFA scan over a framed batch: one u32 table lookup per
 * byte, early exit on accept. This is the strong-CPU host engine the
 * TPU multiple is measured against (filters/compiler/dfa.py builds the
 * tables; scan_python there is the oracle for this loop). The GIL is
 * released for the whole scan.
 *
 *   table:      u32[n_dfa * n_classes]  (row-major)
 *   accept:     u8[n_dfa]
 *   byte_class: i32[256]
 *   start:      state AFTER the BEGIN sentinel step (checked first)
 *   end_class:  class fed after the last byte ($ handling)
 */
typedef struct {
    const uint8_t *src;
    Py_ssize_t src_len;
    const int32_t *ov;
    const uint16_t *tab16;
    const uint32_t *tab32;
    const uint8_t *accept;
    const int32_t *bc;
    unsigned int start, n_classes, end_class, wide;
    char *out;
    Py_ssize_t lo, hi;          /* row range for this worker */
    int bad;
} dfa_job;

/* The scan body over rows [lo, hi): bound by the dependent load chain
 * (state -> table -> state, ~3ns/byte scalar), so DFA_LANES
 * independent lines interleave to overlap the chains. The u16 path
 * (every practical pattern set) takes the interleaved loop; u32 and
 * the remainder fall through to the scalar loop. Pure C over borrowed
 * buffers — safe with the GIL released and across worker threads. */
#define DFA_LANES 4
static void
dfa_scan_rows(dfa_job *job)
{
    const uint8_t *src = job->src;
    const int32_t *ov = job->ov;
    const uint16_t *tab16 = job->tab16;
    const uint32_t *tab32 = job->tab32;
    const uint8_t *accept = job->accept;
    const int32_t *bc = job->bc;
    const unsigned int start = job->start, n_classes = job->n_classes;
    const unsigned int end_class = job->end_class, wide = job->wide;
    char *out = job->out;
    Py_ssize_t i0 = job->lo;
    if (!wide && job->hi - job->lo >= DFA_LANES) {
        for (; i0 + DFA_LANES <= job->hi && !job->bad; i0 += DFA_LANES) {
            const uint8_t *p[DFA_LANES], *pe[DFA_LANES];
            uint32_t s[DFA_LANES];
            int m[DFA_LANES];
            unsigned active = 0;
            for (int l = 0; l < DFA_LANES; l++) {
                int32_t lo = ov[i0 + l], hi = ov[i0 + l + 1];
                if (lo < 0 || hi < lo || hi > job->src_len) {
                    job->bad = 1;
                    break;
                }
                Py_ssize_t len = hi - lo;
                while (len > 0 && src[lo + len - 1] == '\n')
                    len--;
                p[l] = src + lo;
                pe[l] = p[l] + len;
                s[l] = start;
                m[l] = accept[start];
                if (!m[l] && p[l] < pe[l])
                    active |= 1u << l;
            }
            if (job->bad)
                break;
            while (active) {
                for (int l = 0; l < DFA_LANES; l++) {
                    if (!(active & (1u << l)))
                        continue;
                    s[l] = tab16[s[l] * n_classes + (uint32_t)bc[*p[l]]];
                    p[l]++;
                    if (accept[s[l]]) {
                        m[l] = 1;
                        active &= ~(1u << l);
                    } else if (p[l] == pe[l]) {
                        active &= ~(1u << l);
                    }
                }
            }
            for (int l = 0; l < DFA_LANES; l++) {
                if (!m[l]) {
                    uint32_t sf = tab16[s[l] * n_classes + end_class];
                    m[l] = accept[sf];
                }
                out[i0 + l] = (char)m[l];
            }
        }
    }
    for (Py_ssize_t i = i0; i < job->hi && !job->bad; i++) {
        int32_t lo = ov[i], hi = ov[i + 1];
        if (lo < 0 || hi < lo || hi > job->src_len) {
            job->bad = 1;
            break;
        }
        Py_ssize_t len = hi - lo;
        while (len > 0 && src[lo + len - 1] == '\n')
            len--;
        uint32_t s = start;
        int m = accept[s];
        if (!m) {
            const uint8_t *p = src + lo, *pe = p + len;
            if (wide) {
                for (; p < pe; p++) {
                    s = tab32[s * n_classes + (uint32_t)bc[*p]];
                    if (accept[s]) {
                        m = 1;
                        break;
                    }
                }
                if (!m) {
                    s = tab32[s * n_classes + end_class];
                    m = accept[s];
                }
            } else {
                for (; p < pe; p++) {
                    s = tab16[s * n_classes + (uint32_t)bc[*p]];
                    if (accept[s]) {
                        m = 1;
                        break;
                    }
                }
                if (!m) {
                    s = tab16[s * n_classes + end_class];
                    m = accept[s];
                }
            }
        }
        out[i] = (char)m;
    }
}

static void *
dfa_scan_worker(void *arg)
{
    dfa_scan_rows((dfa_job *)arg);
    return NULL;
}

static void
dfa_scan_run(void *arg)
{
    dfa_scan_rows((dfa_job *)arg);
}

static PyObject *
dfa_scan(PyObject *self, PyObject *args)
{
    Py_buffer payload, offs, table, acc, bclass;
    Py_ssize_t n;
    unsigned int start, n_classes, end_class, wide;
    if (!PyArg_ParseTuple(args, "y*y*ny*Iy*y*III", &payload, &offs, &n,
                          &table, &n_classes, &acc, &bclass,
                          &start, &end_class, &wide))
        return NULL;
    const Py_ssize_t elem = wide ? 4 : 2;
    const Py_ssize_t n_dfa = (Py_ssize_t)(acc.len);
    if (n < 0 || offs.len < (n + 1) * 4 || bclass.len < 256 * 4
        || n_classes == 0 || end_class >= n_classes || start >= n_dfa
        || table.len < n_dfa * (Py_ssize_t)n_classes * elem) {
        PyBuffer_Release(&payload);
        PyBuffer_Release(&offs);
        PyBuffer_Release(&table);
        PyBuffer_Release(&acc);
        PyBuffer_Release(&bclass);
        PyErr_SetString(PyExc_ValueError, "dfa_scan: bad buffer sizes");
        return NULL;
    }
    PyObject *mask = PyBytes_FromStringAndSize(NULL, n);
    if (!mask) {
        PyBuffer_Release(&payload);
        PyBuffer_Release(&offs);
        PyBuffer_Release(&table);
        PyBuffer_Release(&acc);
        PyBuffer_Release(&bclass);
        return NULL;
    }
    /* KLOGS_HOST_THREADS row-parallel dispatch (same contract as
     * pack_classify): the table/accept/byte_class buffers are borrowed
     * and read-only, each worker writes a disjoint out range, so the
     * whole scan runs GIL-free. Small batches stay single-threaded
     * (thread spawn ~10us each would swamp a sub-ms scan). */
    dfa_job job = {(const uint8_t *)payload.buf, payload.len,
                   (const int32_t *)offs.buf,
                   (const uint16_t *)table.buf,
                   (const uint32_t *)table.buf,
                   (const uint8_t *)acc.buf,
                   (const int32_t *)bclass.buf,
                   start, n_classes, end_class, wide,
                   PyBytes_AS_STRING(mask), 0, n, 0};
    int nthreads = host_threads();
    int bad;
    if (nthreads <= 1 || n < 8192) {
        Py_BEGIN_ALLOW_THREADS
        dfa_scan_rows(&job);
        Py_END_ALLOW_THREADS
        bad = job.bad;
    } else {
        dfa_job jobs[64];
        int count = slice_jobs((char *)jobs, sizeof(dfa_job), &job, n,
                               nthreads, DFA_LANES,
                               offsetof(dfa_job, lo),
                               offsetof(dfa_job, hi));
        Py_BEGIN_ALLOW_THREADS
        dispatch_row_jobs((char *)jobs, sizeof(dfa_job), count,
                          dfa_scan_worker, dfa_scan_run);
        Py_END_ALLOW_THREADS
        bad = 0;
        for (int t = 0; t < count; t++)
            bad |= jobs[t].bad;
    }
    PyBuffer_Release(&payload);
    PyBuffer_Release(&offs);
    PyBuffer_Release(&table);
    PyBuffer_Release(&acc);
    PyBuffer_Release(&bclass);
    if (bad) {
        Py_DECREF(mask);
        PyErr_SetString(PyExc_ValueError, "dfa_scan: offsets out of range");
        return NULL;
    }
    return mask;
}

/* find_newlines(data, base) -> bytes holding int32 positions
 *
 * Absolute end-offsets (position AFTER each '\n', plus `base`) of every
 * newline in `data` — one memchr sweep. The framed-batcher's line
 * scanner: chunk boundaries never materialize per-line objects. */
static PyObject *
find_newlines(PyObject *self, PyObject *args)
{
    Py_buffer data;
    Py_ssize_t base;
    if (!PyArg_ParseTuple(args, "y*n", &data, &base))
        return NULL;
    if (base < 0 || base + data.len > INT32_MAX) {
        /* Same guard as frame_lines: a >2 GiB pending buffer must fail
         * loudly here, not wrap into negative offsets downstream. */
        PyBuffer_Release(&data);
        PyErr_SetString(PyExc_OverflowError,
                        "framed buffer exceeds int32 offsets");
        return NULL;
    }
    const char *src = (const char *)data.buf;
    Py_ssize_t n = data.len;
    /* Count first (cheap memchr sweep), then fill exactly. */
    Py_ssize_t count = 0;
    for (const char *p = src;
         (p = memchr(p, '\n', n - (p - src))) != NULL; p++)
        count++;
    PyObject *out = PyBytes_FromStringAndSize(NULL, count * 4);
    if (!out) {
        PyBuffer_Release(&data);
        return NULL;
    }
    int32_t *ov = (int32_t *)PyBytes_AS_STRING(out);
    Py_ssize_t k = 0;
    for (const char *p = src;
         (p = memchr(p, '\n', n - (p - src))) != NULL; p++)
        ov[k++] = (int32_t)(base + (p - src) + 1);
    PyBuffer_Release(&data);
    return out;
}

/* join_kept_framed(payload, offsets, n, mask) -> bytes
 *
 * Concatenation of the mask-selected spans, with ADJACENT kept lines
 * coalesced into single memcpys (a 25%-match batch averages long kept/
 * dropped runs; the common all-kept case is ONE memcpy). The framed
 * sibling of join_kept. */
static PyObject *
join_kept_framed(PyObject *self, PyObject *args)
{
    Py_buffer payload, offs, mask;
    Py_ssize_t n;
    if (!PyArg_ParseTuple(args, "y*y*ny*", &payload, &offs, &n, &mask))
        return NULL;
    if (n < 0 || offs.len < (n + 1) * 4 || mask.len < n) {
        PyBuffer_Release(&payload);
        PyBuffer_Release(&offs);
        PyBuffer_Release(&mask);
        PyErr_SetString(PyExc_ValueError, "join_kept_framed: bad sizes");
        return NULL;
    }
    const int32_t *ov = (const int32_t *)offs.buf;
    const char *m = (const char *)mask.buf;
    const char *src = (const char *)payload.buf;
    Py_ssize_t total = 0;
    int bad = 0;
    for (Py_ssize_t i = 0; i < n; i++) {
        if (ov[i] < 0 || ov[i + 1] < ov[i] || ov[i + 1] > payload.len) {
            bad = 1;
            break;
        }
        if (m[i])
            total += ov[i + 1] - ov[i];
    }
    if (bad) {
        PyBuffer_Release(&payload);
        PyBuffer_Release(&offs);
        PyBuffer_Release(&mask);
        PyErr_SetString(PyExc_ValueError,
                        "join_kept_framed: offsets out of range");
        return NULL;
    }
    PyObject *out = PyBytes_FromStringAndSize(NULL, total);
    if (!out) {
        PyBuffer_Release(&payload);
        PyBuffer_Release(&offs);
        PyBuffer_Release(&mask);
        return NULL;
    }
    char *dst = PyBytes_AS_STRING(out);
    Py_ssize_t i = 0;
    while (i < n) {
        if (!m[i]) {
            i++;
            continue;
        }
        Py_ssize_t j = i;
        while (j < n && m[j])
            j++;
        Py_ssize_t len = ov[j] - ov[i];
        memcpy(dst, src + ov[i], len);
        dst += len;
        i = j;
    }
    PyBuffer_Release(&payload);
    PyBuffer_Release(&offs);
    PyBuffer_Release(&mask);
    return out;
}

static PyObject *
join_kept(PyObject *self, PyObject *args)
{
    PyObject *list;
    Py_buffer mask;
    if (!PyArg_ParseTuple(args, "O!y*", &PyList_Type, &list, &mask))
        return NULL;
    Py_ssize_t n = PyList_GET_SIZE(list);
    if (mask.len < n) {
        PyBuffer_Release(&mask);
        PyErr_SetString(PyExc_ValueError, "mask shorter than lines");
        return NULL;
    }
    const char *m = (const char *)mask.buf;

    Py_ssize_t total = 0;
    for (Py_ssize_t i = 0; i < n; i++) {
        if (!m[i])
            continue;
        PyObject *item = PyList_GET_ITEM(list, i);
        if (!PyBytes_Check(item)) {
            PyBuffer_Release(&mask);
            PyErr_SetString(PyExc_TypeError, "lines must be bytes");
            return NULL;
        }
        total += PyBytes_GET_SIZE(item);
    }
    PyObject *buf = PyBytes_FromStringAndSize(NULL, total);
    if (!buf) {
        PyBuffer_Release(&mask);
        return NULL;
    }
    char *out = PyBytes_AS_STRING(buf);
    for (Py_ssize_t i = 0; i < n; i++) {
        if (!m[i])
            continue;
        PyObject *item = PyList_GET_ITEM(list, i);
        Py_ssize_t len = PyBytes_GET_SIZE(item);
        memcpy(out, PyBytes_AS_STRING(item), len);
        out += len;
    }
    PyBuffer_Release(&mask);
    return buf;
}

/* ================= SIMD literal sweep (factor-index narrowing) =======
 *
 * sweep_candidates(blob, payload, offsets, n_lines, simd)
 *     -> bytes holding u32[n_lines, GW] little-endian group bitsets
 *
 * Native twin of FactorIndex.group_candidates (filters/compiler/
 * index.py) in the Hyperscan-FDR/Teddy shape: stage 1 is a SIMD shufti
 * over the payload — per byte position, four nibble-LUT lookups AND'd
 * across the first four bytes of every factor's rarest anchored window
 * (8 bucket bits per byte, so unrelated factor families don't dilute
 * each other's predicate; a 3-byte factor's 4th window byte is its
 * don't-care extension -> wildcard position) — then a 64 KiB union-
 * bloom gate on the exact 4-byte code, and only positions surviving
 * BOTH pay the exact two-tier hash probe + masked-word verify. The tables ARE
 * the device SweepProgram's (packed by FactorIndex.native_sweep_blob):
 * narrow tier keyed on the LE 4-byte window code (3-byte factors as
 * 256 one-byte extensions), wide tier on the Fibonacci mix of two
 * chained half-window codes, open-addressed hash probe bounded by
 * max_probe, exact factor verify as masked u32 compares, per-factor
 * group bitset accumulate, always_mask pre-set on every row. Exact
 * verification makes the mask byte-identical to both the numpy and
 * the device sweeps (the three-way parity oracle in
 * tests/test_native_sweep.py).
 *
 * Stage 1 comes in two widths (v2 blobs, SH_BUCKETS): the classic
 * 8-bucket plane, and a "fat Teddy" 16-bucket mode (the Hyperscan
 * trick) where a SECOND nibble-mask plane (SH_TEDDY2_OFF) carries
 * buckets 8..15 and a position survives when EITHER plane's AND-chain
 * is nonzero — twice the bucket resolution for one extra shuffle
 * chain, chosen at blob-build time when the factor count would
 * otherwise saturate 8 buckets (FactorIndex.native_sweep_blob).
 *
 * Dispatch: AVX-512BW (64-wide) -> AVX2 (32-wide) -> SSSE3 (16-wide)
 * -> portable scalar (256-entry byte LUTs), resolved at runtime from
 * CPUID and clamped by the caller's `simd` argument
 * (KLOGS_NATIVE_SIMD, parsed in Python). The whole scan — offsets
 * validation, padded copy, stage 1, confirms — runs inside
 * Py_BEGIN_ALLOW_THREADS over borrowed read-only buffers and
 * call-local scratch: the indexed engine's slab pipeline and the
 * coalescer's fetch pool overlap sweeps with group scans, packing and
 * device fetches, and the packed tables are shareable across threads
 * (no statics touched). The optional trailing stats buffer
 * (u64[2] = survivors, positions) is written back only after the
 * scan, under the GIL.
 */

#define SWEEP_MAGIC 0x4B535750  /* "PWSK" little-endian */
#define SWEEP_VERSION 2
#define SWEEP_FIB 2654435761u
#define SWEEP_PAD 128           /* zero tail: widest SIMD load + code/verify overreach */
/* The SIMD kernels scan the source buffer IN PLACE (no full-payload
 * copy): positions below n - SWEEP_TAIL are proven in-bounds for
 * every load the scan and confirm paths issue (widest block 64 + 3
 * shifted planes, 8-byte confirm code, 27-byte verify reach), and the
 * last SWEEP_TAIL positions re-scan from a small zero-padded stack
 * copy with SWEEP_TAIL_LEFT bytes of left context for anchored
 * factor verifies reaching back from a tail position. */
#define SWEEP_TAIL 128
#define SWEEP_TAIL_LEFT 32

/* Header word indexes (i32 each; see FactorIndex.native_sweep_blob).
 * v2 appends SH_BUCKETS/SH_TEDDY2_OFF after SH_TOTAL so every v1
 * word keeps its index. */
enum {
    SH_MAGIC = 0, SH_VERSION, SH_F, SH_NW, SH_GW, SH_G,
    SH_TEDDY_OFF, SH_BLOOM_OFF, SH_ALWAYS_OFF, SH_FACLEN_OFF,
    SH_FACWORDS_OFF, SH_FACWMASK_OFF, SH_FACGROUPS_OFF,
    SH_NARROW = 13,             /* 9 words per tier */
    SH_WIDE = 22,
    SH_TOTAL = 31,
    SH_BUCKETS = 32,            /* 8 or 16 (fat Teddy) */
    SH_TEDDY2_OFF = 33,         /* second bucket plane; 0 when 8-bucket */
    SH_WORDS = 34,
};
#define SWEEP_TEDDY_M 4         /* stage-1 window bytes (shufti AND depth) */
#define SWEEP_BLOOM_SIZE 65536  /* union bloom: fold16 of every probe code */
enum { ST_H = 0, ST_E, ST_NE, ST_MAXPROBE,
       ST_SLOTKEY_OFF, ST_SLOTEID_OFF, ST_BSTART_OFF, ST_FID_OFF,
       ST_ANCHOR_OFF };

typedef struct {
    uint32_t H, E, NE, max_probe, bits;
    const uint32_t *slot_key;   /* [H] */
    const int32_t *slot_eid;    /* [H], -1 = empty */
    const int32_t *bucket_start;  /* [E+1] */
    const int32_t *fid;         /* [NE] */
    const int32_t *anchor;      /* [NE] */
} sweep_tier_c;

typedef struct {
    int32_t F, NW, GW, G;
    sweep_tier_c narrow, wide;
    const int32_t *fac_len;     /* [F] */
    const uint32_t *fac_words;  /* [F, NW] LE */
    const uint32_t *fac_wmask;  /* [F, NW] */
    const uint32_t *fac_groups; /* [F, GW] */
    const uint32_t *always;     /* [GW] */
    const uint8_t *teddy;       /* [M][2][16] nibble masks, buckets 0..7 */
    const uint8_t *teddy2;      /* [M][2][16] buckets 8..15; NULL when thin */
    const uint8_t *bloom;       /* [65536] union bloom over probe codes */
} sweep_prog_c;

static inline uint32_t
sweep_le32(const uint8_t *p)
{
    uint32_t v;
    memcpy(&v, p, 4);
#if defined(__BYTE_ORDER__) && __BYTE_ORDER__ == __ORDER_BIG_ENDIAN__
    v = __builtin_bswap32(v);
#endif
    return v;
}

/* Bounds-checked array slice out of the blob; returns NULL on a
 * malformed offset (caller maps to ValueError). */
static const void *
sweep_arr(const char *blob, Py_ssize_t blen, int32_t off, int64_t count,
          int64_t elem)
{
    if (off < 0 || (off & 3) || count < 0
        || (int64_t)off + count * elem > (int64_t)blen)
        return NULL;
    return blob + off;
}

static int
sweep_parse_tier(const char *blob, Py_ssize_t blen, const int32_t *h,
                 sweep_tier_c *t)
{
    t->H = (uint32_t)h[ST_H];
    t->E = (uint32_t)h[ST_E];
    t->NE = (uint32_t)h[ST_NE];
    t->max_probe = (uint32_t)h[ST_MAXPROBE];
    if (t->H & (t->H - 1))
        return -1;              /* hash size must be a power of two */
    t->bits = 0;
    for (uint32_t x = t->H; x > 1; x >>= 1)
        t->bits++;
    /* A probeable tier needs H >= 2: bits=0 would make the probe's
     * `>> (32 - bits)` a shift-by-32 (UB). Internally packed tables
     * are always H >= 16; this guards the untrusted-blob contract. */
    if (t->max_probe > t->H || t->bits >= 32
        || (t->max_probe && t->H < 2))
        return -1;
    t->slot_key = sweep_arr(blob, blen, h[ST_SLOTKEY_OFF], t->H, 4);
    t->slot_eid = sweep_arr(blob, blen, h[ST_SLOTEID_OFF], t->H, 4);
    t->bucket_start = sweep_arr(blob, blen, h[ST_BSTART_OFF],
                                (int64_t)t->E + 1, 4);
    t->fid = sweep_arr(blob, blen, h[ST_FID_OFF], t->NE, 4);
    t->anchor = sweep_arr(blob, blen, h[ST_ANCHOR_OFF], t->NE, 4);
    if (!t->slot_key || !t->slot_eid || !t->bucket_start || !t->fid
        || !t->anchor)
        return -1;
    return 0;
}

static int
sweep_parse_blob(const char *blob, Py_ssize_t blen, sweep_prog_c *sp)
{
    if (blen < SH_WORDS * 4)
        return -1;
    const int32_t *h = (const int32_t *)blob;
    if (h[SH_MAGIC] != SWEEP_MAGIC || h[SH_VERSION] != SWEEP_VERSION
        || h[SH_TOTAL] != (int32_t)blen)
        return -1;
    sp->F = h[SH_F];
    sp->NW = h[SH_NW];
    sp->GW = h[SH_GW];
    sp->G = h[SH_G];
    if (sp->F < 1 || sp->NW < 1 || sp->GW < 1 || sp->G < 1)
        return -1;
    sp->teddy = sweep_arr(blob, blen, h[SH_TEDDY_OFF],
                          SWEEP_TEDDY_M * 32, 1);
    /* Bucket mode: 8 packs a zero second-plane offset (rejected if
     * nonzero — a stale packer would smuggle an unread plane); 16
     * requires the second plane to slice cleanly out of the blob. */
    if (h[SH_BUCKETS] == 8) {
        if (h[SH_TEDDY2_OFF] != 0)
            return -1;
        sp->teddy2 = NULL;
    } else if (h[SH_BUCKETS] == 16) {
        sp->teddy2 = sweep_arr(blob, blen, h[SH_TEDDY2_OFF],
                               SWEEP_TEDDY_M * 32, 1);
        if (!sp->teddy2)
            return -1;
    } else {
        return -1;
    }
    sp->bloom = sweep_arr(blob, blen, h[SH_BLOOM_OFF],
                          SWEEP_BLOOM_SIZE, 1);
    sp->always = sweep_arr(blob, blen, h[SH_ALWAYS_OFF], sp->GW, 4);
    sp->fac_len = sweep_arr(blob, blen, h[SH_FACLEN_OFF], sp->F, 4);
    sp->fac_words = sweep_arr(blob, blen, h[SH_FACWORDS_OFF],
                              (int64_t)sp->F * sp->NW, 4);
    sp->fac_wmask = sweep_arr(blob, blen, h[SH_FACWMASK_OFF],
                              (int64_t)sp->F * sp->NW, 4);
    sp->fac_groups = sweep_arr(blob, blen, h[SH_FACGROUPS_OFF],
                               (int64_t)sp->F * sp->GW, 4);
    if (!sp->teddy || !sp->bloom || !sp->always || !sp->fac_len
        || !sp->fac_words || !sp->fac_wmask || !sp->fac_groups)
        return -1;
    if (sweep_parse_tier(blob, blen, (const int32_t *)blob + SH_NARROW,
                         &sp->narrow) < 0
        || sweep_parse_tier(blob, blen, (const int32_t *)blob + SH_WIDE,
                            &sp->wide) < 0)
        return -1;
    /* Entry tables index factors and buckets; validate once here so
     * the hot confirm loop can trust them. */
    for (int tix = 0; tix < 2; tix++) {
        const sweep_tier_c *t = tix ? &sp->wide : &sp->narrow;
        for (uint32_t i = 0; i < t->H; i++)
            if (t->slot_eid[i] >= (int32_t)t->E)
                return -1;
        for (uint32_t i = 0; i <= t->E; i++)
            if (t->bucket_start[i] < 0
                || t->bucket_start[i] > (int32_t)t->NE
                || (i && t->bucket_start[i] < t->bucket_start[i - 1]))
                return -1;
        for (uint32_t i = 0; i < t->NE; i++)
            if (t->fid[i] < 0 || t->fid[i] >= sp->F || t->anchor[i] < 0
                /* anchors sit inside the factor (<= cap 24 - window),
                 * so the verify never reaches further left than the
                 * tail copy's SWEEP_TAIL_LEFT margin */
                || t->anchor[i] > SWEEP_TAIL_LEFT - 8)
                return -1;
    }
    /* fac_len 0 is the zero-factor index's padding row (never
     * referenced by any tier entry — both tiers are empty there). */
    for (int32_t i = 0; i < sp->F; i++)
        if (sp->fac_len[i] < 0 || (sp->fac_len[i] + 3) / 4 > sp->NW)
            return -1;
    return 0;
}

/* Exact resolution of one stage-1 survivor against one tier: hash
 * probe -> bucket run -> masked-word factor verify -> line bounds ->
 * group bitset accumulate. Mirrors FactorIndex._emit exactly: the
 * line is the one containing the FACTOR START q (not the probe
 * window), and the factor's own bytes must sit inside it.
 *
 * Positions are GLOBAL payload offsets; the byte at global index g
 * lives at buf[g - bias] (bias = 0 when scanning the source buffer
 * directly, nonzero for the zero-padded tail copy). 4-byte loads are
 * valid while they end at or before load_end; past it, bytes are
 * assembled one at a time with zeros beyond n — same value the old
 * full-payload zero-padded copy produced. */
static void
sweep_probe_tier(const sweep_prog_c *sp, const sweep_tier_c *t,
                 uint32_t key, const uint8_t *buf, Py_ssize_t bias,
                 Py_ssize_t n, Py_ssize_t load_end, const int32_t *ov,
                 Py_ssize_t B, Py_ssize_t pos, uint32_t *out)
{
    uint32_t h = (uint32_t)(key * SWEEP_FIB) >> (32 - t->bits);
    int32_t eid = -1;
    for (uint32_t j = 0; j < t->max_probe; j++) {
        uint32_t s = (h + j) & (t->H - 1);
        int32_t e = t->slot_eid[s];
        if (e < 0)
            return;             /* empty slot ends the probe cluster */
        if (t->slot_key[s] == key) {
            eid = e;
            break;
        }
    }
    if (eid < 0)
        return;
    for (int32_t bi = t->bucket_start[eid]; bi < t->bucket_start[eid + 1];
         bi++) {
        int32_t fi = t->fid[bi];
        Py_ssize_t q = pos - t->anchor[bi];
        int32_t L = sp->fac_len[fi];
        if (q < bias || q + L > n)
            continue;
        int32_t W = (L + 3) / 4;
        int ok = 1;
        for (int32_t w = 0; w < W; w++) {
            Py_ssize_t a = q + 4 * (Py_ssize_t)w;
            uint32_t vw;
            if (a + 4 <= load_end) {
                vw = sweep_le32(buf + (a - bias));
            } else {
                uint8_t tb[4] = {0, 0, 0, 0};
                for (int z = 0; z < 4 && a + z < n; z++)
                    tb[z] = buf[a + z - bias];
                vw = sweep_le32(tb);
            }
            if ((vw & sp->fac_wmask[(size_t)fi * sp->NW + w])
                != sp->fac_words[(size_t)fi * sp->NW + w]) {
                ok = 0;
                break;
            }
        }
        if (!ok || q < ov[0])
            continue;
        /* Largest line with ov[line] <= q (searchsorted right - 1). */
        Py_ssize_t a = 0, b = B + 1;
        while (b - a > 1) {
            Py_ssize_t m = a + (b - a) / 2;
            if ((Py_ssize_t)ov[m] <= q)
                a = m;
            else
                b = m;
        }
        if (a >= B || q + L > (Py_ssize_t)ov[a + 1])
            continue;
        uint32_t *row = out + (size_t)a * sp->GW;
        for (int32_t k = 0; k < sp->GW; k++)
            row[k] |= sp->fac_groups[(size_t)fi * sp->GW + k];
    }
}

/* Caller guarantees 8 readable bytes at the survivor position:
 * main-region positions sit >= SWEEP_TAIL bytes before the payload
 * end, tail positions read the zero-padded tail copy. */
static void
sweep_confirm(const sweep_prog_c *sp, const uint8_t *buf,
              Py_ssize_t bias, Py_ssize_t n, Py_ssize_t load_end,
              const int32_t *ov, Py_ssize_t B, Py_ssize_t pos,
              uint32_t *out)
{
    /* Union-bloom gate first (fold16 of the position's 4-byte code,
     * covering BOTH tiers' probe codes — the numpy sweep's stage-1
     * twin): the nibble-LUT stage over-approximates heavily on
     * digit-dense corpora, and this one multiply + cache-resident
     * byte load rules out ~95% of its survivors before any hash
     * probe is paid. */
    const uint8_t *p = buf + (pos - bias);
    uint32_t code = sweep_le32(p);
    if (!sp->bloom[(uint32_t)(code * SWEEP_FIB) >> 16])
        return;
    if (sp->narrow.max_probe)
        sweep_probe_tier(sp, &sp->narrow, code, buf, bias, n,
                         load_end, ov, B, pos, out);
    if (sp->wide.max_probe) {
        uint32_t lo = sweep_le32(p + 4);
        sweep_probe_tier(sp, &sp->wide,
                         (uint32_t)(code * SWEEP_FIB) ^ lo,
                         buf, bias, n, load_end, ov, B, pos, out);
    }
}

/* Portable scalar stage 1: the nibble masks expanded once into
 * 256-entry byte LUTs (cache-resident), then 4 loads + 3 ANDs per
 * position (per bucket plane). Also the tail/readability reference
 * for the SIMD paths: a position survives when ANY plane's AND-chain
 * is nonzero, and every survivor bumps *nsurv (the stage-1
 * survivor-ratio telemetry) before paying its confirm. */
static void
sweep_scan_scalar(const sweep_prog_c *sp, const uint8_t *pad,
                  Py_ssize_t scan_n, Py_ssize_t n, const int32_t *ov,
                  Py_ssize_t B, uint32_t *out, uint64_t *nsurv)
{
    const int fat = sp->teddy2 != NULL;
    uint8_t lut[SWEEP_TEDDY_M][256], lut2[SWEEP_TEDDY_M][256];
    for (int j = 0; j < SWEEP_TEDDY_M; j++) {
        const uint8_t *lo = sp->teddy + j * 32;
        const uint8_t *hi = lo + 16;
        for (int c = 0; c < 256; c++)
            lut[j][c] = (uint8_t)(lo[c & 15] & hi[c >> 4]);
        if (fat) {
            const uint8_t *lo2 = sp->teddy2 + j * 32;
            const uint8_t *hi2 = lo2 + 16;
            for (int c = 0; c < 256; c++)
                lut2[j][c] = (uint8_t)(lo2[c & 15] & hi2[c >> 4]);
        }
    }
    for (Py_ssize_t i = 0; i < scan_n; i++) {
        unsigned v = lut[0][pad[i]] & lut[1][pad[i + 1]]
            & lut[2][pad[i + 2]] & lut[3][pad[i + 3]];
        if (fat)
            v |= lut2[0][pad[i]] & lut2[1][pad[i + 1]]
                & lut2[2][pad[i + 2]] & lut2[3][pad[i + 3]];
        if (v) {
            (*nsurv)++;
            sweep_confirm(sp, pad, 0, n, n, ov, B, i, out);
        }
    }
}

/* Scalar sweep of the last global positions [lo, n): buf is a small
 * stack copy of payload[bias:n] followed by SWEEP_PAD zeros, so every
 * load the confirm path issues is in-bounds and bytes past n read 0 —
 * bit-identical to the old full-payload zero-padded copy. At most
 * SWEEP_TAIL positions, so the plain nibble-mask test (no LUT build)
 * is cheapest. */
static void
sweep_scan_tail(const sweep_prog_c *sp, const uint8_t *buf,
                Py_ssize_t bias, Py_ssize_t lo, Py_ssize_t n,
                const int32_t *ov, Py_ssize_t B, uint32_t *out,
                uint64_t *nsurv)
{
    const int fat = sp->teddy2 != NULL;
    for (Py_ssize_t g = lo; g < n; g++) {
        const uint8_t *p = buf + (g - bias);
        unsigned v = 0xff;
        for (int j = 0; j < SWEEP_TEDDY_M; j++) {
            const uint8_t *m = sp->teddy + j * 32;
            v &= m[p[j] & 15] & m[16 + (p[j] >> 4)];
        }
        if (fat) {
            unsigned v2 = 0xff;
            for (int j = 0; j < SWEEP_TEDDY_M; j++) {
                const uint8_t *m = sp->teddy2 + j * 32;
                v2 &= m[p[j] & 15] & m[16 + (p[j] >> 4)];
            }
            v |= v2;
        }
        if (v) {
            (*nsurv)++;
            sweep_confirm(sp, buf, bias, n,
                          n + SWEEP_PAD - 8, ov, B, g, out);
        }
    }
}

#if defined(__x86_64__) && (defined(__GNUC__) || defined(__clang__))
#define SWEEP_HAVE_X86 1
#include <immintrin.h>

__attribute__((target("ssse3"))) static void
sweep_scan_ssse3(const sweep_prog_c *sp, const uint8_t *pad,
                 Py_ssize_t scan_n, Py_ssize_t n, const int32_t *ov,
                 Py_ssize_t B, uint32_t *out, uint64_t *nsurv)
{
    const __m128i lowm = _mm_set1_epi8(0x0f);
    const int fat = sp->teddy2 != NULL;
    __m128i tl[SWEEP_TEDDY_M], th[SWEEP_TEDDY_M];
    __m128i tl2[SWEEP_TEDDY_M], th2[SWEEP_TEDDY_M];
    for (int j = 0; j < SWEEP_TEDDY_M; j++) {
        tl[j] = _mm_loadu_si128((const __m128i *)(sp->teddy + j * 32));
        th[j] = _mm_loadu_si128(
            (const __m128i *)(sp->teddy + j * 32 + 16));
        tl2[j] = th2[j] = _mm_setzero_si128();
        if (fat) {
            tl2[j] = _mm_loadu_si128(
                (const __m128i *)(sp->teddy2 + j * 32));
            th2[j] = _mm_loadu_si128(
                (const __m128i *)(sp->teddy2 + j * 32 + 16));
        }
    }
    for (Py_ssize_t i = 0; i < scan_n; i += 16) {
        __m128i m = _mm_set1_epi8((char)0xff);
        __m128i m2 = m;
        for (int j = 0; j < SWEEP_TEDDY_M; j++) {
            __m128i d = _mm_loadu_si128((const __m128i *)(pad + i + j));
            __m128i lx = _mm_and_si128(d, lowm);
            __m128i hx = _mm_and_si128(_mm_srli_epi16(d, 4), lowm);
            m = _mm_and_si128(m, _mm_and_si128(
                _mm_shuffle_epi8(tl[j], lx),
                _mm_shuffle_epi8(th[j], hx)));
            if (fat)
                m2 = _mm_and_si128(m2, _mm_and_si128(
                    _mm_shuffle_epi8(tl2[j], lx),
                    _mm_shuffle_epi8(th2[j], hx)));
        }
        if (fat)
            m = _mm_or_si128(m, m2);
        int bits = _mm_movemask_epi8(
            _mm_cmpeq_epi8(m, _mm_setzero_si128())) ^ 0xffff;
        while (bits) {
            int b = __builtin_ctz((unsigned)bits);
            bits &= bits - 1;
            Py_ssize_t pos = i + b;
            if (pos < scan_n) {
                (*nsurv)++;
                sweep_confirm(sp, pad, 0, n, n, ov, B, pos, out);
            }
        }
    }
}

__attribute__((target("avx2"))) static void
sweep_scan_avx2(const sweep_prog_c *sp, const uint8_t *pad,
                Py_ssize_t scan_n, Py_ssize_t n, const int32_t *ov,
                Py_ssize_t B, uint32_t *out, uint64_t *nsurv)
{
    const __m256i lowm = _mm256_set1_epi8(0x0f);
    const int fat = sp->teddy2 != NULL;
    __m256i tl[SWEEP_TEDDY_M], th[SWEEP_TEDDY_M];
    __m256i tl2[SWEEP_TEDDY_M], th2[SWEEP_TEDDY_M];
    for (int j = 0; j < SWEEP_TEDDY_M; j++) {
        tl[j] = _mm256_broadcastsi128_si256(
            _mm_loadu_si128((const __m128i *)(sp->teddy + j * 32)));
        th[j] = _mm256_broadcastsi128_si256(
            _mm_loadu_si128((const __m128i *)(sp->teddy + j * 32 + 16)));
        tl2[j] = th2[j] = _mm256_setzero_si256();
        if (fat) {
            tl2[j] = _mm256_broadcastsi128_si256(_mm_loadu_si128(
                (const __m128i *)(sp->teddy2 + j * 32)));
            th2[j] = _mm256_broadcastsi128_si256(_mm_loadu_si128(
                (const __m128i *)(sp->teddy2 + j * 32 + 16)));
        }
    }
    for (Py_ssize_t i = 0; i < scan_n; i += 32) {
        __m256i m = _mm256_set1_epi8((char)0xff);
        __m256i m2 = m;
        for (int j = 0; j < SWEEP_TEDDY_M; j++) {
            __m256i d = _mm256_loadu_si256(
                (const __m256i *)(pad + i + j));
            __m256i lx = _mm256_and_si256(d, lowm);
            __m256i hx = _mm256_and_si256(_mm256_srli_epi16(d, 4),
                                          lowm);
            m = _mm256_and_si256(m, _mm256_and_si256(
                _mm256_shuffle_epi8(tl[j], lx),
                _mm256_shuffle_epi8(th[j], hx)));
            if (fat)
                m2 = _mm256_and_si256(m2, _mm256_and_si256(
                    _mm256_shuffle_epi8(tl2[j], lx),
                    _mm256_shuffle_epi8(th2[j], hx)));
        }
        if (fat)
            m = _mm256_or_si256(m, m2);
        uint32_t bits = ~(uint32_t)_mm256_movemask_epi8(
            _mm256_cmpeq_epi8(m, _mm256_setzero_si256()));
        while (bits) {
            int b = __builtin_ctz(bits);
            bits &= bits - 1;
            Py_ssize_t pos = i + b;
            if (pos < scan_n) {
                (*nsurv)++;
                sweep_confirm(sp, pad, 0, n, n, ov, B, pos, out);
            }
        }
    }
}

/* 64 positions per iteration; the bucket planes live broadcast in
 * zmm registers and the survivor bitmap falls straight out of
 * _mm512_test_epi8_mask — no compare-against-zero + movemask pair. */
__attribute__((target("avx512f,avx512bw"))) static void
sweep_scan_avx512(const sweep_prog_c *sp, const uint8_t *pad,
                  Py_ssize_t scan_n, Py_ssize_t n, const int32_t *ov,
                  Py_ssize_t B, uint32_t *out, uint64_t *nsurv)
{
    const __m512i lowm = _mm512_set1_epi8(0x0f);
    const int fat = sp->teddy2 != NULL;
    __m512i tl[SWEEP_TEDDY_M], th[SWEEP_TEDDY_M];
    __m512i tl2[SWEEP_TEDDY_M], th2[SWEEP_TEDDY_M];
    for (int j = 0; j < SWEEP_TEDDY_M; j++) {
        tl[j] = _mm512_broadcast_i32x4(
            _mm_loadu_si128((const __m128i *)(sp->teddy + j * 32)));
        th[j] = _mm512_broadcast_i32x4(
            _mm_loadu_si128((const __m128i *)(sp->teddy + j * 32 + 16)));
        tl2[j] = th2[j] = _mm512_setzero_si512();
        if (fat) {
            tl2[j] = _mm512_broadcast_i32x4(_mm_loadu_si128(
                (const __m128i *)(sp->teddy2 + j * 32)));
            th2[j] = _mm512_broadcast_i32x4(_mm_loadu_si128(
                (const __m128i *)(sp->teddy2 + j * 32 + 16)));
        }
    }
    for (Py_ssize_t i = 0; i < scan_n; i += 64) {
        __m512i m = _mm512_set1_epi8((char)0xff);
        __m512i m2 = m;
        for (int j = 0; j < SWEEP_TEDDY_M; j++) {
            __m512i d = _mm512_loadu_si512(
                (const void *)(pad + i + j));
            __m512i lx = _mm512_and_si512(d, lowm);
            __m512i hx = _mm512_and_si512(_mm512_srli_epi16(d, 4),
                                          lowm);
            m = _mm512_and_si512(m, _mm512_and_si512(
                _mm512_shuffle_epi8(tl[j], lx),
                _mm512_shuffle_epi8(th[j], hx)));
            if (fat)
                m2 = _mm512_and_si512(m2, _mm512_and_si512(
                    _mm512_shuffle_epi8(tl2[j], lx),
                    _mm512_shuffle_epi8(th2[j], hx)));
        }
        uint64_t bits = (uint64_t)_mm512_test_epi8_mask(m, m);
        if (fat)
            bits |= (uint64_t)_mm512_test_epi8_mask(m2, m2);
        while (bits) {
            int b = __builtin_ctzll(bits);
            bits &= bits - 1;
            Py_ssize_t pos = i + b;
            if (pos < scan_n) {
                (*nsurv)++;
                sweep_confirm(sp, pad, 0, n, n, ov, B, pos, out);
            }
        }
    }
}

static int
sweep_cpu_level(void)
{
    if (__builtin_cpu_supports("avx512f")
        && __builtin_cpu_supports("avx512bw"))
        return 3;
    if (__builtin_cpu_supports("avx2"))
        return 2;
    if (__builtin_cpu_supports("ssse3"))
        return 1;
    return 0;
}
#else
static int
sweep_cpu_level(void)
{
    return 0;
}
#endif

/* requested: -1 auto, 0 scalar, 1 ssse3, 2 avx2, 3 avx512 — clamped
 * to what the CPU actually has, so a pinned KLOGS_NATIVE_SIMD=avx512
 * on an old box degrades to the best real level instead of
 * faulting. */
static int
sweep_resolve_level(int requested)
{
    int cpu = sweep_cpu_level();
    if (requested < 0 || requested > cpu)
        return cpu;
    return requested;
}

static PyObject *
sweep_simd_level(PyObject *self, PyObject *args)
{
    int requested = -1;
    if (!PyArg_ParseTuple(args, "|i", &requested))
        return NULL;
    return PyLong_FromLong(sweep_resolve_level(requested));
}

static PyObject *
sweep_candidates(PyObject *self, PyObject *args)
{
    Py_buffer blob, payload, offs, stats;
    Py_ssize_t B;
    int requested;
    stats.obj = NULL;
    stats.buf = NULL;
    if (!PyArg_ParseTuple(args, "y*y*y*ni|w*", &blob, &payload, &offs,
                          &B, &requested, &stats))
        return NULL;
    sweep_prog_c sp;
    if (B < 0 || offs.len < (B + 1) * 4
        || (stats.obj && stats.len < 16)
        || sweep_parse_blob((const char *)blob.buf, blob.len, &sp) < 0) {
        PyBuffer_Release(&blob);
        PyBuffer_Release(&payload);
        PyBuffer_Release(&offs);
        PyBuffer_Release(&stats);
        PyErr_SetString(PyExc_ValueError,
                        "sweep_candidates: malformed tables or sizes");
        return NULL;
    }
    const Py_ssize_t n = payload.len;
    PyObject *mask = PyBytes_FromStringAndSize(
        NULL, B * (Py_ssize_t)sp.GW * 4);
    if (!mask) {
        PyBuffer_Release(&blob);
        PyBuffer_Release(&payload);
        PyBuffer_Release(&offs);
        PyBuffer_Release(&stats);
        return PyErr_NoMemory();
    }
    const int32_t *ov = (const int32_t *)offs.buf;
    uint32_t *out = (uint32_t *)PyBytes_AS_STRING(mask);
    int level = sweep_resolve_level(requested);
    uint64_t nsurv = 0;
    int bad = 0;

    Py_BEGIN_ALLOW_THREADS
    /* Offsets must be non-decreasing within the payload: the confirm
     * loop's binary search trusts them. */
    if (ov[0] < 0 || (Py_ssize_t)ov[B] > n)
        bad = 1;
    for (Py_ssize_t i = 0; i < B && !bad; i++)
        if (ov[i] > ov[i + 1])
            bad = 1;
    if (!bad) {
        /* Every row starts as the always-candidate mask (groups owning
         * unguarded patterns), exactly like the host sweep. */
        for (Py_ssize_t i = 0; i < B; i++)
            memcpy(out + (size_t)i * sp.GW, sp.always,
                   (size_t)sp.GW * 4);
        if (n >= 3) {
            /* In-place scan of the source buffer up to scan_n (every
             * load proven in-bounds there — see SWEEP_TAIL), then the
             * last positions from a small zero-padded stack copy.
             * Replaces a full-payload copy that cost ~1 ms per 8 MB
             * slab in malloc page faults + memcpy. */
            const uint8_t *src = (const uint8_t *)payload.buf;
            Py_ssize_t scan_n = n > SWEEP_TAIL ? n - SWEEP_TAIL : 0;
            if (scan_n) {
#if SWEEP_HAVE_X86
                if (level >= 3)
                    sweep_scan_avx512(&sp, src, scan_n, n, ov, B, out,
                                      &nsurv);
                else if (level == 2)
                    sweep_scan_avx2(&sp, src, scan_n, n, ov, B, out,
                                    &nsurv);
                else if (level == 1)
                    sweep_scan_ssse3(&sp, src, scan_n, n, ov, B, out,
                                     &nsurv);
                else
                    sweep_scan_scalar(&sp, src, scan_n, n, ov, B, out,
                                      &nsurv);
#else
                (void)level;
                sweep_scan_scalar(&sp, src, scan_n, n, ov, B, out,
                                  &nsurv);
#endif
            }
            uint8_t tbuf[SWEEP_TAIL_LEFT + SWEEP_TAIL + SWEEP_PAD];
            Py_ssize_t tbase = scan_n > SWEEP_TAIL_LEFT
                ? scan_n - SWEEP_TAIL_LEFT : 0;
            memcpy(tbuf, src + tbase, (size_t)(n - tbase));
            memset(tbuf + (n - tbase), 0, SWEEP_PAD);
            sweep_scan_tail(&sp, tbuf, tbase, scan_n, n, ov, B, out,
                            &nsurv);
        }
    }
    Py_END_ALLOW_THREADS

    if (!bad && stats.obj) {
        /* u64[2] = [stage-1 survivors, scanned byte positions]: the
         * survivor-ratio telemetry BENCH_SWEEP reports. Written under
         * the GIL, after the scan — the caller owns the buffer and
         * must not share it across in-flight sweeps. */
        uint64_t sb[2];
        sb[0] = nsurv;
        sb[1] = (uint64_t)n;
        memcpy(stats.buf, sb, sizeof sb);
        const size_t nbits = (size_t)sp.GW * 32;
        if ((size_t)stats.len >= (3 + nbits) * 8) {
            /* Extended layout u64[3 + 32*GW]: [2] = lines with any
             * candidate bit, [3+g] = per-group candidate column sums.
             * A ctz walk over the packed mask costs ~total-set-bits;
             * the equivalent numpy axis-0 reduction over the unpacked
             * [B, G] matrix measured ~4-6 ms/slab at K=1024. */
            uint64_t *sbx = (uint64_t *)stats.buf;
            uint64_t lines = 0;
            uint64_t *colsum = sbx + 3;
            memset(colsum, 0, nbits * 8);
            for (Py_ssize_t i = 0; i < B; i++) {
                const uint32_t *row = out + (size_t)i * sp.GW;
                uint32_t any = 0;
                for (int32_t w = 0; w < sp.GW; w++) {
                    uint32_t v = row[w];
                    any |= v;
                    while (v) {
                        colsum[w * 32 + __builtin_ctz(v)]++;
                        v &= v - 1;
                    }
                }
                lines += any != 0;
            }
            sbx[2] = lines;
        }
    }
    PyBuffer_Release(&blob);
    PyBuffer_Release(&payload);
    PyBuffer_Release(&offs);
    PyBuffer_Release(&stats);
    if (bad) {
        Py_DECREF(mask);
        PyErr_SetString(PyExc_ValueError,
                        "sweep_candidates: offsets out of range");
        return NULL;
    }
    return mask;
}

/* ================= MultiDFA batched group scan =======================
 *
 * group_scan(blob, payload, offsets, n_lines, cand, stride, cols,
 *            order, out, packed=0) -> scanned candidate cells (int)
 *
 * The "confirm" stage of the indexed engine done in one native call
 * (Hyperscan-FDR shape; filters/indexed.py): instead of a Python loop
 * dispatching one dfa_scan per candidate GROUP — each paying a gathered
 * sub-frame copy and its own GIL round-trip — every DFA-backed group's
 * flat scan tables travel in ONE MultiDFA program blob
 * (FactorIndex-side builder: filters/compiler/index.py multidfa_blob)
 * and this kernel walks all (row, group) candidate cells in place via
 * the framed offsets: zero sub-frame copies, one native call per slab.
 *
 *   blob:    MultiDFA program (validated header below; native byte
 *            order — the blob is process-local, never persisted)
 *   payload: framed slab bytes (borrowed, read-only)
 *   offsets: i32[n_lines+1] exclusive prefix offsets
 *   cand:    u8[n_lines, stride] candidate matrix (0 = the sweep
 *            ruled the cell out). `stride` + `cols` let the engine
 *            pass its FULL [B, n_groups] bool group matrix with zero
 *            copies: member m's candidate column is cand[., cols[m]].
 *            With packed=1 cand is instead the sweep kernel's RAW
 *            u32[n_lines, stride] group bitset (member m's candidacy
 *            is bit cols[m]&31 of word cols[m]>>5) — no host-side
 *            unpackbits at all; a single ctz walk over the masked
 *            words builds every member's candidate row list up front
 *            instead of re-reading all rows once per member.
 *   order:   i32[M] scan order over members (the engine passes
 *            ascending candidate count: most selective first, so
 *            always-candidate groups run last and inherit every
 *            earlier accept as an early-out)
 *   out:     u8[n_lines] verdict bytes, WRITABLE, monotonic 0->1 only
 *            (rows already 1 on entry are skipped entirely)
 *
 * Group-major walk with exact early-out: members run in `order`; each
 * member scans the candidate rows no earlier member accepted. That is
 * cell-for-cell the same skip set as a row-major walk (each (row,
 * member) cell runs iff no member earlier in `order` accepted the
 * row) but keeps one member's tables hot in cache across its whole
 * run. Parallelism reuses the slice_jobs/dispatch_row_jobs machinery
 * over ROW ranges — each worker owns a disjoint slice of rows and
 * with it that slice's verdict bytes, so the shared `out` array sees
 * monotonic, non-racing writes by construction and the early-out is
 * exact (not opportunistic). The whole walk runs inside
 * Py_BEGIN_ALLOW_THREADS over borrowed read-only buffers + the
 * caller-owned out buffer.
 *
 * Start-state acceleration (Hyperscan "accel state" shape): at parse
 * time each member's start-state row is scanned for its ESCAPE bytes
 * — bytes whose class leaves the start state. A member with <= 2
 * escape bytes runs a memchr-driven loop: while the automaton sits in
 * its start state, memchr jumps straight to the next escape byte
 * (every skipped byte provably self-loops), and the table walk only
 * runs from there until the state falls back to start. On literal-ish
 * patterns the scan approaches memchr speed instead of the ~1 GB/s
 * dependent-load table walk; the state trajectory is identical by
 * construction.
 *
 * Per-cell semantics are exactly dfa_scan's scalar loop (strip
 * trailing '\n', start state after BEGIN, accept latched per byte,
 * end_class step last, match_all short-circuit) — so the per-group
 * dfa_scan path is this kernel's parity oracle, cell for cell. State
 * ids loaded from the (untrusted-bytes) tables are bounds-checked in
 * the loop before use: a corrupt blob raises, never reads out of
 * bounds.
 */

#define MDFA_MAGIC 0x4B4D4446   /* "FDMK" little-endian */
#define MDFA_VERSION 1
/* Header word indexes (i32; see multidfa_blob in compiler/index.py). */
enum { MH_MAGIC = 0, MH_VERSION, MH_M, MH_TOTAL, MH_WORDS = 8 };
/* Per-member descriptor words following the header. */
enum { MD_NDFA = 0, MD_NCLASSES, MD_START, MD_ENDCLASS, MD_WIDE,
       MD_MATCHALL, MD_TABLE_OFF, MD_ACCEPT_OFF, MD_BCLASS_OFF,
       MD_WORDS = 10 };

#define MDFA_MAX_ESC 2          /* accel only for <= 2 escape bytes */

typedef struct {
    int32_t n_dfa, n_classes, start, end_class, wide, match_all;
    const uint16_t *tab16;      /* [n_dfa * n_classes] when !wide */
    const uint32_t *tab32;      /* [n_dfa * n_classes] when wide */
    const uint8_t *accept;      /* [n_dfa] */
    const int32_t *bc;          /* [256], entries < n_classes */
    int esc_n;                  /* start-state escape bytes (-1 = many) */
    uint8_t esc[MDFA_MAX_ESC];
} mdfa_member;

static int
mdfa_parse_blob(const char *blob, Py_ssize_t blen, int32_t *m_out,
                mdfa_member **members_out)
{
    if (blen < MH_WORDS * 4)
        return -1;
    const int32_t *h = (const int32_t *)blob;
    if (h[MH_MAGIC] != MDFA_MAGIC || h[MH_VERSION] != MDFA_VERSION
        || h[MH_TOTAL] != (int32_t)blen)
        return -1;
    int32_t M = h[MH_M];
    if (M < 1
        || (int64_t)MH_WORDS * 4 + (int64_t)M * MD_WORDS * 4 > (int64_t)blen)
        return -1;
    mdfa_member *mem = PyMem_Malloc((size_t)M * sizeof(mdfa_member));
    if (!mem)
        return -1;
    for (int32_t m = 0; m < M; m++) {
        const int32_t *d = h + MH_WORDS + (size_t)m * MD_WORDS;
        mdfa_member *mm = &mem[m];
        mm->n_dfa = d[MD_NDFA];
        mm->n_classes = d[MD_NCLASSES];
        mm->start = d[MD_START];
        mm->end_class = d[MD_ENDCLASS];
        mm->wide = d[MD_WIDE];
        mm->match_all = d[MD_MATCHALL];
        if (mm->n_dfa < 1 || mm->n_classes < 1
            || mm->start < 0 || mm->start >= mm->n_dfa
            || mm->end_class < 0 || mm->end_class >= mm->n_classes
            || (mm->wide != 0 && mm->wide != 1)
            || (mm->match_all != 0 && mm->match_all != 1)) {
            PyMem_Free(mem);
            return -1;
        }
        const void *tab = sweep_arr(blob, blen, d[MD_TABLE_OFF],
                                    (int64_t)mm->n_dfa * mm->n_classes,
                                    mm->wide ? 4 : 2);
        mm->accept = sweep_arr(blob, blen, d[MD_ACCEPT_OFF],
                               mm->n_dfa, 1);
        mm->bc = sweep_arr(blob, blen, d[MD_BCLASS_OFF], 256, 4);
        if (!tab || !mm->accept || !mm->bc) {
            PyMem_Free(mem);
            return -1;
        }
        mm->tab16 = (const uint16_t *)tab;
        mm->tab32 = (const uint32_t *)tab;
        for (int c = 0; c < 256; c++) {
            if (mm->bc[c] < 0 || mm->bc[c] >= mm->n_classes) {
                PyMem_Free(mem);
                return -1;
            }
        }
        /* Start-state escape set for the memchr acceleration: bytes
         * whose class maps start anywhere but back to start. */
        mm->esc_n = 0;
        for (int c = 0; c < 256 && mm->esc_n >= 0; c++) {
            uint32_t nxt = mm->wide
                ? mm->tab32[(size_t)mm->start * mm->n_classes
                            + (uint32_t)mm->bc[c]]
                : mm->tab16[(size_t)mm->start * mm->n_classes
                            + (uint32_t)mm->bc[c]];
            if (nxt == (uint32_t)mm->start)
                continue;
            if (mm->esc_n >= MDFA_MAX_ESC)
                mm->esc_n = -1;  /* too many: plain table walk */
            else
                mm->esc[mm->esc_n++] = (uint8_t)c;
        }
    }
    *m_out = M;
    *members_out = mem;
    return 0;
}

typedef struct {
    const mdfa_member *mem;     /* [M] parsed program members */
    int32_t M;
    int32_t n_ord;              /* members to scan (order entries) */
    const uint8_t *src;
    Py_ssize_t src_len;
    const int32_t *ov;          /* [B+1] framed offsets */
    const uint8_t *cand;        /* [B, stride] candidate bytes, or in
                                 * packed mode [B, stride] u32 words
                                 * (bit col&31 of word col>>5) */
    Py_ssize_t stride;
    const int32_t *cols;        /* [M] member -> cand column */
    const int32_t *order;       /* [n_ord] member scan order — the
                                 * caller may omit members it knows
                                 * have zero candidates */
    uint8_t *out;               /* [B] verdict bytes (monotonic 0->1) */
    long long scanned;          /* candidate cells actually scanned */
    Py_ssize_t lo, hi;          /* row range for this worker */
    int bad;                    /* 1 offsets, 2 state id, 4 memory */
    int packed;                 /* cand holds u32 bit words */
    const int32_t *bit2slot;    /* [stride*32] packed col -> order
                                 * slot, -1 for unlisted columns */
    const uint32_t *colmask;    /* [stride] OR of listed column bits */
} gs_job;

/* One (row, member) cell: dfa_scan's scalar loop with an in-loop
 * state-id bound check (the blob is untrusted bytes — a corrupt table
 * entry must raise, not index past accept[]) and the memchr start-
 * state acceleration (header comment). Returns 1 on accept. */
static inline int
gs_scan_cell(const mdfa_member *d, const uint8_t *row, Py_ssize_t len,
             int *bad)
{
    const uint32_t nc = (uint32_t)d->n_classes;
    const uint32_t nd = (uint32_t)d->n_dfa;
    const uint32_t start = (uint32_t)d->start;
    uint32_t s = start;
    if (d->accept[s])
        return 1;
    const uint8_t *p = row;
    const uint8_t *pe = row + len;
    if (d->esc_n == 0)
        p = pe;                 /* no byte ever leaves the start state */
    while (p < pe) {
        if (s == start && d->esc_n > 0) {
            /* Every byte before the next escape byte provably maps
             * start -> start: jump straight there. */
            const uint8_t *q = memchr(p, d->esc[0], (size_t)(pe - p));
            if (d->esc_n == 2) {
                /* Only the region BEFORE the first hit can move the
                 * jump target earlier — searching past it rescans
                 * bytes the first memchr already cleared. */
                const uint8_t *q2 = memchr(p, d->esc[1],
                                           q ? (size_t)(q - p)
                                             : (size_t)(pe - p));
                if (q2)
                    q = q2;
            }
            if (!q)
                break;
            p = q;
        }
        s = d->wide ? d->tab32[s * nc + (uint32_t)d->bc[*p]]
                    : d->tab16[s * nc + (uint32_t)d->bc[*p]];
        p++;
        if (s >= nd) {
            *bad = 2;
            return 0;
        }
        if (d->accept[s])
            return 1;
    }
    s = d->wide ? d->tab32[s * nc + (uint32_t)d->end_class]
                : d->tab16[s * nc + (uint32_t)d->end_class];
    if (s >= nd) {
        *bad = 2;
        return 0;
    }
    return d->accept[s];
}

/* Scan one member over the candidate rows listed in rl[0..rn).
 * Rows already accepted by an earlier member are skipped here (NOT
 * counted as scanned), so the early-out semantics match the original
 * row-major walk cell for cell regardless of how the list was built. */
static void
gs_scan_member(gs_job *job, int32_t g, const int32_t *rl, int32_t rn)
{
    const uint8_t *src = job->src;
    const int32_t *ov = job->ov;
    const mdfa_member *d = &job->mem[g];
    if (d->esc_n < 0 && !d->match_all && !d->wide
        && !d->accept[d->start]) {
        /* No start-state acceleration possible (broad escape set):
         * interleave DFA_LANES candidate rows so the dependent
         * state->table->state load chains overlap — the same trick
         * as dfa_scan_rows, gathered over this member's candidate
         * rows. */
        const uint32_t nc = (uint32_t)d->n_classes;
        const uint32_t nd = (uint32_t)d->n_dfa;
        Py_ssize_t idx[DFA_LANES];
        const uint8_t *p[DFA_LANES], *pe[DFA_LANES];
        uint32_t s[DFA_LANES];
        int nl = 0;
        for (int32_t t = 0; t <= rn; t++) {
            if (t < rn) {
                Py_ssize_t i = rl[t];
                if (job->out[i])
                    continue;
                job->scanned++;
                int32_t rlo = ov[i];
                Py_ssize_t len = ov[i + 1] - rlo;
                while (len > 0 && src[rlo + len - 1] == '\n')
                    len--;
                idx[nl] = i;
                p[nl] = src + rlo;
                pe[nl] = p[nl] + len;
                s[nl] = (uint32_t)d->start;
                nl++;
                if (nl < DFA_LANES)
                    continue;
            }
            unsigned active = 0;
            for (int l = 0; l < nl; l++)
                if (p[l] < pe[l])
                    active |= 1u << l;
                else
                    s[l] = UINT32_MAX;  /* empty: end step below */
            while (active) {
                for (int l = 0; l < nl; l++) {
                    if (!(active & (1u << l)))
                        continue;
                    uint32_t nxt = d->tab16[s[l] * nc
                                   + (uint32_t)d->bc[*p[l]]];
                    p[l]++;
                    if (nxt >= nd) {
                        job->bad = 2;
                        return;
                    }
                    if (d->accept[nxt]) {
                        job->out[idx[l]] = 1;
                        active &= ~(1u << l);
                    } else if (p[l] == pe[l]) {
                        s[l] = nxt;
                        active &= ~(1u << l);
                    } else {
                        s[l] = nxt;
                    }
                }
            }
            for (int l = 0; l < nl; l++) {
                if (job->out[idx[l]])
                    continue;
                uint32_t sf = s[l] == UINT32_MAX
                    ? (uint32_t)d->start : s[l];
                sf = d->tab16[sf * nc + (uint32_t)d->end_class];
                if (sf >= nd) {
                    job->bad = 2;
                    return;
                }
                if (d->accept[sf])
                    job->out[idx[l]] = 1;
            }
            nl = 0;
        }
        return;
    }
    for (int32_t t = 0; t < rn; t++) {
        Py_ssize_t i = rl[t];
        if (job->out[i])
            continue;
        job->scanned++;
        int32_t rlo = ov[i];
        Py_ssize_t len = ov[i + 1] - rlo;
        while (len > 0 && src[rlo + len - 1] == '\n')
            len--;
        if (d->match_all
            || gs_scan_cell(d, src + rlo, len, &job->bad))
            job->out[i] = 1;
        if (job->bad)
            return;
    }
}

static void
group_scan_rows(gs_job *job)
{
    const int32_t *ov = job->ov;
    /* Validate this slice's offsets ONCE; the per-member passes below
     * then trust them. */
    for (Py_ssize_t i = job->lo; i < job->hi; i++) {
        if (ov[i] < 0 || ov[i + 1] < ov[i] || ov[i + 1] > job->src_len) {
            job->bad = 1;
            return;
        }
    }
    /* Group-major: one member's tables stay cache-hot across its
     * whole row run; early-out semantics match the row-major walk
     * cell for cell (header comment). */
    if (job->packed) {
        /* One ctz walk over the sweep's packed bit matrix builds every
         * member's candidate row list at once. The byte-matrix shape
         * below re-reads all B rows once PER member (n_ord * B loads —
         * ~2 ms on a 64k-row slab at K=1k with only a handful of live
         * members); here the listed-column mask prunes dead bits in
         * bulk and each set bit costs one counted-sort insert. */
        const uint32_t *cw = (const uint32_t *)job->cand;
        const Py_ssize_t GW = job->stride;
        int32_t *cnt = calloc((size_t)job->n_ord + 1, sizeof(int32_t));
        if (!cnt) {
            job->bad = 4;
            return;
        }
        int64_t total = 0;
        for (Py_ssize_t i = job->lo; i < job->hi; i++) {
            const uint32_t *row = cw + (size_t)i * GW;
            for (Py_ssize_t w = 0; w < GW; w++) {
                uint32_t v = row[w] & job->colmask[w];
                while (v) {
                    int b = __builtin_ctz(v);
                    v &= v - 1;
                    cnt[job->bit2slot[w * 32 + b]]++;
                    total++;
                }
            }
        }
        int32_t *start = malloc(((size_t)job->n_ord + 1)
                                * sizeof(int32_t));
        int32_t *fill = malloc(((size_t)job->n_ord + 1)
                               * sizeof(int32_t));
        int32_t *lists = malloc(total ? (size_t)total * sizeof(int32_t)
                                      : sizeof(int32_t));
        if (!start || !fill || !lists) {
            free(cnt);
            free(start);
            free(fill);
            free(lists);
            job->bad = 4;
            return;
        }
        int32_t acc = 0;
        for (int32_t k = 0; k < job->n_ord; k++) {
            start[k] = fill[k] = acc;
            acc += cnt[k];
        }
        for (Py_ssize_t i = job->lo; i < job->hi; i++) {
            const uint32_t *row = cw + (size_t)i * GW;
            for (Py_ssize_t w = 0; w < GW; w++) {
                uint32_t v = row[w] & job->colmask[w];
                while (v) {
                    int b = __builtin_ctz(v);
                    v &= v - 1;
                    lists[fill[job->bit2slot[w * 32 + b]]++] =
                        (int32_t)i;
                }
            }
        }
        for (int32_t k = 0; k < job->n_ord && !job->bad; k++)
            gs_scan_member(job, job->order[k], lists + start[k],
                           cnt[k]);
        free(cnt);
        free(start);
        free(fill);
        free(lists);
        return;
    }
    Py_ssize_t nrows = job->hi - job->lo;
    int32_t *tmp = malloc(nrows ? (size_t)nrows * sizeof(int32_t)
                                : sizeof(int32_t));
    if (!tmp) {
        job->bad = 4;
        return;
    }
    for (int32_t k = 0; k < job->n_ord && !job->bad; k++) {
        const int32_t g = job->order[k];
        const int32_t col = job->cols[g];
        int32_t rn = 0;
        for (Py_ssize_t i = job->lo; i < job->hi; i++)
            if (job->cand[(size_t)i * job->stride + col])
                tmp[rn++] = (int32_t)i;
        gs_scan_member(job, g, tmp, rn);
    }
    free(tmp);
}

static void *
group_scan_worker(void *arg)
{
    group_scan_rows((gs_job *)arg);
    return NULL;
}

static void
group_scan_run(void *arg)
{
    group_scan_rows((gs_job *)arg);
}

static PyObject *
group_scan(PyObject *self, PyObject *args)
{
    Py_buffer blob, payload, offs, cand, cols, order, outb;
    Py_ssize_t B, stride;
    int packed = 0;
    if (!PyArg_ParseTuple(args, "y*y*y*ny*ny*y*w*|i", &blob, &payload,
                          &offs, &B, &cand, &stride, &cols, &order,
                          &outb, &packed))
        return NULL;
    int32_t M = 0;
    mdfa_member *mem = NULL;
    int32_t *bit2slot = NULL;
    uint32_t *colmask = NULL;
    int ok = (B >= 0 && stride >= 1 && offs.len >= (B + 1) * 4
              && mdfa_parse_blob((const char *)blob.buf, blob.len,
                                 &M, &mem) == 0);
    /* order may name FEWER members than the program holds — the
     * caller omits members it knows have zero candidate rows. */
    const int32_t n_ord = (int32_t)(order.len / 4);
    /* Packed mode: cand is the sweep's u32[B, stride] group bitset
     * (bit col&31 of word col>>5 = that column's candidacy), consumed
     * zero-copy; byte mode keeps the original [B, stride] matrix. */
    if (ok && (cand.len < (int64_t)B * stride * (packed ? 4 : 1)
               || cols.len < (Py_ssize_t)M * 4
               || n_ord > M || outb.len < B))
        ok = 0;
    if (ok) {
        const int32_t *colv = (const int32_t *)cols.buf;
        const int32_t *ordv = (const int32_t *)order.buf;
        const Py_ssize_t ncol = packed ? stride * 32 : stride;
        for (int32_t k = 0; k < M; k++)
            if (colv[k] < 0 || colv[k] >= ncol)
                ok = 0;
        for (int32_t k = 0; k < n_ord; k++)
            if (ordv[k] < 0 || ordv[k] >= M)
                ok = 0;
        if (ok && packed) {
            bit2slot = PyMem_Malloc((size_t)stride * 32
                                    * sizeof(int32_t));
            colmask = PyMem_Calloc((size_t)stride, sizeof(uint32_t));
            if (!bit2slot || !colmask) {
                PyMem_Free(mem);
                PyMem_Free(bit2slot);
                PyMem_Free(colmask);
                PyBuffer_Release(&blob);
                PyBuffer_Release(&payload);
                PyBuffer_Release(&offs);
                PyBuffer_Release(&cand);
                PyBuffer_Release(&cols);
                PyBuffer_Release(&order);
                PyBuffer_Release(&outb);
                return PyErr_NoMemory();
            }
            memset(bit2slot, 0xff, (size_t)stride * 32
                                   * sizeof(int32_t));
            for (int32_t k = 0; k < n_ord; k++) {
                int32_t c = colv[ordv[k]];
                if (bit2slot[c] != -1)
                    ok = 0;  /* duplicate column: lists would split */
                bit2slot[c] = k;
                colmask[c >> 5] |= 1u << (c & 31);
            }
        }
    }
    if (!ok) {
        PyMem_Free(bit2slot);
        PyMem_Free(colmask);
        PyMem_Free(mem);
        PyBuffer_Release(&blob);
        PyBuffer_Release(&payload);
        PyBuffer_Release(&offs);
        PyBuffer_Release(&cand);
        PyBuffer_Release(&cols);
        PyBuffer_Release(&order);
        PyBuffer_Release(&outb);
        PyErr_SetString(PyExc_ValueError,
                        "group_scan: malformed program blob or sizes");
        return NULL;
    }
    /* Escape-byte density sampling: the memchr acceleration LOSES to
     * the interleaved table walk when the escape byte saturates the
     * corpus (an 'e' every few bytes means a memchr restart per hit);
     * histogram the payload head once and demote dense-escape members
     * to the interleaved path. Pure cost heuristic — both paths step
     * the identical automaton. */
    {
        size_t hn = payload.len < 4096 ? (size_t)payload.len : 4096;
        uint32_t hist[256] = {0};
        const uint8_t *hp = (const uint8_t *)payload.buf;
        for (size_t i = 0; i < hn; i++)
            hist[hp[i]]++;
        for (int32_t m = 0; m < M; m++) {
            if (mem[m].esc_n <= 0)
                continue;
            uint32_t cnt = 0;
            for (int e = 0; e < mem[m].esc_n; e++)
                cnt += hist[mem[m].esc[e]];
            /* Break-even measured on the BENCH_K corpus: memchr +
             * range-limited second probe beats the interleaved walk
             * up to ~1/8 escape density; only truly saturated escape
             * bytes (an 'e'-every-few-bytes corpus) still demote. */
            if (hn && (size_t)cnt * 8 > hn)
                mem[m].esc_n = -1;
        }
    }
    gs_job job = {mem, M, n_ord, (const uint8_t *)payload.buf,
                  payload.len, (const int32_t *)offs.buf,
                  (const uint8_t *)cand.buf, stride,
                  (const int32_t *)cols.buf,
                  (const int32_t *)order.buf, (uint8_t *)outb.buf,
                  0, 0, B, 0, packed, bit2slot, colmask};
    int nthreads = host_threads();
    long long scanned = 0;
    int bad = 0;
    if (nthreads <= 1 || B < 8192) {
        /* Small slabs stay single-threaded (spawn cost would swamp a
         * sub-ms scan) but still release the GIL: sibling Python
         * threads sweep/pack while this slab confirms. */
        Py_BEGIN_ALLOW_THREADS
        group_scan_rows(&job);
        Py_END_ALLOW_THREADS
        scanned = job.scanned;
        bad = job.bad;
    } else {
        gs_job jobs[64];
        int count = slice_jobs((char *)jobs, sizeof(gs_job), &job, B,
                               nthreads, 1, offsetof(gs_job, lo),
                               offsetof(gs_job, hi));
        Py_BEGIN_ALLOW_THREADS
        dispatch_row_jobs((char *)jobs, sizeof(gs_job), count,
                          group_scan_worker, group_scan_run);
        Py_END_ALLOW_THREADS
        for (int t = 0; t < count; t++) {
            scanned += jobs[t].scanned;
            bad |= jobs[t].bad;
        }
    }
    PyMem_Free(bit2slot);
    PyMem_Free(colmask);
    PyMem_Free(mem);
    PyBuffer_Release(&blob);
    PyBuffer_Release(&payload);
    PyBuffer_Release(&offs);
    PyBuffer_Release(&cand);
    PyBuffer_Release(&cols);
    PyBuffer_Release(&order);
    PyBuffer_Release(&outb);
    if (bad) {
        if (bad & 4)
            return PyErr_NoMemory();
        PyErr_SetString(PyExc_ValueError,
                        bad & 2 ? "group_scan: table state id out of range"
                                : "group_scan: offsets out of range");
        return NULL;
    }
    return PyLong_FromLongLong(scanned);
}

static PyMethodDef Methods[] = {
    {"pack_lines", pack_lines, METH_VARARGS,
     "pack_lines(lines, width, rows) -> (bytes, int32-lengths-bytes)"},
    {"pack_classify", pack_classify, METH_VARARGS,
     "pack_classify(lines, width, rows, table, begin, end, pad)"
     " -> (int8-cls-bytes, int32-lengths-bytes)"},
    {"classify_chunk", classify_chunk_c, METH_VARARGS,
     "classify_chunk(data, B, L, rem, table, begin, end, pad, first,"
     " final) -> int8-cls-bytes"},
    {"join_kept", join_kept, METH_VARARGS,
     "join_kept(lines, mask) -> bytes of mask-selected lines"},
    {"frame_lines", frame_lines, METH_VARARGS,
     "frame_lines(lines, strip_nl) -> (payload, int32-offsets-bytes,"
     " raw_total)"},
    {"split_frame", split_frame, METH_VARARGS,
     "split_frame(payload, offsets, n) -> list[bytes]"},
    {"pack_classify_framed", pack_classify_framed, METH_VARARGS,
     "pack_classify_framed(payload, offsets, n, sel, width, rows, table,"
     " begin, end, pad) -> (int8-cls-bytes, int32-lengths-bytes)"},
    {"dfa_scan", dfa_scan, METH_VARARGS,
     "dfa_scan(payload, offsets, n, table, n_classes, accept, byte_class,"
     " start, end_class, wide) -> mask bytes"},
    {"find_newlines", find_newlines, METH_VARARGS,
     "find_newlines(data, base) -> int32 after-newline positions"},
    {"join_kept_framed", join_kept_framed, METH_VARARGS,
     "join_kept_framed(payload, offsets, n, mask) -> bytes"},
    {"sweep_candidates", sweep_candidates, METH_VARARGS,
     "sweep_candidates(blob, payload, offsets, n_lines, simd,"
     " stats=None) -> u32[n_lines, GW] group-bitset bytes; stats is an"
     " optional writable u64[2] receiving [survivors, positions], or"
     " u64[3 + 32*GW] to also receive [candidate lines, per-group"
     " column sums]"},
    {"sweep_simd_level", sweep_simd_level, METH_VARARGS,
     "sweep_simd_level(requested=-1) -> resolved SIMD level"
     " (0 scalar, 1 ssse3, 2 avx2, 3 avx512)"},
    {"group_scan", group_scan, METH_VARARGS,
     "group_scan(blob, payload, offsets, n_lines, cand, stride, cols,"
     " order, out, packed=0) -> scanned candidate cells (out updated"
     " in place); packed=1 reads cand as the sweep's u32 bit words"},
    {NULL, NULL, 0, NULL},
};

static struct PyModuleDef module = {
    PyModuleDef_HEAD_INIT, "_hostops",
    "Native host-side packing/gather for klogs_tpu", -1, Methods,
};

PyMODINIT_FUNC
PyInit__hostops(void)
{
    return PyModule_Create(&module);
}
