/* _hostops — native host-side hot loop for klogs_tpu.
 *
 * The TPU engine consumes fixed-width [batch, width] uint8 tensors; the
 * pure-Python packer (one numpy frombuffer+copy per line) caps the host
 * path well below device rate. This module does the pack in one C pass.
 *
 * The reference's only native aspect is being a compiled Go binary
 * (SURVEY.md section 2); its host hot loop is io.Copy
 * (/root/reference/cmd/root.go:359-374). This is the equivalent
 * native layer for the batched-filter design.
 *
 * Exposed functions (GIL-holding except pack_classify's optional
 * KLOGS_HOST_THREADS row-parallel phase; no numpy C-API dependency —
 * callers wrap the returned buffers with np.frombuffer):
 *
 *   pack_lines(lines: list[bytes], width: int, rows: int)
 *       -> (buffer: bytes, lengths: bytes holding int32[rows])
 *     Zero-padded row-major [rows, width] pack; rows >= len(lines), the
 *     excess rows are zero (empty lines). A line longer than width is
 *     truncated (callers route long lines to the chunked path first).
 *
 *   count_keep_bytes(lines: list[bytes], mask: bytes) -> int
 *   join_kept(lines: list[bytes], mask: bytes) -> bytes
 *     Gather of mask-selected lines into one contiguous write buffer.
 */

#define PY_SSIZE_T_CLEAN
#include <Python.h>
#include <string.h>
#include <stdint.h>

/* Pair-LUT classification: one 64K-entry uint16 table maps two input
 * bytes to two class bytes per lookup — measured 3.65 GB/s vs 2.43 GB/s
 * for the per-byte 256-entry loop on the bench host (tools microbench,
 * 2026-07-30); the per-byte table stays for odd tails. Built lazily and
 * cached against the 256-byte source table (one filter process uses one
 * classifier; a memcmp guards pattern-set changes). GIL held throughout
 * this module, so the static cache needs no locking. The build is
 * endian-agnostic: index and entry are composed through memcpy exactly
 * like the hot loop reads/writes them. */
static uint8_t pair_src[256];
static uint16_t pair_tab[65536];
static int pair_valid = 0;

static const uint16_t *
get_pair_tab(const int8_t *tab)
{
    if (!pair_valid || memcmp(pair_src, tab, 256) != 0) {
        for (int a = 0; a < 256; a++) {
            for (int b = 0; b < 256; b++) {
                uint8_t pr[2] = {(uint8_t)a, (uint8_t)b};
                uint8_t cr[2] = {(uint8_t)tab[a], (uint8_t)tab[b]};
                uint16_t w, c;
                memcpy(&w, pr, 2);
                memcpy(&c, cr, 2);
                pair_tab[w] = c;
            }
        }
        memcpy(pair_src, tab, 256);
        pair_valid = 1;
    }
    return pair_tab;
}

/* Classify `len` bytes from src into dst via the pair LUT. */
static inline void
classify_span(int8_t *dst, const uint8_t *src, Py_ssize_t len,
              const int8_t *tab, const uint16_t *ptab)
{
    Py_ssize_t j = 0;
    for (; j + 2 <= len; j += 2) {
        uint16_t w, c;
        memcpy(&w, src + j, 2);
        c = ptab[w];
        memcpy(dst + j, &c, 2);
    }
    if (j < len)
        dst[j] = tab[src[j]];
}

/* Optional row-parallel execution of the pack_classify body.
 *
 * KLOGS_HOST_THREADS=N (N>1) splits the row loop across N pthreads with
 * the GIL RELEASED — the per-row work below is pure C over buffers whose
 * line pointers/lengths were snapshotted under the GIL (PyBytes are
 * immutable, and the caller's list holds the references alive for the
 * duration of the call). On the single-core bench host this cannot be
 * measured (nproc=1); it exists for production TPU hosts, where dozens
 * of cores feed one device and the single-threaded packer (9.4M
 * lines/s here) would otherwise be the sustained-rate bound against a
 * faster-than-tunnel device link. Default (unset / 1) takes the
 * original GIL-holding single-pass path, byte-for-byte identical
 * output (covered by tests/test_native.py parity over both settings).
 */
#include <pthread.h>

typedef struct {
    const char **ptrs;          /* [rows] line pointers (NULL past n) */
    const Py_ssize_t *lens;     /* [rows] clamped line lengths */
    int8_t *out;
    int32_t *lengths;
    Py_ssize_t T;
    const int8_t *tab;
    const uint16_t *ptab;
    int begin_c, end_c, pad_c;
    Py_ssize_t lo, hi;          /* row range for this worker */
} pack_job;

static void
pack_rows(const pack_job *job)
{
    const Py_ssize_t T = job->T;
    for (Py_ssize_t i = job->lo; i < job->hi; i++) {
        int8_t *row = job->out + i * T;
        Py_ssize_t len = job->lens[i];
        if (len > 0)
            classify_span(row + 1, (const uint8_t *)job->ptrs[i], len,
                          job->tab, job->ptab);
        row[0] = (int8_t)job->begin_c;
        row[1 + len] = (int8_t)job->end_c;
        memset(row + 2 + len, (int8_t)job->pad_c, T - 2 - len);
        job->lengths[i] = (int32_t)len;
    }
}

static void *
pack_worker(void *arg)
{
    pack_rows((const pack_job *)arg);
    return NULL;
}

static int
host_threads(void)
{
    const char *s = getenv("KLOGS_HOST_THREADS");
    if (!s)
        return 1;
    int n = atoi(s);
    return n < 1 ? 1 : (n > 64 ? 64 : n);
}

/* THE one spawn/join/inline-fallback loop for row-parallel work
 * (pack_classify, pack_classify_framed, dfa_scan all dispatch through
 * here — the failure-handling rules live in exactly one place):
 * jobs[0..count) are pre-sliced clones; the LAST live slice runs
 * inline on this thread, a failed pthread_create degrades that slice
 * to inline execution, and every spawned worker is joined before
 * return. Call with the GIL released; job structs must reference no
 * Python objects. */
/* Clone *proto into jobs[0..count) slices covering [0, rows) in
 * contiguous ranges of ceil(rows/nthreads) rounded up to `align` rows
 * (lane-aligned splits keep interleaved loops on full groups except at
 * each slice's own tail); writes the bounds through the lo/hi field
 * offsets so pack_job and dfa_job share one slicer. Returns the live
 * slice count. */
#include <stddef.h>

static int
slice_jobs(char *jobs, size_t jsz, const void *proto, Py_ssize_t rows,
           int nthreads, Py_ssize_t align, size_t lo_off, size_t hi_off)
{
    Py_ssize_t per = (rows + nthreads - 1) / nthreads;
    per = (per + align - 1) / align * align;
    if (per < 1)
        per = 1;
    int count = 0;
    for (int t = 0; t < nthreads; t++) {
        Py_ssize_t lo = (Py_ssize_t)t * per;
        Py_ssize_t hi = lo + per < rows ? lo + per : rows;
        if (lo >= hi)
            break;
        char *j = jobs + (size_t)count * jsz;
        memcpy(j, proto, jsz);
        *(Py_ssize_t *)(j + lo_off) = lo;
        *(Py_ssize_t *)(j + hi_off) = hi;
        count++;
    }
    return count;
}

static void
pack_rows_run(void *arg)
{
    pack_rows((const pack_job *)arg);
}

static void
dispatch_row_jobs(char *jobs, size_t jsz, int count,
                  void *(*worker)(void *), void (*run)(void *))
{
    pthread_t tids[64];
    int started = 0;
    for (int t = 0; t < count; t++) {
        void *j = jobs + (size_t)t * jsz;
        if (t == count - 1) {
            run(j);
            break;
        }
        if (pthread_create(&tids[started], NULL, worker, j) != 0) {
            run(j);
            continue;
        }
        started++;
    }
    for (int t = 0; t < started; t++)
        pthread_join(tids[t], NULL);
}

static PyObject *
pack_lines(PyObject *self, PyObject *args)
{
    PyObject *list;
    Py_ssize_t width, rows;
    if (!PyArg_ParseTuple(args, "O!nn", &PyList_Type, &list, &width, &rows))
        return NULL;
    Py_ssize_t n = PyList_GET_SIZE(list);
    if (rows < n)
        rows = n;
    if (width <= 0) {
        PyErr_SetString(PyExc_ValueError, "width must be positive");
        return NULL;
    }

    PyObject *buf = PyBytes_FromStringAndSize(NULL, rows * width);
    PyObject *lens = PyBytes_FromStringAndSize(NULL, rows * 4);
    if (!buf || !lens) {
        Py_XDECREF(buf);
        Py_XDECREF(lens);
        return NULL;
    }
    char *out = PyBytes_AS_STRING(buf);
    int32_t *lengths = (int32_t *)PyBytes_AS_STRING(lens);
    memset(out, 0, rows * width);
    memset(lengths, 0, rows * 4);

    for (Py_ssize_t i = 0; i < n; i++) {
        PyObject *item = PyList_GET_ITEM(list, i);
        char *p;
        Py_ssize_t len;
        if (PyBytes_AsStringAndSize(item, &p, &len) < 0) {
            Py_DECREF(buf);
            Py_DECREF(lens);
            return NULL;
        }
        Py_ssize_t c = len < width ? len : width;
        memcpy(out + i * width, p, c);
        lengths[i] = (int32_t)c;
    }
    return Py_BuildValue("(NN)", buf, lens);
}

/* pack_classify(lines, width, rows, table[256] bytes, begin, end, pad)
 *   -> (cls: bytes holding int8[rows, width+3], lengths: int32[rows])
 *
 * Fused pack + byte->class classification with the sentinel layout the
 * grouped Pallas kernel consumes directly (klogs_tpu/ops/pallas_nfa.py):
 *   col 0            BEGIN
 *   cols 1..len      table[byte]
 *   col len+1        END
 *   cols len+2..     PAD (includes the accept-latch step)
 * Device-side classify_chunk (a [B,T] gather) measured as ~85% of the
 * single-chip hot-path device time (BENCH_DEVICE.json "host_classify"
 * probe, 2026-07-29); one host pass removes it entirely. Excess rows
 * (rows > len(lines)) are packed as empty lines (BEGIN,END,PAD...).
 */
static PyObject *
pack_classify(PyObject *self, PyObject *args)
{
    PyObject *list;
    Py_ssize_t width, rows;
    Py_buffer table;
    int begin_c, end_c, pad_c;
    if (!PyArg_ParseTuple(args, "O!nny*iii", &PyList_Type, &list, &width,
                          &rows, &table, &begin_c, &end_c, &pad_c))
        return NULL;
    if (table.len < 256) {
        PyBuffer_Release(&table);
        PyErr_SetString(PyExc_ValueError, "class table must have 256 entries");
        return NULL;
    }
    Py_ssize_t n = PyList_GET_SIZE(list);
    if (rows < n)
        rows = n;
    if (width <= 0) {
        PyBuffer_Release(&table);
        PyErr_SetString(PyExc_ValueError, "width must be positive");
        return NULL;
    }
    const Py_ssize_t T = width + 3;
    PyObject *buf = PyBytes_FromStringAndSize(NULL, rows * T);
    PyObject *lens = PyBytes_FromStringAndSize(NULL, rows * 4);
    if (!buf || !lens) {
        PyBuffer_Release(&table);
        Py_XDECREF(buf);
        Py_XDECREF(lens);
        return NULL;
    }
    const int8_t *tab = (const int8_t *)table.buf;
    const uint16_t *ptab = get_pair_tab(tab);
    int8_t *out = (int8_t *)PyBytes_AS_STRING(buf);
    int32_t *lengths = (int32_t *)PyBytes_AS_STRING(lens);
    int nthreads = host_threads();

    if (nthreads <= 1 || rows < 4096) {
        /* Default path: one fused pass, zero scratch allocations (the
         * measured 9.4M lines/s loop). Also the degrade target when the
         * threaded path's snapshots can't be allocated. No up-front
         * whole-buffer memset: each row writes BEGIN + body + END and
         * pads only its own tail — for near-full rows (the common
         * bucket) that is a handful of bytes instead of touching the
         * 30+ MB buffer twice. */
fused:
        for (Py_ssize_t i = 0; i < rows; i++) {
            int8_t *row = out + i * T;
            Py_ssize_t len = 0;
            if (i < n) {
                PyObject *item = PyList_GET_ITEM(list, i);
                char *p;
                if (PyBytes_AsStringAndSize(item, &p, &len) < 0) {
                    PyBuffer_Release(&table);
                    Py_DECREF(buf);
                    Py_DECREF(lens);
                    return NULL;
                }
                if (len > width)
                    len = width;
                classify_span(row + 1, (const uint8_t *)p, len, tab, ptab);
            }
            row[0] = (int8_t)begin_c;
            row[1 + len] = (int8_t)end_c;
            memset(row + 2 + len, (int8_t)pad_c, T - 2 - len);
            lengths[i] = (int32_t)len;
        }
        PyBuffer_Release(&table);
        return Py_BuildValue("(NN)", buf, lens);
    }

    /* Threaded path (KLOGS_HOST_THREADS>1): snapshot line pointers/
     * lengths under the GIL, then run the row loop GIL-free across
     * pthreads. Requirements, all enforced below — failure of any
     * allocation degrades to the fused path above via `goto fused`:
     * (a) workers must never read the shared static pair-LUT cache
     *     (another Python thread could call in with a different
     *     classifier and rebuild it mid-read) -> call-local copies;
     * (b) the caller's list can be mutated with the GIL released, so
     *     each item is incref'd for the window and the owned pointers
     *     are recorded in their own array (NOT re-read from the list
     *     at cleanup: by then the list may hold different objects). */
    const char **ptrs = PyMem_Malloc(rows * sizeof(char *));
    Py_ssize_t *lenv = PyMem_Malloc(rows * sizeof(Py_ssize_t));
    PyObject **objs = n > 0 ? PyMem_Malloc(n * sizeof(PyObject *)) : NULL;
    int8_t *tab_copy = PyMem_Malloc(256);
    uint16_t *ptab_copy = PyMem_Malloc(65536 * sizeof(uint16_t));
    if (!ptrs || !lenv || (n > 0 && !objs) || !tab_copy || !ptab_copy) {
        PyMem_Free(ptrs);
        PyMem_Free(lenv);
        PyMem_Free(objs);
        PyMem_Free(tab_copy);
        PyMem_Free(ptab_copy);
        nthreads = 1;
        goto fused;
    }
    memcpy(tab_copy, tab, 256);
    memcpy(ptab_copy, ptab, 65536 * sizeof(uint16_t));

    Py_ssize_t held = 0;
    for (Py_ssize_t i = 0; i < rows; i++) {
        ptrs[i] = NULL;
        lenv[i] = 0;
        if (i < n) {
            PyObject *item = PyList_GET_ITEM(list, i);
            char *p;
            Py_ssize_t len;
            if (PyBytes_AsStringAndSize(item, &p, &len) < 0) {
                for (Py_ssize_t k = 0; k < held; k++)
                    Py_DECREF(objs[k]);
                PyMem_Free(ptrs);
                PyMem_Free(lenv);
                PyMem_Free(objs);
                PyMem_Free(tab_copy);
                PyMem_Free(ptab_copy);
                PyBuffer_Release(&table);
                Py_DECREF(buf);
                Py_DECREF(lens);
                return NULL;
            }
            Py_INCREF(item);
            objs[held++] = item;
            ptrs[i] = p;
            lenv[i] = len > width ? width : len;
        }
    }

    {
        pack_job job = {ptrs, lenv, out, lengths, T, tab_copy, ptab_copy,
                        begin_c, end_c, pad_c, 0, rows};
        pack_job jobs[64];
        int count = slice_jobs((char *)jobs, sizeof(pack_job), &job,
                               rows, nthreads, 1,
                               offsetof(pack_job, lo),
                               offsetof(pack_job, hi));
        Py_BEGIN_ALLOW_THREADS
        dispatch_row_jobs((char *)jobs, sizeof(pack_job), count,
                          pack_worker, pack_rows_run);
        Py_END_ALLOW_THREADS
    }
    for (Py_ssize_t k = 0; k < held; k++)
        Py_DECREF(objs[k]);
    PyMem_Free(ptrs);
    PyMem_Free(lenv);
    PyMem_Free(objs);
    PyMem_Free(tab_copy);
    PyMem_Free(ptab_copy);
    PyBuffer_Release(&table);
    return Py_BuildValue("(NN)", buf, lens);
}

/* classify_chunk(data[B*L] bytes, B, L, rem int32[B] bytes, table[256]
 * bytes, begin, end, pad, first, final)
 *   -> bytes holding int8[B, T], the carried-state chunk layout of
 * klogs_tpu.filters.tpu.classify_chunk_host (BEGIN column when first;
 * END at chunk-local position rem when it falls inside this chunk's
 * window — the final chunk gets an extra column so END can land at L —
 * plus the accept-latch PAD column when final). One C pass instead of
 * several numpy passes over multi-MB chunk batches. */
static PyObject *
classify_chunk_c(PyObject *self, PyObject *args)
{
    Py_buffer data, rembuf, table;
    Py_ssize_t B, L;
    int begin_c, end_c, pad_c, first, final;
    if (!PyArg_ParseTuple(args, "y*nny*y*iiiii", &data, &B, &L, &rembuf,
                          &table, &begin_c, &end_c, &pad_c, &first, &final))
        return NULL;
    if (B < 0 || L <= 0 || data.len < B * L || rembuf.len < B * 4
        || table.len < 256) {
        PyBuffer_Release(&data);
        PyBuffer_Release(&rembuf);
        PyBuffer_Release(&table);
        PyErr_SetString(PyExc_ValueError, "classify_chunk: bad buffer sizes");
        return NULL;
    }
    const Py_ssize_t off = first ? 1 : 0;
    const Py_ssize_t Lb = L + (final ? 1 : 0);
    const Py_ssize_t T = off + Lb + (final ? 1 : 0);
    PyObject *buf = PyBytes_FromStringAndSize(NULL, B * T);
    if (!buf) {
        PyBuffer_Release(&data);
        PyBuffer_Release(&rembuf);
        PyBuffer_Release(&table);
        return NULL;
    }
    const uint8_t *src0 = (const uint8_t *)data.buf;
    const int32_t *remv = (const int32_t *)rembuf.buf;
    const int8_t *tab = (const int8_t *)table.buf;
    const uint16_t *ptab = get_pair_tab(tab);
    int8_t *out = (int8_t *)PyBytes_AS_STRING(buf);
    for (Py_ssize_t i = 0; i < B; i++) {
        int8_t *row = out + i * T;
        const uint8_t *src = src0 + i * L;
        int32_t rem = remv[i];
        Py_ssize_t n = rem < 0 ? 0 : (rem > L ? L : (Py_ssize_t)rem);
        if (first)
            row[0] = (int8_t)begin_c;
        classify_span(row + off, src, n, tab, ptab);
        memset(row + off + n, (int8_t)pad_c, T - off - n);
        if (rem >= 0 && rem < Lb)
            row[off + rem] = (int8_t)end_c;
    }
    PyBuffer_Release(&data);
    PyBuffer_Release(&rembuf);
    PyBuffer_Release(&table);
    return buf;
}

/* frame_lines(lines: list[bytes], strip_nl) -> (payload, offsets, raw_total)
 *
 * Contiguous "framed batch" builder: payload = concatenation of the
 * lines (trailing '\n' runs stripped when strip_nl, matching the
 * engine's rstrip(b"\n") parity rule), offsets = int32[n+1] exclusive
 * prefix sums, raw_total = sum of UNstripped lengths (the stats
 * bytes-in figure). One C pass; this is the collector-side cost of the
 * framed wire/service path, replacing per-line msgpack objects. */
static PyObject *
frame_lines(PyObject *self, PyObject *args)
{
    PyObject *list;
    int strip_nl;
    if (!PyArg_ParseTuple(args, "O!i", &PyList_Type, &list, &strip_nl))
        return NULL;
    Py_ssize_t n = PyList_GET_SIZE(list);
    Py_ssize_t total = 0, raw = 0;
    for (Py_ssize_t i = 0; i < n; i++) {
        PyObject *item = PyList_GET_ITEM(list, i);
        char *p;
        Py_ssize_t len;
        if (PyBytes_AsStringAndSize(item, &p, &len) < 0)
            return NULL;
        raw += len;
        if (strip_nl)
            while (len > 0 && p[len - 1] == '\n')
                len--;
        total += len;
    }
    if (total > INT32_MAX) {
        PyErr_SetString(PyExc_OverflowError,
                        "framed batch exceeds int32 offsets");
        return NULL;
    }
    PyObject *payload = PyBytes_FromStringAndSize(NULL, total);
    PyObject *offs = PyBytes_FromStringAndSize(NULL, (n + 1) * 4);
    if (!payload || !offs) {
        Py_XDECREF(payload);
        Py_XDECREF(offs);
        return NULL;
    }
    char *out = PyBytes_AS_STRING(payload);
    int32_t *ov = (int32_t *)PyBytes_AS_STRING(offs);
    Py_ssize_t pos = 0;
    ov[0] = 0;
    for (Py_ssize_t i = 0; i < n; i++) {
        PyObject *item = PyList_GET_ITEM(list, i);
        char *p = PyBytes_AS_STRING(item);
        Py_ssize_t len = PyBytes_GET_SIZE(item);
        if (strip_nl)
            while (len > 0 && p[len - 1] == '\n')
                len--;
        memcpy(out + pos, p, len);
        pos += len;
        ov[i + 1] = (int32_t)pos;
    }
    return Py_BuildValue("(NNn)", payload, offs, raw);
}

/* split_frame(payload, offsets, n) -> list[bytes]
 * Inverse of frame_lines (fallback bridge for engines without a framed
 * fast path): one PyBytes per span. */
static PyObject *
split_frame(PyObject *self, PyObject *args)
{
    Py_buffer payload, offs;
    Py_ssize_t n;
    if (!PyArg_ParseTuple(args, "y*y*n", &payload, &offs, &n))
        return NULL;
    if (n < 0 || offs.len < (n + 1) * 4) {
        PyBuffer_Release(&payload);
        PyBuffer_Release(&offs);
        PyErr_SetString(PyExc_ValueError, "split_frame: bad offsets size");
        return NULL;
    }
    const int32_t *ov = (const int32_t *)offs.buf;
    const char *src = (const char *)payload.buf;
    PyObject *list = PyList_New(n);
    if (!list)
        goto fail;
    for (Py_ssize_t i = 0; i < n; i++) {
        int32_t lo = ov[i], hi = ov[i + 1];
        if (lo < 0 || hi < lo || hi > payload.len) {
            Py_DECREF(list);
            list = NULL;
            PyErr_SetString(PyExc_ValueError,
                            "split_frame: offsets out of range");
            goto fail;
        }
        PyObject *b = PyBytes_FromStringAndSize(src + lo, hi - lo);
        if (!b) {
            Py_DECREF(list);
            list = NULL;
            goto fail;
        }
        PyList_SET_ITEM(list, i, b);
    }
fail:
    PyBuffer_Release(&payload);
    PyBuffer_Release(&offs);
    return list;
}

/* pack_classify_framed(payload, offsets, n, sel, width, rows, table,
 *                      begin, end, pad) -> (cls bytes, lens bytes)
 *
 * Framed-batch variant of pack_classify: line i is
 * payload[offsets[i]:offsets[i+1]] (trailing '\n' runs stripped,
 * idempotent with frame_lines' stripping). ``sel`` selects a row
 * subset as int32 indices (width-bucketing), or None for all n rows in
 * order. No per-line PyObject is ever created — this is the server-side
 * hot path of the framed service protocol. Reuses the pair-LUT
 * classifier and the KLOGS_HOST_THREADS row-parallel worker pool; the
 * GIL is released for the whole row loop even single-threaded (the
 * asyncio event loop keeps serving while a jumbo batch packs). */
static PyObject *
pack_classify_framed(PyObject *self, PyObject *args)
{
    Py_buffer payload, offs, table;
    PyObject *selobj;
    Py_ssize_t n, width, rows;
    int begin_c, end_c, pad_c;
    if (!PyArg_ParseTuple(args, "y*y*nOnny*iii", &payload, &offs, &n,
                          &selobj, &width, &rows, &table,
                          &begin_c, &end_c, &pad_c))
        return NULL;
    Py_buffer sel = {0};
    int have_sel = 0;
    if (selobj != Py_None) {
        if (PyObject_GetBuffer(selobj, &sel, PyBUF_SIMPLE) < 0) {
            PyBuffer_Release(&payload);
            PyBuffer_Release(&offs);
            PyBuffer_Release(&table);
            return NULL;
        }
        have_sel = 1;
        n = sel.len / 4;  /* row count = selected count */
    }
    const Py_ssize_t nspans = have_sel ? (offs.len / 4) - 1 : n;
    if (n < 0 || width <= 0 || table.len < 256
        || offs.len < (nspans + 1) * 4) {
        if (have_sel)
            PyBuffer_Release(&sel);
        PyBuffer_Release(&payload);
        PyBuffer_Release(&offs);
        PyBuffer_Release(&table);
        PyErr_SetString(PyExc_ValueError,
                        "pack_classify_framed: bad sizes");
        return NULL;
    }
    if (rows < n)
        rows = n;
    const Py_ssize_t T = width + 3;
    PyObject *buf = PyBytes_FromStringAndSize(NULL, rows * T);
    PyObject *lens = PyBytes_FromStringAndSize(NULL, rows * 4);
    const char **ptrs = PyMem_Malloc(rows * sizeof(char *));
    Py_ssize_t *lenv = PyMem_Malloc(rows * sizeof(Py_ssize_t));
    if (!buf || !lens || !ptrs || !lenv) {
        if (have_sel)
            PyBuffer_Release(&sel);
        PyBuffer_Release(&payload);
        PyBuffer_Release(&offs);
        PyBuffer_Release(&table);
        Py_XDECREF(buf);
        Py_XDECREF(lens);
        PyMem_Free(ptrs);
        PyMem_Free(lenv);
        return NULL;
    }
    const int32_t *ov = (const int32_t *)offs.buf;
    const int32_t *sv = have_sel ? (const int32_t *)sel.buf : NULL;
    const char *src = (const char *)payload.buf;
    for (Py_ssize_t i = 0; i < rows; i++) {
        ptrs[i] = NULL;
        lenv[i] = 0;
        if (i >= n)
            continue;
        Py_ssize_t r = have_sel ? (Py_ssize_t)sv[i] : i;
        if (r < 0 || r >= nspans)
            goto bad_span;
        int32_t lo = ov[r], hi = ov[r + 1];
        if (lo < 0 || hi < lo || hi > payload.len)
            goto bad_span;
        Py_ssize_t len = hi - lo;
        while (len > 0 && src[lo + len - 1] == '\n')
            len--;
        ptrs[i] = src + lo;
        lenv[i] = len > width ? width : len;
    }

    {
        const int8_t *tab = (const int8_t *)table.buf;
        const uint16_t *ptab = get_pair_tab(tab);
        pack_job job = {ptrs, lenv, (int8_t *)PyBytes_AS_STRING(buf),
                        (int32_t *)PyBytes_AS_STRING(lens), T, tab, ptab,
                        begin_c, end_c, pad_c, 0, rows};
        int nthreads = host_threads();
        /* EVERY branch below releases the GIL, so the static pair-LUT
         * cache could be rebuilt under us by another Python thread
         * packing with a different classifier — copy it call-locally
         * ONCE here (one block, not one per branch: code-review r5);
         * on alloc failure run GIL-HELD on the statics. */
        int8_t *tab_copy = PyMem_Malloc(256);
        uint16_t *ptab_copy = PyMem_Malloc(65536 * sizeof(uint16_t));
        if (!tab_copy || !ptab_copy) {
            PyMem_Free(tab_copy);
            PyMem_Free(ptab_copy);
            pack_rows(&job);
        } else {
            memcpy(tab_copy, tab, 256);
            memcpy(ptab_copy, ptab, 65536 * sizeof(uint16_t));
            job.tab = tab_copy;
            job.ptab = ptab_copy;
            if (nthreads <= 1 || rows < 4096) {
                Py_BEGIN_ALLOW_THREADS
                pack_rows(&job);
                Py_END_ALLOW_THREADS
            } else {
                pack_job jobs[64];
                int count = slice_jobs((char *)jobs, sizeof(pack_job),
                                       &job, rows, nthreads, 1,
                                       offsetof(pack_job, lo),
                                       offsetof(pack_job, hi));
                Py_BEGIN_ALLOW_THREADS
                dispatch_row_jobs((char *)jobs, sizeof(pack_job), count,
                                  pack_worker, pack_rows_run);
                Py_END_ALLOW_THREADS
            }
            PyMem_Free(tab_copy);
            PyMem_Free(ptab_copy);
        }
    }
    PyMem_Free(ptrs);
    PyMem_Free(lenv);
    if (have_sel)
        PyBuffer_Release(&sel);
    PyBuffer_Release(&payload);
    PyBuffer_Release(&offs);
    PyBuffer_Release(&table);
    return Py_BuildValue("(NN)", buf, lens);

bad_span:
    PyMem_Free(ptrs);
    PyMem_Free(lenv);
    if (have_sel)
        PyBuffer_Release(&sel);
    PyBuffer_Release(&payload);
    PyBuffer_Release(&offs);
    PyBuffer_Release(&table);
    Py_DECREF(buf);
    Py_DECREF(lens);
    PyErr_SetString(PyExc_ValueError,
                    "pack_classify_framed: offsets/sel out of range");
    return NULL;
}

/* dfa_scan(payload, offsets, n, table, n_classes, accept, byte_class,
 *          start, end_class) -> mask bytes[n]
 *
 * Flat-table DFA scan over a framed batch: one u32 table lookup per
 * byte, early exit on accept. This is the strong-CPU host engine the
 * TPU multiple is measured against (filters/compiler/dfa.py builds the
 * tables; scan_python there is the oracle for this loop). The GIL is
 * released for the whole scan.
 *
 *   table:      u32[n_dfa * n_classes]  (row-major)
 *   accept:     u8[n_dfa]
 *   byte_class: i32[256]
 *   start:      state AFTER the BEGIN sentinel step (checked first)
 *   end_class:  class fed after the last byte ($ handling)
 */
typedef struct {
    const uint8_t *src;
    Py_ssize_t src_len;
    const int32_t *ov;
    const uint16_t *tab16;
    const uint32_t *tab32;
    const uint8_t *accept;
    const int32_t *bc;
    unsigned int start, n_classes, end_class, wide;
    char *out;
    Py_ssize_t lo, hi;          /* row range for this worker */
    int bad;
} dfa_job;

/* The scan body over rows [lo, hi): bound by the dependent load chain
 * (state -> table -> state, ~3ns/byte scalar), so DFA_LANES
 * independent lines interleave to overlap the chains. The u16 path
 * (every practical pattern set) takes the interleaved loop; u32 and
 * the remainder fall through to the scalar loop. Pure C over borrowed
 * buffers — safe with the GIL released and across worker threads. */
#define DFA_LANES 4
static void
dfa_scan_rows(dfa_job *job)
{
    const uint8_t *src = job->src;
    const int32_t *ov = job->ov;
    const uint16_t *tab16 = job->tab16;
    const uint32_t *tab32 = job->tab32;
    const uint8_t *accept = job->accept;
    const int32_t *bc = job->bc;
    const unsigned int start = job->start, n_classes = job->n_classes;
    const unsigned int end_class = job->end_class, wide = job->wide;
    char *out = job->out;
    Py_ssize_t i0 = job->lo;
    if (!wide && job->hi - job->lo >= DFA_LANES) {
        for (; i0 + DFA_LANES <= job->hi && !job->bad; i0 += DFA_LANES) {
            const uint8_t *p[DFA_LANES], *pe[DFA_LANES];
            uint32_t s[DFA_LANES];
            int m[DFA_LANES];
            unsigned active = 0;
            for (int l = 0; l < DFA_LANES; l++) {
                int32_t lo = ov[i0 + l], hi = ov[i0 + l + 1];
                if (lo < 0 || hi < lo || hi > job->src_len) {
                    job->bad = 1;
                    break;
                }
                Py_ssize_t len = hi - lo;
                while (len > 0 && src[lo + len - 1] == '\n')
                    len--;
                p[l] = src + lo;
                pe[l] = p[l] + len;
                s[l] = start;
                m[l] = accept[start];
                if (!m[l] && p[l] < pe[l])
                    active |= 1u << l;
            }
            if (job->bad)
                break;
            while (active) {
                for (int l = 0; l < DFA_LANES; l++) {
                    if (!(active & (1u << l)))
                        continue;
                    s[l] = tab16[s[l] * n_classes + (uint32_t)bc[*p[l]]];
                    p[l]++;
                    if (accept[s[l]]) {
                        m[l] = 1;
                        active &= ~(1u << l);
                    } else if (p[l] == pe[l]) {
                        active &= ~(1u << l);
                    }
                }
            }
            for (int l = 0; l < DFA_LANES; l++) {
                if (!m[l]) {
                    uint32_t sf = tab16[s[l] * n_classes + end_class];
                    m[l] = accept[sf];
                }
                out[i0 + l] = (char)m[l];
            }
        }
    }
    for (Py_ssize_t i = i0; i < job->hi && !job->bad; i++) {
        int32_t lo = ov[i], hi = ov[i + 1];
        if (lo < 0 || hi < lo || hi > job->src_len) {
            job->bad = 1;
            break;
        }
        Py_ssize_t len = hi - lo;
        while (len > 0 && src[lo + len - 1] == '\n')
            len--;
        uint32_t s = start;
        int m = accept[s];
        if (!m) {
            const uint8_t *p = src + lo, *pe = p + len;
            if (wide) {
                for (; p < pe; p++) {
                    s = tab32[s * n_classes + (uint32_t)bc[*p]];
                    if (accept[s]) {
                        m = 1;
                        break;
                    }
                }
                if (!m) {
                    s = tab32[s * n_classes + end_class];
                    m = accept[s];
                }
            } else {
                for (; p < pe; p++) {
                    s = tab16[s * n_classes + (uint32_t)bc[*p]];
                    if (accept[s]) {
                        m = 1;
                        break;
                    }
                }
                if (!m) {
                    s = tab16[s * n_classes + end_class];
                    m = accept[s];
                }
            }
        }
        out[i] = (char)m;
    }
}

static void *
dfa_scan_worker(void *arg)
{
    dfa_scan_rows((dfa_job *)arg);
    return NULL;
}

static void
dfa_scan_run(void *arg)
{
    dfa_scan_rows((dfa_job *)arg);
}

static PyObject *
dfa_scan(PyObject *self, PyObject *args)
{
    Py_buffer payload, offs, table, acc, bclass;
    Py_ssize_t n;
    unsigned int start, n_classes, end_class, wide;
    if (!PyArg_ParseTuple(args, "y*y*ny*Iy*y*III", &payload, &offs, &n,
                          &table, &n_classes, &acc, &bclass,
                          &start, &end_class, &wide))
        return NULL;
    const Py_ssize_t elem = wide ? 4 : 2;
    const Py_ssize_t n_dfa = (Py_ssize_t)(acc.len);
    if (n < 0 || offs.len < (n + 1) * 4 || bclass.len < 256 * 4
        || n_classes == 0 || end_class >= n_classes || start >= n_dfa
        || table.len < n_dfa * (Py_ssize_t)n_classes * elem) {
        PyBuffer_Release(&payload);
        PyBuffer_Release(&offs);
        PyBuffer_Release(&table);
        PyBuffer_Release(&acc);
        PyBuffer_Release(&bclass);
        PyErr_SetString(PyExc_ValueError, "dfa_scan: bad buffer sizes");
        return NULL;
    }
    PyObject *mask = PyBytes_FromStringAndSize(NULL, n);
    if (!mask) {
        PyBuffer_Release(&payload);
        PyBuffer_Release(&offs);
        PyBuffer_Release(&table);
        PyBuffer_Release(&acc);
        PyBuffer_Release(&bclass);
        return NULL;
    }
    /* KLOGS_HOST_THREADS row-parallel dispatch (same contract as
     * pack_classify): the table/accept/byte_class buffers are borrowed
     * and read-only, each worker writes a disjoint out range, so the
     * whole scan runs GIL-free. Small batches stay single-threaded
     * (thread spawn ~10us each would swamp a sub-ms scan). */
    dfa_job job = {(const uint8_t *)payload.buf, payload.len,
                   (const int32_t *)offs.buf,
                   (const uint16_t *)table.buf,
                   (const uint32_t *)table.buf,
                   (const uint8_t *)acc.buf,
                   (const int32_t *)bclass.buf,
                   start, n_classes, end_class, wide,
                   PyBytes_AS_STRING(mask), 0, n, 0};
    int nthreads = host_threads();
    int bad;
    if (nthreads <= 1 || n < 8192) {
        Py_BEGIN_ALLOW_THREADS
        dfa_scan_rows(&job);
        Py_END_ALLOW_THREADS
        bad = job.bad;
    } else {
        dfa_job jobs[64];
        int count = slice_jobs((char *)jobs, sizeof(dfa_job), &job, n,
                               nthreads, DFA_LANES,
                               offsetof(dfa_job, lo),
                               offsetof(dfa_job, hi));
        Py_BEGIN_ALLOW_THREADS
        dispatch_row_jobs((char *)jobs, sizeof(dfa_job), count,
                          dfa_scan_worker, dfa_scan_run);
        Py_END_ALLOW_THREADS
        bad = 0;
        for (int t = 0; t < count; t++)
            bad |= jobs[t].bad;
    }
    PyBuffer_Release(&payload);
    PyBuffer_Release(&offs);
    PyBuffer_Release(&table);
    PyBuffer_Release(&acc);
    PyBuffer_Release(&bclass);
    if (bad) {
        Py_DECREF(mask);
        PyErr_SetString(PyExc_ValueError, "dfa_scan: offsets out of range");
        return NULL;
    }
    return mask;
}

/* find_newlines(data, base) -> bytes holding int32 positions
 *
 * Absolute end-offsets (position AFTER each '\n', plus `base`) of every
 * newline in `data` — one memchr sweep. The framed-batcher's line
 * scanner: chunk boundaries never materialize per-line objects. */
static PyObject *
find_newlines(PyObject *self, PyObject *args)
{
    Py_buffer data;
    Py_ssize_t base;
    if (!PyArg_ParseTuple(args, "y*n", &data, &base))
        return NULL;
    if (base < 0 || base + data.len > INT32_MAX) {
        /* Same guard as frame_lines: a >2 GiB pending buffer must fail
         * loudly here, not wrap into negative offsets downstream. */
        PyBuffer_Release(&data);
        PyErr_SetString(PyExc_OverflowError,
                        "framed buffer exceeds int32 offsets");
        return NULL;
    }
    const char *src = (const char *)data.buf;
    Py_ssize_t n = data.len;
    /* Count first (cheap memchr sweep), then fill exactly. */
    Py_ssize_t count = 0;
    for (const char *p = src;
         (p = memchr(p, '\n', n - (p - src))) != NULL; p++)
        count++;
    PyObject *out = PyBytes_FromStringAndSize(NULL, count * 4);
    if (!out) {
        PyBuffer_Release(&data);
        return NULL;
    }
    int32_t *ov = (int32_t *)PyBytes_AS_STRING(out);
    Py_ssize_t k = 0;
    for (const char *p = src;
         (p = memchr(p, '\n', n - (p - src))) != NULL; p++)
        ov[k++] = (int32_t)(base + (p - src) + 1);
    PyBuffer_Release(&data);
    return out;
}

/* join_kept_framed(payload, offsets, n, mask) -> bytes
 *
 * Concatenation of the mask-selected spans, with ADJACENT kept lines
 * coalesced into single memcpys (a 25%-match batch averages long kept/
 * dropped runs; the common all-kept case is ONE memcpy). The framed
 * sibling of join_kept. */
static PyObject *
join_kept_framed(PyObject *self, PyObject *args)
{
    Py_buffer payload, offs, mask;
    Py_ssize_t n;
    if (!PyArg_ParseTuple(args, "y*y*ny*", &payload, &offs, &n, &mask))
        return NULL;
    if (n < 0 || offs.len < (n + 1) * 4 || mask.len < n) {
        PyBuffer_Release(&payload);
        PyBuffer_Release(&offs);
        PyBuffer_Release(&mask);
        PyErr_SetString(PyExc_ValueError, "join_kept_framed: bad sizes");
        return NULL;
    }
    const int32_t *ov = (const int32_t *)offs.buf;
    const char *m = (const char *)mask.buf;
    const char *src = (const char *)payload.buf;
    Py_ssize_t total = 0;
    int bad = 0;
    for (Py_ssize_t i = 0; i < n; i++) {
        if (ov[i] < 0 || ov[i + 1] < ov[i] || ov[i + 1] > payload.len) {
            bad = 1;
            break;
        }
        if (m[i])
            total += ov[i + 1] - ov[i];
    }
    if (bad) {
        PyBuffer_Release(&payload);
        PyBuffer_Release(&offs);
        PyBuffer_Release(&mask);
        PyErr_SetString(PyExc_ValueError,
                        "join_kept_framed: offsets out of range");
        return NULL;
    }
    PyObject *out = PyBytes_FromStringAndSize(NULL, total);
    if (!out) {
        PyBuffer_Release(&payload);
        PyBuffer_Release(&offs);
        PyBuffer_Release(&mask);
        return NULL;
    }
    char *dst = PyBytes_AS_STRING(out);
    Py_ssize_t i = 0;
    while (i < n) {
        if (!m[i]) {
            i++;
            continue;
        }
        Py_ssize_t j = i;
        while (j < n && m[j])
            j++;
        Py_ssize_t len = ov[j] - ov[i];
        memcpy(dst, src + ov[i], len);
        dst += len;
        i = j;
    }
    PyBuffer_Release(&payload);
    PyBuffer_Release(&offs);
    PyBuffer_Release(&mask);
    return out;
}

static PyObject *
join_kept(PyObject *self, PyObject *args)
{
    PyObject *list;
    Py_buffer mask;
    if (!PyArg_ParseTuple(args, "O!y*", &PyList_Type, &list, &mask))
        return NULL;
    Py_ssize_t n = PyList_GET_SIZE(list);
    if (mask.len < n) {
        PyBuffer_Release(&mask);
        PyErr_SetString(PyExc_ValueError, "mask shorter than lines");
        return NULL;
    }
    const char *m = (const char *)mask.buf;

    Py_ssize_t total = 0;
    for (Py_ssize_t i = 0; i < n; i++) {
        if (!m[i])
            continue;
        PyObject *item = PyList_GET_ITEM(list, i);
        if (!PyBytes_Check(item)) {
            PyBuffer_Release(&mask);
            PyErr_SetString(PyExc_TypeError, "lines must be bytes");
            return NULL;
        }
        total += PyBytes_GET_SIZE(item);
    }
    PyObject *buf = PyBytes_FromStringAndSize(NULL, total);
    if (!buf) {
        PyBuffer_Release(&mask);
        return NULL;
    }
    char *out = PyBytes_AS_STRING(buf);
    for (Py_ssize_t i = 0; i < n; i++) {
        if (!m[i])
            continue;
        PyObject *item = PyList_GET_ITEM(list, i);
        Py_ssize_t len = PyBytes_GET_SIZE(item);
        memcpy(out, PyBytes_AS_STRING(item), len);
        out += len;
    }
    PyBuffer_Release(&mask);
    return buf;
}

static PyMethodDef Methods[] = {
    {"pack_lines", pack_lines, METH_VARARGS,
     "pack_lines(lines, width, rows) -> (bytes, int32-lengths-bytes)"},
    {"pack_classify", pack_classify, METH_VARARGS,
     "pack_classify(lines, width, rows, table, begin, end, pad)"
     " -> (int8-cls-bytes, int32-lengths-bytes)"},
    {"classify_chunk", classify_chunk_c, METH_VARARGS,
     "classify_chunk(data, B, L, rem, table, begin, end, pad, first,"
     " final) -> int8-cls-bytes"},
    {"join_kept", join_kept, METH_VARARGS,
     "join_kept(lines, mask) -> bytes of mask-selected lines"},
    {"frame_lines", frame_lines, METH_VARARGS,
     "frame_lines(lines, strip_nl) -> (payload, int32-offsets-bytes,"
     " raw_total)"},
    {"split_frame", split_frame, METH_VARARGS,
     "split_frame(payload, offsets, n) -> list[bytes]"},
    {"pack_classify_framed", pack_classify_framed, METH_VARARGS,
     "pack_classify_framed(payload, offsets, n, sel, width, rows, table,"
     " begin, end, pad) -> (int8-cls-bytes, int32-lengths-bytes)"},
    {"dfa_scan", dfa_scan, METH_VARARGS,
     "dfa_scan(payload, offsets, n, table, n_classes, accept, byte_class,"
     " start, end_class, wide) -> mask bytes"},
    {"find_newlines", find_newlines, METH_VARARGS,
     "find_newlines(data, base) -> int32 after-newline positions"},
    {"join_kept_framed", join_kept_framed, METH_VARARGS,
     "join_kept_framed(payload, offsets, n, mask) -> bytes"},
    {NULL, NULL, 0, NULL},
};

static struct PyModuleDef module = {
    PyModuleDef_HEAD_INIT, "_hostops",
    "Native host-side packing/gather for klogs_tpu", -1, Methods,
};

PyMODINIT_FUNC
PyInit__hostops(void)
{
    return PyModule_Create(&module);
}
