"""Native host-ops loader: compile-on-first-use with Python fallback.

The extension is a single C file with no dependencies beyond CPython;
building it is one cc invocation, done lazily and cached next to the
source. Environments without a toolchain (or where the build fails for
any reason) silently fall back to the pure-Python implementations —
the native layer is a fast path, never a requirement.

Set KLOGS_NO_NATIVE=1 to force the fallback (used by tests to cover
both paths).
"""

import os
import subprocess
import sys
import sysconfig

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "_hostops.c")
_SO = os.path.join(_DIR, f"_hostops{sysconfig.get_config_var('EXT_SUFFIX') or '.so'}")

hostops = None


def _build() -> bool:
    include = sysconfig.get_paths()["include"]
    cc = os.environ.get("CC", "cc")
    cmd = [cc, "-O3", "-shared", "-fPIC", "-pthread", f"-I{include}",
           _SRC, "-o", _SO]
    try:
        res = subprocess.run(cmd, capture_output=True, timeout=120)
        return res.returncode == 0
    except (OSError, subprocess.TimeoutExpired):
        return False


def _load():
    global hostops
    if os.environ.get("KLOGS_NO_NATIVE"):
        return
    if not os.path.exists(_SO) or (
        os.path.exists(_SRC) and os.path.getmtime(_SRC) > os.path.getmtime(_SO)
    ):
        if not _build():
            return
    try:
        import importlib.util

        spec = importlib.util.spec_from_file_location("klogs_tpu.native._hostops", _SO)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        hostops = mod
    except Exception:
        hostops = None


_load()
