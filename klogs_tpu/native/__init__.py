"""Native host-ops loader: compile-on-first-use with Python fallback.

The extension is a single C file with no dependencies beyond CPython;
building it is one cc invocation, done lazily and cached next to the
source — or, when the package directory is not writable (installed
site-packages owned by root, or the single-file klogs.pyz zipapp where
the "directory" is inside a zip), under ``~/.cache/klogs-tpu`` keyed by
a hash of the C source, so every build of the artifact gets its own
cached object. Environments without a toolchain (or where the build
fails for any reason) silently fall back to the pure-Python
implementations — the native layer is a fast path, never a requirement.

Set KLOGS_NO_NATIVE=1 to force the fallback (used by tests to cover
both paths).
"""

import hashlib
import os
import subprocess
import sysconfig

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "_hostops.c")
_EXT = sysconfig.get_config_var("EXT_SUFFIX") or ".so"

hostops = None


def _read_source() -> "bytes | None":
    """C source bytes — from the filesystem, or from inside the zipapp
    via the package loader when there is no real file."""
    try:
        with open(_SRC, "rb") as f:
            return f.read()
    except OSError:
        pass
    try:
        import importlib.resources

        return (importlib.resources.files(__package__)
                .joinpath("_hostops.c").read_bytes())
    except Exception:
        return None


def _cache_path(src: bytes) -> str:
    from klogs_tpu.utils.cache import cache_dir

    tag = hashlib.sha256(src).hexdigest()[:16]
    return os.path.join(cache_dir(), f"_hostops-{tag}{_EXT}")


def _build(c_src: str, so_path: str) -> bool:
    """Compile to a pid-suffixed temp and os.replace into place: the
    cache can be shared by many concurrently-starting processes, and a
    half-written .so observed by another process would silently pin it
    to the pure-Python fallback for its lifetime."""
    include = sysconfig.get_paths()["include"]
    cc = os.environ.get("CC", "cc")
    tmp = f"{so_path}.tmp{os.getpid()}"
    cmd = [cc, "-O3", "-shared", "-fPIC", "-pthread", f"-I{include}",
           c_src, "-o", tmp]
    try:
        res = subprocess.run(cmd, capture_output=True, timeout=120)
        if res.returncode != 0:
            return False
        os.replace(tmp, so_path)
        return True
    except (OSError, subprocess.TimeoutExpired):
        return False
    finally:
        try:
            os.remove(tmp)
        except OSError:
            pass


def _ensure_so() -> "str | None":
    """Path to an up-to-date compiled extension, building if needed.
    Preference order: next to the source (repo checkouts — mtime keeps
    it fresh), else the user cache keyed by source hash (read-only
    installs and zipapps)."""
    in_tree = os.path.join(_DIR, f"_hostops{_EXT}")
    src_exists = os.path.exists(_SRC)
    if src_exists and os.path.exists(in_tree) and (
            os.path.getmtime(_SRC) <= os.path.getmtime(in_tree)):
        return in_tree
    if src_exists and os.access(_DIR, os.W_OK):
        return in_tree if _build(_SRC, in_tree) else None
    # Read-only package (or zipapp): build into the user cache.
    src = _read_source()
    if src is None:
        return None
    cached = _cache_path(src)
    if os.path.exists(cached):
        return cached
    try:
        os.makedirs(os.path.dirname(cached), exist_ok=True)
    except OSError:
        return None
    tmp_c = cached[: -len(_EXT)] + ".c"
    try:
        with open(tmp_c, "wb") as f:
            f.write(src)
    except OSError:
        return None
    return cached if _build(tmp_c, cached) else None


def _load():
    global hostops
    from klogs_tpu.utils.env import read as _env_read

    if _env_read("KLOGS_NO_NATIVE"):
        return
    # KLOGS_NATIVE_SO pins the exact extension binary to load — the
    # sanitizer harness (tools/build_native_asan.py, docs/NATIVE.md)
    # uses it to run the parity tests against an ASan/UBSan build. A
    # pinned path that fails to load raises instead of silently
    # falling back: a sanitizer run that quietly tested the pure-
    # Python path would green-light memory bugs.
    forced = _env_read("KLOGS_NATIVE_SO")
    so = forced if forced else _ensure_so()
    if so is None:
        return
    try:
        import importlib.util

        spec = importlib.util.spec_from_file_location(
            "klogs_tpu.native._hostops", so)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        hostops = mod
    except Exception as e:
        hostops = None
        if forced:
            raise RuntimeError(
                f"KLOGS_NATIVE_SO={forced!r} could not be loaded: {e}"
            ) from e


_load()
