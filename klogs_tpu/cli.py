"""CLI flag surface.

Reference parity: the ten klogs flags registered in ``init``
(cmd/root.go:485-497) with identical names, shorthands, defaults, and
semantics:

  -n/--namespace    select namespace ("" -> kubeconfig current context)
  -l/--label        repeatable; union of per-label results, no dedup
                    (cmd/root.go:458-460)
  -p/--logpath      default ``logs/<YYYY-MM-DDTHH-MM>`` (cmd/root.go:47)
  --kubeconfig      default ``$HOME/.kube/config`` (cmd/root.go:71-73)
  -a/--all          skip the interactive pod picker (cmd/root.go:151)
  -s/--since        Go duration; server-side SinceSeconds (root.go:204-212)
  -t/--tail         default -1 = unlimited (cmd/root.go:213-216,492)
  -f/--follow       stream; q-to-quit (cmd/root.go:465-468)
  -v/--version      print version, exit 0 (cmd/root.go:445-448)
  -i/--init         include init containers (cmd/root.go:240-251)

New (north-star) flags, absent from the reference:

  --match           repeatable regex; only matching lines are written
  --exclude         repeatable regex; drop matching lines (alone =
                    keep everything EXCEPT matches)
  -I/--ignore-case  case-insensitive --match/--exclude patterns
  --watch-new       with -f and -a/-l: stream pods created mid-follow
                    (stern-style dynamic discovery)
  -o/--output       files (reference behavior) | stdout (stern-style
                    prefixed console stream, no files) | both
  --format          console stream format: text (prefixed lines) |
                    json (one object per line, stern -o json analog)
  -c/--container    only containers whose name matches this regex
                    (stern parity; the reference streams all containers)
  -E/--exclude-container  drop containers whose name matches this regex
  --previous        logs of the previous terminated container instance
                    (kubectl -p parity; PodLogOptions.Previous)
  --timestamps      server-side RFC3339 timestamp prefix per line
                    (kubectl parity; PodLogOptions.Timestamps)
  --since-time      only logs after an absolute RFC3339 time
                    (kubectl parity; PodLogOptions.SinceTime;
                    mutually exclusive with -s/--since)
  --backend         filter engine: cpu (host regex) | tpu (batch NFA)
  --remote          gate writes via klogs-filterd service(s) (gRPC);
                    a comma-separated list shards batches across the
                    fleet with per-endpoint breakers, hedged dispatch,
                    and /readyz-driven drain (docs/RESILIENCE.md)
  --shard-mode      multi-endpoint --remote routing: round-robin
                    (rotate per batch) | hash (pin by pattern-set
                    fingerprint on a consistent-hash ring)
  --resolver        live fleet membership for --remote: KIND:SPEC
                    (static:HOST:PORT[,...] | file:/path | dns:HOST:PORT
                    | kube:NAMESPACE/NAME[:PORT]); polled on
                    KLOGS_RESOLVER_INTERVAL_S, joiners verified before
                    their first batch (docs/RESILIENCE.md)
  --on-filter-error what to do when the filter service is unavailable
                    after retries: pass | drop | abort (default abort;
                    see docs/RESILIENCE.md)
  --profile         write a JAX profiler trace of the run to DIR
  --stats           print lines/sec, matched %, batch-latency summary
  --metrics-port    serve Prometheus /metrics + /healthz for this run
                    (obs subsystem; see docs/OBSERVABILITY.md)
  --stats-json      one-shot JSON metrics dump at exit (non-server runs)
  --trace-json      per-batch trace spans as JSON lines (tracing +
                    flight recorder; see docs/OBSERVABILITY.md)
  --profile-json    continuous pipeline utilization profiler: one JSON
                    snapshot per tick (per-stage busy-seconds and
                    utilization, queue/in-flight samples); same doc as
                    /profile on --metrics-port
  --cluster         cluster backend: kube (real) | fake (hermetic demo)
  --source          non-kube log source (docs/SOURCES.md):
                    replay:PATH[,PATH...] streams local files/dirs/globs
                    with rotation handling; socket:HOST:PORT or
                    socket:unix:/path.sock listens for newline-delimited
                    ingest (requires -f)
  --backfill        archive backfill mode: read rotated/gzip/zstd logs
                    under the given paths through the full pipeline to
                    completion, then exit with match/shed accounting
                    (incompatible with -f and --source)
  --replay-rate     pace replay at N lines/s (default: as fast as the
                    disk goes; KLOGS_REPLAY_RATE sets a default)
"""

import argparse
import sys
from dataclasses import dataclass, field

from klogs_tpu.ui import term
from klogs_tpu.utils.naming import default_log_path
from klogs_tpu.version import BUILD_VERSION


@dataclass
class Options:
    namespace: str = ""
    labels: list[str] = field(default_factory=list)
    log_path: str = ""
    kubeconfig: str = ""
    all_pods: bool = False
    since: str = ""
    tail: int = -1
    follow: bool = False
    print_version: bool = False
    init_containers: bool = False
    # North-star extensions
    match: list[str] = field(default_factory=list)
    exclude: list[str] = field(default_factory=list)
    ignore_case: bool = False
    backend: str = "cpu"
    remote: str | None = None
    shard_mode: str = "round-robin"
    resolver: str | None = None
    on_filter_error: str = "abort"
    stats: bool = False
    metrics_port: int | None = None
    stats_json: str | None = None
    trace_json: str | None = None
    profile_json: str | None = None
    profile: str | None = None
    cluster: str = "kube"
    watch_new: bool = False
    output: str = "files"
    previous: bool = False
    timestamps: bool = False
    container: str = ""
    exclude_container: str = ""
    format: str = "text"
    since_time: str = ""
    source: str = ""
    backfill: list[str] = field(default_factory=list)
    replay_rate: float | None = None


USE = "klogs"
SHORT = "Get logs from Pods, super fast! \U0001f680"
LONG = (
    "klogs is a CLI tool to get logs from Kubernetes Pods.\n"
    "It is designed to be fast and efficient, and can get logs from "
    "multiple Pods/Containers at once. Blazing fast. \U0001f525"
)


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog=USE, description=LONG, add_help=True)
    p.add_argument("-n", "--namespace", default="", help="Select namespace")
    p.add_argument(
        "-l", "--label", action="append", default=[], dest="labels", help="Select label"
    )
    p.add_argument(
        "-p", "--logpath", default=None, dest="log_path", help="Custom log path"
    )
    p.add_argument(
        "--kubeconfig",
        default="",
        help="(optional) Absolute path to the kubeconfig file",
    )
    p.add_argument(
        "-a",
        "--all",
        action="store_true",
        dest="all_pods",
        help="Get logs for all pods in the namespace",
    )
    p.add_argument(
        "-s",
        "--since",
        default="",
        help=(
            "Only return logs newer than a relative duration like 5s, 2m, or 3h. "
            "Defaults to all logs."
        ),
    )
    p.add_argument(
        "-t",
        "--tail",
        type=int,
        default=-1,
        help="Lines of the most recent log to save",
    )
    p.add_argument(
        "-f",
        "--follow",
        action="store_true",
        help="Specify if the logs should be streamed",
    )
    p.add_argument(
        "-v",
        "--version",
        action="store_true",
        dest="print_version",
        help="Print the version of the tool",
    )
    p.add_argument(
        "-i",
        "--init",
        action="store_true",
        dest="init_containers",
        help="Get logs for init containers",
    )
    # --- north-star extensions ---
    p.add_argument(
        "--match",
        action="append",
        default=[],
        help="Only save log lines matching this regex (repeatable; a line "
        "is kept if ANY pattern matches)",
    )
    p.add_argument(
        "-I",
        "--ignore-case",
        action="store_true",
        dest="ignore_case",
        help="Case-insensitive --match patterns (all engines)",
    )
    p.add_argument(
        "--backend",
        choices=["cpu", "tpu"],
        default="cpu",
        help="Line-filter engine: host regex (cpu) or batch-NFA on TPU",
    )
    p.add_argument(
        "--remote",
        default=None,
        metavar="HOST:PORT[,HOST:PORT...]",
        help="Filter via remote klogs-filterd service(s) "
        "(python -m klogs_tpu.service) instead of an in-process engine. "
        "A comma-separated list shards batches across the fleet "
        "(--shard-mode) with per-endpoint circuit breakers, hedged "
        "dispatch, and /readyz-driven drain — one dead or draining "
        "server is routed around, not an outage",
    )
    p.add_argument(
        "--shard-mode",
        choices=["round-robin", "hash"],
        default="round-robin",
        dest="shard_mode",
        help="With a multi-endpoint --remote list: rotate batches "
        "across the fleet (round-robin) or pin this collector's "
        "pattern-set fingerprint to one owner on a consistent-hash "
        "ring (hash; maximizes the owner's coalescer/compile-cache "
        "locality, keys move minimally when an endpoint dies)",
    )
    p.add_argument(
        "--resolver",
        default=None,
        metavar="KIND:SPEC",
        help="Live fleet membership for the filterd tier: "
        "static:HOST:PORT[,...], file:/path (one endpoint per line, "
        "re-read each poll), dns:HOST:PORT (re-resolve every "
        "A/AAAA record), or kube:NAMESPACE/NAME[:PORT] (watch an "
        "Endpoints object). Joining endpoints pass the pattern-set "
        "handshake before their first batch; --remote (optional "
        "with this flag) is only the initial seed",
    )
    p.add_argument(
        "--on-filter-error",
        choices=["pass", "drop", "abort"],
        default="abort",
        dest="on_filter_error",
        help="With --match/--exclude: how sinks degrade when the filter "
        "service stays unavailable (retries exhausted, circuit breaker "
        "open): write lines UNFILTERED (pass), discard them (drop), or "
        "end the run with one clear error (abort, default)",
    )
    p.add_argument(
        "--stats",
        action="store_true",
        help="Print lines/sec, matched %%, and batch-latency summary",
    )
    p.add_argument(
        "--metrics-port",
        type=int,
        default=None,
        metavar="PORT",
        help="Serve Prometheus /metrics and /healthz for this run on "
        "an HTTP sidecar port (0 = ephemeral; binds 127.0.0.1). See "
        "docs/OBSERVABILITY.md for the metric inventory",
    )
    p.add_argument(
        "--stats-json",
        default=None,
        dest="stats_json",
        metavar="PATH",
        help="Write a one-shot JSON dump of all pipeline metrics to "
        "PATH at exit (the scrapeless option for batch runs)",
    )
    p.add_argument(
        "--trace-json",
        default=None,
        dest="trace_json",
        metavar="PATH",
        help="Write every finished trace span as one JSON line to PATH "
        "(batch tracing across fanout/coalescer/shard/RPC/device/sink; "
        "implies KLOGS_TRACE_SAMPLE=1 unless that variable is set). "
        "The same spans serve /traces on --metrics-port and feed the "
        "degrade flight recorder — see docs/OBSERVABILITY.md",
    )
    p.add_argument(
        "--profile-json",
        default=None,
        dest="profile_json",
        metavar="PATH",
        help="Continuous pipeline utilization profiling: append one "
        "JSON snapshot per tick (per-stage busy-seconds and rolling "
        "utilization folded from trace spans, plus queue-depth/"
        "in-flight/executor samples) to PATH. The same snapshot "
        "serves /profile on the --metrics-port sidecar; "
        "KLOGS_PROFILE_SAMPLE pins the span-sampling rate (0 "
        "disables). See docs/OBSERVABILITY.md",
    )
    p.add_argument(
        "-o",
        "--output",
        choices=["files", "stdout", "both"],
        default="files",
        help="Where log lines go: per-container files (reference "
        "behavior), a pod/container-prefixed stdout stream "
        "(stern-style), or both",
    )
    p.add_argument(
        "--format",
        choices=["text", "json"],
        default="text",
        help="Console stream format with -o stdout|both: prefixed text "
        "lines or one JSON object per line ({pod, container, line})",
    )
    p.add_argument(
        "-c",
        "--container",
        default="",
        metavar="REGEX",
        help="Only stream containers whose name matches this regex "
        "(stern-style; default: all containers)",
    )
    p.add_argument(
        "-E",
        "--exclude-container",
        default="",
        dest="exclude_container",
        metavar="REGEX",
        help="Drop containers whose name matches this regex "
        "(stern-style; composes with -c)",
    )
    p.add_argument(
        "--previous",
        action="store_true",
        help="Get logs of the PREVIOUS terminated container instance "
        "(kubectl logs -p); incompatible with -f",
    )
    p.add_argument(
        "--since-time",
        default="",
        dest="since_time",
        metavar="RFC3339",
        help="Only return logs after an absolute time, e.g. "
        "2026-07-31T06:00:00Z (kubectl logs --since-time; "
        "mutually exclusive with -s/--since)",
    )
    p.add_argument(
        "--timestamps",
        action="store_true",
        help="Prefix each log line with its server-side RFC3339 "
        "timestamp (kubectl logs --timestamps)",
    )
    p.add_argument(
        "--exclude",
        action="append",
        default=[],
        metavar="REGEX",
        help="Drop lines matching this pattern even when --match keeps "
        "them (repeatable; alone = keep everything EXCEPT matches)",
    )
    p.add_argument(
        "--watch-new",
        action="store_true",
        dest="watch_new",
        help="With -f and -a/-l: keep watching for NEW pods matching the "
        "selection and stream them as they appear (stern-style; the "
        "reference fixes the pod set at startup)",
    )
    p.add_argument(
        "--profile",
        default=None,
        metavar="DIR",
        help="Write a JAX profiler trace of the run to DIR (inspect with "
        "TensorBoard / xprof)",
    )
    p.add_argument(
        "--cluster",
        choices=["kube", "fake"],
        default="kube",
        help="Cluster backend: real Kubernetes API or hermetic fake (demo/test)",
    )
    p.add_argument(
        "--source",
        default="",
        metavar="SPEC",
        help="Non-kube log source: replay:PATH[,PATH...] (local "
        "files/dirs/globs with rotation handling) or socket:HOST:PORT / "
        "socket:unix:/path.sock (newline-delimited listener, needs -f)",
    )
    p.add_argument(
        "--backfill",
        nargs="+",
        default=[],
        metavar="PATH",
        help="Read rotated/gzip/zstd archives under PATH(s) through the "
        "full pipeline to completion, then exit with match/shed "
        "accounting (incompatible with -f and --source)",
    )
    p.add_argument(
        "--replay-rate",
        type=float,
        default=None,
        dest="replay_rate",
        metavar="LPS",
        help="Pace a replay source at LPS lines/s (default: unpaced; "
        "KLOGS_REPLAY_RATE sets a default)",
    )
    return p


def parse_args(argv: list[str] | None = None) -> Options:
    ns = build_parser().parse_args(argv)
    return Options(
        namespace=ns.namespace,
        labels=list(ns.labels),
        log_path=ns.log_path if ns.log_path is not None else default_log_path(),
        kubeconfig=ns.kubeconfig,
        all_pods=ns.all_pods,
        since=ns.since,
        tail=ns.tail,
        follow=ns.follow,
        print_version=ns.print_version,
        init_containers=ns.init_containers,
        match=list(ns.match),
        exclude=list(ns.exclude),
        ignore_case=ns.ignore_case,
        backend=ns.backend,
        remote=ns.remote,
        shard_mode=ns.shard_mode,
        resolver=ns.resolver,
        on_filter_error=ns.on_filter_error,
        stats=ns.stats,
        metrics_port=ns.metrics_port,
        stats_json=ns.stats_json,
        trace_json=ns.trace_json,
        profile_json=ns.profile_json,
        profile=ns.profile,
        cluster=ns.cluster,
        watch_new=ns.watch_new,
        output=ns.output,
        previous=ns.previous,
        timestamps=ns.timestamps,
        container=ns.container,
        exclude_container=ns.exclude_container,
        format=ns.format,
        since_time=ns.since_time,
        source=ns.source,
        backfill=list(ns.backfill),
        replay_rate=ns.replay_rate,
    )


def main(argv: list[str] | None = None) -> int:
    """Process entry point (analog of main.go:8-10 + Execute, root.go:478-483)."""
    opts = parse_args(argv)

    # Version short-circuit before any other work (cmd/root.go:445-448).
    if opts.print_version:
        term.info("Version: %s", BUILD_VERSION)
        return 0

    # Statically invalid combos are rejected before any cluster work
    # (kubectl parity: "only one of follow or previous may be specified");
    # app.build_log_options keeps a backstop for library callers.
    if opts.previous and opts.follow:
        term.error("--previous is incompatible with -f/--follow "
                   "(a terminated instance cannot stream)")
        return 1
    if opts.since and opts.since_time:
        term.error("at most one of -s/--since and --since-time may be "
                   "given (kubectl parity)")
        return 1
    if opts.since_time:
        from datetime import datetime

        try:
            dt = datetime.fromisoformat(
                opts.since_time.replace("Z", "+00:00"))
            # fromisoformat also accepts date-only and offset-naive
            # forms that are NOT RFC3339; a naive cutoff would be
            # interpreted in the machine's local zone (wrong window)
            # and the apiserver would 400 the verbatim string.
            if dt.tzinfo is None:
                raise ValueError("missing timezone offset")
        except ValueError:
            term.error("invalid --since-time %r (want RFC3339 with a "
                       "timezone, e.g. 2026-07-31T06:00:00Z)",
                       opts.since_time)
            return 1
    if opts.source and opts.backfill:
        term.error("--source and --backfill are mutually exclusive "
                   "(backfill IS a source)")
        return 1
    if opts.backfill and opts.follow:
        term.error("--backfill is a run-to-completion mode and cannot "
                   "be combined with -f/--follow")
        return 1
    if opts.source:
        if not (opts.source.startswith("replay:")
                or opts.source.startswith("socket:")):
            term.error("invalid --source %r: expected "
                       "replay:PATH[,PATH...], socket:HOST:PORT, or "
                       "socket:unix:/path.sock", opts.source)
            return 1
        if opts.source.startswith("socket:") and not opts.follow:
            term.error("--source socket: is a live listener and "
                       "requires -f/--follow")
            return 1
    if opts.replay_rate is not None:
        if opts.replay_rate <= 0:
            term.error("--replay-rate must be a positive lines/s value")
            return 1
        if not opts.source.startswith("replay:"):
            term.warning("--replay-rate only applies to a replay "
                         "source; ignoring")
    if opts.resolver is not None:
        from klogs_tpu.service.resolver import split_spec

        try:
            split_spec(opts.resolver)
        except ValueError as e:
            term.error("%s", e)
            return 1
        if not opts.match and not opts.exclude:
            term.warning("--resolver without --match/--exclude builds "
                         "no filter pipeline; ignoring")
    if opts.shard_mode != "round-robin" and opts.resolver is None and (
            opts.remote is None or "," not in opts.remote):
        # One endpoint is below the routing layer entirely (the plain
        # client is used) — say so rather than silently dropping the
        # flag a user sized their fleet around.
        term.warning("--shard-mode only applies with a multi-endpoint "
                     "--remote list; ignoring")
    for flag, pat in (("-c/--container", opts.container),
                      ("-E/--exclude-container", opts.exclude_container)):
        if pat:
            import re

            try:
                re.compile(pat)
            except re.error as e:
                term.error("invalid %s pattern %r: %s", flag, pat, e)
                return 1

    from klogs_tpu.app import run
    from klogs_tpu.cluster.backend import ClusterError
    from klogs_tpu.sources import SourceError
    from klogs_tpu.ui.interactive import NotInteractive

    try:
        return run(opts)
    except term.FatalError:
        return 1
    except (ClusterError, SourceError) as e:
        # One friendly line for control-plane failures (401/403/
        # unreachable apiserver), not a traceback; ≙ pterm.Fatal/panic
        # in the reference (cmd/root.go:78,110,130).
        term.error("%s", e)
        return 1
    except NotInteractive as e:
        term.error("%s", e)
        return 1
    except KeyboardInterrupt:
        return 130
    except Exception as e:
        # --remote surprises (pattern handshake mismatch, bad transport
        # security config): still one line + exit 1, matching the
        # reference's pterm.Fatal style. Lazy + guarded import: grpc is
        # optional, and an ImportError here must not mask the original
        # exception.
        try:
            from klogs_tpu.service.client import (
                PatternMismatch,
                ServiceConfigError,
            )
        except ImportError:
            raise e
        if isinstance(e, (PatternMismatch, ServiceConfigError)):
            term.error("%s", e)
            return 1
        raise


if __name__ == "__main__":
    sys.exit(main())
