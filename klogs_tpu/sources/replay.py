"""File/directory replay source.

Streams local files through the same per-stream fanout workers the
kube path uses — the source that lets follow-mode soaks and parity
tests run at disk speed instead of apiserver speed. Handles the
logrotate lifecycle:

* **rotation/rename** — EOF + a changed inode at the path means the
  file was rotated away; the old fd is drained first (bytes written
  between our last read and the rename are not lost), then the new
  file is picked up from offset 0 and a ``klogs_source_rotations_total``
  tick is recorded.
* **truncation in place** (``copytruncate``) — size < our position
  reopens at 0.
* **resume offsets** — per (path, inode) the source remembers the last
  *line-aligned* byte delivered; re-opening the same file resumes
  there, so a drop/re-open re-emits at most the one partial line that
  was in flight (the PR 5 reconnect gap-bounds analog for files).
* **glob watching** — ``discover()`` re-expands directories and glob
  patterns, so in follow mode new files join the fanout via the same
  poller that handles ``--watch-new`` pods.

Chunks are slab-sized (256 KiB) and cut at the last newline with the
tail carried, so the downstream FramedBatcher's native newline sweep
gets full lines without any per-line Python here. Optional pacing
(``--replay-rate`` / KLOGS_REPLAY_RATE) throttles to N lines/s for
follow-mode realism.
"""

from __future__ import annotations

import asyncio
import glob
import os
import time
import zlib
from typing import BinaryIO

from klogs_tpu.cluster.types import LogOptions
from klogs_tpu.obs import trace
from klogs_tpu.resilience.faults import FAULTS, InjectedFault
from klogs_tpu.sources.base import (
    Source,
    SourceError,
    SourceMetrics,
    SourceRef,
    SourceStream,
    safe_group_name,
)

DEFAULT_READ_SIZE = 256 << 10
DEFAULT_POLL_S = 0.2
_GLOB_CHARS = frozenset("*?[")


def _expand_paths(specs: "list[str]") -> "list[str]":
    """Files, directories (their direct regular files), and glob
    patterns → ordered, deduplicated file list."""
    out: "list[str]" = []
    for spec in specs:
        if _GLOB_CHARS & set(spec):
            out.extend(sorted(p for p in glob.glob(spec)
                              if os.path.isfile(p)))
        elif os.path.isdir(spec):
            for name in sorted(os.listdir(spec)):
                p = os.path.join(spec, name)
                if os.path.isfile(p) and not name.startswith("."):
                    out.append(p)
        elif os.path.isfile(spec):
            out.append(spec)
    seen: "set[str]" = set()
    uniq: "list[str]" = []
    for p in out:
        if p not in seen:
            seen.add(p)
            uniq.append(p)
    return uniq


async def _fire_fault(point: str, metrics: SourceMetrics, target: str,
                      path: str) -> None:
    if not FAULTS.active:
        return
    try:
        await FAULTS.fire(point, target=target)
    except InjectedFault as exc:
        metrics.error()
        raise SourceError(f"injected {point} fault: {exc}",
                          path=path) from exc


class ReplayStream(SourceStream):
    """One file's stream. All blocking I/O runs via to_thread; the
    async side only ever sees newline-aligned slabs."""

    def __init__(self, ref: SourceRef, follow: bool, *,
                 offsets: "dict[str, tuple[int, int]]",
                 metrics: SourceMetrics,
                 rate_lps: "float | None" = None,
                 poll_s: float = DEFAULT_POLL_S,
                 read_size: int = DEFAULT_READ_SIZE) -> None:
        self._ref = ref
        self._path = ref.target
        self._follow = follow
        self._offsets = offsets
        self._metrics = metrics
        self._rate = rate_lps
        self._poll_s = poll_s
        self._read_size = read_size
        self._f: "BinaryIO | None" = None
        self._inode = -1
        self._pos = 0
        self._tail = b""
        self._closed = False
        self._wake: "asyncio.Event | None" = None  # lazy: no eager loop bind
        self._t0: "float | None" = None
        self._due = 0.0

    def _wake_ev(self) -> asyncio.Event:
        if self._wake is None:
            self._wake = asyncio.Event()
        return self._wake

    # -- blocking half (thread) ---------------------------------------

    def _open_file(self) -> None:
        f = open(self._path, "rb")
        try:
            st = os.fstat(f.fileno())
            pos = 0
            prev = self._offsets.get(self._path)
            if prev is not None and prev[0] == st.st_ino \
                    and prev[1] <= st.st_size:
                pos = prev[1]
            f.seek(pos)
        except BaseException:
            # fstat/seek failing between open and ownership transfer
            # would otherwise leak the fd into the poller thread.
            f.close()
            raise
        self._f, self._inode, self._pos = f, st.st_ino, pos

    def _step(self) -> "tuple[str, bytes]":
        """One poll: ('data', raw) | ('rotate', old_fd_remainder) |
        ('eof', b'') | ('wait', b'')."""
        if self._f is None:
            try:
                self._open_file()
            except FileNotFoundError:
                return ("wait", b"") if self._follow else ("eof", b"")
        assert self._f is not None
        data = self._f.read(self._read_size)
        if data:
            self._pos += len(data)
            return ("data", data)
        if not self._follow:
            return ("eof", b"")
        try:
            st = os.stat(self._path)
        except FileNotFoundError:
            # Renamed away with no successor yet; old fd is drained,
            # keep watching the path for a recreated file.
            self._close_file(forget=True)
            return ("wait", b"")
        if st.st_ino != self._inode:
            rest = self._f.read()
            self._close_file(forget=True)
            return ("rotate", rest)
        if st.st_size < self._pos:
            self._f.seek(0)
            self._pos = 0
            return ("rotate", b"")
        return ("wait", b"")

    def _close_file(self, forget: bool = False) -> None:
        if self._f is not None:
            try:
                self._f.close()
            finally:
                self._f = None
        if forget:
            self._offsets.pop(self._path, None)
            self._inode = -1
            self._pos = 0

    # -- async half ---------------------------------------------------

    def __aiter__(self) -> "ReplayStream":
        return self

    async def __anext__(self) -> bytes:
        while True:
            if self._closed:
                raise StopAsyncIteration
            await _fire_fault("source.read", self._metrics,
                              self._ref.group, self._path)
            with trace.TRACER.span("source.read", kind="file",
                                   group=self._ref.group):
                kind, data = await asyncio.to_thread(self._step)
            if kind == "data":
                buf = self._tail + data
                cut = buf.rfind(b"\n")
                if cut < 0:
                    self._tail = buf
                    continue
                out, self._tail = buf[:cut + 1], buf[cut + 1:]
                # Resume point: everything up to the carried tail was
                # delivered line-aligned.
                self._offsets[self._path] = (
                    self._inode, self._pos - len(self._tail))
                self._metrics.add_bytes(len(out))
                await self._pace(out)
                return out
            if kind == "rotate":
                self._metrics.rotation()
                out, self._tail = self._tail + data, b""
                if out:
                    self._metrics.add_bytes(len(out))
                    return out
                continue
            if kind == "eof":
                out, self._tail = self._tail, b""
                if out:
                    self._metrics.add_bytes(len(out))
                    return out
                raise StopAsyncIteration
            try:  # wait
                await asyncio.wait_for(self._wake_ev().wait(),
                                       self._poll_s)
            except asyncio.TimeoutError:
                pass

    async def _pace(self, out: bytes) -> None:
        if self._rate is None:
            return
        now = time.monotonic()
        if self._t0 is None:
            self._t0 = now
        self._due += out.count(b"\n") / self._rate
        delay = self._due - (now - self._t0)
        if delay > 0:
            await asyncio.sleep(delay)

    async def close(self) -> None:
        self._closed = True
        self._wake_ev().set()
        await asyncio.to_thread(self._close_file)


class ReplaySource(Source):
    kind = "file"

    def __init__(self, paths: "list[str]", *,
                 rate_lps: "float | None" = None,
                 poll_interval_s: float = DEFAULT_POLL_S,
                 read_size: int = DEFAULT_READ_SIZE) -> None:
        super().__init__()
        self.paths = list(paths)
        self.rate_lps = rate_lps
        self.poll_interval_s = poll_interval_s
        self.read_size = read_size
        # path -> (inode, line-aligned offset); consulted on re-open.
        self._offsets: "dict[str, tuple[int, int]]" = {}

    async def discover(self) -> "list[SourceRef]":
        files = await asyncio.to_thread(_expand_paths, self.paths)
        refs: "list[SourceRef]" = []
        groups: "set[str]" = set()
        for path in files:
            group = safe_group_name(path)
            if group in groups:
                # Distinct paths that sanitize identically stay
                # distinct (stable: derived from the path itself).
                group = f"{group}-{zlib.crc32(path.encode()) & 0xffff:04x}"
            groups.add(group)
            refs.append(SourceRef(kind=self.kind, group=group,
                                  unit="log", target=path))
        return refs

    async def open_stream(self, ref: SourceRef,
                          opts: LogOptions) -> SourceStream:
        await _fire_fault("source.open", self.metrics, ref.group,
                          ref.target)
        if not opts.follow \
                and not await asyncio.to_thread(os.path.isfile, ref.target):
            self.metrics.error()
            raise SourceError(f"no such file: {ref.target}",
                              path=ref.target)
        return ReplayStream(ref, opts.follow, offsets=self._offsets,
                            metrics=self.metrics, rate_lps=self.rate_lps,
                            poll_s=self.poll_interval_s,
                            read_size=self.read_size)
