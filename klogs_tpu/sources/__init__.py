"""Pluggable log sources (docs/SOURCES.md).

Only ``base`` is imported eagerly: ``cluster/backend.py`` imports
``sources.base`` for the shared stream contract, so pulling the
concrete implementations (which import back into cluster/) at package
import time would be a cycle. ``make_source`` resolves them lazily.
"""

from __future__ import annotations

from typing import TYPE_CHECKING

from klogs_tpu.sources.base import (
    Source,
    SourceConfigError,
    SourceError,
    SourceMetrics,
    SourceRef,
    SourceStream,
    safe_group_name,
)

if TYPE_CHECKING:
    from klogs_tpu.cli import Options

__all__ = [
    "Source",
    "SourceConfigError",
    "SourceError",
    "SourceMetrics",
    "SourceRef",
    "SourceStream",
    "make_source",
    "safe_group_name",
]


def make_source(opts: "Options") -> "Source | None":
    """Build the Source selected by ``--source``/``--backfill``, or
    None on the default kube path. Knobs: KLOGS_SOURCE_READAHEAD_MB
    (archive read-ahead), KLOGS_REPLAY_RATE (replay pacing, 0 = as
    fast as the disk goes; ``--replay-rate`` overrides),
    KLOGS_SOCKET_MAX_CONNS (listener accept cap)."""
    from klogs_tpu.utils.env import nonneg_float, warn_positive_int

    backfill = getattr(opts, "backfill", None)
    spec = getattr(opts, "source", None)
    if backfill:
        from klogs_tpu.sources.archive import ArchiveSource

        readahead = warn_positive_int("KLOGS_SOURCE_READAHEAD_MB", 8)
        return ArchiveSource(list(backfill), readahead_mb=readahead)
    if not spec:
        return None
    if spec.startswith("replay:"):
        from klogs_tpu.sources.replay import ReplaySource

        paths = [p for p in spec[len("replay:"):].split(",") if p]
        if not paths:
            raise SourceConfigError(
                "--source replay: needs at least one path "
                "(replay:PATH[,PATH...])")
        rate = getattr(opts, "replay_rate", None)
        if rate is None:
            rate = nonneg_float("KLOGS_REPLAY_RATE", 0.0)
        return ReplaySource(paths, rate_lps=rate if rate > 0 else None)
    if spec.startswith("socket:"):
        from klogs_tpu.sources.socket import SocketSource

        target = spec[len("socket:"):]
        if not target:
            raise SourceConfigError(
                "--source socket: needs a listen address "
                "(socket:HOST:PORT or socket:unix:/path.sock)")
        max_conns = warn_positive_int("KLOGS_SOCKET_MAX_CONNS", 64)
        return SocketSource(target, max_conns=max_conns)
    raise SourceConfigError(
        f"unknown --source {spec!r}: expected replay:PATH[,PATH...], "
        "socket:HOST:PORT, or socket:unix:/path.sock")
