"""Socket/journald-style ingest: a newline-delimited TCP or UDS
listener whose connections become fanout streams.

Backpressure is propagated to the peer by construction, never by
buffering: bytes are read from a connection only inside the stream's
``__anext__`` — when the downstream sink stalls, the StreamReader's
flow-control limit (64 KiB) pauses the transport, the kernel receive
window fills, and the peer's ``send`` blocks. No unbounded queue
exists anywhere on the path (the test asserts a slow consumer blocks
a fast peer).

Connections are ``ephemeral`` SourceRefs: a peer hanging up ends its
stream without the reconnect machinery or a "premature end" warning —
EOF *is* the lifecycle. New connections join through the same
discover() polling that picks up new pods under ``--watch-new``, so
the mode requires ``-f``. The accept cap (KLOGS_SOCKET_MAX_CONNS)
bounds both memory and the per-connection metric label space.
"""

from __future__ import annotations

import asyncio
import os
import stat as stat_mod

from klogs_tpu.cluster.types import LogOptions
from klogs_tpu.obs import trace
from klogs_tpu.sources.base import (
    Source,
    SourceError,
    SourceMetrics,
    SourceRef,
    SourceStream,
)
from klogs_tpu.sources.replay import _fire_fault

READ_SIZE = 1 << 16
FLOW_LIMIT = 1 << 16  # StreamReader high-water mark == one read slab


class SocketStream(SourceStream):
    def __init__(self, ref: SourceRef, reader: asyncio.StreamReader,
                 writer: asyncio.StreamWriter, *,
                 metrics: SourceMetrics,
                 source: "SocketSource") -> None:
        self._ref = ref
        self._reader = reader
        self._writer = writer
        self._metrics = metrics
        self._source = source
        self._closed = False

    def __aiter__(self) -> "SocketStream":
        return self

    async def __anext__(self) -> bytes:
        if self._closed:
            raise StopAsyncIteration
        await _fire_fault("source.read", self._metrics, self._ref.group,
                          self._ref.target)
        with trace.TRACER.span("source.read", kind="socket",
                               group=self._ref.group):
            try:
                data = await self._reader.read(READ_SIZE)
            except (ConnectionError, OSError) as exc:
                self._metrics.error()
                # Peer-error close only; on cancellation the listener
                # still owns this stream, SocketSource.close() reaps it.
                # klogs: ignore[cancel-safety] — owner reaps on cancel
                await self.close()
                raise SourceError(
                    f"socket peer {self._ref.group}: {exc}") from exc
        if not data:
            await self.close()
            raise StopAsyncIteration
        self._metrics.add_bytes(len(data))
        return data

    async def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        await self._source._release(self._ref.target)
        self._writer.close()
        try:
            await self._writer.wait_closed()
        except (ConnectionError, OSError):
            pass


class SocketSource(Source):
    """Listener lifecycle: ``start()`` binds (lazily — never in the
    constructor), the accept callback only registers the connection,
    and ``discover()`` surfaces registered peers as ephemeral refs."""

    kind = "socket"

    def __init__(self, target: str, *, max_conns: int = 64) -> None:
        super().__init__()
        self.target = target
        self.max_conns = max_conns
        self._server: "asyncio.base_events.Server | None" = None
        self._unix_path: "str | None" = None
        # conn id -> (reader, writer); mutated only from the loop.
        self._conns: "dict[str, tuple[asyncio.StreamReader, asyncio.StreamWriter]]" = {}
        self._next_id = 0

    async def start(self) -> None:
        if self._server is not None:
            return
        try:
            if self.target.startswith("unix:"):
                path = self.target[len("unix:"):]
                await asyncio.to_thread(self._unlink_stale, path)
                self._server = await asyncio.start_unix_server(
                    self._on_conn, path=path, limit=FLOW_LIMIT)
                self._unix_path = path
            else:
                host, _, port = self.target.rpartition(":")
                if not host or not port.isdigit():
                    raise SourceError(
                        f"bad socket listen spec {self.target!r}: "
                        "expected HOST:PORT or unix:/path.sock")
                self._server = await asyncio.start_server(
                    self._on_conn, host=host, port=int(port),
                    limit=FLOW_LIMIT)
        except OSError as exc:
            self.metrics.error()
            raise SourceError(
                f"cannot listen on {self.target}: {exc}") from exc

    @staticmethod
    def _unlink_stale(path: str) -> None:
        try:
            if stat_mod.S_ISSOCK(os.stat(path).st_mode):
                os.unlink(path)
        except FileNotFoundError:
            pass

    def bound_port(self) -> int:
        """The kernel-assigned port (tests listen on port 0)."""
        assert self._server is not None and self._server.sockets
        addr = self._server.sockets[0].getsockname()
        return int(addr[1]) if isinstance(addr, tuple) else 0

    async def _on_conn(self, reader: asyncio.StreamReader,
                       writer: asyncio.StreamWriter) -> None:
        if len(self._conns) >= self.max_conns:
            writer.close()
            return
        name = f"conn-{self._next_id:04d}"
        self._next_id += 1
        self._conns[name] = (reader, writer)
        self.metrics.connection()

    async def _release(self, name: str) -> None:
        self._conns.pop(name, None)

    async def discover(self) -> "list[SourceRef]":
        await self.start()
        return [
            SourceRef(kind=self.kind, group=name, unit="peer",
                      target=name, ephemeral=True)
            for name in self._conns
        ]

    async def open_stream(self, ref: SourceRef,
                          opts: LogOptions) -> SourceStream:
        await _fire_fault("source.open", self.metrics, ref.group,
                          ref.target)
        pair = self._conns.get(ref.target)
        if pair is None:
            self.metrics.error()
            raise SourceError(f"connection {ref.target} is gone")
        return SocketStream(ref, pair[0], pair[1], metrics=self.metrics,
                            source=self)

    async def close(self) -> None:
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
            self._server = None
        for _reader, writer in list(self._conns.values()):
            writer.close()
        self._conns.clear()
        if self._unix_path is not None:
            await asyncio.to_thread(self._unlink_stale, self._unix_path)
            self._unix_path = None
