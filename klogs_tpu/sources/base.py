"""Source contract: where log bytes come from.

`klogs_tpu/cluster/backend.py` grew the original stream contract
(`ClusterBackend`/`LogStream`) around one source — the kube API. This
module extracts the source-agnostic half so files, archives, and
sockets feed the SAME per-stream machinery (fanout workers, framed
sinks, reconnect policy, metrics) the kube path uses:

* ``SourceStream`` — async iterator of byte chunks + ``close()``; the
  exact shape ``LogStream`` always had (``LogStream`` now subclasses
  it, so every existing backend stream is already conformant).
* ``SourceRef`` — generalizes pod identity: ``group`` plays the pod
  role (one output file / sink per group+unit), ``unit`` the container
  role. ``ephemeral`` marks streams whose end is their lifecycle (a
  socket peer hanging up), not a failure to reconnect.
* ``Source`` — discover refs, open a stream per ref, close. The kube
  backend is adapted by ``sources.cluster.ClusterSource``; FakeCluster
  passes the conformance suite through the same adapter.

Chunk contract: sources SHOULD emit slabs cut at a newline boundary
(``rfind(b"\\n")`` + carried tail) so the downstream ``FramedBatcher``
newline sweep never straddles, but the framer tolerates arbitrary
splits — the cut is a throughput courtesy, not a correctness
requirement.

Fault points ``source.open`` / ``source.read`` (resilience/faults.py)
fire on the non-kube implementations; the kube path keeps its
``kube.*`` points so existing chaos specs are undisturbed.
"""

from __future__ import annotations

import abc
import os
import re
from dataclasses import dataclass
from typing import TYPE_CHECKING, AsyncIterator

from klogs_tpu.cluster.types import LogOptions

if TYPE_CHECKING:
    from klogs_tpu.obs.metrics import Registry


class SourceError(Exception):
    """Opening or reading a source stream failed.

    Carries the offending ``path`` and byte ``offset`` when the
    implementation knows them (e.g. a truncated gzip member reports
    the archive path and the compressed offset where decoding died),
    so operators can locate the bad byte without re-running under a
    debugger."""

    def __init__(self, msg: str, *, path: "str | None" = None,
                 offset: "int | None" = None) -> None:
        super().__init__(msg)
        self.path = path
        self.offset = offset


class SourceConfigError(SourceError):
    """A ``--source``/``--backfill`` spec is malformed or names a
    capability this build lacks (e.g. zstd without the zstandard
    package). Raised before any stream opens."""


class SourceStream(abc.ABC):
    """One open byte stream. Async-iterate chunks; ``close()`` is
    idempotent and unblocks a pending ``__anext__``."""

    @abc.abstractmethod
    def __aiter__(self) -> AsyncIterator[bytes]:
        """Iterate raw log chunks until the stream ends."""

    @abc.abstractmethod
    async def close(self) -> None:
        """Release the stream. Safe to call twice."""

    async def __aenter__(self) -> "SourceStream":
        return self

    async def __aexit__(self, *exc: object) -> None:
        await self.close()


@dataclass(frozen=True)
class SourceRef:
    """Addressable stream identity within a source.

    ``group``/``unit`` generalize pod/container: the fanout layer keys
    sinks, output files, and per-stream metrics on them exactly as it
    keys pods. ``target`` is the source-private address (file path,
    connection id); ``ephemeral`` streams are never reconnected and
    their EOF is not "premature"."""

    kind: str
    group: str
    unit: str
    target: str = ""
    ephemeral: bool = False


class SourceMetrics:
    """Lazy view over the ``klogs_source_*`` families; every method is
    a no-op until a registry is bound (mirrors FilterStats's optional-
    registry discipline so library use stays metrics-free)."""

    def __init__(self, kind: str) -> None:
        self.kind = kind
        self._bytes: object = None
        self._rotations: object = None
        self._members: object = None
        self._errors: object = None
        self._conns: object = None

    def bind(self, registry: "Registry | None") -> None:
        if registry is None:
            return
        self._bytes = registry.family(
            "klogs_source_bytes_total").labels(kind=self.kind)
        self._rotations = registry.family("klogs_source_rotations_total")
        self._members = registry.family(
            "klogs_source_archive_members_total")
        self._errors = registry.family(
            "klogs_source_errors_total").labels(kind=self.kind)
        self._conns = registry.family("klogs_source_connections_total")

    def add_bytes(self, n: int) -> None:
        if self._bytes is not None:
            self._bytes.inc(n)  # type: ignore[attr-defined]

    def rotation(self) -> None:
        if self._rotations is not None:
            self._rotations.inc()  # type: ignore[attr-defined]

    def member(self) -> None:
        if self._members is not None:
            self._members.inc()  # type: ignore[attr-defined]

    def error(self) -> None:
        if self._errors is not None:
            self._errors.inc()  # type: ignore[attr-defined]

    def connection(self) -> None:
        if self._conns is not None:
            self._conns.inc()  # type: ignore[attr-defined]


class Source(abc.ABC):
    """A place log streams come from.

    Lifecycle: ``start()`` (bind listeners — must run on the event
    loop, never in ``__init__``), ``discover()`` (current refs; polled
    in follow mode so new files/connections join live), ``open_stream``
    per ref, ``close()``. Implementations keep constructors free of
    asyncio primitives (Py3.10 binds them to the construction-time
    loop)."""

    kind: str = "source"

    def __init__(self) -> None:
        self.metrics = SourceMetrics(self.kind)

    async def start(self) -> None:
        """One-time async setup (default: none)."""

    @abc.abstractmethod
    async def discover(self) -> "list[SourceRef]":
        """Enumerate the streams currently available."""

    @abc.abstractmethod
    async def open_stream(self, ref: SourceRef,
                          opts: LogOptions) -> SourceStream:
        """Open one stream. Raises SourceError on failure."""

    async def close(self) -> None:
        """Release listeners/threads. Safe to call twice."""

    def bind_registry(self, registry: "Registry | None") -> None:
        """Attach the klogs_source_* metric families."""
        self.metrics.bind(registry)


_UNSAFE = re.compile(r"[^A-Za-z0-9._-]+")


def safe_group_name(path: str) -> str:
    """Collapse a filesystem path into a pod-shaped group name (it
    becomes part of the output file name, so no separators)."""
    name = _UNSAFE.sub("_", path.replace(os.sep, "_")).strip("_.")
    return name or "stream"
