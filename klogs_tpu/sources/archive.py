"""Compressed-archive backfill source (GLoP's incident-response
scenario: "grep a week of archived logs for 1k patterns").

Decompression runs in one daemon thread per logical stream, feeding a
bounded queue of newline-aligned slabs (~1 MiB) the event loop
consumes — decompress → newline-scan → framed payload with no
per-line Python anywhere on the path. zlib releases the GIL while
inflating, so producer threads overlap with the native sweep/confirm
engine; the queue bound (KLOGS_SOURCE_READAHEAD_MB) is the
backpressure: a slow engine blocks the producer's ``put``, never
grows memory.

Rotated sets are ONE logical stream in chronological order: for a base
name ``app.log`` the members ``app.log.3.gz … app.log.1.gz, app.log``
replay oldest-first, so backfill output ordering matches what a live
follow of the same file would have produced (the byte-parity
acceptance test).

Error taxonomy: a gzip member that ends mid-stream raises
``SourceError`` naming the archive path and the compressed byte offset
where decoding died — never a raw EOFError; corrupt bytes raise the
same with the zlib detail. zstd needs the ``zstandard`` package and is
cleanly refused (SourceConfigError) when absent — never an ImportError
at stream time.
"""

from __future__ import annotations

import asyncio
import os
import queue
import re
import threading
import zlib
from typing import Iterator, Union

from klogs_tpu.cluster.types import LogOptions
from klogs_tpu.obs import trace
from klogs_tpu.obs.profiler import PROFILER
from klogs_tpu.sources.base import (
    Source,
    SourceConfigError,
    SourceError,
    SourceMetrics,
    SourceRef,
    SourceStream,
    safe_group_name,
)
from klogs_tpu.sources.replay import _expand_paths, _fire_fault

DEFAULT_SLAB_BYTES = 1 << 20
_COMPRESS_EXTS = (".gz", ".zst", ".zstd")
_ROTATE_N = re.compile(r"^(?P<base>.+)\.(?P<n>\d+)$")
# queue items: a slab, the terminal error, or the end-of-stream None.
_Item = Union[bytes, SourceError, None]


def strip_compress_ext(path: str) -> "tuple[str, str]":
    """('app.log.2', 'gz') from 'app.log.2.gz'; codec '' = plain."""
    for ext in _COMPRESS_EXTS:
        if path.endswith(ext):
            return path[: -len(ext)], ext.lstrip(".")
    return path, ""


def group_archives(files: "list[str]") -> "dict[str, list[str]]":
    """Group rotated members under their base name, ordered
    oldest-first: numeric rotation suffixes descending, the bare
    (current) file last. ``{'d/app.log': ['d/app.log.2.gz',
    'd/app.log.1.gz', 'd/app.log']}``."""
    groups: "dict[str, list[tuple[int, str]]]" = {}
    for path in files:
        logical, _codec = strip_compress_ext(path)
        m = _ROTATE_N.match(logical)
        if m:
            groups.setdefault(m.group("base"), []).append(
                (int(m.group("n")), path))
        else:
            # Rotation index -1 == the live file: sorts after every
            # numbered member under reverse ordering.
            groups.setdefault(logical, []).append((-1, path))
    return {
        base: [p for _n, p in sorted(members, key=lambda t: -t[0])]
        for base, members in sorted(groups.items())
    }


class ArchiveStream(SourceStream):
    """One logical (rotated) archive set, decompressed by a producer
    thread into a bounded slab queue.

    Loop-affine state is limited to ``_closed`` (declared in the
    lock-discipline SHARED_STATE table): the thread communicates only
    through the queue and the threadsafe wake callback."""

    def __init__(self, ref: SourceRef, members: "list[str]", *,
                 metrics: SourceMetrics,
                 readahead_slabs: int = 8,
                 slab_bytes: int = DEFAULT_SLAB_BYTES) -> None:
        self._ref = ref
        self._members = list(members)
        self._metrics = metrics
        self._readahead = max(1, readahead_slabs)
        self._slab = slab_bytes
        self._q: "queue.Queue[_Item] | None" = None
        self._thread: "threading.Thread | None" = None
        self._wake: "asyncio.Event | None" = None
        self._loop: "asyncio.AbstractEventLoop | None" = None
        self._done = False
        self._closed = False

    # -- producer thread ----------------------------------------------

    def _put(self, item: _Item) -> bool:
        assert self._q is not None
        while True:
            try:
                # The timeout only exists to re-check _closed; space
                # freed by the consumer wakes the put immediately.
                self._q.put(item, timeout=0.2)
            except queue.Full:
                if self._closed:
                    return False
                continue
            self._notify()
            return True

    def _notify(self) -> None:
        loop, wake = self._loop, self._wake
        if loop is None or wake is None:
            return
        try:
            loop.call_soon_threadsafe(wake.set)
        except RuntimeError:
            pass  # loop already closed (teardown race)

    def _produce(self) -> None:
        tail = b""  # carried partial last line (no newline yet)
        try:
            for path in self._members:
                if self._closed:
                    return
                it = self._decompress(path)
                while True:
                    # Re-checked every slab, not only when a put blocks:
                    # a drained-by-close() queue never fills, and without
                    # this the producer would decompress the whole
                    # archive after close() and outlive the join below.
                    if self._closed:
                        return
                    slab = None
                    # The span covers the actual source work (decompress
                    # + newline cut) so `source.read` busy answers
                    # "can the source keep up". The put — where engine
                    # backpressure parks this thread — stays OUTSIDE:
                    # waiting for a slower consumer is not source cost.
                    # Each decompressed chunk becomes one slab, cut at
                    # its last newline with the remainder carried: one
                    # byte-copy per byte, because every copy here holds
                    # the GIL and is stolen from the event loop.
                    with trace.TRACER.span("source.read", kind="archive",
                                           group=self._ref.group):
                        chunk = next(it, None)
                        if chunk is not None:
                            cut = chunk.rfind(b"\n")
                            if cut < 0:
                                tail += chunk
                                if len(tail) >= 4 * self._slab:
                                    # Pathological no-newline data:
                                    # emit raw rather than grow
                                    # without bound.
                                    slab, tail = tail, b""
                            else:
                                mv = memoryview(chunk)
                                slab = (b"".join((tail, mv[:cut + 1]))
                                        if tail else chunk[:cut + 1])
                                tail = bytes(mv[cut + 1:])
                    if slab is not None and not self._put(slab):
                        return
                    if chunk is None:
                        break
                self._metrics.member()
            if tail:
                self._put(tail)
            self._put(None)
        except SourceError as exc:
            self._metrics.error()
            self._put(exc)
        except Exception as exc:  # noqa: BLE001 — surface as SourceError
            self._metrics.error()
            self._put(SourceError(f"archive read failed: {exc}"))

    def _decompress(self, path: str) -> Iterator[bytes]:
        if path.endswith(".gz"):
            yield from self._gunzip(path)
        elif path.endswith((".zst", ".zstd")):
            yield from self._unzstd(path)
        else:
            with open(path, "rb") as f:
                while chunk := f.read(self._slab):
                    yield chunk

    def _gunzip(self, path: str) -> Iterator[bytes]:
        """Streaming multi-member gunzip. Truncation mid-member and
        corrupt bytes both raise SourceError with the compressed byte
        offset — the named-error contract."""
        with open(path, "rb") as f:
            d = zlib.decompressobj(31)  # 31 = gzip wrapper
            consumed = 0  # compressed bytes fully decoded so far
            mid_member = False
            while True:
                # Read ~half a slab of compressed bytes per step: at
                # typical log ratios one step decompresses to roughly
                # one slab, so slabs stay near their target size.
                raw = f.read(max(1 << 18, self._slab >> 1))
                if not raw:
                    if mid_member:
                        raise SourceError(
                            f"truncated gzip member in {path} at "
                            f"compressed byte {consumed}",
                            path=path, offset=consumed)
                    return
                data = raw
                while data:
                    try:
                        out = d.decompress(data)
                    except zlib.error as exc:
                        raise SourceError(
                            f"corrupt gzip data in {path} near "
                            f"compressed byte {consumed}: {exc}",
                            path=path, offset=consumed) from exc
                    if out:
                        yield out
                    if d.eof:
                        leftover = d.unused_data
                        consumed += len(data) - len(leftover)
                        d = zlib.decompressobj(31)
                        mid_member = False
                        data = leftover
                    else:
                        consumed += len(data)
                        mid_member = True
                        data = b""

    def _unzstd(self, path: str) -> Iterator[bytes]:
        try:
            import zstandard
        except ImportError:
            raise SourceConfigError(
                f"cannot read {path}: zstd support requires the "
                "'zstandard' package", path=path) from None
        with open(path, "rb") as f:
            # read_across_frames: a rotated-then-appended archive is
            # concatenated zstd frames (the same multi-member shape
            # _gunzip handles for .gz); without it the reader stops
            # silently at the first frame boundary.
            with zstandard.ZstdDecompressor().stream_reader(
                    f, read_across_frames=True) as r:
                while True:
                    try:
                        chunk = r.read(self._slab)
                    except zstandard.ZstdError as exc:
                        off = f.tell()
                        raise SourceError(
                            f"corrupt or truncated zstd data in {path} "
                            f"near compressed byte {off}: {exc}",
                            path=path, offset=off) from exc
                    if not chunk:
                        return
                    yield chunk

    # -- consumer (event loop) ----------------------------------------

    def _ensure_started(self) -> None:
        if self._thread is not None:
            return
        self._loop = asyncio.get_running_loop()
        self._wake = asyncio.Event()
        self._q = queue.Queue(maxsize=self._readahead)
        self._thread = threading.Thread(
            target=self._produce, daemon=True,
            name=f"klogs-archive-{self._ref.group}")
        self._thread.start()

    def readahead_depth(self) -> int:
        q = self._q
        return q.qsize() if q is not None else 0

    def __aiter__(self) -> "ArchiveStream":
        return self

    async def __anext__(self) -> bytes:
        self._ensure_started()
        assert self._q is not None and self._wake is not None
        if self._closed or self._done:
            raise StopAsyncIteration
        await _fire_fault("source.read", self._metrics, self._ref.group,
                          self._members[0] if self._members else "")
        # No span here: the producer thread's decompress work carries
        # the `source.read` attribution. Waiting on the queue is either
        # backpressure (the engine's cost) or loop lag — billing it to
        # the source would make every run look source-bound.
        while True:
            try:
                item = self._q.get_nowait()
                break
            except queue.Empty:
                pass
            self._wake.clear()
            try:
                item = self._q.get_nowait()
                break
            except queue.Empty:
                pass
            if self._closed:
                raise StopAsyncIteration
            await self._wake.wait()
        if item is None:
            self._done = True
            raise StopAsyncIteration
        if isinstance(item, SourceError):
            self._done = True
            raise item
        self._metrics.add_bytes(len(item))
        return item

    async def close(self) -> None:
        self._closed = True
        if self._wake is not None:
            self._wake.set()
        q = self._q
        if q is not None:
            # Drain so a producer blocked on put() notices _closed.
            try:
                while True:
                    q.get_nowait()
            except queue.Empty:
                pass
        t = self._thread
        if t is not None and t.is_alive():
            # Join off-loop: the producer exits at its next _closed
            # check (loop head, or a blocked put's 0.2s timeout), so
            # this is bounded — without it the daemon thread keeps
            # inflating into a dead queue past close().
            await asyncio.to_thread(t.join, 2.0)


class ArchiveSource(Source):
    kind = "archive"

    def __init__(self, paths: "list[str]", *, readahead_mb: int = 8,
                 slab_bytes: int = DEFAULT_SLAB_BYTES) -> None:
        super().__init__()
        self.paths = list(paths)
        self.slab_bytes = slab_bytes
        self.readahead_slabs = max(
            1, (readahead_mb << 20) // max(1, slab_bytes))
        self._members: "dict[str, list[str]]" = {}
        self._live: "set[ArchiveStream]" = set()
        self._probe_added = False

    async def start(self) -> None:
        if not self._probe_added:
            PROFILER.add_probe("source.readahead_slabs",
                               self._readahead_probe)
            self._probe_added = True

    def _readahead_probe(self) -> float:
        return float(sum(s.readahead_depth() for s in self._live))

    async def discover(self) -> "list[SourceRef]":
        files = await asyncio.to_thread(_expand_paths, self.paths)
        if not files:
            raise SourceError(
                "backfill: no archive files found under "
                + ", ".join(self.paths))
        refs: "list[SourceRef]" = []
        groups: "set[str]" = set()
        for base, members in group_archives(files).items():
            group = safe_group_name(base)
            if group in groups:
                group = f"{group}-{len(groups)}"
            groups.add(group)
            self._members[group] = members
            refs.append(SourceRef(kind=self.kind, group=group,
                                  unit="archive", target=base))
        return refs

    async def open_stream(self, ref: SourceRef,
                          opts: LogOptions) -> SourceStream:
        await _fire_fault("source.open", self.metrics, ref.group,
                          ref.target)
        members = self._members.get(ref.group)
        if not members:
            self.metrics.error()
            raise SourceError(f"unknown archive set: {ref.group}",
                              path=ref.target)
        stream = ArchiveStream(ref, members, metrics=self.metrics,
                               readahead_slabs=self.readahead_slabs,
                               slab_bytes=self.slab_bytes)
        self._live.add(stream)
        return stream

    async def close(self) -> None:
        if self._probe_added:
            PROFILER.remove_probe("source.readahead_slabs")
            self._probe_added = False
        for stream in list(self._live):
            await stream.close()
        self._live.clear()
