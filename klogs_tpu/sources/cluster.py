"""ClusterSource: a ClusterBackend viewed through the Source contract.

The adapter the fanout layer wraps around any backend when no
``--source`` is given, and the conformance harness FakeCluster runs
under in tests. It deliberately adds nothing: discovery is
``list_pods`` flattened to (pod, container) refs and ``open_stream``
is ``open_log_stream`` verbatim — keeping the kube path byte-identical
while file/socket sources ride the same worker loop.
"""

from __future__ import annotations

from klogs_tpu.cluster.backend import ClusterBackend
from klogs_tpu.cluster.types import LogOptions
from klogs_tpu.sources.base import Source, SourceRef, SourceStream


class ClusterSource(Source):
    kind = "pod"

    def __init__(self, backend: ClusterBackend, namespace: str,
                 include_init: bool = False) -> None:
        super().__init__()
        self.backend = backend
        self.namespace = namespace
        self.include_init = include_init

    async def discover(self) -> "list[SourceRef]":
        refs: "list[SourceRef]" = []
        for pod in await self.backend.list_pods(self.namespace):
            containers = list(pod.containers)
            if self.include_init:
                containers += list(pod.init_containers)
            for c in containers:
                refs.append(SourceRef(kind=self.kind, group=pod.name,
                                      unit=c.name, target=pod.name))
        return refs

    async def open_stream(self, ref: SourceRef,
                          opts: LogOptions) -> SourceStream:
        # opts.container carries the unit, exactly as the fanout worker
        # has always passed it; kube.* fault points fire inside the
        # backend, so no source.* point is layered on top here.
        return await self.backend.open_log_stream(
            self.namespace, ref.group, opts)

    async def close(self) -> None:
        await self.backend.close()
