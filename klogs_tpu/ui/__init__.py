from klogs_tpu.ui.term import (
    Printer,
    blue,
    colors_enabled,
    error,
    fatal,
    gray,
    green,
    info,
    red,
    set_colors,
    warning,
)

__all__ = [
    "Printer",
    "blue",
    "colors_enabled",
    "error",
    "fatal",
    "gray",
    "green",
    "info",
    "red",
    "set_colors",
    "warning",
]
