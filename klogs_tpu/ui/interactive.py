"""Interactive terminal selection.

Reference parity: pterm InteractiveSelect for namespaces
(cmd/root.go:117-122) and InteractiveMultiselect for pods — filter
disabled, Enter=confirm, Space=select, MaxHeight 15 (cmd/root.go:167-182).

Implementation: raw-mode arrow-key navigation via termios. Both entry
points accept an injectable ``keys`` iterator so tests can drive them
without a tty; without a tty and without injected keys they raise.
"""

import sys
from typing import Iterable, Iterator

from klogs_tpu.ui import term

MAX_HEIGHT = 15

UP, DOWN, ENTER, SPACE = "up", "down", "enter", "space"


def _read_keys_tty() -> Iterator[str]:
    import termios
    import tty

    fd = sys.stdin.fileno()
    old = termios.tcgetattr(fd)
    try:
        tty.setcbreak(fd)
        while True:
            ch = sys.stdin.read(1)
            if ch == "\x1b":
                seq = sys.stdin.read(2)
                if seq == "[A":
                    yield UP
                elif seq == "[B":
                    yield DOWN
            elif ch in ("\r", "\n"):
                yield ENTER
            elif ch == " ":
                yield SPACE
            elif ch in ("\x03", "q"):
                yield "quit"
            else:
                yield ch
    finally:
        termios.tcsetattr(fd, termios.TCSADRAIN, old)


class NotInteractive(RuntimeError):
    pass


def _keys_or_tty(keys: Iterable[str] | None) -> Iterator[str]:
    if keys is not None:
        return iter(keys)
    try:
        if sys.stdin.isatty():
            return _read_keys_tty()
    except Exception:
        pass
    raise NotInteractive(
        "interactive selection requires a terminal "
        "(select explicitly with flags instead: -n <namespace>, -a for all "
        "pods, or -l <label>)"
    )


def _render(options: list[str], cursor: int, selected: set[int] | None,
            top: int, out) -> int:
    """Render a window of options; returns number of lines printed."""
    height = min(len(options), MAX_HEIGHT)
    lines = 0
    for i in range(top, top + height):
        marker = ">" if i == cursor else " "
        if selected is not None:
            box = "[x]" if i in selected else "[ ]"
            text = f"{marker} {box} {options[i]}"
        else:
            text = f"{marker} {options[i]}"
        if i == cursor:
            text = term.green(text)
        print(text, file=out)
        lines += 1
    return lines


def _clear(n: int, out) -> None:
    try:
        is_tty = out.isatty()
    except Exception:
        is_tty = False
    if is_tty and n:
        print(f"\x1b[{n}A\x1b[0J", end="", file=out)


def interactive_select(
    options: list[str], default_text: str,
    keys: Iterable[str] | None = None, out=None,
) -> str:
    """Single choice (namespace picker, cmd/root.go:117-122)."""
    out = out or term.ui_stream()
    key_iter = _keys_or_tty(keys)
    cursor, top = 0, 0
    print(f"{default_text}:", file=out)
    printed = _render(options, cursor, None, top, out)
    for key in key_iter:
        _clear(printed, out)
        if key == UP:
            cursor = max(0, cursor - 1)
        elif key == DOWN:
            cursor = min(len(options) - 1, cursor + 1)
        elif key == ENTER:
            return options[cursor]
        top = min(max(top, cursor - MAX_HEIGHT + 1), cursor)
        printed = _render(options, cursor, None, top, out)
    # keys exhausted without Enter (test injection): current cursor wins
    return options[cursor]


def interactive_multiselect(
    options: list[str], default_text: str,
    keys: Iterable[str] | None = None, out=None,
) -> list[str]:
    """Multi choice (pod picker, cmd/root.go:167-182): Space toggles,
    Enter confirms, no filter, window of MAX_HEIGHT."""
    out = out or term.ui_stream()
    key_iter = _keys_or_tty(keys)
    cursor, top = 0, 0
    selected: set[int] = set()
    print(f"{default_text} (space=select, enter=confirm):", file=out)
    printed = _render(options, cursor, selected, top, out)
    for key in key_iter:
        _clear(printed, out)
        if key == UP:
            cursor = max(0, cursor - 1)
        elif key == DOWN:
            cursor = min(len(options) - 1, cursor + 1)
        elif key == SPACE:
            selected.symmetric_difference_update({cursor})
        elif key == ENTER:
            break
        top = min(max(top, cursor - MAX_HEIGHT + 1), cursor)
        printed = _render(options, cursor, selected, top, out)
    return [options[i] for i in sorted(selected)]
