"""Terminal colors and severity printers.

Reference parity: klogs does all terminal output through pterm's
severity printers (Info/Warning/Error/Fatal prefixes, e.g.
cmd/root.go:78,98,102,147,267,274,284,316,327,393) and color helpers
(pterm.Green/Red/Gray/Blue). This module is the stdlib-only analog:
ANSI SGR with a global on/off switch (auto-detected from tty / NO_COLOR)
so tests can force deterministic output.
"""

import os
import sys

_FORCED: bool | None = None


def _auto() -> bool:
    if "NO_COLOR" in os.environ:
        return False
    try:
        return sys.stdout.isatty()
    except Exception:
        return False


def colors_enabled() -> bool:
    return _FORCED if _FORCED is not None else _auto()


def set_colors(enabled: bool | None) -> None:
    """Force colors on/off, or None to restore auto-detection."""
    global _FORCED
    _FORCED = enabled


_UI_STREAM = None


def set_ui_stream(stream) -> None:
    """Route all UI output (severity printers, widgets) to ``stream``;
    None restores sys.stdout. ``-o stdout|both`` points this at stderr
    so log lines own stdout — a piped ``klogs -o stdout | grep`` sees
    only log lines, and UI text can never interleave into (or reorder
    around) the byte stream sharing the fd."""
    global _UI_STREAM
    _UI_STREAM = stream


def ui_stream():
    return _UI_STREAM if _UI_STREAM is not None else sys.stdout


def _sgr(code: str, text: str) -> str:
    if not colors_enabled():
        return text
    return f"\x1b[{code}m{text}\x1b[0m"


def green(text: str) -> str:
    return _sgr("32", text)


def red(text: str) -> str:
    return _sgr("31", text)


def gray(text: str) -> str:
    return _sgr("90", text)


def blue(text: str) -> str:
    return _sgr("34", text)


def yellow(text: str) -> str:
    return _sgr("33", text)


def cyan(text: str) -> str:
    return _sgr("36", text)


def bold(text: str) -> str:
    return _sgr("1", text)


class Printer:
    """A pterm-style severity printer: `` PREFIX  message``."""

    def __init__(self, prefix: str, code: str, stream=None):
        self.prefix = prefix
        self.code = code
        self.stream = stream

    def __call__(self, fmt: str, *args) -> None:
        out = self.stream or ui_stream()
        msg = (fmt % args) if args else fmt
        badge = _sgr(self.code, f" {self.prefix} ")
        print(f"{badge} {msg}", file=out)


info = Printer("INFO", "30;46")
warning = Printer("WARNING", "30;43")
error = Printer("ERROR", "30;41")


class FatalError(SystemExit):
    """Raised by fatal(); carries exit status 1 like pterm.Fatal."""


def fatal(fmt: str, *args) -> None:
    Printer("FATAL", "30;41")(fmt, *args)
    raise FatalError(1)
