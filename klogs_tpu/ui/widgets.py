"""Terminal widgets: splash banner, tree, boxed table, spinner.

Reference parity:
- splash: pterm BigText "KLogs", K blue + "Logs" white (cmd/root.go:56-66)
- tree: per-pod container tree (cmd/root.go:231-273)
- table: boxed, header row, Pod/Container/Size (cmd/root.go:279-309)
- spinner: animated "press q" hint in follow mode (cmd/root.go:407)
"""

import asyncio
import itertools

from klogs_tpu.ui import term

# 5-row banner glyphs (figlet-style) for the letters of "KLogs".
_BIG = {
    "K": ["#   #", "#  # ", "###  ", "#  # ", "#   #"],
    "L": ["#    ", "#    ", "#    ", "#    ", "#####"],
    "o": ["     ", " ### ", "#   #", "#   #", " ### "],
    "g": [" ####", "#   #", " ####", "    #", " ### "],
    "s": [" ####", "#    ", " ### ", "    #", "#### "],
}


def splash_screen(out=None) -> None:
    out = out or term.ui_stream()
    rows = ["", "", "", "", ""]
    for i, ch in enumerate("KLogs"):
        glyph = _BIG[ch]
        for r in range(5):
            piece = glyph[r] + "  "
            rows[r] += term.blue(piece) if i == 0 else piece
    print("\n".join(rows) + "\n", file=out)


def render_tree(root: str, children: list[str], out=None) -> None:
    """One pod tree: root label + branch per container."""
    out = out or term.ui_stream()
    print(root, file=out)
    for i, child in enumerate(children):
        branch = "└─" if i == len(children) - 1 else "├─"
        print(f"{branch}{child}", file=out)


def render_table(data: list[list[str]], out=None) -> None:
    """Boxed table with a header row (pterm WithHasHeader().WithBoxed())."""
    out = out or term.ui_stream()
    if not data:
        return
    ncols = max(len(r) for r in data)
    widths = [0] * ncols
    for row in data:
        for i, cell in enumerate(row):
            widths[i] = max(widths[i], len(_strip_ansi(cell)))

    def fmt_row(row: list[str]) -> str:
        cells = []
        for i in range(ncols):
            cell = row[i] if i < len(row) else ""
            pad = widths[i] - len(_strip_ansi(cell))
            cells.append(cell + " " * pad)
        return "│ " + " │ ".join(cells) + " │"

    def edge(left: str, mid: str, right: str) -> str:
        return left + mid.join("─" * (w + 2) for w in widths) + right

    print(edge("┌", "┬", "┐"), file=out)
    print(fmt_row(data[0]), file=out)
    print(edge("├", "┼", "┤"), file=out)
    for row in data[1:]:
        print(fmt_row(row), file=out)
    print(edge("└", "┴", "┘"), file=out)


def _strip_ansi(s: str) -> str:
    import re

    return re.sub(r"\x1b\[[0-9;]*m", "", s)


class Spinner:
    """Async spinner; removed from the line when stopped (RemoveWhenDone)."""

    FRAMES = [".  ", ".. ", ".|.", " ..", "  ."]

    def __init__(self, text: str, out=None):
        self.text = text
        self.out = out or term.ui_stream()
        self._task: asyncio.Task | None = None

    async def _spin(self) -> None:
        try:
            is_tty = self.out.isatty()
        except Exception:
            is_tty = False
        if not is_tty:
            print(self.text, file=self.out)
            return
        for frame in itertools.cycle(self.FRAMES):
            print(f"\r{frame} {self.text}", end="", flush=True, file=self.out)
            await asyncio.sleep(0.15)

    async def __aenter__(self) -> "Spinner":
        self._task = asyncio.create_task(self._spin())
        return self

    async def __aexit__(self, *exc) -> None:
        if self._task:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
        try:
            if self.out.isatty():
                print("\r\x1b[2K", end="", flush=True, file=self.out)
        except Exception:
            pass
