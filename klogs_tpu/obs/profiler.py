"""Continuous pipeline utilization profiler + the fleet capacity signal.

Spans (obs.trace) say where one batch went; metrics (obs.metrics) say
how often things happened. Neither answers the operating question the
ROADMAP's elasticity items need answered continuously: *which stage is
the bottleneck right now, and how much headroom does this process
have?* This module closes that gap with two cooperating pieces:

- ``PipelineProfiler`` — folds every finished span whose name is in
  the pipeline stage catalog (PR 9's spans: fanout.read ->
  coalescer.dispatch -> device.sweep/groupscan/kernel/fetch ->
  sink.write -> rpc.client/server ...) into per-stage busy-seconds,
  and on a cheap periodic tick derives rolling per-stage utilization
  (busy-seconds per wall-second over the tick window, unbiased by the
  trace sampling rate), samples registered probes (queue depth,
  in-flight slots, executor saturation), and serves the result as the
  ``/profile`` JSON endpoint on the obs sidecar plus an optional
  ``--profile-json`` rolling JSONL file. Off by default: until
  ``enable()`` runs, the tracer sink is never installed, so the
  per-span cost of a disabled profiler is exactly zero.

- ``FleetCapacity`` — the filterd-side capacity accountant: offered vs
  admitted lines (offered = entered a match RPC; admitted = passed
  tenancy admission and produced verdicts), rolling rates over a short
  window, and a headroom estimate in [0, 1] combining the profiler's
  observed peak stage utilization with the admitted-rate-vs-envelope
  ratio (``KLOGS_FLEET_CAPACITY_LPS``, falling back to the
  OPERATING_POINT.json sweep's measured ceiling). The server
  advertises all three through Hello so ``ShardedFilterClient``
  re-exports them per endpoint (``klogs_fleet_endpoint_*``) — the
  scrape an HPA consumes.

Design rules (the obs budget discipline):

- Folding rides the span stream — per-BATCH, never per-line — and is
  one dict lookup + two float adds per span. The <2% overhead budget
  on the K=1024 bench path is measured and recorded by
  ``tools/bench_fleet.py`` (BENCH_FLEET.json ``overhead`` row).
- Utilization is windowed at tick time, not per span; gauges and the
  JSONL line update once per ``KLOGS_PROFILE_INTERVAL_S``.
- Everything is bounded: the stage catalog is a fixed enum, probes are
  a small named dict, the capacity history is a pruned deque.
"""

import json
import os
import threading
import time
from collections import deque
from typing import TYPE_CHECKING, Any, Callable

from klogs_tpu.obs import trace as _trace

if TYPE_CHECKING:
    import asyncio

    from klogs_tpu.obs.metrics import Registry

# The pipeline stage catalog: the span names (docs/OBSERVABILITY.md
# "Span catalog") the profiler folds. A fixed enum — the `stage` label
# on the klogs_profile_* families is bounded by this tuple.
STAGES: "tuple[str, ...]" = (
    "source.read",
    "fanout.read",
    "sink.flush",
    "sink.write",
    "coalescer.dispatch",
    "shard.dispatch",
    "rpc.client",
    "rpc.server",
    "tenant.admit",
    "device.frame",
    "device.sweep",
    "device.groupscan",
    "device.kernel",
    "device.fetch",
    "mesh.dispatch",
)
_STAGE_SET = frozenset(STAGES)

DEFAULT_INTERVAL_S = 1.0
# Rolling window for the offered/admitted rate estimate.
_CAPACITY_WINDOW_S = 30.0
# Minimum spacing between capacity history samples.
_CAPACITY_SAMPLE_S = 0.5

# Fallback zero point for process uptime when /proc is unreadable.
_T0 = time.monotonic()


def _profile_sample_from_env(default: float) -> float:
    """KLOGS_PROFILE_SAMPLE: the trace-sampling rate the profiler
    requests when enabled (0..1; 0 = profiling stays off even when
    --profile-json asks for it). Malformed values raise naming the
    variable — a typo'd knob silently profiling nothing is exactly the
    blind spot this subsystem exists to remove."""
    from klogs_tpu.utils.env import read as env_read

    raw = env_read("KLOGS_PROFILE_SAMPLE")
    if raw is None:
        return default
    try:
        val = float(raw)
    except ValueError:
        raise ValueError(
            f"KLOGS_PROFILE_SAMPLE={raw!r}: expected a number in [0, 1]"
        ) from None
    if not 0.0 <= val <= 1.0:
        raise ValueError(
            f"KLOGS_PROFILE_SAMPLE={raw!r}: expected a number in [0, 1]")
    return val


def process_uptime_s() -> float:
    """Seconds since THIS process started (not since module import):
    /proc/self/stat field 22 is the start time in clock ticks since
    boot, /proc/uptime the seconds since boot. Falls back to the
    module-load zero point where /proc is unavailable."""
    try:
        with open("/proc/self/stat", "rb") as f:
            stat = f.read()
        with open("/proc/uptime", "rb") as f:
            boot_uptime = float(f.read().split()[0])
        # Fields after the parenthesized comm (which may contain
        # spaces): field 22 (1-based) = starttime, i.e. index 19 after
        # the closing paren.
        after = stat.rsplit(b")", 1)[1].split()
        start_ticks = int(after[19])
        hz = os.sysconf("SC_CLK_TCK")
        return max(0.0, boot_uptime - start_ticks / float(hz))
    except (OSError, ValueError, IndexError):
        return time.monotonic() - _T0


def process_rss_bytes() -> int:
    """Current resident set size in bytes (/proc/self/statm field 2 x
    page size); 0 where /proc is unavailable."""
    try:
        with open("/proc/self/statm", "rb") as f:
            pages = int(f.read().split()[1])
        return pages * os.sysconf("SC_PAGE_SIZE")
    except (OSError, ValueError, IndexError):
        return 0


def refresh_process_metrics(registry: "Registry | None") -> None:
    """Update the process-level gauges (klogs_process_uptime_seconds /
    klogs_process_rss_bytes) so headroom math and dashboards need no
    node exporter. Called before each /metrics render (off the event
    loop), at --stats-json dump time, and on every profiler tick."""
    if registry is None:
        return
    registry.family("klogs_process_uptime_seconds").set(process_uptime_s())
    registry.family("klogs_process_rss_bytes").set(process_rss_bytes())


class PipelineProfiler:
    """Per-stage busy-seconds accounting over the finished-span stream.

    ``PROFILER`` below is the process-global instance (one pipeline
    story per process, like the tracer); private instances isolate
    tests. Until ``enable()`` runs, ``on_span`` is never installed as a
    tracer sink — a disabled profiler costs literally nothing per span.
    """

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._enabled = False
        self._sample = 0.0
        self._interval_s = DEFAULT_INTERVAL_S
        self._t_enabled: "float | None" = None
        # stage -> [busy_s, span_count]; mutated under _lock (the span
        # stream arrives from loop and executor threads alike).
        self._stages: "dict[str, list[float]]" = {}
        # parent span_id -> folded-child duration accumulated so far:
        # stages nest (shard.dispatch wraps rpc.client wraps the wire),
        # so each span folds its SELF time — duration minus folded
        # children — or the outermost wrapper would always "win" the
        # bottleneck. Bounded: entries whose parent never folds (e.g.
        # an unfolded ancestor) are evicted oldest-first past the cap.
        self._child_busy: "dict[str, float]" = {}
        self._util: "dict[str, float]" = {}
        self._last_tick: "tuple[float, dict[str, float]] | None" = None
        self._last_doc: "dict[str, Any] | None" = None
        self._probes: "dict[str, Callable[[], float]]" = {}
        self._capacity: "FleetCapacity | None" = None
        self._json_lock = threading.Lock()
        self._json_path: "str | None" = None
        self._registry: "Registry | None" = None
        # Already-synced (busy_s, spans) per stage, so counter families
        # advance by tick-time deltas (counters cannot be set).
        self._synced: "dict[str, tuple[float, int]]" = {}

    # -- configuration ------------------------------------------------

    @property
    def enabled(self) -> bool:
        return self._enabled

    def enable(self, sample: "float | None" = None) -> bool:
        """Turn the profiler on: install the span-fold sink and make
        sure spans actually flow (raises the tracer's sampling rate to
        the profile sample unless KLOGS_TRACE_SAMPLE explicitly pins
        one). ``sample`` defaults to KLOGS_PROFILE_SAMPLE, else 1.0 —
        asking for a profile means you want the profile. Returns
        whether the profiler is enabled (KLOGS_PROFILE_SAMPLE=0 keeps
        it off even against an explicit --profile-json)."""
        rate = sample if sample is not None else _profile_sample_from_env(1.0)
        if rate <= 0.0:
            return self._enabled
        from klogs_tpu.utils.env import positive_float

        # Validated HERE, on the main enablement path: a malformed
        # interval raising later inside the background ticker task
        # would kill profiling silently — exactly the typo'd-knob
        # blind spot this subsystem exists to remove.
        interval = positive_float("KLOGS_PROFILE_INTERVAL_S",
                                  DEFAULT_INTERVAL_S)
        with self._lock:
            self._enabled = True
            self._sample = rate
            self._interval_s = interval
            if self._t_enabled is None:
                self._t_enabled = time.perf_counter()
        _trace.TRACER.ensure_sample(rate)
        # Idempotent install (trace.reset() in tests drops all sinks).
        _trace.TRACER.remove_sink(self.on_span)
        _trace.TRACER.add_sink(self.on_span)
        return True

    def maybe_enable(self) -> bool:
        """Env-driven enablement: on iff KLOGS_PROFILE_SAMPLE > 0."""
        rate = _profile_sample_from_env(0.0)
        if rate > 0.0:
            return self.enable(rate)
        return self._enabled

    def bind_registry(self, registry: "Registry | None") -> None:
        with self._lock:
            self._registry = registry
            self._synced = {}

    def attach_capacity(self, capacity: "FleetCapacity | None") -> None:
        """Attach the filterd's capacity accountant so /profile and the
        JSONL stream carry the offered/admitted/headroom block (a later
        server instance in the same process rebinds, like the tracer's
        registry binding)."""
        self._capacity = capacity

    def set_json_path(self, path: "str | None") -> None:
        """--profile-json PATH: append one JSON line per tick."""
        with self._json_lock:
            self._json_path = path

    def add_probe(self, name: str, fn: "Callable[[], float]") -> None:
        """Register a named point-in-time sampler (queue depth,
        in-flight slots, executor saturation) read on each tick. A
        re-registration under the same name replaces the probe (one
        live pipeline per process owns each name)."""
        with self._lock:
            self._probes[name] = fn

    def remove_probe(self, name: str,
                     fn: "Callable[[], float] | None" = None) -> None:
        """Drop a probe; with ``fn`` given, only when it is still the
        registered one (a replaced probe belongs to its new owner)."""
        with self._lock:
            if fn is None or self._probes.get(name) is fn:
                self._probes.pop(name, None)

    def reset(self) -> None:
        """Test hook: disable, uninstall the sink, wipe all state."""
        _trace.TRACER.remove_sink(self.on_span)
        with self._lock:
            self._enabled = False
            self._sample = 0.0
            self._interval_s = DEFAULT_INTERVAL_S
            self._t_enabled = None
            self._stages = {}
            self._child_busy = {}
            self._util = {}
            self._last_tick = None
            self._last_doc = None
            self._probes = {}
            self._registry = None
            self._synced = {}
        with self._json_lock:
            self._json_path = None
        self._capacity = None

    # -- the span fold (tracer sink) ----------------------------------

    def on_span(self, doc: "dict[str, Any]") -> None:
        """Fold one finished span into its stage's SELF busy-seconds
        (duration minus already-folded children — children finish
        before their parent, so their durations are waiting in
        ``_child_busy`` when the parent arrives). A few dict ops +
        float adds under a lock — the whole per-span cost of an
        enabled profiler."""
        name = doc.get("name")
        if not self._enabled or name not in _STAGE_SET:
            return
        dur = doc.get("duration_s")
        if not isinstance(dur, (int, float)):
            return
        span_id = doc.get("span_id")
        parent_id = doc.get("parent_id")
        with self._lock:
            child = (self._child_busy.pop(span_id, 0.0)
                     if isinstance(span_id, str) else 0.0)
            if isinstance(parent_id, str):
                if len(self._child_busy) >= 4096:
                    # Orphaned accumulators (parent ended unfolded or
                    # was cancelled before its children): drop the
                    # oldest half rather than growing forever.
                    for key in list(self._child_busy)[:2048]:
                        del self._child_busy[key]
                self._child_busy[parent_id] = (
                    self._child_busy.get(parent_id, 0.0) + float(dur))
            acc = self._stages.get(name)  # type: ignore[arg-type]
            if acc is None:
                acc = self._stages[name] = [0.0, 0]  # type: ignore[index]
            acc[0] += max(0.0, float(dur) - child)
            acc[1] += 1

    def max_utilization(self) -> "float | None":
        """Peak per-stage utilization over the last completed tick
        window — the saturation signal FleetCapacity.headroom folds
        in. None before the first full window (or when disabled)."""
        with self._lock:
            if not self._enabled or not self._util:
                return None
            return max(self._util.values())

    # -- ticking ------------------------------------------------------

    def tick(self, io: bool = True) -> "dict[str, Any] | None":
        """One profiler tick: derive windowed utilization, sample the
        probes, sync metric families, store (and append, with
        --profile-json) the snapshot doc. Returns the doc, or None
        when disabled. Runs off the event loop (run_ticker hops it
        through a thread; the JSONL append and the /proc refresh are
        file I/O). ``io=False`` (profile_doc's on-demand path, which
        CAN run on the loop) skips both."""
        if not self._enabled:
            return None
        now = time.perf_counter()
        with self._lock:
            stages = {k: (v[0], int(v[1])) for k, v in self._stages.items()}
            last = self._last_tick
            self._last_tick = (now, {k: b for k, (b, _) in stages.items()})
            t_enabled = self._t_enabled if self._t_enabled is not None else now
            probes = list(self._probes.items())
            registry = self._registry
        # Unbias by the LIVE trace-sampling rate: at sample=s only a
        # fraction s of batches carry spans, so observed busy-seconds
        # underestimate true occupancy by that factor.
        rate = _trace.TRACER.sample_rate()
        util: "dict[str, float]" = {}
        if last is not None and now - last[0] > 0:
            dt = now - last[0]
            for k, (busy, _) in stages.items():
                util[k] = (busy - last[1].get(k, 0.0)) / dt / max(rate, 1e-9)
        with self._lock:
            self._util = util
        if registry is not None:
            self._sync_metrics(registry, stages, util)
            if io:
                refresh_process_metrics(registry)
        samples: "dict[str, float]" = {}
        for name, fn in probes:
            try:
                v = fn()
            except Exception:
                continue  # a broken probe must never kill the tick
            if isinstance(v, (int, float)):
                samples[name] = float(v)
        bottleneck = (max(util, key=lambda k: util[k])
                      if any(v > 0 for v in util.values()) else None)
        doc: "dict[str, Any]" = {
            "t": time.time(),
            "enabled": True,
            "sample": rate,
            "wall_s": round(now - t_enabled, 6),
            "stages": {
                k: {"busy_s": round(b, 6), "spans": n,
                    "utilization": round(util.get(k, 0.0), 6)}
                for k, (b, n) in sorted(stages.items())},
            "samples": samples,
            "bottleneck": bottleneck,
        }
        cap = self._capacity
        if cap is not None:
            doc["capacity"] = cap.doc()
        with self._lock:
            self._last_doc = doc
        if io:
            with self._json_lock:
                path = self._json_path
                if path is not None:
                    try:
                        with open(path, "a", encoding="utf-8") as f:
                            f.write(json.dumps(doc) + "\n")
                    except OSError:
                        pass  # best-effort; the pipeline owns the run
        return doc

    def _sync_metrics(self, registry: "Registry",
                      stages: "dict[str, tuple[float, int]]",
                      util: "dict[str, float]") -> None:
        busy = registry.family("klogs_profile_stage_busy_seconds_total")
        spans = registry.family("klogs_profile_stage_spans_total")
        gauge = registry.family("klogs_profile_stage_utilization")
        with self._lock:
            synced = dict(self._synced)
            self._synced = {k: (b, n) for k, (b, n) in stages.items()}
        for k, (b, n) in stages.items():
            last_b, last_n = synced.get(k, (0.0, 0))
            if b > last_b:
                busy.labels(stage=k).inc(b - last_b)
            if n > last_n:
                spans.labels(stage=k).inc(n - last_n)
        for k, u in util.items():
            gauge.labels(stage=k).set(u)

    def profile_doc(self) -> "dict[str, Any]":
        """What GET /profile serves: the last ticked snapshot verbatim
        (so the endpoint and the --profile-json stream can never
        disagree — the /traces parity discipline), computing one on
        demand only when no tick has run yet."""
        with self._lock:
            doc = self._last_doc
            enabled = self._enabled
        if doc is not None:
            return doc
        if not enabled:
            return {"enabled": False, "stages": {}, "samples": {},
                    "bottleneck": None}
        # On-demand (no tick has run yet): this path serves the HTTP
        # handler ON the event loop — no JSONL append, no /proc reads.
        return self.tick(io=False) or {"enabled": False}

    async def run_ticker(self, stop: "asyncio.Event",
                         interval_s: "float | None" = None) -> None:
        """Periodic tick driver (a background task on the collector or
        filterd loop). Stop-aware wait (the blessed poller idiom); one
        final tick at teardown so the JSONL stream always ends with
        the complete picture. The tick itself (probe sampling + file
        append) hops through a worker thread."""
        import asyncio

        # The env interval was validated (loudly) at enable time.
        period = (interval_s if interval_s is not None
                  else self._interval_s)
        while not stop.is_set():
            try:
                await asyncio.wait_for(stop.wait(), timeout=period)
                break
            except asyncio.TimeoutError:
                pass
            await asyncio.to_thread(self.tick)
        await asyncio.to_thread(self.tick)


class FleetCapacity:
    """Offered vs admitted lines + the headroom estimate a filterd
    advertises through Hello (and exports as klogs_fleet_* when a
    registry is bound).

    - *offered*: lines that entered a match RPC (before tenancy
      admission) — the demand signal.
    - *admitted*: lines that produced verdicts (past quota shed and
      the fair gate) — the served signal. offered - admitted over a
      window is the shed pressure an autoscaler should add capacity
      for.
    - *headroom*: in [0, 1], by signal trust (see ``headroom()``):
      1 - admitted_rate / envelope when the operator calibrated one
      (KLOGS_FLEET_CAPACITY_LPS), else 1 - peak stage utilization
      from the live profiler, else the committed OPERATING_POINT.json
      ceiling as the rate envelope, else None (profiler off and no
      envelope) — an advertised guess would be worse than silence.
    """

    def __init__(self, registry: "Registry | None" = None,
                 envelope_lps: "float | None" = None,
                 profiler: "PipelineProfiler | None" = None) -> None:
        self._lock = threading.Lock()
        self._offered = 0
        self._admitted = 0
        # Baseline sample at construction: the first rate read measures
        # against process start, not against its own first call.
        self._hist: "deque[tuple[float, int, int]]" = deque(
            [(time.monotonic(), 0, 0)])
        self._envelope = envelope_lps
        self._envelope_resolved = envelope_lps is not None
        self._envelope_from_ctor = envelope_lps is not None
        self._profiler = profiler
        self._m_offered: Any = None
        self._m_admitted: Any = None
        self._m_headroom: Any = None
        if registry is not None:
            self._m_offered = registry.family(
                "klogs_fleet_offered_lines_total")
            self._m_admitted = registry.family(
                "klogs_fleet_admitted_lines_total")
            self._m_headroom = registry.family("klogs_fleet_headroom")

    # -- accounting ---------------------------------------------------

    def note_offered(self, n: int) -> None:
        if n <= 0:
            return
        with self._lock:
            self._offered += n
        if self._m_offered is not None:
            self._m_offered.inc(n)

    def note_admitted(self, n: int) -> None:
        if n <= 0:
            return
        with self._lock:
            self._admitted += n
        if self._m_admitted is not None:
            self._m_admitted.inc(n)

    @property
    def offered(self) -> int:
        with self._lock:
            return self._offered

    @property
    def admitted(self) -> int:
        with self._lock:
            return self._admitted

    def _roll(self, now: float) -> None:
        with self._lock:
            if (not self._hist
                    or now - self._hist[-1][0] >= _CAPACITY_SAMPLE_S):
                self._hist.append((now, self._offered, self._admitted))
            while (len(self._hist) > 1
                   and now - self._hist[0][0] > _CAPACITY_WINDOW_S):
                self._hist.popleft()

    def rates(self) -> "tuple[float | None, float | None]":
        """(offered lines/s, admitted lines/s) over the rolling window:
        LIVE totals against the oldest retained sample, so the rate is
        current at read time (a Hello between history samples must not
        advertise a stale rate). (None, None) until a baseline sample
        has aged past the minimum spacing."""
        now = time.monotonic()
        self._roll(now)
        with self._lock:
            if not self._hist:
                return None, None
            t0, off0, adm0 = self._hist[0]
            off1, adm1 = self._offered, self._admitted
        dt = now - t0
        if dt < _CAPACITY_SAMPLE_S / 2:
            return None, None
        return (off1 - off0) / dt, (adm1 - adm0) / dt

    # -- the signal ---------------------------------------------------

    def envelope_lps(self) -> "float | None":
        """The rate envelope, in trust order: KLOGS_FLEET_CAPACITY_LPS
        when set (the deployment's own calibration — an operator's
        number beats any inference), else — only as the
        better-than-nothing default for processes with no profiler
        signal — the best measured lines/s from the committed
        OPERATING_POINT.json sweep. ``trusted`` says which case this
        is: the file's ceiling was measured on the sweep's hardware,
        not necessarily THIS deployment's, so live utilization
        outranks it (see headroom)."""
        from klogs_tpu.utils.env import is_set, positive_float

        if is_set("KLOGS_FLEET_CAPACITY_LPS"):
            return positive_float("KLOGS_FLEET_CAPACITY_LPS", 0.0)
        if self._envelope_resolved:
            return self._envelope
        self._envelope_resolved = True
        self._envelope = _operating_point_lps()
        return self._envelope

    def headroom(self) -> "float | None":
        """1 - saturation, clamped to [0, 1], by signal trust:

        1. An explicit envelope (KLOGS_FLEET_CAPACITY_LPS, or one
           passed to the constructor): 1 - admitted_rate / envelope.
           Concurrency-free, directly HPA-consumable, and the
           operator calibrated it for THIS deployment.
        2. Else the profiler's peak stage utilization, clamped at 1
           (utilization is concurrency-inclusive: 16 in-flight RPCs
           legitimately read >1, which means 'saturated', not '16x').
        3. Else the committed OPERATING_POINT.json ceiling — measured
           on the sweep's hardware, not necessarily this one's, so it
           only stands in when no live signal exists at all.
        4. None when nothing exists — an advertised guess would be
           worse than silence."""
        from klogs_tpu.utils.env import is_set

        explicit = (is_set("KLOGS_FLEET_CAPACITY_LPS")
                    or (self._envelope_resolved
                        and self._envelope is not None
                        and self._envelope_from_ctor))
        if explicit:
            cap = self.envelope_lps()
            if cap:
                # Before the rolling window has aged (process just
                # started) the observed rate is ~0 by definition — a
                # fresh server advertises full rate-headroom.
                _, admitted_lps = self.rates()
                return max(0.0, min(1.0,
                                    1.0 - (admitted_lps or 0.0) / cap))
        prof = self._profiler if self._profiler is not None else PROFILER
        util = prof.max_utilization()
        if util is not None:
            return max(0.0, 1.0 - min(1.0, util))
        cap = self.envelope_lps()
        if cap:
            _, admitted_lps = self.rates()
            return max(0.0, min(1.0, 1.0 - (admitted_lps or 0.0) / cap))
        return None

    def doc(self) -> "dict[str, Any]":
        """The capacity block Hello (and /profile) carries."""
        offered_lps, admitted_lps = self.rates()
        head = self.headroom()
        if self._m_headroom is not None and head is not None:
            self._m_headroom.set(head)
        with self._lock:
            offered, admitted = self._offered, self._admitted
        return {
            "offered_lines": offered,
            "admitted_lines": admitted,
            "offered_lps": (round(offered_lps, 1)
                            if offered_lps is not None else None),
            "admitted_lps": (round(admitted_lps, 1)
                             if admitted_lps is not None else None),
            "headroom": head,
        }


def _operating_point_lps() -> "float | None":
    """Best measured lines/s across the committed operating-point
    sweep (OPERATING_POINT.json at the repo root, when present — a
    deployed package without it relies on KLOGS_FLEET_CAPACITY_LPS)."""
    path = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        os.pardir, "OPERATING_POINT.json")
    try:
        with open(path, encoding="utf-8") as f:
            doc = json.load(f)
    except (OSError, ValueError):
        return None
    best = 0.0
    try:
        for entry in doc:
            for run in entry.get("runs", []):
                lps = run.get("lps")
                if isinstance(lps, (int, float)):
                    best = max(best, float(lps))
    except (TypeError, AttributeError):
        return None
    return best or None


# Process-global profiler: what --profile-json, the /profile endpoint,
# and the pipeline layers' probes use by default (one pipeline story
# per process, mirroring obs.trace.TRACER).
PROFILER = PipelineProfiler()
