"""klogs_tpu.obs — the observability subsystem.

Dependency-free metrics core (Counter/Gauge/Histogram/Registry),
Prometheus text exposition, JSON snapshots, and the /metrics + /healthz
HTTP sidecar. The metric inventory (names, types, help, buckets) lives
in obs.inventory and is linted against docs/OBSERVABILITY.md by
tools/check_metrics_docs.py.
"""

from klogs_tpu.obs.expo import render, snapshot
from klogs_tpu.obs.http import Health, MetricsHTTPServer
from klogs_tpu.obs.inventory import SPECS, register_all
from klogs_tpu.obs.metrics import (
    REGISTRY,
    Counter,
    Family,
    Gauge,
    Histogram,
    Registry,
)

__all__ = [
    "REGISTRY", "Registry", "Family", "Counter", "Gauge", "Histogram",
    "Health", "MetricsHTTPServer", "SPECS", "register_all", "render",
    "snapshot",
]
