"""The metric inventory: every metric name this codebase can register.

One table, five instrumented layers (engine, coalescer, sink, fanout,
RPC) plus process-level info. Instrumented modules obtain families via
``registry.family(name)`` which resolves through SPECS, so a name used
anywhere in the code is guaranteed to carry the type/help/buckets
documented here — and tools/check_metrics_docs.py fails tier-1 when a
SPECS entry is missing from docs/OBSERVABILITY.md (or vice versa).

Label cardinality rule: labels must be bounded by DEPLOYMENT SHAPE
(method names, pod set, client hosts), never by traffic content (line
text, pattern hits). Per-pod labels are acceptable at the reference's
scale (hundreds of pods per collector); anything keyed by raw peer
address is normalized to the host (ports churn per connection).
"""

from typing import TYPE_CHECKING

from klogs_tpu.obs.metrics import LATENCY_BUCKETS

if TYPE_CHECKING:
    from klogs_tpu.obs.metrics import Registry

# Power-of-two ladders matching the engine's bucketing discipline.
WIDTH_BUCKETS = (128, 256, 512, 1024, 2048, 4096, 8192,
                 16384, 32768, 65536, 131072)
GROUP_MEMBER_BUCKETS = (1, 2, 4, 8, 16, 32, 64, 128, 256)
GROUP_LINE_BUCKETS = (64, 256, 1024, 4096, 8192, 16384,
                      65536, 262144, 1048576)
# Index-build extraction counts (clauses/factors per pattern) and the
# candidate-narrowing ratio ladder (fractions of lines x groups).
PATTERN_EXTRACT_BUCKETS = (0, 1, 2, 4, 8, 16, 32)
RATIO_BUCKETS = (0.001, 0.01, 0.05, 0.1, 0.25, 0.5, 0.75, 1.0)


def _m(mtype: str, help: str, labels: tuple = (),
       buckets: "tuple | None" = None,
       bounds: "dict | None" = None) -> dict:
    """``bounds`` declares, per label, how its value domain is bounded
    (the metric-cardinality pass enforces one entry per label):
    ``enum`` — values are code-chosen literals; ``config`` — values
    come from deployment shape (flags, fleet membership, pod set);
    ``evictable:<KLOGS_KNOB>`` — values derive from runtime input,
    live-series count capped by the knob, and the family must have a
    remove path for evicted entities. docs/OBSERVABILITY.md "Label
    cardinality rules" documents every non-enum label."""
    spec = {"type": mtype, "help": help}
    if labels:
        spec["labels"] = tuple(labels)
    if buckets is not None:
        spec["buckets"] = tuple(buckets)
    if bounds is not None:
        spec["bounds"] = dict(bounds)
    return spec


SPECS: dict[str, dict] = {
    # -- process ------------------------------------------------------
    "klogs_build_info": _m(
        "gauge", "Constant 1, labeled with the build version.",
        labels=("version",), bounds={"version": "config"}),
    "klogs_process_uptime_seconds": _m(
        "gauge", "Seconds since this process started (refreshed per "
        "/metrics scrape, --stats-json dump, and profiler tick — no "
        "node exporter needed for headroom math)."),
    "klogs_process_rss_bytes": _m(
        "gauge", "Current resident set size of this process in bytes "
        "(refreshed like klogs_process_uptime_seconds)."),

    # -- pipeline profiler (obs/profiler.py) --------------------------
    # The `stage` label is the fixed span-name catalog
    # (obs.profiler.STAGES) — a code-chosen enum.
    "klogs_profile_stage_busy_seconds_total": _m(
        "counter", "Cumulative busy-seconds folded from finished "
        "spans per pipeline stage (the profiler's utilization "
        "numerator; synced once per tick).", labels=("stage",),
        bounds={"stage": "enum"}),
    "klogs_profile_stage_spans_total": _m(
        "counter", "Finished spans folded per pipeline stage by the "
        "profiler.", labels=("stage",), bounds={"stage": "enum"}),
    "klogs_profile_stage_utilization": _m(
        "gauge", "Rolling per-stage utilization over the last profiler "
        "tick window: busy-seconds per wall-second, unbiased by the "
        "trace sampling rate. May exceed 1.0 for stages that run "
        "concurrently (N in-flight RPCs).", labels=("stage",),
        bounds={"stage": "enum"}),

    # -- fleet capacity (the autoscaling signal) ----------------------
    # Server-side (filterd): unlabeled totals + the advertised
    # headroom. Collector-side: the sharded client re-exports what each
    # endpoint's Hello advertised, labeled by endpoint (the --remote
    # fleet — deployment shape).
    "klogs_fleet_offered_lines_total": _m(
        "counter", "Lines that entered a match RPC on this filterd "
        "(before tenancy admission) — the demand signal."),
    "klogs_fleet_admitted_lines_total": _m(
        "counter", "Lines that produced verdicts on this filterd "
        "(past quota shed and the fair gate). offered - admitted is "
        "the shed pressure an autoscaler should add capacity for."),
    "klogs_fleet_headroom": _m(
        "gauge", "This filterd's advertised headroom estimate in "
        "[0, 1], by signal trust: 1 - admitted rate / envelope when "
        "KLOGS_FLEET_CAPACITY_LPS calibrates one, else 1 - peak stage "
        "utilization from the live profiler, else the committed "
        "operating-point ceiling. Advertised through Hello; see "
        "docs/OBSERVABILITY.md Fleet telemetry."),
    "klogs_fleet_endpoint_headroom": _m(
        "gauge", "Headroom last advertised by each filterd endpoint's "
        "Hello, re-exported by the sharded client for an HPA to "
        "consume.", labels=("endpoint",), bounds={"endpoint": "config"}),
    "klogs_fleet_endpoint_offered_lines_total": _m(
        "counter", "Offered-lines total last advertised by each "
        "endpoint's Hello, re-exported collector-side (advanced by "
        "observed deltas; a restarted server restarts its series).",
        labels=("endpoint",), bounds={"endpoint": "config"}),
    "klogs_fleet_endpoint_admitted_lines_total": _m(
        "counter", "Admitted-lines total last advertised by each "
        "endpoint's Hello, re-exported collector-side like the "
        "offered twin.", labels=("endpoint",),
        bounds={"endpoint": "config"}),

    # -- sink layer (FilteredSink / FilterStats view) -----------------
    "klogs_sink_lines_total": _m(
        "counter", "Lines that entered the filter stage."),
    "klogs_sink_lines_matched_total": _m(
        "counter", "Lines the filter kept (written to the sink)."),
    "klogs_sink_bytes_in_total": _m(
        "counter", "Raw bytes entering the filter stage."),
    "klogs_sink_bytes_out_total": _m(
        "counter", "Bytes written after filtering."),
    "klogs_sink_batches_total": _m(
        "counter", "Filter batches flushed."),
    "klogs_sink_batch_latency_seconds": _m(
        "histogram", "End-to-end batch latency: enqueue to verdicts, "
        "sink-observed.", buckets=LATENCY_BUCKETS),
    "klogs_sink_deadline_flush_total": _m(
        "counter", "Flushes forced by the follow-mode deadline rather "
        "than batch-size."),

    # -- coalescer layer (AsyncFilterService) -------------------------
    "klogs_coalescer_queue_depth": _m(
        "gauge", "Caller batches waiting to coalesce into a group."),
    "klogs_coalescer_pending_lines": _m(
        "gauge", "Lines waiting to coalesce into a group."),
    "klogs_coalescer_queue_wait_seconds": _m(
        "histogram", "Per-caller wait from enqueue to device dispatch "
        "(coalesce window + backpressure).", buckets=LATENCY_BUCKETS),
    "klogs_coalescer_groups_total": _m(
        "counter", "Coalesced groups dispatched to the engine."),
    "klogs_coalescer_group_members": _m(
        "histogram", "Caller batches merged per coalesced group.",
        buckets=GROUP_MEMBER_BUCKETS),
    "klogs_coalescer_group_lines": _m(
        "histogram", "Lines per coalesced group.",
        buckets=GROUP_LINE_BUCKETS),
    "klogs_coalescer_group_splits_total": _m(
        "counter", "Groups split because the combined payload would "
        "exceed int32 offsets (2 GiB)."),
    "klogs_coalescer_backpressure_wait_seconds": _m(
        "histogram", "Wait for an in-flight slot (max_in_flight "
        "semaphore) before dispatch.", buckets=LATENCY_BUCKETS),
    "klogs_coalescer_dispatch_seconds": _m(
        "histogram", "Device dispatch (enqueue) cost per group — NOT "
        "the round trip; see klogs_engine_device_batch_seconds.",
        buckets=LATENCY_BUCKETS),

    # -- engine layer (NFAEngineFilter / tune) ------------------------
    "klogs_engine_device_batch_seconds": _m(
        "histogram", "Dispatch-to-verdicts-fetched device round trip "
        "per group.", buckets=LATENCY_BUCKETS),
    "klogs_engine_compile_total": _m(
        "counter", "New (width, rows) batch geometries first seen by "
        "the engine — each is one jit trace/compile."),
    "klogs_engine_bucket_width_bytes": _m(
        "histogram", "Padded line-width bucket per dispatched "
        "sub-batch.", buckets=WIDTH_BUCKETS),
    "klogs_engine_pad_bytes_total": _m(
        "counter", "Padding waste: bucketed tensor bytes minus payload "
        "bytes."),
    "klogs_engine_payload_bytes_total": _m(
        "counter", "Useful payload bytes packed into device batches."),
    "klogs_engine_prefilter_lines_total": _m(
        "counter", "Lines through the gated (prefiltered) kernel."),
    "klogs_engine_prefilter_candidates_total": _m(
        "counter", "Prefilter candidate lines (tiles ran the scan)."),
    "klogs_engine_prefilter_tiles_total": _m(
        "counter", "Kernel tiles considered by the prefilter gate."),
    "klogs_engine_prefilter_tiles_live_total": _m(
        "counter", "Kernel tiles that actually ran the scan loop."),
    "klogs_engine_tune_runs_total": _m(
        "counter", "Autotune sweeps completed (ops.tune.tune_grouped)."),
    "klogs_engine_tune_best_lines_per_second": _m(
        "gauge", "Winning configuration's measured throughput from the "
        "last autotune sweep."),

    # -- regex index (IndexedFilter / compiler grouping) --------------
    "klogs_prefilter_pattern_clauses": _m(
        "histogram", "Mandatory pair-CNF clauses extracted per pattern "
        "at index build (0 = pattern contributes no clause gating).",
        buckets=PATTERN_EXTRACT_BUCKETS),
    "klogs_prefilter_pattern_factors": _m(
        "histogram", "Mandatory literal factors extracted per pattern "
        "at index build (0 = pattern rides the always-candidate path).",
        buckets=PATTERN_EXTRACT_BUCKETS),
    "klogs_prefilter_narrowing_ratio": _m(
        "histogram", "Per-batch candidate-narrowing ratio: candidate "
        "(line, group) scan units over lines x groups — 1.0 means the "
        "index ruled nothing out, lower is better.",
        buckets=RATIO_BUCKETS),
    "klogs_prefilter_groups": _m(
        "gauge", "Pattern groups compiled by the thousand-pattern "
        "index (grouping bounds per-group DFA construction)."),
    "klogs_prefilter_reguard_total": _m(
        "counter", "Guard factors banned by the adaptive re-guard: an "
        "IndexedFilter measured these factors in more than "
        "KLOGS_INDEX_DENSE_RATIO of swept lines after its probation "
        "window and rebuilt the index on next-best guard clauses."),
    "klogs_prefilter_table_cache_events_total": _m(
        "counter", "On-disk DFA table cache outcomes during index "
        "compiles: hit (table loaded), miss (determinized fresh), "
        "evict (LRU removal past KLOGS_DFA_CACHE_MB).",
        labels=("event",), bounds={"event": "enum"}),

    # -- literal sweep (device/host narrowing stage) ------------------
    "klogs_sweep_batches_total": _m(
        "counter", "Batches narrowed by the literal sweep, by which "
        "stage ran: device (fused on-device sweep, ops/sweep.py) or "
        "host (host factor sweep).", labels=("path",),
        bounds={"path": "enum"}),
    "klogs_sweep_lines_total": _m(
        "counter", "Lines swept by the literal sweep, by stage.",
        labels=("path",), bounds={"path": "enum"}),
    "klogs_sweep_candidate_lines_total": _m(
        "counter", "Lines the sweep could NOT rule out (at least one "
        "candidate group), by stage. candidate/swept is the live "
        "narrowing ratio.", labels=("path",), bounds={"path": "enum"}),
    "klogs_sweep_seconds": _m(
        "histogram", "Sweep-stage latency per batch, by stage.",
        labels=("path",), buckets=LATENCY_BUCKETS,
        bounds={"path": "enum"}),
    "klogs_sweep_impl_batches_total": _m(
        "counter", "Batches narrowed by the literal sweep, by "
        "IMPLEMENTATION: device (fused on-device sweep), native (SIMD "
        "kernel in the C extension, the host default), or numpy (the "
        "vectorized fallback when no toolchain or KLOGS_NATIVE_SIMD="
        "off).", labels=("impl",), bounds={"impl": "enum"}),
    "klogs_sweep_fallback_total": _m(
        "counter", "Device-sweep degrades: build or kernel failures "
        "that dropped a batch (and every later one) to the fallback "
        "path."),

    # -- batched group scan (indexed engine confirm stage) ------------
    "klogs_groupscan_batches_total": _m(
        "counter", "Slabs that ran the candidate group-scan (confirm) "
        "stage, by implementation: native (one batched MultiDFA "
        "group_scan call for every DFA-backed group) or python (the "
        "per-group dispatch loop — the KLOGS_NATIVE_GROUPSCAN=off / "
        "no-toolchain fallback and parity oracle).",
        labels=("impl",), bounds={"impl": "enum"}),
    "klogs_groupscan_rows_total": _m(
        "counter", "Rows entering the group-scan stage with at least "
        "one candidate DFA-backed group, by implementation.",
        labels=("impl",), bounds={"impl": "enum"}),
    "klogs_groupscan_cells_total": _m(
        "counter", "Candidate (row, group) cells the confirm stage "
        "actually scanned, by implementation — below the sweep's "
        "candidate-cell count when early-out skipped cells whose row "
        "an earlier group already accepted.",
        labels=("impl",), bounds={"impl": "enum"}),
    "klogs_groupscan_seconds": _m(
        "histogram", "Group-scan stage latency per slab, by "
        "implementation.", labels=("impl",), buckets=LATENCY_BUCKETS,
        bounds={"impl": "enum"}),
    "klogs_groupscan_fallback_total": _m(
        "counter", "Batched group-scan degrades: a native kernel "
        "failure dropped this process permanently to the per-group "
        "Python loop."),
    "klogs_sweep_bypass_total": _m(
        "counter", "Adaptive sweep bypasses: an IndexedFilter observed "
        "a narrowing ratio above KLOGS_INDEX_BYPASS_RATIO after its "
        "probation window and switched itself to scan-all."),

    # -- fanout layer (FanoutRunner) ----------------------------------
    "klogs_fanout_active_streams": _m(
        "gauge", "Log streams currently open."),
    "klogs_fanout_stream_bytes_total": _m(
        "counter", "Bytes received per container stream.",
        labels=("pod", "container"),
        bounds={"pod": "config", "container": "config"}),
    "klogs_fanout_reconnects_total": _m(
        "counter", "Follow-mode stream reconnect attempts.",
        labels=("pod", "container"),
        bounds={"pod": "config", "container": "config"}),
    "klogs_fanout_stream_errors_total": _m(
        "counter", "Streams that ended with a terminal error."),
    "klogs_fanout_backpressure_stalls_total": _m(
        "counter", "Sink writes that blocked longer than the stall "
        "threshold (downstream backpressure)."),

    # -- source layer (sources/*: replay, archive, socket) ------------
    "klogs_source_bytes_total": _m(
        "counter", "Bytes delivered by non-kube sources, by source "
        "kind (file, archive, socket).", labels=("kind",),
        bounds={"kind": "enum"}),
    "klogs_source_rotations_total": _m(
        "counter", "File rotations/truncations detected by the replay "
        "source (inode change or shrink at the watched path)."),
    "klogs_source_archive_members_total": _m(
        "counter", "Archive members (rotated/compressed files) fully "
        "decoded by the backfill source."),
    "klogs_source_errors_total": _m(
        "counter", "Source open/read failures (SourceError), by "
        "source kind.", labels=("kind",), bounds={"kind": "enum"}),
    "klogs_source_connections_total": _m(
        "counter", "Connections accepted by the socket source "
        "(KLOGS_SOCKET_MAX_CONNS bounds the concurrent set)."),

    # -- resilience layer (retry/breaker/faults/degrade) --------------
    "klogs_retry_attempts_total": _m(
        "counter", "Retries performed by the shared resilience policy, "
        "by call site (kube, fanout, rpc@endpoint — RPC sites carry "
        "the endpoint so a sharded fleet's servers stay "
        "distinguishable).", labels=("site",), bounds={"site": "config"}),
    "klogs_breaker_state": _m(
        "gauge", "Circuit-breaker state: 0=closed, 1=open, 2=half-open.",
        labels=("breaker",), bounds={"breaker": "config"}),
    "klogs_faults_injected_total": _m(
        "counter", "Chaos faults fired, by registered fault point "
        "(test API or KLOGS_FAULTS).", labels=("point",),
        bounds={"point": "config"}),
    "klogs_filter_degraded_batches_total": _m(
        "counter", "Sink flushes degraded because the filter service "
        "was unavailable, by --on-filter-error action.",
        labels=("action",), bounds={"action": "enum"}),
    "klogs_filter_degraded_lines_total": _m(
        "counter", "Lines written unfiltered (action=pass) or dropped "
        "(action=drop) while the filter service was unavailable.",
        labels=("action",), bounds={"action": "enum"}),

    # -- shard tier (ShardedFilterClient over N filterds) -------------
    # Endpoint labels are the --remote fleet: deployment shape (a
    # handful of servers), never traffic content.
    "klogs_shard_batches_total": _m(
        "counter", "Batches resolved by each filterd endpoint (the "
        "winning attempt only — hedge losers are cancelled, never "
        "counted).", labels=("endpoint",),
        bounds={"endpoint": "config"}),
    "klogs_shard_hedges_total": _m(
        "counter", "Hedged duplicate dispatches launched against a "
        "sibling after the primary exceeded the hedge deadline, by "
        "sibling endpoint.", labels=("endpoint",),
        bounds={"endpoint": "config"}),
    "klogs_shard_reroutes_total": _m(
        "counter", "Batches routed away from an endpoint: skipped as "
        "primary (breaker open / not ready) or failed over after a "
        "terminal attempt error.", labels=("endpoint", "reason"),
        bounds={"endpoint": "config", "reason": "enum"}),
    "klogs_shard_endpoint_ready": _m(
        "gauge", "Endpoint readiness as last observed by the /readyz "
        "prober (1 ready, 0 draining or unreachable).",
        labels=("endpoint",), bounds={"endpoint": "config"}),
    "klogs_shard_endpoint_weight": _m(
        "gauge", "Effective routing weight (headroom-learned, "
        "staleness-decayed toward 1.0) the weighted round-robin "
        "actually uses for each endpoint right now.",
        labels=("endpoint",), bounds={"endpoint": "config"}),
    "klogs_fleet_membership_events_total": _m(
        "counter", "Live-membership changes applied by the endpoint "
        "resolver: add (endpoint joined, unverified), remove "
        "(endpoint retired), error (poll failed or snapshot rejected "
        "— fleet kept as-is).", labels=("action",),
        bounds={"action": "enum"}),
    "klogs_fleet_membership_size": _m(
        "gauge", "Endpoints currently in the sharded client's fleet "
        "(verified or not; quarantined endpoints still count until "
        "the resolver removes them)."),

    # -- adaptive tuning (ops/tune.py AdaptiveController) -------------
    "klogs_tune_steps_total": _m(
        "counter", "Operating-point adjustments the adaptive "
        "controller applied, by parameter (coalesce_lines, "
        "max_in_flight) and direction (up, down).",
        labels=("param", "direction"),
        bounds={"param": "enum", "direction": "enum"}),
    "klogs_tune_value": _m(
        "gauge", "Current value of each controller-managed parameter "
        "(equals the fixed flag value while KLOGS_TUNE=off).",
        labels=("param",), bounds={"param": "enum"}),

    # -- tenancy (multi-set registry, service/tenancy.py) -------------
    # The `set` label is a pattern-set fingerprint: bounded by the
    # registry capacity KLOGS_TENANT_MAX_SETS (a deployment knob), so
    # per-set series obey the deployment-shape cardinality rule even
    # though fingerprints derive from collector invocations.
    "klogs_tenant_sets": _m(
        "gauge", "Pattern sets currently registered (compiled engines "
        "live in this process)."),
    "klogs_tenant_registrations_total": _m(
        "counter", "Register RPC outcomes: new (engine built) or "
        "shared (content-addressed reuse of a live engine).",
        labels=("outcome",), bounds={"outcome": "enum"}),
    "klogs_tenant_engine_builds_total": _m(
        "counter", "Engines compiled by the registry. Two tenants "
        "registering the same fingerprint advance this ONCE — the "
        "content-addressed-sharing acceptance counter."),
    "klogs_tenant_evictions_total": _m(
        "counter", "Registered sets evicted, by reason: idle (past "
        "KLOGS_TENANT_IDLE_S), capacity (LRU past "
        "KLOGS_TENANT_MAX_SETS), shutdown.", labels=("reason",),
        bounds={"reason": "enum"}),
    "klogs_tenant_shed_total": _m(
        "counter", "Batches shed over the per-set pending-line quota "
        "(KLOGS_TENANT_QUOTA_LINES); the client degrades them through "
        "--on-filter-error — never a silent drop.", labels=("set",),
        bounds={"set": "evictable:KLOGS_TENANT_MAX_SETS"}),
    "klogs_tenant_pending_lines": _m(
        "gauge", "Lines admitted or awaiting admission per set lane "
        "(the quota accounting the shed decision reads).",
        labels=("set",),
        bounds={"set": "evictable:KLOGS_TENANT_MAX_SETS"}),
    "klogs_tenant_lines_total": _m(
        "counter", "Lines admitted (past quota + fair gate) per set "
        "lane.", labels=("set",),
        bounds={"set": "evictable:KLOGS_TENANT_MAX_SETS"}),
    "klogs_tenant_admission_wait_seconds": _m(
        "histogram", "Wait for a weighted-fair admission slot before a "
        "batch may dispatch — the fairness latency an abusive sibling "
        "inflicts.", buckets=LATENCY_BUCKETS),

    # -- tracing / flight recorder (obs.trace) ------------------------
    "klogs_trace_spans_total": _m(
        "counter", "Finished sampled spans recorded by the tracer "
        "(KLOGS_TRACE_SAMPLE head sampling; see docs/OBSERVABILITY.md "
        "Tracing)."),
    "klogs_flight_dumps_total": _m(
        "counter", "Flight-recorder dumps written, by trigger reason "
        "(breaker-open, filter-degrade, sweep-fallback, "
        "abort-escalation).", labels=("reason",), bounds={"reason": "enum"}),

    # -- RPC layer (filterd gRPC server) ------------------------------
    "klogs_rpc_requests_total": _m(
        "counter", "RPCs received, by method.", labels=("method",),
        bounds={"method": "enum"}),
    "klogs_rpc_errors_total": _m(
        "counter", "RPCs that failed (including aborts), by method.",
        labels=("method",), bounds={"method": "enum"}),
    "klogs_rpc_request_seconds": _m(
        "histogram", "Server-side RPC handling latency, by method.",
        labels=("method",), buckets=LATENCY_BUCKETS,
        bounds={"method": "enum"}),
    "klogs_rpc_client_requests_total": _m(
        "counter", "RPCs per client HOST (peer address normalized to "
        "drop the per-connection port).", labels=("client",),
        bounds={"client": "config"}),
}


def register_all(registry: "Registry") -> None:
    """Instantiate every inventory family in ``registry`` so a scrape
    exposes the full instrument panel (zero-valued where idle) from the
    first request — an operator's dashboard never has to guess whether
    a missing series means 'no traffic yet' or 'not instrumented'."""
    for name in SPECS:
        registry.family(name)
