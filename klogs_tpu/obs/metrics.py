"""Dependency-free, thread-safe metrics core.

The instrument panel the ROADMAP's production north star needs on the
collector -> framer -> coalescer -> device-kernel -> sink pipeline:
``Counter``, ``Gauge``, and ``Histogram`` (fixed buckets + a bounded
reservoir so exact percentiles stay queryable in-process), organized
into named families with optional label children, owned by a
``Registry`` that the Prometheus exposition (obs.expo) and the HTTP
sidecar (obs.http) walk.

Design rules:

- One lock per child, taken only around the few-word state mutation —
  instrumentation rides the per-BATCH path (thousands of lines per
  call), never the per-line path, so contention is negligible and the
  device-pipelined hot loop stays within its <2% budget.
- Families are get-or-create by name: a second ``register`` of the same
  name returns the existing family (and raises on a conflicting type or
  label set), so independent pipeline stages can share one process
  registry without coordination.
- Metric NAMES and their help/type/buckets live in ONE place
  (obs.inventory.SPECS); call sites say ``registry.family(name)`` and
  can never drift from the documented inventory — the
  tools/check_metrics_docs.py lint enforces docs/OBSERVABILITY.md
  against the same SPECS table.
"""

import random
import threading
import time
from typing import Any, Iterable

# Bounded reservoir per histogram child: constant memory over unbounded
# series while p50/p99 stay statistically sound (moved here from
# filters.base, which now views these histograms through FilterStats).
RESERVOIR_SIZE = 8192

# Latency histograms share one bucket ladder (seconds): sub-ms device
# dispatches up through multi-second stalls.
LATENCY_BUCKETS = (0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
                   0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0)


def _percentile(xs: list[float], q: float) -> float:
    if not xs:
        return 0.0
    xs = sorted(xs)
    idx = min(len(xs) - 1, max(0, round(q / 100 * (len(xs) - 1))))
    return xs[idx]


class _Reservoir:
    """Bounded uniform sample over an unbounded series."""

    __slots__ = ("xs", "count", "_rng")

    def __init__(self) -> None:
        self.xs: list[float] = []
        self.count = 0
        self._rng = random.Random(0)

    def add(self, x: float) -> None:
        self.count += 1
        if len(self.xs) < RESERVOIR_SIZE:
            self.xs.append(x)
        else:  # reservoir sampling: uniform over all samples so far
            j = self._rng.randrange(self.count)
            if j < RESERVOIR_SIZE:
                self.xs[j] = x


class Counter:
    """Monotonic counter. ``inc`` with a negative amount raises — a
    decreasing counter silently corrupts every rate() over it."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._value = 0.0

    def inc(self, amount: float = 1) -> None:
        if amount < 0:
            raise ValueError(f"counter increment must be >= 0, got {amount}")
        with self._lock:
            self._value += amount

    @property
    def value(self) -> float:
        with self._lock:
            return self._value


class Gauge:
    """Point-in-time value (queue depth, active streams)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._value = 0.0

    def set(self, value: float) -> None:
        with self._lock:
            self._value = value

    def inc(self, amount: float = 1) -> None:
        with self._lock:
            self._value += amount

    def dec(self, amount: float = 1) -> None:
        with self._lock:
            self._value -= amount

    @property
    def value(self) -> float:
        with self._lock:
            return self._value


class Histogram:
    """Fixed-bucket histogram plus a bounded reservoir.

    Buckets serve the Prometheus exposition (cumulative ``le`` counts);
    the reservoir serves in-process percentile queries (the --stats
    summary), replacing the ad-hoc reservoirs FilterStats used to keep
    as a parallel bookkeeping path.
    """

    def __init__(self, buckets: Iterable[float] = LATENCY_BUCKETS) -> None:
        self._lock = threading.Lock()
        self.buckets = tuple(sorted(buckets))
        if not self.buckets:
            raise ValueError("histogram needs at least one bucket bound")
        self.bucket_counts = [0] * len(self.buckets)
        self.sum = 0.0
        self.count = 0
        self._reservoir = _Reservoir()
        # Last exemplar per bucket (index len(buckets) = +Inf): the
        # trace link the OpenMetrics exposition attaches to the bucket
        # sample, so a latency outlier points straight at its trace.
        self._exemplars: "dict[int, tuple[dict, float, float]]" = {}

    def observe(self, value: float,
                exemplar: "dict | None" = None) -> None:
        with self._lock:
            self.count += 1
            self.sum += value
            hit = len(self.buckets)  # +Inf
            for i, bound in enumerate(self.buckets):
                if value <= bound:
                    self.bucket_counts[i] += 1
                    hit = i
                    break
            if exemplar is not None:
                self._exemplars[hit] = (exemplar, value, time.time())
            self._reservoir.add(value)

    def exemplars(self) -> "dict[int, tuple[dict, float, float]]":
        """bucket index -> (labels, observed value, unix ts); index
        len(buckets) is the +Inf bucket."""
        with self._lock:
            return dict(self._exemplars)

    def percentile(self, q: float) -> float:
        with self._lock:
            return _percentile(self._reservoir.xs, q)

    def snapshot(self) -> tuple[list[int], float, int]:
        """(per-bucket counts, sum, count) — one consistent view."""
        with self._lock:
            return list(self.bucket_counts), self.sum, self.count


_TYPES = {"counter": Counter, "gauge": Gauge, "histogram": Histogram}


class Family:
    """One named metric with zero or more label children.

    Without ``labelnames`` the family IS its single child: ``inc`` /
    ``set`` / ``observe`` / ``value`` / ``count`` / ``percentile``
    delegate to an eagerly-created default child, so the common
    unlabeled case needs no ``labels()`` hop and always exposes a
    (possibly zero) sample. With labelnames, children are created on
    first ``labels(...)`` and the bare family refuses samples.
    """

    def __init__(self, name: str, mtype: str, help: str = "",
                 labelnames: tuple = (),
                 buckets: "Iterable[float] | None" = None) -> None:
        if mtype not in _TYPES:
            raise ValueError(f"unknown metric type {mtype!r}")
        self.name = name
        self.type = mtype
        self.help = help
        self.labelnames = tuple(labelnames)
        self._buckets = buckets
        self._lock = threading.Lock()
        self._children: dict[tuple, object] = {}
        if not self.labelnames:
            self._children[()] = self._make_child()

    def _make_child(self) -> Any:
        if self.type == "histogram":
            return Histogram(self._buckets or LATENCY_BUCKETS)
        return _TYPES[self.type]()

    def labels(self, **labelvalues: object) -> Any:
        if set(labelvalues) != set(self.labelnames):
            raise ValueError(
                f"{self.name} takes labels {self.labelnames}, "
                f"got {tuple(labelvalues)}")
        key = tuple(str(labelvalues[n]) for n in self.labelnames)
        with self._lock:
            child = self._children.get(key)
            if child is None:
                child = self._children[key] = self._make_child()
            return child

    def remove(self, **labelvalues: object) -> bool:
        """Drop one labeled child (and its series from the exposition).
        For label values with a bounded LIFETIME churn but bounded
        LIVE count — e.g. the tenancy registry's per-set series, where
        evicted fingerprints would otherwise accumulate dead series
        forever. Returns False when the child never existed."""
        if set(labelvalues) != set(self.labelnames):
            raise ValueError(
                f"{self.name} takes labels {self.labelnames}, "
                f"got {tuple(labelvalues)}")
        key = tuple(str(labelvalues[n]) for n in self.labelnames)
        with self._lock:
            return self._children.pop(key, None) is not None

    def children(self) -> list:
        """Sorted (labelvalues, child) pairs — a stable exposition
        order regardless of observation order."""
        with self._lock:
            return sorted(self._children.items())

    # -- unlabeled delegation -----------------------------------------
    def _default(self) -> Any:
        try:
            return self._children[()]
        except KeyError:
            raise ValueError(
                f"{self.name} has labels {self.labelnames}; "
                "use .labels(...)") from None

    def inc(self, amount: float = 1) -> None:
        self._default().inc(amount)

    def dec(self, amount: float = 1) -> None:
        self._default().dec(amount)

    def set(self, value: float) -> None:
        self._default().set(value)

    def observe(self, value: float,
                exemplar: "dict | None" = None) -> None:
        self._default().observe(value, exemplar=exemplar)

    def percentile(self, q: float) -> float:
        return self._default().percentile(q)

    @property
    def value(self) -> float:
        return self._default().value

    @property
    def count(self) -> int:
        return self._default().count


class Registry:
    """Named metric families; the unit the /metrics endpoint scrapes.

    ``REGISTRY`` below is the process-global instance (what a served
    /metrics endpoint and module-level instrumentation default to);
    private instances keep tests and independent pipelines isolated.
    """

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._families: dict[str, Family] = {}

    def register(self, name: str, mtype: str, help: str = "",
                 labelnames: tuple = (),
                 buckets: "Iterable[float] | None" = None) -> Family:
        """Get-or-create; re-registration with a different shape is a
        bug worth failing loudly on."""
        with self._lock:
            fam = self._families.get(name)
            if fam is not None:
                if fam.type != mtype or fam.labelnames != tuple(labelnames):
                    raise ValueError(
                        f"metric {name} already registered as {fam.type}"
                        f"{fam.labelnames}, requested {mtype}"
                        f"{tuple(labelnames)}")
                return fam
            fam = Family(name, mtype, help=help, labelnames=labelnames,
                         buckets=buckets)
            self._families[name] = fam
            return fam

    def counter(self, name: str, help: str = "",
                labelnames: tuple = ()) -> Family:
        return self.register(name, "counter", help, labelnames)

    def gauge(self, name: str, help: str = "",
              labelnames: tuple = ()) -> Family:
        return self.register(name, "gauge", help, labelnames)

    def histogram(self, name: str, help: str = "", labelnames: tuple = (),
                  buckets: "Iterable[float] | None" = None) -> Family:
        return self.register(name, "histogram", help, labelnames, buckets)

    def family(self, name: str) -> Family:
        """Get-or-create from the documented inventory (obs.inventory
        SPECS) — THE way instrumented modules obtain metrics, so names,
        help text, and bucket ladders can never drift from
        docs/OBSERVABILITY.md."""
        with self._lock:
            fam = self._families.get(name)
        if fam is not None:
            return fam
        from klogs_tpu.obs.inventory import SPECS

        spec = SPECS.get(name)
        if spec is None:
            raise KeyError(
                f"metric {name!r} is not in obs.inventory.SPECS — add it "
                "there (and to docs/OBSERVABILITY.md) first")
        return self.register(name, spec["type"], help=spec["help"],
                             labelnames=spec.get("labels", ()),
                             buckets=spec.get("buckets"))

    def get(self, name: str) -> "Family | None":
        with self._lock:
            return self._families.get(name)

    def collect(self) -> list[Family]:
        with self._lock:
            return [self._families[n] for n in sorted(self._families)]


# The process-global registry: what `--metrics-port` sidecars serve by
# default. Pipelines that need isolation (tests, parallel benches)
# construct private Registry instances instead.
REGISTRY = Registry()
