"""The /metrics + /healthz HTTP sidecar.

A deliberately tiny asyncio HTTP/1.1 server (the environment bakes in
no HTTP framework, and a scrape endpoint needs none): GET /metrics
serves the Prometheus text exposition of one Registry; GET /healthz and
GET /readyz serve the Health state as JSON.

Liveness vs readiness (the kubelet distinction, and the reason one
boolean is not enough during cold start): a filterd that is COMPILING
its first kernel is alive — restarting it would only restart the
compile — but not ready — routing traffic to it queues RPCs behind a
multi-second jit trace. /healthz (liveness) answers "should this
process be restarted?"; /readyz answers "should traffic be routed
here?". Readiness flips when the warmup batch completes (engine warm +
device reachable, proven by an actual round trip) and liveness checks
keep watching the coalescer loop afterwards.
"""

import asyncio
import json
from typing import TYPE_CHECKING, Callable

from klogs_tpu.obs.expo import render

if TYPE_CHECKING:
    from klogs_tpu.obs.metrics import Registry

_REQ_TIMEOUT_S = 5.0


class Health:
    """Named liveness/readiness checks + the explicit warm flag.

    ``live_checks`` / ``ready_checks`` map name -> () -> bool; a check
    that RAISES counts as failed (a health probe must never take the
    process down). Readiness additionally requires ``set_ready()`` —
    the cold-start gate the warmup batch flips.
    """

    def __init__(self) -> None:
        self._ready = False
        self._drained = False
        self.live_checks: dict[str, Callable[[], bool]] = {}
        self.ready_checks: dict[str, Callable[[], bool]] = {}

    def add_live_check(self, name: str, fn: Callable[[], bool]) -> None:
        self.live_checks[name] = fn

    def add_ready_check(self, name: str, fn: Callable[[], bool]) -> None:
        self.ready_checks[name] = fn

    def set_ready(self, ready: bool = True) -> None:
        self._ready = ready
        self._drained = not ready

    def mark_warm(self) -> None:
        """Cold-start gate: flip readiness on — UNLESS an explicit
        ``set_ready(False)`` drain arrived while the warmup was still
        in flight. A rolling restart can start draining a server the
        moment it comes up; the warmup batch landing a beat later must
        not silently un-drain it (set_ready(True) still does, that one
        is an operator decision)."""
        if not self._drained:
            self._ready = True

    @staticmethod
    def _run(checks: dict[str, Callable[[], bool]]) -> tuple[bool, dict]:
        detail = {}
        ok = True
        for name, fn in checks.items():
            try:
                good = bool(fn())
            except Exception:
                good = False
            detail[name] = good
            ok = ok and good
        return ok, detail

    def liveness(self) -> tuple[bool, dict]:
        ok, detail = self._run(self.live_checks)
        return ok, {"live": ok, "ready": self._ready, "checks": detail}

    def readiness(self) -> tuple[bool, dict]:
        ok, detail = self._run(self.ready_checks)
        ok = ok and self._ready
        return ok, {"ready": ok, "warm": self._ready, "checks": detail}


class MetricsHTTPServer:
    """Serves one Registry (+ optional Health) over plain HTTP.

    Binds 127.0.0.1 by default: metrics and health are operator
    surfaces, exposed beyond localhost only by explicit host choice
    (cluster deployments front this with the pod network, where the
    scrape config in docs/OBSERVABILITY.md points)."""

    def __init__(self, registry: "Registry", health: "Health | None" = None,
                 host: str = "127.0.0.1", port: int = 0,
                 tracer=None, profiler=None) -> None:
        self.registry = registry
        self.health = health
        self.host = host
        self.port = port
        # /traces serves this tracer's finished spans; None = the
        # process-global one (a process runs one trace story). Same
        # rule for /profile and the profiler.
        self.tracer = tracer
        self.profiler = profiler
        self._server: asyncio.AbstractServer | None = None

    async def start(self) -> int:
        """Bind + serve; returns the bound port (port=0 asks the OS)."""
        self._server = await asyncio.start_server(
            self._handle, self.host, self.port)
        self.port = self._server.sockets[0].getsockname()[1]
        return self.port

    async def stop(self) -> None:
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
            self._server = None

    async def _handle(self, reader: asyncio.StreamReader,
                      writer: asyncio.StreamWriter) -> None:
        try:
            line = await asyncio.wait_for(reader.readline(), _REQ_TIMEOUT_S)
            parts = line.decode("latin-1", "replace").split()
            if len(parts) < 2:
                return
            target = parts[1].split("?", 1)
            method, path = parts[0], target[0]
            query = target[1] if len(target) > 1 else ""
            # Drain headers (requests are tiny; bodies unsupported).
            while True:
                h = await asyncio.wait_for(reader.readline(),
                                           _REQ_TIMEOUT_S)
                if h in (b"\r\n", b"\n", b""):
                    break
            if method == "GET" and path == "/metrics":
                # Process-level gauges (uptime, RSS) refresh per
                # scrape; the /proc reads are file I/O, so off the
                # loop like every other blocking read here.
                from klogs_tpu.obs.profiler import refresh_process_metrics

                await asyncio.to_thread(refresh_process_metrics,
                                        self.registry)
            status, ctype, body = self._route(method, path, query)
            head = (f"HTTP/1.1 {status}\r\n"
                    f"Content-Type: {ctype}\r\n"
                    f"Content-Length: {len(body)}\r\n"
                    "Connection: close\r\n\r\n")
            writer.write(head.encode("latin-1") + body)
            await writer.drain()
        except (asyncio.TimeoutError, ConnectionError):
            pass
        except Exception:
            # E.g. a header line past the StreamReader limit raises
            # ValueError. An operator surface must never let a garbage
            # request propagate into 'Task exception was never
            # retrieved' noise; drop the connection.
            pass
        finally:
            try:
                writer.close()
                await writer.wait_closed()
            except Exception:
                pass

    def _route(self, method: str, path: str,
               query: str = "") -> tuple[str, str, bytes]:
        if method != "GET":
            return ("405 Method Not Allowed", "text/plain; charset=utf-8",
                    b"method not allowed\n")
        if path == "/metrics":
            # Exemplars only on explicit opt-in (?exemplars=1): the
            # advertised 0.0.4 text parser rejects any suffix after a
            # sample value, so emitting them unasked would fail every
            # plain Prometheus scrape the moment tracing turns on.
            want_ex = "exemplars=1" in query
            body = render(self.registry, exemplars=want_ex).encode()
            return ("200 OK",
                    "text/plain; version=0.0.4; charset=utf-8", body)
        if path == "/traces":
            from klogs_tpu.obs import trace as _trace

            tracer = self.tracer if self.tracer is not None else _trace.TRACER
            body = (json.dumps(tracer.traces_doc()) + "\n").encode()
            return ("200 OK", "application/json", body)
        if path == "/profile":
            from klogs_tpu.obs import profiler as _profiler

            prof = (self.profiler if self.profiler is not None
                    else _profiler.PROFILER)
            body = (json.dumps(prof.profile_doc()) + "\n").encode()
            return ("200 OK", "application/json", body)
        if path in ("/healthz", "/readyz"):
            if self.health is None:
                return ("200 OK", "application/json",
                        b'{"live": true}\n')
            ok, doc = (self.health.liveness() if path == "/healthz"
                       else self.health.readiness())
            body = (json.dumps(doc) + "\n").encode()
            return ("200 OK" if ok else "503 Service Unavailable",
                    "application/json", body)
        return ("404 Not Found", "text/plain; charset=utf-8",
                b"try /metrics, /healthz, /readyz, /traces, or "
                b"/profile\n")
