"""Distributed tracing + the degrade flight recorder.

Aggregate metrics (obs.metrics) say how OFTEN the pipeline hedged,
rerouted, or fell back; they cannot say where one specific batch went
and why. This module adds the per-batch story: a dependency-free span
core instrumenting one batch's full life — fanout read -> sink flush ->
shard routing (hedge/reroute/failover as events) -> RPC client/server
(context propagated in gRPC metadata) -> server coalescer -> device
frame/sweep/kernel/fetch -> sink write — plus a flight recorder that
turns every degrade event into a self-contained JSON artifact.

Design rules (same budget discipline as obs.metrics):

- **Head-based sampling, off by default.** ``KLOGS_TRACE_SAMPLE`` is
  the fraction of traces recorded (0..1); the decision is made ONCE at
  the trace root and rides the context (and the wire), so a trace is
  always complete or absent. At 0 (default) ``span()`` is a float
  compare returning a no-op singleton — nothing on the framed hot path
  regresses.
- **Spans ride per-batch code, never per-line.** The busiest span site
  is one per fanout chunk / sink flush.
- **Task-safe context.** The current span lives in a ``contextvars``
  ContextVar: asyncio tasks inherit it at creation, so a hedge attempt
  task is automatically parented under the shard dispatch span.
  Executor threads do NOT inherit it — by convention the await site
  owns the span (``device.fetch`` wraps the ``run_in_executor`` await),
  and the span-discipline analysis pass (tools/analysis) keeps spans
  out of fire-and-forget tasks.
- **Bounded everything.** Attributes, events, the finished-span ring,
  and the recorder ring all have fixed caps; a runaway trace cannot
  grow process memory.

The flight recorder (``FlightRecorder``) keeps a fixed ring of recent
finished spans. ``trigger(reason)`` — fired on breaker open,
``--on-filter-error`` degrade, sweep/prefilter fallback, and abort
escalation — arms a dump that is written when the CURRENT trace's root
span finishes, so the artifact contains the triggering batch's complete
hop sequence with per-stage durations (a dump at trigger time would cut
the story mid-batch).
"""

import contextvars
import json
import os
import random
import threading
import time
from collections import deque
from typing import TYPE_CHECKING, Any, Callable, Iterable

if TYPE_CHECKING:
    from klogs_tpu.obs.metrics import Registry

# gRPC metadata key carrying the W3C-style traceparent
# (00-<32hex trace>-<16hex span>-<2hex flags>); lowercase as gRPC
# requires. service/transport.py re-exports it as the wire contract.
TRACEPARENT_KEY = "klogs-traceparent"

# Bounds: per-span attribute count / value length, events per span,
# finished-span ring (feeds /traces and the recorder).
MAX_ATTRS = 32
MAX_ATTR_LEN = 256
MAX_EVENTS = 64
DEFAULT_RING = 4096

_SENTINEL = object()  # "parent not given" marker for start_span

# Trace/span ids come from a private PRNG (seeded from the OS) so tests
# that seed the global `random` module cannot collide trace identities.
_IDS = random.Random()


def _sample_from_env() -> float:
    """KLOGS_TRACE_SAMPLE: fraction of traces to record (0..1).
    Malformed values raise naming the variable — a typo'd knob
    silently tracing nothing (or everything) is undebuggable."""
    from klogs_tpu.utils.env import read as env_read

    raw = env_read("KLOGS_TRACE_SAMPLE")
    if raw is None:
        return 0.0
    try:
        val = float(raw)
    except ValueError:
        raise ValueError(
            f"KLOGS_TRACE_SAMPLE={raw!r}: expected a number in [0, 1]"
        ) from None
    if not 0.0 <= val <= 1.0:
        raise ValueError(
            f"KLOGS_TRACE_SAMPLE={raw!r}: expected a number in [0, 1]")
    return val


class SpanContext:
    """The propagatable identity of a span: what a child (local or
    across the gRPC hop) needs to parent itself. ``remote`` marks a
    context that crossed a process boundary (extracted from wire
    metadata): a span parented under one is this PROCESS's root of the
    trace — the flight recorder treats it as a story-completion point,
    since the true root lives in another process."""

    __slots__ = ("trace_id", "span_id", "sampled", "remote")

    def __init__(self, trace_id: int, span_id: int, sampled: bool,
                 remote: bool = False) -> None:
        self.trace_id = trace_id
        self.span_id = span_id
        self.sampled = sampled
        self.remote = remote

    def traceparent(self) -> str:
        return (f"00-{self.trace_id:032x}-{self.span_id:016x}-"
                f"{'01' if self.sampled else '00'}")

    @classmethod
    def from_traceparent(cls, value: str) -> "SpanContext | None":
        parts = value.split("-")
        if len(parts) != 4:
            return None
        try:
            trace_id = int(parts[1], 16)
            span_id = int(parts[2], 16)
            flags = int(parts[3], 16)
        except ValueError:
            return None
        if len(parts[1]) != 32 or len(parts[2]) != 16:
            return None
        return cls(trace_id, span_id, bool(flags & 1))


def _clip(value: object) -> object:
    if isinstance(value, (int, float, bool)) or value is None:
        return value
    s = str(value)
    return s if len(s) <= MAX_ATTR_LEN else s[:MAX_ATTR_LEN] + "…"


class Span:
    """One timed operation. A context manager: ``with tracer.span(...)``
    is THE way to hold one open (the span-discipline analysis pass
    enforces it in the plumbing scope); ``__exit__`` records an escaping
    exception as status=error (CancelledError as status=cancelled — the
    hedge-loser signature) and reports to the tracer.

    Unsampled spans still enter the context (so the head decision
    propagates to children and across the wire) but record nothing."""

    __slots__ = ("_tracer", "name", "trace_id", "span_id", "parent_id",
                 "sampled", "local_root", "root_span_id", "start_unix",
                 "_t0", "duration_s", "status", "attrs", "events",
                 "_token", "_ended")

    def __init__(self, tracer: "Tracer", name: str, trace_id: int,
                 span_id: int, parent_id: "int | None", sampled: bool,
                 attrs: "dict[str, object] | None" = None,
                 local_root: bool = False,
                 root_span_id: "int | None" = None) -> None:
        self._tracer = tracer
        self.name = name
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.sampled = sampled
        self.local_root = local_root or parent_id is None
        # The span whose end completes THIS process's part of the
        # story (the flight recorder waits for it): self when a local
        # root, else inherited down the local chain.
        self.root_span_id = (span_id if self.local_root
                             else (root_span_id if root_span_id is not None
                                   else parent_id))
        self.start_unix = time.time() if sampled else 0.0
        self._t0 = time.perf_counter()
        self.duration_s: "float | None" = None
        self.status = "ok"
        self.attrs: "dict[str, object]" = {}
        self.events: "list[dict[str, object]]" = []
        self._token: "contextvars.Token[object] | None" = None
        self._ended = False
        if sampled and attrs:
            for k, v in attrs.items():
                self.set_attr(k, v)

    # -- recording ----------------------------------------------------

    def context(self) -> SpanContext:
        return SpanContext(self.trace_id, self.span_id, self.sampled)

    def set_attr(self, key: str, value: object) -> None:
        if self.sampled and len(self.attrs) < MAX_ATTRS:
            self.attrs[key] = _clip(value)

    def add_event(self, name: str, **attrs: object) -> None:
        if self.sampled and len(self.events) < MAX_EVENTS:
            ev: "dict[str, object]" = {
                "name": name, "t_s": time.perf_counter() - self._t0}
            for k, v in attrs.items():
                ev[k] = _clip(v)
            self.events.append(ev)

    def set_status(self, status: str) -> None:
        if self.sampled:
            self.status = status

    def end(self) -> None:
        """Finish the span and report it. Idempotent (the with-block and
        a manual finally may both call it)."""
        if self._ended:
            return
        self._ended = True
        self.duration_s = time.perf_counter() - self._t0
        if self.sampled:
            self._tracer._finish(self)

    # -- context management -------------------------------------------

    def __enter__(self) -> "Span":
        self._token = _CURRENT.set(self)
        return self

    def __exit__(self, exc_type: "type[BaseException] | None",
                 exc: "BaseException | None", tb: object) -> None:
        if self._token is not None:
            _CURRENT.reset(self._token)
            self._token = None
        if exc is not None and self.sampled:
            import asyncio

            if isinstance(exc, asyncio.CancelledError):
                self.status = "cancelled"
            else:
                self.status = "error"
                self.set_attr("error", f"{type(exc).__name__}: {exc}")
        self.end()

    def to_dict(self) -> "dict[str, object]":
        return {
            "name": self.name,
            "trace_id": f"{self.trace_id:032x}",
            "span_id": f"{self.span_id:016x}",
            "parent_id": (None if self.parent_id is None
                          else f"{self.parent_id:016x}"),
            "local_root": self.local_root,
            "start_unix": self.start_unix,
            "duration_s": self.duration_s,
            "status": self.status,
            "attrs": dict(self.attrs),
            "events": list(self.events),
        }


class _NoopSpan:
    """The zero-cost span when tracing is off: every method is a no-op
    and the context var is never touched (nothing downstream can
    sample, because the rate is 0)."""

    __slots__ = ()
    sampled = False
    name = ""

    def context(self) -> None:
        return None

    def set_attr(self, key: str, value: object) -> None:
        pass

    def add_event(self, name: str, **attrs: object) -> None:
        pass

    def set_status(self, status: str) -> None:
        pass

    def end(self) -> None:
        pass

    def __enter__(self) -> "_NoopSpan":
        return self

    def __exit__(self, *exc: object) -> None:
        pass


NOOP_SPAN = _NoopSpan()

# The active span for the current task/thread. Module-level (contextvars
# must be created once); shared by every Tracer in the process — in
# practice one process runs one TRACER, and tests that build private
# tracers run their spans inside their own with-blocks.
_CURRENT: "contextvars.ContextVar[object]" = contextvars.ContextVar(
    "klogs_trace_current", default=None)


class Tracer:
    """Span factory + finished-span ring.

    ``TRACER`` below is the process-global instance every instrumented
    module uses (collector and filterd share one process-wide trace
    story each); private instances isolate tests. The sample rate comes
    from ``KLOGS_TRACE_SAMPLE`` unless ``configure()`` overrides it."""

    def __init__(self, sample: "float | None" = None,
                 ring: int = DEFAULT_RING) -> None:
        self._lock = threading.Lock()
        self._sample = sample
        self._ring: "deque[dict[str, object]]" = deque(maxlen=ring)
        self._sinks: "list[Callable[[dict[str, object]], None]]" = []
        self._json_lock = threading.Lock()
        self._json_path: "str | None" = None
        self._m_spans: Any = None

    # -- configuration ------------------------------------------------

    def _rate(self) -> float:
        if self._sample is None:
            self._sample = _sample_from_env()
        return self._sample

    @property
    def enabled(self) -> bool:
        return self._rate() > 0.0

    def configure(self, sample: "float | None" = None) -> None:
        """Override the sample rate (None = re-read the env on next
        use). ``--trace-json`` calls ``enable_default()`` instead so an
        explicit KLOGS_TRACE_SAMPLE still wins."""
        self._sample = sample

    def sample_rate(self) -> float:
        """The effective head-sampling rate (env-resolved) — what the
        profiler divides observed busy-seconds by to unbias stage
        utilization."""
        return self._rate()

    def ensure_sample(self, rate: float) -> None:
        """Raise the sampling rate to at least ``rate`` — the
        profiler's enablement path (profiling needs spans to fold) —
        UNLESS KLOGS_TRACE_SAMPLE explicitly pins one: an operator's
        explicit rate, including 0, always wins."""
        from klogs_tpu.utils.env import is_set

        if is_set("KLOGS_TRACE_SAMPLE"):
            return
        if rate > self._rate():
            self._sample = rate

    def enable_default(self) -> None:
        """Turn sampling fully on UNLESS KLOGS_TRACE_SAMPLE is set —
        the --trace-json ergonomics: asking for a trace file means you
        want traces, but an explicit rate (including 0) is respected."""
        from klogs_tpu.utils.env import is_set

        if not is_set("KLOGS_TRACE_SAMPLE"):
            self._sample = 1.0

    def bind_registry(self, registry: "Registry | None") -> None:
        self._m_spans = (registry.family("klogs_trace_spans_total")
                         if registry is not None else None)

    def reset(self, sample: "float | None" = None) -> None:
        """Test hook: drop every finished span, sink, and file sink,
        then set the rate (None = env)."""
        with self._lock:
            self._ring.clear()
            self._sinks = []
        with self._json_lock:
            self._json_path = None
        self._sample = sample
        self._m_spans = None

    # -- span creation ------------------------------------------------

    def start_span(self, name: str, parent: object = _SENTINEL,
                   **attrs: object) -> "Span | _NoopSpan":
        """Create a span. ``parent`` defaults to the current span (the
        contextvar); pass an explicit ``SpanContext`` (e.g. extracted
        from gRPC metadata, or a coalesced group's carrying member) or
        ``None`` to force a new root. Returns the no-op singleton when
        nothing samples — callers never branch."""
        if parent is _SENTINEL:
            parent = _CURRENT.get()
        if parent is None:
            rate = self._rate()
            if rate <= 0.0:
                return NOOP_SPAN
            sampled = rate >= 1.0 or _IDS.random() < rate
            return Span(self, name, _IDS.getrandbits(128),
                        _IDS.getrandbits(64), None, sampled, attrs or None)
        root_id: "int | None" = None
        if isinstance(parent, Span):
            root_id = parent.root_span_id
            ctx: "SpanContext | None" = parent.context()
        elif isinstance(parent, _NoopSpan):
            ctx = None
        else:
            ctx = parent
        if ctx is None:
            return NOOP_SPAN
        assert isinstance(ctx, SpanContext)
        return Span(self, name, ctx.trace_id, _IDS.getrandbits(64),
                    ctx.span_id, ctx.sampled, attrs or None,
                    local_root=ctx.remote, root_span_id=root_id)

    # The idiomatic entry (`with tracer.span("name"):`).
    span = start_span

    def current_span(self) -> "Span | None":
        cur = _CURRENT.get()
        return cur if isinstance(cur, Span) else None

    def current_context(self) -> "SpanContext | None":
        cur = _CURRENT.get()
        return cur.context() if isinstance(cur, Span) else None

    def event(self, name: str, **attrs: object) -> None:
        """Add an event to the current span, if one is recording — the
        convenience for deep helpers (routing demotions, degrades) that
        should annotate whatever batch is in flight."""
        cur = _CURRENT.get()
        if isinstance(cur, Span):
            cur.add_event(name, **attrs)

    def exemplar(self) -> "dict[str, str] | None":
        """Exemplar labels ({trace_id, span_id}) for the current
        sampled span, linking a histogram observation to its trace in
        the Prometheus exposition (OpenMetrics exemplar syntax)."""
        cur = _CURRENT.get()
        if isinstance(cur, Span) and cur.sampled:
            return {"trace_id": f"{cur.trace_id:032x}",
                    "span_id": f"{cur.span_id:016x}"}
        return None

    # -- wire propagation ---------------------------------------------

    def inject(self) -> "tuple[tuple[str, str], ...]":
        """gRPC metadata entries carrying the current span context
        (empty when nothing is recording)."""
        cur = _CURRENT.get()
        if isinstance(cur, Span) and cur.sampled:
            return ((TRACEPARENT_KEY, cur.context().traceparent()),)
        return ()

    def extract(self, metadata: "Iterable[tuple[str, str]] | None"
                ) -> "SpanContext | None":
        """Parse a traceparent out of gRPC invocation metadata; None
        when absent/malformed (the RPC then roots its own trace under
        local sampling)."""
        if not metadata:
            return None
        for key, value in metadata:
            if key == TRACEPARENT_KEY and isinstance(value, str):
                ctx = SpanContext.from_traceparent(value)
                if ctx is not None:
                    # Crossed a process boundary: spans parented under
                    # this are THIS process's roots of the trace.
                    ctx.remote = True
                return ctx
        return None

    # -- finished spans -----------------------------------------------

    def _finish(self, span: Span) -> None:
        doc = span.to_dict()
        with self._lock:
            self._ring.append(doc)
            sinks = list(self._sinks)
        if self._m_spans is not None:
            self._m_spans.inc()
        path = self._json_path
        if path is not None:
            self._write_json(path, doc)
        for sink in sinks:
            try:
                sink(doc)
            except Exception:
                pass  # a broken sink must never take the pipeline down

    def _write_json(self, path: str, doc: "dict[str, object]") -> None:
        try:
            with self._json_lock:
                with open(path, "a", encoding="utf-8") as f:
                    f.write(json.dumps(doc) + "\n")
        except OSError:
            pass  # tracing is best-effort; the pipeline owns the run

    def add_sink(self, fn: "Callable[[dict[str, object]], None]") -> None:
        with self._lock:
            self._sinks.append(fn)

    def remove_sink(self, fn: "Callable[[dict[str, object]], None]"
                    ) -> None:
        with self._lock:
            if fn in self._sinks:
                self._sinks.remove(fn)

    def set_json_path(self, path: "str | None") -> None:
        """--trace-json PATH: append every finished span as one JSON
        line (JSONL; the file-sink twin of the /traces endpoint)."""
        with self._json_lock:
            self._json_path = path

    def finished_spans(self) -> "list[dict[str, object]]":
        with self._lock:
            return list(self._ring)

    def traces_doc(self) -> "dict[str, object]":
        """Finished spans grouped by trace for the /traces endpoint:
        {"traces": [{"trace_id", "spans": [...]}, ...]}, spans in start
        order, traces in first-seen order."""
        groups: "dict[str, list[dict[str, object]]]" = {}
        for doc in self.finished_spans():
            groups.setdefault(str(doc["trace_id"]), []).append(doc)
        traces = []
        for tid, spans in groups.items():
            spans.sort(key=lambda d: (d.get("start_unix") or 0.0))
            traces.append({"trace_id": tid, "spans": spans})
        return {"traces": traces}


class FlightRecorder:
    """Fixed ring of recent spans, dumped as one JSON artifact when a
    degrade event fires.

    Registered as a tracer sink; ``trigger(reason)`` arms a dump that
    is written when the next ROOT span finishes — so the artifact
    contains the triggering batch's complete hop sequence, not a story
    cut off mid-dispatch. Per-reason rate limiting keeps a flapping
    breaker from writing a dump per flap; ``flush()`` writes an armed
    dump immediately (pipeline teardown, tests)."""

    def __init__(self, capacity: int = 1024,
                 dir_path: "str | None" = None,
                 min_interval_s: float = 30.0) -> None:
        self._lock = threading.Lock()
        self._ring: "deque[dict[str, object]]" = deque(maxlen=capacity)
        self._dir = dir_path
        self._min_interval_s = min_interval_s
        self._last: "dict[str, float]" = {}
        self._pending: "list[dict[str, object]]" = []
        self._seq = 0
        self._writers: "list[threading.Thread]" = []
        self.dumps: "list[str]" = []
        self._m_dumps: Any = None

    def configure(self, dir_path: "str | None" = None,
                  min_interval_s: "float | None" = None) -> None:
        with self._lock:
            if dir_path is not None:
                self._dir = dir_path
            if min_interval_s is not None:
                self._min_interval_s = min_interval_s

    def bind_registry(self, registry: "Registry | None") -> None:
        self._m_dumps = (registry.family("klogs_flight_dumps_total")
                         if registry is not None else None)

    def reset(self) -> None:
        self.join_writes()
        with self._lock:
            self._ring.clear()
            self._pending = []
            self._last = {}
            self._writers = []
            self.dumps = []
        self._m_dumps = None

    def _dump_dir(self) -> str:
        if self._dir is not None:
            return self._dir
        from klogs_tpu.utils.env import read as env_read

        env = env_read("KLOGS_FLIGHT_DIR")
        if env:
            return env
        import tempfile

        return tempfile.gettempdir()

    # -- span stream (tracer sink) ------------------------------------

    def record(self, doc: "dict[str, object]") -> None:
        pending = None
        with self._lock:
            self._ring.append(doc)
            if self._pending:
                # Write when the span whose end completes the
                # TRIGGERING chain's story finishes: the exact root
                # span recorded at trigger time (true root on a
                # collector; the remote-parented rpc.server on a
                # filterd — a propagated trace has no local parentless
                # span there). A trigger armed outside any trace
                # flushes on the next local root. Matching the exact
                # span — not just the trace — matters when one process
                # hosts both ends (tests): the server-side local root
                # of the SAME trace ends first and must not cut the
                # collector-side story out of the artifact.
                wanted = {t.get("root_span_id") for t in self._pending}
                if ((None in wanted and doc.get("local_root"))
                        or doc.get("span_id") in wanted):
                    pending, self._pending = self._pending, []
        if pending is not None:
            self._write(pending)

    # -- triggers -----------------------------------------------------

    def trigger(self, reason: str, **attrs: object) -> None:
        """Arm a dump for ``reason`` (breaker-open, filter-degrade,
        sweep-fallback, abort-escalation). No-op when there is no story
        to dump (tracing off: no recording trace AND an empty ring) or
        inside the per-reason rate-limit window."""
        now = time.monotonic()
        # WHICH chain tripped the trigger: the dump waits for that
        # chain's local root span (the failed batch's full story in
        # this process), not whichever concurrent trace finishes
        # first.
        cur = TRACER.current_span()
        if cur is not None and not cur.sampled:
            cur = None
        with self._lock:
            if cur is None and not self._ring and not self._pending:
                return
            last = self._last.get(reason)
            if last is not None and now - last < self._min_interval_s:
                return
            self._last[reason] = now
            entry: "dict[str, object]" = {"reason": reason,
                                          "wall": time.time()}
            entry["trace_id"] = (f"{cur.trace_id:032x}"
                                 if cur is not None else None)
            entry["root_span_id"] = (
                f"{cur.root_span_id:016x}"
                if cur is not None and cur.root_span_id is not None
                else None)
            for k, v in attrs.items():
                entry[k] = _clip(v)
            self._pending.append(entry)
            # Bounded: a trigger whose trace never completes (process
            # shutting down, span dropped) must not accumulate for the
            # life of a daemon.
            if len(self._pending) > 32:
                del self._pending[0]

    def flush(self) -> "str | None":
        """Write an armed dump immediately (no root may ever end after
        teardown), waiting for the file to land. Returns the path, or
        None when nothing was armed."""
        with self._lock:
            pending, self._pending = self._pending, []
        if not pending:
            self.join_writes()
            return None
        return self._write(pending, wait=True)

    def join_writes(self, timeout_s: float = 5.0) -> None:
        """Wait for in-flight background dump writes (teardown/tests)."""
        with self._lock:
            writers = list(self._writers)
        for w in writers:
            w.join(timeout_s)

    def _write(self, triggers: "list[dict[str, object]]",
               wait: bool = False) -> "str | None":
        with self._lock:
            spans = list(self._ring)
            self._seq += 1
            seq = self._seq
        path = os.path.join(self._dump_dir(),
                            f"klogs-flight-{os.getpid()}-{seq}.json")
        # Serialization + disk I/O off the caller: record() runs on the
        # event loop (a span just ended there), and a full ring is
        # hundreds of KB — stalling the loop at the exact moment the
        # pipeline is degrading would worsen the incident being
        # recorded. ``wait`` (teardown/tests) joins before returning.
        worker = threading.Thread(
            target=self._write_blob, args=(triggers, spans, path),
            name="klogs-flight-dump", daemon=True)
        with self._lock:
            self._writers.append(worker)
            if len(self._writers) > 8:
                self._writers = [w for w in self._writers
                                 if w.is_alive()][-8:]
        worker.start()
        if wait:
            worker.join(5.0)
        return path

    def _write_blob(self, triggers: "list[dict[str, object]]",
                    spans: "list[dict[str, object]]", path: str) -> None:
        doc = {
            "reasons": triggers,
            "wall": time.time(),
            "pid": os.getpid(),
            "spans": spans,
        }
        try:
            with open(path, "w", encoding="utf-8") as f:
                json.dump(doc, f, indent=1)
        except OSError as e:
            from klogs_tpu.ui import term

            term.warning("cannot write flight-recorder dump %s: %s",
                         path, e)
            return
        with self._lock:
            self.dumps.append(path)
        if self._m_dumps is not None:
            for t in triggers:
                self._m_dumps.labels(reason=t["reason"]).inc()
        from klogs_tpu.ui import term

        term.info("flight recorder dump (%s) written to %s",
                  ", ".join(str(t["reason"]) for t in triggers), path)


# Process-global tracer + recorder: what every instrumented module and
# the /traces endpoint use by default. The recorder rides the tracer's
# span stream as a sink.
TRACER = Tracer()
RECORDER = FlightRecorder()
TRACER.add_sink(RECORDER.record)


def flight_trigger(reason: str, **attrs: object) -> None:
    """Module-level trigger hook for the degrade call sites (breaker
    open, --on-filter-error degrade, sweep fallback, abort escalation).
    Cheap no-op when tracing is off."""
    RECORDER.trigger(reason, **attrs)


def reset(sample: "float | None" = None) -> None:
    """Test hook: wipe the global tracer AND recorder, re-wire the
    recorder sink, set the sample rate (None = env-driven again)."""
    TRACER.reset(sample)
    RECORDER.reset()
    TRACER.add_sink(RECORDER.record)
