"""Prometheus text exposition (format 0.0.4) + JSON snapshots.

``render`` walks a Registry into the text format a Prometheus scrape
expects; ``snapshot`` produces the JSON-able dict behind the CLI's
``--stats-json`` one-shot dump. Both read the same families — there is
no second bookkeeping path to drift.
"""

from typing import TYPE_CHECKING, Iterable, Sequence

if TYPE_CHECKING:
    from klogs_tpu.obs.metrics import Registry


def _fmt(v: "float | int") -> str:
    """Numbers render canonically: integral floats without the '.0'
    (Prometheus parsers take either; goldens want stability)."""
    if isinstance(v, float) and v == int(v) and abs(v) < 2**53:
        return str(int(v))
    return repr(v) if isinstance(v, float) else str(v)


def _escape_help(s: str) -> str:
    return s.replace("\\", "\\\\").replace("\n", "\\n")


def _escape_label(s: str) -> str:
    return (s.replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _labelstr(names: Sequence[str], values: Sequence[str],
              extra: Iterable[tuple] = ()) -> str:
    pairs = [(n, v) for n, v in zip(names, values)]
    pairs.extend(extra)
    if not pairs:
        return ""
    body = ",".join(f'{n}="{_escape_label(str(v))}"' for n, v in pairs)
    return "{" + body + "}"


def _exemplar_str(ex: "tuple[dict, float, float] | None") -> str:
    """OpenMetrics exemplar suffix for a bucket sample: links the
    observation to its trace (` # {trace_id="..",span_id=".."} v ts`).
    Empty when the bucket never recorded one."""
    if ex is None:
        return ""
    labels, value, ts = ex
    body = ",".join(f'{k}="{_escape_label(str(v))}"'
                    for k, v in labels.items())
    return f" # {{{body}}} {_fmt(value)} {_fmt(round(ts, 3))}"


def render(registry: "Registry", exemplars: bool = False) -> str:
    """Registry -> Prometheus text exposition.

    ``exemplars=False`` (the default, and what a plain /metrics scrape
    gets) emits strict text format 0.0.4 — the classic parser rejects
    anything after a sample value, so exemplar suffixes there would
    fail the ENTIRE scrape. ``exemplars=True`` appends OpenMetrics
    exemplar syntax to bucket samples that recorded one; the sidecar
    serves it only when the scraper opts in (/metrics?exemplars=1)."""
    out: list[str] = []
    for fam in registry.collect():
        out.append(f"# HELP {fam.name} {_escape_help(fam.help)}")
        out.append(f"# TYPE {fam.name} {fam.type}")
        for labelvalues, child in fam.children():
            lab = _labelstr(fam.labelnames, labelvalues)
            if fam.type == "histogram":
                counts, total, n = child.snapshot()
                ex = child.exemplars() if exemplars else {}
                cum = 0
                for i, (bound, c) in enumerate(zip(child.buckets, counts)):
                    cum += c
                    le = _labelstr(fam.labelnames, labelvalues,
                                   extra=(("le", _fmt(float(bound))),))
                    out.append(f"{fam.name}_bucket{le} {cum}"
                               + _exemplar_str(ex.get(i)))
                inf = _labelstr(fam.labelnames, labelvalues,
                                extra=(("le", "+Inf"),))
                out.append(f"{fam.name}_bucket{inf} {n}"
                           + _exemplar_str(ex.get(len(child.buckets))))
                out.append(f"{fam.name}_sum{lab} {_fmt(total)}")
                out.append(f"{fam.name}_count{lab} {n}")
            else:
                out.append(f"{fam.name}{lab} {_fmt(child.value)}")
    return "\n".join(out) + "\n"


def snapshot(registry: "Registry") -> dict:
    """Registry -> JSON-able dict (--stats-json). Histograms carry
    bucket bounds/counts plus sum/count; labeled families list one
    entry per child."""
    doc: dict = {}
    for fam in registry.collect():
        samples = []
        for labelvalues, child in fam.children():
            labels = dict(zip(fam.labelnames, labelvalues))
            if fam.type == "histogram":
                counts, total, n = child.snapshot()
                # In-process reservoir percentiles ride every
                # histogram sample (the --stats-json exit dump's exact
                # quantiles, next to the bucketed approximation a
                # remote scrape would have to settle for). Additive
                # keys only — goldens over the existing layout hold.
                sample = {"buckets": dict(zip(
                    (_fmt(float(b)) for b in child.buckets), counts)),
                    "sum": total, "count": n,
                    "p50": child.percentile(50),
                    "p90": child.percentile(90),
                    "p99": child.percentile(99)}
            else:
                sample = {"value": child.value}
            if labels:
                sample["labels"] = labels
            samples.append(sample)
        doc[fam.name] = {"type": fam.type, "help": fam.help,
                         "samples": samples}
    return doc
