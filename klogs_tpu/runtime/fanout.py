"""Concurrent per-container log acquisition.

Reference parity: the goroutine-per-container fan-out
(cmd/root.go:224-339): one worker per (pod, container), all log files
created (truncated) up front, a shared WaitGroup, apiserver burst 100
(cmd/root.go:80), per-stream error isolation (one bad container never
kills the run, cmd/root.go:326-329), and the follow-mode "Streaming
logs ended prematurely" warning (cmd/root.go:314-317).

Deliberate improvement over the reference: follow mode tears down with
explicit cancellation (stop() closes every stream and flushes every
sink) instead of exiting the process with goroutines still running
(SURVEY.md §3.3 quirk).

Source-agnostic since PR 18: workers open streams through the Source
contract (sources/base.py) — a kube backend is silently adapted via
ClusterSource, and file/archive/socket sources get the same per-stream
sinks, reconnect policy, error isolation, and metrics. Pod identity
generalizes to SourceRef (group/unit play pod/container); refs marked
``ephemeral`` (socket peers) end without reconnect or a premature-end
warning.
"""

import asyncio
import os
import re
import time
from dataclasses import dataclass
from typing import Callable

from klogs_tpu.cluster.backend import ClusterBackend
from klogs_tpu.cluster.types import LogOptions, PodInfo
from klogs_tpu.obs import trace
from klogs_tpu.resilience import RetryPolicy
from klogs_tpu.runtime.sink import FileSink, Sink, SinkError
from klogs_tpu.sources.base import Source, SourceError, SourceRef
from klogs_tpu.sources.cluster import ClusterSource
from klogs_tpu.ui import term
from klogs_tpu.utils.naming import log_file_name

# Reference: rest config Burst = 100, the one tuning constant
# (cmd/root.go:80). Bounds concurrent stream-open requests.
DEFAULT_OPEN_BURST = 100

# Follow-mode reconnection (improvement over the reference, which has no
# retry anywhere — SURVEY.md §5 "Failure detection"): a follow stream
# that dies is reopened via the shared resilience RetryPolicy with a
# server-side `since` covering the gap. A connection that delivered
# data and lived this long counts as healthy and resets the attempt
# budget. The module-level backoff knobs feed the default policy and
# are read at decision time (tests monkeypatch them).
DEFAULT_MAX_RECONNECTS = 5
RECONNECT_HEALTHY_S = 5.0
_BACKOFF_BASE_S = 0.5
_BACKOFF_MAX_S = 10.0

# A sink.write blocking past this counts as a backpressure stall (the
# downstream filter/file/console is the bottleneck, not the apiserver).
STALL_THRESHOLD_S = 0.05


@dataclass
class StreamJob:
    pod: str
    container: str
    init: bool
    path: str
    # Non-cluster sources attach the ref the job was planned from;
    # None = classic pod identity (the worker synthesizes a pod ref).
    ref: "SourceRef | None" = None


@dataclass
class StreamResult:
    job: StreamJob
    bytes_written: int = 0
    error: str | None = None
    premature_end: bool = False  # stream ended while follow was requested


SinkFactory = Callable[[StreamJob], Sink]


def plan_jobs(
    pods: list[PodInfo], log_path: str, include_init: bool,
    container_re: "re.Pattern | None" = None,
    exclude_container_re: "re.Pattern | None" = None,
) -> list[StreamJob]:
    """File creation order matches the reference: per pod, init
    containers first (if -i), then regular (cmd/root.go:240-262).

    A pod matched by several -l selectors appears in ``pods`` more than
    once (label union keeps reference semantics, cmd/root.go:458-460)
    but must stream only once — two workers on one path would truncate
    and interleave the same file, so duplicate (pod, container) pairs
    are dropped here.

    ``container_re`` / ``exclude_container_re`` (stern-style ``-c`` /
    ``-E``; additive, the reference streams every container
    unconditionally) keep only containers whose NAME re.search-matches
    the include (when given) and not the exclude — applied here so
    static plans and --watch-new discovery select identically."""
    jobs = []
    seen: set[tuple[str, str, bool]] = set()

    def want(name: str) -> bool:
        if container_re is not None and not container_re.search(name):
            return False
        return (exclude_container_re is None
                or not exclude_container_re.search(name))

    for pod in pods:
        if include_init:
            for c in pod.init_containers:
                key = (pod.name, c.name, True)
                if key not in seen and want(c.name):
                    seen.add(key)
                    jobs.append(StreamJob(pod.name, c.name, True,
                                          os.path.join(log_path, log_file_name(pod.name, c.name))))
        for c in pod.containers:
            key = (pod.name, c.name, False)
            if key not in seen and want(c.name):
                seen.add(key)
                jobs.append(StreamJob(pod.name, c.name, False,
                                      os.path.join(log_path, log_file_name(pod.name, c.name))))
    return jobs


def plan_source_jobs(refs: "list[SourceRef]",
                     log_path: str) -> list[StreamJob]:
    """plan_jobs for non-cluster sources: one job per ref, with
    group/unit standing in for pod/container so the per-stream output
    files, sinks, and metric labels follow the same naming scheme."""
    jobs = []
    seen: set[tuple[str, str]] = set()
    for ref in refs:
        key = (ref.group, ref.unit)
        if key in seen:
            continue
        seen.add(key)
        jobs.append(StreamJob(
            ref.group, ref.unit, False,
            os.path.join(log_path, log_file_name(ref.group, ref.unit)),
            ref=ref))
    return jobs


class FanoutRunner:
    def __init__(
        self,
        backend: "ClusterBackend | None",
        namespace: str,
        log_opts: LogOptions,
        sink_factory: SinkFactory | None = None,
        open_burst: int = DEFAULT_OPEN_BURST,
        max_reconnects: int = DEFAULT_MAX_RECONNECTS,
        create_files: bool = True,
        registry=None,
        reconnect_policy: "RetryPolicy | None" = None,
        source: "Source | None" = None,
    ):
        if source is None:
            if backend is None:
                raise ValueError("FanoutRunner needs a backend or a source")
            # The classic construction: adapt the cluster backend. The
            # adapter adds nothing, so the kube path is unchanged.
            source = ClusterSource(backend, namespace)
        self.backend = backend
        self.source = source
        self.namespace = namespace
        self.log_opts = log_opts
        self.sink_factory = sink_factory or (lambda job: FileSink(job.path))
        # asyncio primitives are created lazily inside run(): on Py3.10
        # they bind the loop that exists at CONSTRUCTION, and runners
        # are built before asyncio.run() starts the real one (the
        # full-suite-order-only failure class; see docs/
        # STATIC_ANALYSIS.md task-lifecycle).
        self._open_burst = open_burst
        self._open_sem: "asyncio.Semaphore | None" = None
        self._streams: list = []
        self._stopping = False
        self._stop_event: "asyncio.Event | None" = None
        self.max_reconnects = max_reconnects
        # Reconnect policy override; None = the default built from
        # max_reconnects + the module backoff knobs at decision time
        # (so test monkeypatching of _BACKOFF_* keeps working).
        self.reconnect_policy = reconnect_policy
        # -o stdout streams to the console only: job paths stay as
        # stable (pod, container) identities but no file is touched.
        self.create_files = create_files
        # Fan-out instrumentation (an obs.Registry, wired by --metrics-
        # port / --stats-json); None keeps the zero-overhead path.
        self._m = None
        if registry is not None:
            self._m = {
                "active": registry.family("klogs_fanout_active_streams"),
                "bytes": registry.family("klogs_fanout_stream_bytes_total"),
                "reconnects": registry.family(
                    "klogs_fanout_reconnects_total"),
                "errors": registry.family(
                    "klogs_fanout_stream_errors_total"),
                "stalls": registry.family(
                    "klogs_fanout_backpressure_stalls_total"),
                "retries": registry.family(
                    "klogs_retry_attempts_total").labels(site="fanout"),
            }

    # Lazy asyncio-primitive accessors: every caller below runs on the
    # event loop, so first use binds the RUNNING loop (never the
    # default loop a pre-run construction would capture on Py3.10).
    def _stop_ev(self) -> asyncio.Event:
        if self._stop_event is None:
            self._stop_event = asyncio.Event()
        return self._stop_event

    def _open_gate(self) -> asyncio.Semaphore:
        if self._open_sem is None:
            self._open_sem = asyncio.Semaphore(self._open_burst)
        return self._open_sem

    async def _worker(self, job: StreamJob) -> StreamResult:
        result = StreamResult(job=job)
        opts = LogOptions(
            since_seconds=self.log_opts.since_seconds,
            tail_lines=self.log_opts.tail_lines,
            follow=self.log_opts.follow,
            container=job.container,
            previous=self.log_opts.previous,
            timestamps=self.log_opts.timestamps,
            since_time=self.log_opts.since_time,
        )
        sink = self.sink_factory(job)
        # Hoist the labeled children: the chunk loop must not pay a
        # labels() dict hop per chunk.
        m_bytes = (self._m["bytes"].labels(pod=job.pod,
                                           container=job.container)
                   if self._m is not None else None)
        attempt = 0
        # Last moment data was actually received, persisted ACROSS
        # reconnects: an unproductive reconnect must not advance it, or
        # the still-unfetched gap would be silently skipped. None until
        # the first stream opens.
        last_data: float | None = None
        ref = job.ref or SourceRef(kind="pod", group=job.pod,
                                   unit=job.container, target=job.pod)
        try:
            while True:
                try:
                    async with self._open_gate():
                        stream = await self.source.open_stream(ref, opts)
                except SourceError as e:
                    if await self._should_reconnect(job, attempt, e):
                        attempt += 1
                        continue
                    # Per-stream error isolation (cmd/root.go:326-329).
                    term.error("Error getting logs for container %s\n%s",
                               job.container, e)
                    result.error = str(e)
                    return result

                if self._stopping:
                    # stop() already ran; a stream opened after teardown
                    # would never be closed and run() would hang.
                    await stream.close()
                    return result
                self._streams.append(stream)
                if self._m is not None:
                    self._m["active"].inc()
                opened_at = time.monotonic()
                # Gap re-fetch must start at the LAST RECEIVED chunk, not
                # the stream open: a long-lived healthy follow stream that
                # drops would otherwise re-fetch (and duplicate) its whole
                # connection lifetime of logs.
                if last_data is None:
                    last_data = opened_at
                got_data = False
                stream_err: SourceError | None = None
                sink_err: SinkError | None = None
                # Per-chunk trace root: the first hop of a batch's
                # life. With sampling off span() is a no-op singleton
                # (one compare per CHUNK, never per line); sampled
                # chunks parent whatever the write triggers downstream
                # (sink flush -> coalescer/shard -> RPC -> device).
                tr = trace.TRACER
                try:
                    if m_bytes is None:
                        async for chunk in stream:
                            got_data = True
                            last_data = time.monotonic()
                            with tr.span("fanout.read", pod=job.pod,
                                         container=job.container,
                                         bytes=len(chunk)):
                                await sink.write(chunk)
                    else:
                        stalls = self._m["stalls"]
                        async for chunk in stream:
                            got_data = True
                            last_data = time.monotonic()
                            m_bytes.inc(len(chunk))
                            with tr.span("fanout.read", pod=job.pod,
                                         container=job.container,
                                         bytes=len(chunk)):
                                await sink.write(chunk)
                            # A slow write = the filter/file/console is
                            # the bottleneck, not the apiserver: the
                            # operator's signal to scale the sink side.
                            if (time.monotonic() - last_data
                                    >= STALL_THRESHOLD_S):
                                stalls.inc()
                except SourceError as e:
                    stream_err = e
                except SinkError as e:
                    sink_err = e
                finally:
                    await stream.close()
                    try:
                        self._streams.remove(stream)
                        if self._m is not None:
                            self._m["active"].dec()
                    except ValueError:
                        pass

                if sink_err is not None:
                    # The sink is dead (disk full, revoked mount):
                    # reconnecting the STREAM would loop straight back
                    # into the same failure with nowhere to put the
                    # bytes. End this job cleanly with the sink's one
                    # clear error (resilience subsystem; the upstream
                    # log stream itself is fine).
                    term.error("Sink failed for container %s\n%s",
                               job.container, sink_err)
                    result.error = str(sink_err)
                    return result

                if ref.ephemeral:
                    # Connection-scoped stream (socket peer): its EOF is
                    # the lifecycle, not a premature end, and there is
                    # nothing to reconnect TO once the peer is gone.
                    if stream_err is not None and not self._stopping:
                        term.error("Error reading logs for container %s\n%s",
                                   job.container, stream_err)
                        result.error = str(stream_err)
                    return result

                if not self.log_opts.follow or self._stopping:
                    if stream_err is not None and not self._stopping:
                        term.error("Error reading logs for container %s\n%s",
                                   job.container, stream_err)
                        result.error = str(stream_err)
                    return result

                # Follow stream ended while still wanted: reconnect with
                # a server-side `since` covering the gap (plus 1s overlap
                # margin; duplicate suppression is up to downstream, as
                # with kubectl). A healthy long-lived connection resets
                # the attempt budget.
                if got_data and time.monotonic() - opened_at >= RECONNECT_HEALTHY_S:
                    attempt = 0
                if not await self._should_reconnect(job, attempt, stream_err):
                    # cmd/root.go:314-317: deferred premature-end warning.
                    result.premature_end = True
                    if stream_err is not None:
                        result.error = str(stream_err)
                    term.warning(
                        "Streaming logs ended prematurely for Pod: %s, Container: %s",
                        job.pod, job.container,
                    )
                    return result
                attempt += 1
                # Reconnect bound: gap-covering since_seconds (+1s
                # overlap) by default. A --since-time LATER than that
                # cutoff (a future or very recent bound) is the
                # stricter one and must survive the reconnect —
                # otherwise the reconnected stream can emit lines
                # before the requested bound (PodLogOptions takes ONE
                # of SinceSeconds/SinceTime, so pick the stricter;
                # ADVICE r4). previous never reaches here
                # (previous+follow is rejected at option build);
                # timestamps must survive a reconnect.
                gap_s = max(1, int(time.monotonic() - last_data) + 1)
                since_time = None
                if self.log_opts.since_time is not None:
                    from datetime import datetime, timedelta, timezone

                    try:
                        bound = datetime.fromisoformat(
                            self.log_opts.since_time.replace("Z", "+00:00"))
                        if bound.tzinfo is None:
                            bound = bound.replace(tzinfo=timezone.utc)
                        cutoff = (datetime.now(timezone.utc)
                                  - timedelta(seconds=gap_s))
                        if bound > cutoff:
                            since_time = self.log_opts.since_time
                    except ValueError:
                        pass  # unparseable bound: gap cutoff (as before)
                opts = LogOptions(
                    since_seconds=None if since_time else gap_s,
                    tail_lines=None,  # tail would re-dump history after a cut
                    follow=True,
                    container=job.container,
                    since_time=since_time,
                    timestamps=self.log_opts.timestamps,
                )
        finally:
            try:
                await sink.close()
            except SinkError as e:
                # ENOSPC at the final flush: record ONE clear error
                # (unless the worker already has one) without masking
                # an in-flight exception from the try body.
                if result.error is None:
                    term.error("Sink close failed for container %s\n%s",
                               job.container, e)
                    result.error = str(e)
            result.bytes_written = sink.bytes_written
            if self._m is not None and result.error is not None:
                self._m["errors"].inc()

    def _reconnect_policy(self) -> RetryPolicy:
        """The effective reconnect policy: the injected one, or the
        default assembled from max_reconnects + the module backoff
        knobs (read HERE, not at import, so tests can monkeypatch
        them). RetryPolicy.max_attempts keeps its documented meaning —
        ALL tries including the first — where the "first try" is the
        initial stream open, so the default grants max_reconnects
        retries (identical behavior, consistent semantics across the
        rpc/kube/fanout sites)."""
        if self.reconnect_policy is not None:
            return self.reconnect_policy
        return RetryPolicy(max_attempts=self.max_reconnects + 1,
                           base_s=_BACKOFF_BASE_S, max_s=_BACKOFF_MAX_S,
                           jitter=0.0)

    async def _should_reconnect(self, job: StreamJob, attempt: int,
                                err: "SourceError | None") -> bool:
        """Backoff-gated reconnect decision for follow mode; sleeps the
        shared RetryPolicy's backoff (stop-aware) when reconnecting —
        the same policy implementation the RPC and kube layers use.
        ``attempt`` is the 0-based count of reconnects already spent."""
        if not self.log_opts.follow or self._stopping:
            return False
        policy = self._reconnect_policy()
        if not policy.retries_left(attempt):
            return False
        delay = policy.delay_s(attempt)
        term.warning(
            "Stream for %s/%s ended (%s); reconnecting in %.1fs (attempt %d/%d)",
            job.pod, job.container, err if err else "EOF", delay,
            attempt + 1, policy.max_attempts - 1,
        )
        if not await policy.wait(delay, self._stop_ev()):
            return False  # stop fired during backoff
        if not self._stopping and self._m is not None:
            self._m["reconnects"].labels(
                pod=job.pod, container=job.container).inc()
            self._m["retries"].inc()
        return not self._stopping

    def _create_file(self, job: StreamJob) -> None:
        # Create (truncate) the log file up front (cmd/root.go:245-257).
        if not self.create_files:
            return
        os.makedirs(os.path.dirname(job.path) or ".", exist_ok=True)
        open(job.path, "wb").close()

    def _create_all_files(self, jobs: list) -> None:
        """run()'s up-front phase, batched so the whole sweep costs one
        executor hop (called via asyncio.to_thread)."""
        for job in jobs:
            self._create_file(job)

    async def _spawn(self, job: StreamJob, tasks: list) -> None:
        # makedirs + truncate are disk I/O; in follow mode the loop is
        # already streaming every other container, so they run off it.
        await asyncio.to_thread(self._create_file, job)
        tasks.append(asyncio.create_task(self._worker(job)))

    async def _discover_loop(self, plan_new, interval_s: float,
                             seen: set, tasks: list) -> None:
        """Poll-based dynamic discovery (stern-style --watch-new, beyond
        the reference, whose pod set is fixed at startup): periodically
        re-plan, spawn workers for unseen (pod, container, init) keys.
        Polling over the watch API keeps this backend-agnostic and free
        of resourceVersion bookkeeping; at reference scale a re-list
        every few seconds is far below the Burst budget. List failures
        are transient apiserver weather: warn and keep polling."""
        while not self._stopping:
            try:
                await asyncio.wait_for(self._stop_ev().wait(),
                                       timeout=interval_s)
                return  # stop fired
            except asyncio.TimeoutError:
                pass
            try:
                jobs = await plan_new()
                if self._stopping:
                    return  # stop fired while the list was in flight
                fresh = [j for j in jobs
                         if (j.pod, j.container, j.init) not in seen]
                if not fresh:
                    continue
                term.info("Discovered %d new container stream(s): %s",
                          len(fresh),
                          ", ".join(f"{j.pod}/{j.container}"
                                    for j in fresh[:6])
                          + ("…" if len(fresh) > 6 else ""))
                for j in fresh:
                    # seen only AFTER a successful spawn: a transient
                    # file-creation failure must leave the job eligible
                    # for the next poll, not silently drop it forever.
                    await self._spawn(j, tasks)
                    seen.add((j.pod, j.container, j.init))
            except Exception as e:
                # Includes _spawn's file creation (full disk, lost
                # permissions): warn and keep polling — a transient
                # fault must not silently kill discovery for the rest
                # of the session.
                term.warning("pod discovery poll failed (%s); retrying", e)

    async def run(
        self,
        jobs: list[StreamJob],
        stop: asyncio.Event | None = None,
        plan_new=None,
        discover_interval_s: float = 5.0,
    ) -> list[StreamResult]:
        """Run all stream workers to completion; if ``stop`` fires first,
        shut down cleanly (close streams, flush sinks) and return.

        ``plan_new`` (async () -> list[StreamJob], follow mode only)
        enables dynamic discovery: the plan is re-polled every
        ``discover_interval_s`` and workers spawn for jobs not yet seen
        — new pods matching the selection start streaming mid-follow.
        With discovery active the run ends on ``stop`` (new work can
        always appear), never by worker exhaustion."""
        # Two phases, as the reference does it (cmd/root.go:245-257):
        # create/truncate EVERY log file before any worker starts, so a
        # file-creation failure propagates with zero tasks running (no
        # orphaned streams to leak). Off-loop in ONE thread hop:
        # truncating hundreds of files is disk I/O, and an in-process
        # metrics sidecar may already be serving on this loop.
        await asyncio.to_thread(self._create_all_files, jobs)
        # Utilization-profiler probe: live open-stream count in the
        # /profile snapshot (read only at tick time; dropped with the
        # run so a finished runner cannot be sampled).
        from klogs_tpu.obs.profiler import PROFILER

        def _streams_probe() -> float:
            return float(len(self._streams))

        PROFILER.add_probe("fanout.active_streams", _streams_probe)
        tasks: list[asyncio.Task] = [
            asyncio.create_task(self._worker(j)) for j in jobs]

        seen = {(j.pod, j.container, j.init) for j in jobs}
        poller = (asyncio.create_task(
                      self._discover_loop(plan_new, discover_interval_s,
                                          seen, tasks))
                  if plan_new is not None and self.log_opts.follow else None)
        stop_task = asyncio.create_task(stop.wait()) if stop is not None else None

        try:
            try:
                while True:
                    pending = [t for t in tasks if not t.done()]
                    if not pending and poller is None:
                        break  # static plan fully drained
                    waiters = set(pending)
                    if stop_task is not None:
                        waiters.add(stop_task)
                    if poller is not None:
                        waiters.add(poller)
                    if not waiters:
                        break
                    done, _ = await asyncio.wait(
                        waiters, return_when=asyncio.FIRST_COMPLETED)
                    if stop_task is not None and stop_task in done:
                        await self.stop()
                        break
                    if poller is not None and poller.done():
                        # Normal exit = stop fired inside the poll loop;
                        # the loop swallows per-iteration faults, so
                        # anything else here is unexpected — surface it,
                        # don't let the task die with an unretrieved
                        # exception.
                        exc = poller.exception()
                        if exc is not None:
                            term.warning(
                                "pod discovery stopped unexpectedly: %s",
                                exc)
                        poller = None
            finally:
                if poller is not None:
                    self._stop_ev().set()
                    try:
                        await poller
                    except Exception as e:
                        term.warning(
                            "pod discovery stopped unexpectedly: %s", e)
                if stop_task is not None:
                    stop_task.cancel()
                PROFILER.remove_probe("fanout.active_streams",
                                      _streams_probe)
            return await asyncio.gather(*tasks)
        except BaseException:
            # A worker escalated (--on-filter-error=abort raising
            # Unavailable) — or run() itself was cancelled, whether in
            # the supervision wait above or mid-gather: close every
            # other stream and let the workers drain before the
            # error/cancellation surfaces, so no task is destroyed
            # pending at loop teardown.
            await self.stop()
            await asyncio.gather(*tasks, return_exceptions=True)
            raise

    async def stop(self) -> None:
        """Explicit teardown: close all live streams; workers then drain
        and flush their sinks."""
        self._stopping = True
        self._stop_ev().set()
        for s in list(self._streams):
            await s.close()
