"""Sinks: where stream bytes land.

Reference parity: writeLogToDisk (cmd/root.go:359-374) — buffered
chunked copy, NOT line-by-line (the v1.1.12 perf change,
CHANGELOG.md:60-62), flushed at stream end. FileSink preserves exactly
that: chunks go to a buffered file object untouched.

The filter stage (north star) slots in as a different Sink
implementation at this same boundary (see klogs_tpu.filters.sink),
leaving the unfiltered path byte-identical to the reference.

Failure semantics (resilience subsystem): a write/flush failure (disk
full, revoked mount) marks the sink FAILED with one clear error — a
``SinkError`` naming the path — releases the fd immediately, and every
later write re-raises that same error without touching the OS again.
Retrying a dead disk in a loop helps nobody; the fanout worker ends
the job cleanly on SinkError instead of burning its reconnect budget
(see FanoutRunner._worker). ``sink.write`` is a registered chaos fault
point (docs/RESILIENCE.md).
"""

import abc

from klogs_tpu.resilience.faults import FAULTS, InjectedFault


class SinkError(Exception):
    """A sink write/flush failed terminally; the message is the single
    operator-facing line (path + cause)."""


class Sink(abc.ABC):
    @abc.abstractmethod
    async def write(self, chunk: bytes) -> None: ...

    @abc.abstractmethod
    async def close(self) -> None:
        """Flush and release. Must be idempotent — including after a
        write/flush error already released the underlying resource."""

    async def flush(self) -> None:
        """Push buffered bytes through (for live tailing); default no-op."""

    @property
    @abc.abstractmethod
    def bytes_written(self) -> int: ...


class FileSink(Sink):
    """Buffered whole-stream copy to one log file (bufio analog)."""

    def __init__(self, path: str, buffer_size: int = 1 << 16):
        self._path = path
        # os.Create semantics: truncate on open (cmd/root.go:349)
        self._f = open(path, "wb", buffering=buffer_size)
        self._bytes = 0
        self._closed = False
        self._failed: "str | None" = None

    def _fail(self, what: str, e: BaseException) -> "SinkError":
        """Mark failed (one clear error), release the fd, and return the
        SinkError to raise. Buffered-but-unflushed bytes are already
        lost to the underlying failure; holding the fd open would only
        leak it for the rest of the run."""
        self._failed = f"{what} {self._path} failed: {e}"
        self._closed = True
        try:
            self._f.close()
        except OSError:
            pass  # close's own flush hits the same dead disk; fd is
            # released regardless (BufferedWriter closes raw on error)
        return SinkError(self._failed)

    async def write(self, chunk: bytes) -> None:
        if self._failed is not None:
            raise SinkError(self._failed)
        try:
            if FAULTS.active:
                await FAULTS.fire("sink.write")
            self._f.write(chunk)
        except (OSError, InjectedFault) as e:
            raise self._fail("write to", e) from e
        self._bytes += len(chunk)

    async def flush(self) -> None:
        if self._closed or self._failed is not None:
            return
        try:
            self._f.flush()
        except OSError as e:
            raise self._fail("flush of", e) from e

    async def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        try:
            self._f.flush()
        except OSError as e:
            # Disk filled between the last write and close: surface ONE
            # clear error, but never leak the fd (the pre-resilience
            # bug: flush raised and close() was skipped entirely).
            self._failed = f"flush of {self._path} failed: {e}"
            raise SinkError(self._failed) from e
        finally:
            try:
                self._f.close()
            except OSError:
                pass  # flush already reported; raw fd is released

    @property
    def bytes_written(self) -> int:
        return self._bytes
