"""Sinks: where stream bytes land.

Reference parity: writeLogToDisk (cmd/root.go:359-374) — buffered
chunked copy, NOT line-by-line (the v1.1.12 perf change,
CHANGELOG.md:60-62), flushed at stream end. FileSink preserves exactly
that: chunks go to a buffered file object untouched.

The filter stage (north star) slots in as a different Sink
implementation at this same boundary (see klogs_tpu.filters.sink),
leaving the unfiltered path byte-identical to the reference.
"""

import abc


class Sink(abc.ABC):
    @abc.abstractmethod
    async def write(self, chunk: bytes) -> None: ...

    @abc.abstractmethod
    async def close(self) -> None:
        """Flush and release. Must be idempotent."""

    async def flush(self) -> None:
        """Push buffered bytes through (for live tailing); default no-op."""

    @property
    @abc.abstractmethod
    def bytes_written(self) -> int: ...


class FileSink(Sink):
    """Buffered whole-stream copy to one log file (bufio analog)."""

    def __init__(self, path: str, buffer_size: int = 1 << 16):
        # os.Create semantics: truncate on open (cmd/root.go:349)
        self._f = open(path, "wb", buffering=buffer_size)
        self._bytes = 0
        self._closed = False

    async def write(self, chunk: bytes) -> None:
        self._f.write(chunk)
        self._bytes += len(chunk)

    async def flush(self) -> None:
        if not self._closed:
            self._f.flush()

    async def close(self) -> None:
        if not self._closed:
            self._closed = True
            self._f.flush()
            self._f.close()

    @property
    def bytes_written(self) -> int:
        return self._bytes
