"""Console sinks: stern-style multiplexed stdout output.

The reference writes logs only to files (writeLogToDisk,
cmd/root.go:359-374); ``-o stdout`` / ``-o both`` is an additive
capability documented in PARITY.md: each complete log line is prefixed
with its colored ``pod container`` origin and written to stdout.

Chunks are framed into lines first, so concurrent streams interleave at
line granularity — one container's line is never split by another's
output. (The fan-out runtime is single-loop asyncio and each line batch
is emitted as one ``write`` call on the shared buffer, so no extra
locking is needed.)

The prefix color is stable per pod name across runs (CRC-based, not
``hash()`` which is salted per process), like stern's pod coloring.
"""

import json
import sys
import zlib

from klogs_tpu.filters.framer import LineFramer
from klogs_tpu.runtime.sink import Sink
from klogs_tpu.ui import term

# SGR codes for pod prefixes: the six distinguishable base colors, then
# their bright variants. Red is reserved for the severity printers.
_POD_COLOR_CODES = ("36", "32", "33", "35", "34",
                    "96", "92", "93", "95", "94")


def pod_color_code(pod: str) -> str:
    """Stable pod -> SGR color code mapping."""
    return _POD_COLOR_CODES[zlib.crc32(pod.encode()) % len(_POD_COLOR_CODES)]


_HL_ON = b"\x1b[1;31m"
_HL_OFF = b"\x1b[0m"


def compile_highlights(patterns, ignore_case: bool = False) -> list:
    """--match patterns as bytes regexes for console highlighting.
    Only used when colors are on; a pattern Python `re` cannot take
    (shouldn't happen — the NFA subset is property-tested against re)
    is skipped rather than breaking the stream."""
    import re

    out = []
    for p in patterns or ():
        try:
            out.append(re.compile(p.encode(),
                                  re.IGNORECASE if ignore_case else 0))
        except (re.error, UnicodeEncodeError):
            pass
    return out


class _ConsoleSink(Sink):
    """Shared console-sink lifecycle: incremental framing, write-through
    flushing (the console is a live surface, not a bulk file copy —
    stdout's own buffering would hold lines for seconds on quiet
    streams), and a close() that emits any unterminated final fragment.
    Subclasses provide ``_render(lines) -> bytes``."""

    def __init__(self, out=None):
        self._framer = LineFramer()
        self._out = out if out is not None else sys.stdout.buffer
        self._bytes = 0
        self._closed = False

    async def write(self, chunk: bytes) -> None:
        self._emit(self._framer.feed(chunk))

    def _emit(self, lines: list) -> None:
        if not lines:
            return
        buf = self._render(lines)
        self._out.write(buf)
        self._out.flush()
        self._bytes += len(buf)

    async def flush(self) -> None:
        if not self._closed:
            self._out.flush()

    async def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        rest = self._framer.flush()
        if rest is not None:
            # Stream ended mid-line: terminate the fragment, or (in text
            # form) it would visually fuse with the next stream's prefix.
            self._emit([rest + b"\n"])
        self._out.flush()

    @property
    def bytes_written(self) -> int:
        return self._bytes


class StdoutSink(_ConsoleSink):
    """Line-prefixed console sink for one (pod, container) stream.

    ``highlight`` (compile_highlights output) wraps each --match hit in
    bold red, stern-style — only consulted when colors are on.
    """

    def __init__(self, pod: str, container: str, out=None,
                 highlight: list | None = None):
        super().__init__(out)
        prefix = f"{pod} {container}"
        if term.colors_enabled():
            prefix = f"\x1b[{pod_color_code(pod)}m{prefix}\x1b[0m"
            self._highlight = highlight or []
        else:
            self._highlight = []
        self._prefix = (prefix + " ").encode()

    def _decorate(self, ln: bytes) -> bytes:
        # Spans are computed on the RAW body (newline excluded, matching
        # RegexFilter's rstrip semantics) and the SGR codes inserted in
        # one pass afterwards — sequential re.sub would let later
        # patterns match inside earlier patterns' escape codes, and a
        # whitespace match swallowing the newline would strand the reset
        # on the next visual row.
        body = ln[:-1] if ln.endswith(b"\n") else ln
        spans = []
        for rx in self._highlight:
            for m in rx.finditer(body):
                if m.group(0):  # zero-width (e.g. `a*`) adds nothing
                    spans.append((m.start(), m.end()))
        if not spans:
            return ln
        spans.sort()
        merged = [list(spans[0])]
        for s, e in spans[1:]:
            if s <= merged[-1][1]:
                merged[-1][1] = max(merged[-1][1], e)
            else:
                merged.append([s, e])
        out = bytearray()
        prev = 0
        for s, e in merged:
            out += body[prev:s] + _HL_ON + body[s:e] + _HL_OFF
            prev = e
        out += body[prev:]
        return bytes(out) + ln[len(body):]

    def _render(self, lines: list) -> bytes:
        if self._highlight:
            lines = [self._decorate(ln) for ln in lines]
        return b"".join(self._prefix + ln for ln in lines)


class JsonStdoutSink(_ConsoleSink):
    """``-o stdout --format json``: one JSON object per log line —
    ``{"pod": ..., "container": ..., "line": ...}`` — for jq/log-shipper
    consumption (stern's ``-o json`` analog). No prefixes, colors, or
    highlighting; the line is decoded as UTF-8 with replacement (log
    bytes are not guaranteed text) and carries no trailing newline
    (close()'s fragment terminator is stripped with the rest)."""

    def __init__(self, pod: str, container: str, out=None):
        super().__init__(out)
        self._pod = pod
        self._container = container

    def _render(self, lines: list) -> bytes:
        return b"".join(
            json.dumps({
                "pod": self._pod,
                "container": self._container,
                "line": ln.rstrip(b"\n").decode("utf-8", "replace"),
            }, ensure_ascii=False).encode() + b"\n"
            for ln in lines
        )


class TeeSink(Sink):
    """Fan one stream's bytes to several sinks (``-o both``).

    ``bytes_written`` reports the FIRST sink's count — with ``both``
    that is the file, keeping the size table consistent with ``files``
    mode (the console copy carries prefixes, so its count differs).
    """

    def __init__(self, *sinks: Sink):
        if not sinks:
            raise ValueError("TeeSink needs at least one sink")
        self._sinks = sinks
        self._closed = False

    async def write(self, chunk: bytes) -> None:
        for s in self._sinks:
            await s.write(chunk)

    async def flush(self) -> None:
        for s in self._sinks:
            await s.flush()

    async def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        for s in self._sinks:
            await s.close()

    @property
    def bytes_written(self) -> int:
        return self._sinks[0].bytes_written
