"""IndexedFilter — the thousand-pattern host engine.

Two phases per batch, per "Regular Expression Indexing for Log
Analysis" (PAPERS.md): a single shared factor-index sweep
(filters/compiler/index.py) narrows each line to its candidate pattern
GROUPS, then only those groups' compiled engines scan the line. The
pattern set is partitioned by filters/compiler/groups.py — bounded,
factor-clustered groups, each compiled to the strongest engine it
admits (native DFA scan via the LRU table cache; combined-`re`; else
K-sequential `re`) — so compile cost and DFA subset construction stay
bounded at K=4096 while scan cost tracks CANDIDATES, not K.

Verdict semantics are identical to RegexFilter (any-match over the
whole set): the index is a necessary condition, so a skipped
(line, group) pair can never hide a match.

The scan-all-K comparator (``narrow=False``) runs the same group
engines over every line — bench.py's K-axis uses it to quantify the
index's win honestly (same tables, same engines, only the narrowing
differs).
"""

import time
from typing import Any

import numpy as np

from klogs_tpu.filters.base import LogFilter, frame_lines
from klogs_tpu.obs import trace
from klogs_tpu.filters.compiler.groups import (
    MAX_GROUP_PATTERNS,
    MAX_GROUP_POSITIONS,
    GroupPlan,
    PatternInfo,
    analyze,
    plan_groups,
)
from klogs_tpu.filters.compiler.index import FactorIndex

# Per-group DFA state budget: small enough that ~128 groups of tables
# stay cache-friendly and subset construction per group is sub-second;
# a group that overflows degrades to combined-`re` for just that group.
GROUP_MAX_STATES = 8192
# Lines per sweep slab: bounds the sweep's transient numpy arrays
# (~16 bytes per payload byte) regardless of caller batch size.
SLAB_LINES = 65536
# The native packed path holds only the sweep's u32 bitset
# (4*ceil(G/32) bytes per line) plus verdict bytes per slab, so it
# affords a 4x larger slab — fewer per-slab python round-trips and
# kernel warmups (a measured ~10% e2e win at K=1024 on the BENCH_K
# 100k-line corpus, where 2 slabs become 1). The numpy fallback and
# the device sweep keep the small bound above.
NATIVE_SLAB_LINES = 262144
# Device-sweep row-width cap: a slab holding a line longer than this
# sweeps on the host instead (padding every row to a jumbo line's
# width would swamp the device pass; long lines are rare in log
# corpora and the host sweep is O(payload)).
SWEEP_MAX_WIDTH = 4096
# ... and a padded-batch byte cap: ONE moderately long line in a full
# slab would otherwise pad rows x width to hundreds of MB (65536 rows
# x 4096 B = 256 MB for ~10 MB of payload). Past the cap the slab
# narrows on the host — same degrade, bounded memory.
SWEEP_MAX_BATCH_BYTES = 64 << 20
# Adaptive bypass (KLOGS_INDEX_BYPASS_RATIO / _LINES): once this many
# lines have been swept, a cumulative narrowing ratio still above the
# threshold means the index is not paying for itself on this stream —
# switch to scan-all for subsequent batches and say so once.
BYPASS_RATIO = 0.5
BYPASS_MIN_LINES = 65536
# Adaptive re-guard (KLOGS_INDEX_DENSE_RATIO / _LINES): after the
# probation window, guard factors observed in more than DENSE_RATIO of
# swept lines are banned and the index re-guarded — see the
# constructor comment. RATIO >= 1 disables (no factor can exceed it).
DENSE_RATIO = 0.5
DENSE_MIN_LINES = 65536
# One loud notice per process when auto mode wanted the native batched
# group scan but the extension is unavailable (mirrors the sweep's
# _warned_no_native discipline).
_warned_no_groupscan = False


class _Group:
    """One compiled pattern group: members + the strongest engine the
    group admits."""

    def __init__(self, members: "list[int]", patterns: "list[str]",
                 ignore_case: bool, cache: bool,
                 on_cache_event: Any) -> None:
        import re as _re

        from klogs_tpu.filters.cpu import (
            _GROUP_REF_RE,
            CombinedRegexFilter,
            DFAFilter,
            DFAStateOverflow,
            RegexFilter,
        )

        self.members = members
        self.patterns = patterns
        # True when the DFA failed on the STATE BUDGET alone: the set
        # is compilable, just not together — the group builder bisects
        # those instead of degrading every member to combined-re.
        self.split_hint = False
        try:
            self.filt: LogFilter = DFAFilter(
                patterns, ignore_case=ignore_case,
                max_states=GROUP_MAX_STATES, cache=cache,
                cache_events=on_cache_event)
            self.kind = "dfa"
            return
        except DFAStateOverflow:
            self.split_hint = True
        except Exception:
            pass
        if any(_GROUP_REF_RE.search(p) for p in patterns):
            # Renumbering-sensitive groups stay K-sequential (same rule
            # as best_host_filter; see filters/cpu.py).
            self.filt = RegexFilter(patterns, ignore_case=ignore_case)
            self.kind = "re"
            return
        try:
            self.filt = CombinedRegexFilter(patterns,
                                            ignore_case=ignore_case)
            self.kind = "combined-re"
        except _re.error:
            self.filt = RegexFilter(patterns, ignore_case=ignore_case)
            self.kind = "re"


def _build_groups(members: "list[int]", patterns: "list[str]",
                  ignore_case: bool, cache: bool,
                  on_cache_event: Any) -> "list[_Group]":
    """Compile one planned group, bisecting on DFA state overflow.

    Half the union usually fits the budget (subset construction grows
    superlinearly in the union automaton), and every half that does
    rides the batched native group_scan instead of degrading the WHOLE
    group to the per-line combined-re path — a measured ~8 us/row
    confirm tail at K=256 (BENCH_K merge_s 0.58 s vs 0.04 s of
    group_scan). Singletons that still overflow genuinely degrade."""
    grp = _Group(members, [patterns[i] for i in members], ignore_case,
                 cache, on_cache_event)
    if grp.kind == "dfa" or not grp.split_hint or len(members) < 2:
        return [grp]
    mid = (len(members) + 1) // 2
    return (_build_groups(members[:mid], patterns, ignore_case, cache,
                          on_cache_event)
            + _build_groups(members[mid:], patterns, ignore_case,
                            cache, on_cache_event))


class IndexedFilter(LogFilter):
    """Factor-index narrowing + per-group scan (module docstring)."""

    def __init__(self, patterns: "list[str]", ignore_case: bool = False,
                 *, narrow: bool = True, cache: bool = True,
                 max_group_patterns: int = MAX_GROUP_PATTERNS,
                 max_group_positions: int = MAX_GROUP_POSITIONS,
                 registry: Any = None, sweep: str = "auto") -> None:
        if not patterns:
            raise ValueError("IndexedFilter needs at least one pattern")
        if sweep not in ("auto", "host", "device"):
            raise ValueError(
                f"sweep={sweep!r}: expected auto, host or device")
        from klogs_tpu.obs.metrics import Registry

        self.registry = registry if registry is not None else Registry()
        r = self.registry
        self._m_clauses = r.family("klogs_prefilter_pattern_clauses")
        self._m_factors = r.family("klogs_prefilter_pattern_factors")
        self._m_ratio = r.family("klogs_prefilter_narrowing_ratio")
        self._m_groups = r.family("klogs_prefilter_groups")
        cache_events = r.family("klogs_prefilter_table_cache_events_total")
        self._m_cache = {kind: cache_events.labels(event=kind)
                         for kind in ("hit", "miss", "evict")}
        self._m_sweep_batches = r.family("klogs_sweep_batches_total")
        self._m_sweep_lines = r.family("klogs_sweep_lines_total")
        self._m_sweep_cand = r.family("klogs_sweep_candidate_lines_total")
        self._m_sweep_s = r.family("klogs_sweep_seconds")
        self._m_sweep_fallback = r.family("klogs_sweep_fallback_total")
        self._m_bypass = r.family("klogs_sweep_bypass_total")
        self._m_sweep_impl = r.family("klogs_sweep_impl_batches_total")
        gs_batches = r.family("klogs_groupscan_batches_total")
        gs_rows = r.family("klogs_groupscan_rows_total")
        gs_cells = r.family("klogs_groupscan_cells_total")
        gs_s = r.family("klogs_groupscan_seconds")
        self._m_gs = {impl: (gs_batches.labels(impl=impl),
                             gs_rows.labels(impl=impl),
                             gs_cells.labels(impl=impl),
                             gs_s.labels(impl=impl))
                      for impl in ("native", "python")}
        self._m_gs_fallback = r.family("klogs_groupscan_fallback_total")
        self._m_reguard = r.family("klogs_prefilter_reguard_total")

        self.narrow = narrow
        self.infos: "list[PatternInfo]" = analyze(
            patterns, ignore_case=ignore_case)
        self.plan: GroupPlan = plan_groups(
            self.infos, max_group_patterns=max_group_patterns,
            max_group_positions=max_group_positions)
        for info in self.infos:
            self._m_clauses.observe(info.clauses)
            self._m_factors.observe(info.factors)
        # Compile groups, bisecting any whose union DFA overflows the
        # state budget (_build_groups); when a split happened, the plan
        # is re-derived so the index's group columns stay 1:1 with the
        # compiled groups.
        always = set(int(g) for g in self.plan.always_groups)
        self.groups = []
        split_members: "list[list[int]]" = []
        split_always: "list[int]" = []
        for g, members in enumerate(self.plan.groups):
            for grp in _build_groups(members, patterns, ignore_case,
                                     cache, self._on_cache_event):
                if g in always:
                    split_always.append(len(split_members))
                split_members.append(grp.members)
                self.groups.append(grp)
        if len(split_members) != len(self.plan.groups):
            group_of = np.zeros(len(self.infos), dtype=np.int32)
            for gi, members in enumerate(split_members):
                for p in members:
                    group_of[p] = gi
            self.plan = GroupPlan(groups=split_members,
                                  group_of=group_of,
                                  always_groups=tuple(split_always))
        self.index = FactorIndex(self.infos, self.plan)
        self._m_groups.set(len(self.groups))
        # Group partition for the confirm stage: DFA-backed groups ride
        # the batched MultiDFA native scan (one group_scan call per
        # slab); the combined-re/re remainder keeps the per-group
        # Python path.
        self._dfa_cols = [g for g, grp in enumerate(self.groups)
                          if grp.kind == "dfa"]
        self._dfa_cols_arr = np.asarray(self._dfa_cols, dtype=np.int32)
        self._rest_cols = [g for g, grp in enumerate(self.groups)
                           if grp.kind != "dfa"]
        # MultiDFA program blob cache: rebuilt (incrementally, via the
        # per-member chunk cache) only when a member group's tables
        # object changes — e.g. the DFA LRU refreshed it.
        self._mdfa_key: Any = None
        self._mdfa_blob: "bytes | None" = None
        self._mdfa_chunks: "dict[int, tuple[bytes, bytes, bytes]]" = {}
        self._groupscan_broken = False
        # Per-stage time attribution (BENCH_K's sweep_s / group_scan_s
        # / merge_s breakdown): cumulative seconds per pipeline stage,
        # and which confirm implementation the last slab ran.
        self.stage_s = {"sweep": 0.0, "group_scan": 0.0, "merge": 0.0}
        self.group_scan_impl = "python"
        # Cumulative narrowing tallies (bench/introspection).
        self.swept_lines = 0
        self.swept_cells = 0
        self.candidate_cells = 0
        self.candidate_lines = 0
        # Adaptive bypass state: once the stream's cumulative narrowing
        # ratio proves the index is not narrowing (class satellite:
        # BENCH_K K=32 ratio 0.67 -> indexed 0.18x of scan-all), stop
        # paying the sweep. bypassed is only ever flipped on, and only
        # after _bypass_min_lines have been swept.
        self.bypassed = False
        self._bypass_ratio = _env_float(
            "KLOGS_INDEX_BYPASS_RATIO", BYPASS_RATIO)
        self._bypass_min_lines = int(_env_float(
            "KLOGS_INDEX_BYPASS_LINES", BYPASS_MIN_LINES))
        # Adaptive re-guard (one-shot, probation-gated like the
        # bypass): a guard factor observed in ~every line narrows
        # nothing while taxing every sweep position AND making its
        # groups dense-candidate — after KLOGS_INDEX_DENSE_LINES swept
        # lines, factors whose line-hit density exceeds
        # KLOGS_INDEX_DENSE_RATIO are BANNED and the index rebuilt:
        # ban-aware guard extraction (factors.guard_factors) re-guards
        # each affected pattern on its next-best clause ("FATAL|CRIT"
        # instead of an omnipresent "code="), or degrades it to
        # always-candidate. Groups, plans, and compiled engines are
        # untouched; verdicts cannot change (the guard stays a
        # necessary condition under any ban).
        self._ignore_case = ignore_case
        self._reguarded = False
        self.banned_factors: "tuple[bytes, ...]" = ()
        self._dense_ratio = _env_float(
            "KLOGS_INDEX_DENSE_RATIO", DENSE_RATIO)
        self._dense_min_lines = int(_env_float(
            "KLOGS_INDEX_DENSE_LINES", DENSE_MIN_LINES))
        # Narrowing stage: the device sweep (ops/sweep.py via jax) when
        # requested — or in auto mode when a real accelerator backend
        # is up — else the host sweep. Device-path failures fall back
        # to the host sweep loudly and permanently (the host sweep is
        # the parity oracle, so the verdicts cannot change).
        self._sweep_path = "host"
        self._sweep_tables: Any = None
        # Slab pipeline depth (KLOGS_SWEEP_PIPELINE): in-flight slabs
        # per frame, 1 = the serial schedule. Parsed once per filter —
        # the knob is deployment config, not per-batch state.
        self._pipe_depth = _sweep_pipeline_depth()
        if sweep != "host":
            self._init_device_sweep(sweep)

    def _init_device_sweep(self, sweep: str) -> None:
        import sys

        if sweep == "auto":
            from klogs_tpu.filters.cpu import device_sweep_env

            if device_sweep_env() == "0":
                # KLOGS_TPU_SWEEP=0 kills every AUTO sweep path — the
                # host engine's device narrowing included. An explicit
                # sweep="device" constructor arg is code, not config,
                # and stays above the env knob.
                return
            if "jax" not in sys.modules:
                # A process that never imported jax is a --backend=cpu
                # deployment (jax is the optional [tpu] extra): auto
                # mode must not pay the jax import — let alone a
                # device-client init — for a narrowing stage it would
                # reject anyway.
                return
        try:
            import jax

            from klogs_tpu.ops.sweep import device_sweep_tables
        except ImportError:
            if sweep == "device":
                raise
            return  # expected configuration, not a degrade
        try:
            if sweep == "auto" and jax.default_backend() in ("cpu",):
                # Dense device sweep on the CPU backend is gather-bound
                # and loses to the host sweep (BENCH_SWEEP.json) —
                # auto only flips on real accelerators.
                return
            self._sweep_tables = device_sweep_tables(
                self.index.sweep_program())
            self._sweep_path = "device"
        except Exception as e:
            if sweep == "device":
                raise
            from klogs_tpu.ui import term

            term.warning(
                "device sweep unavailable (%s: %s); narrowing on the "
                "host sweep", type(e).__name__, e)

    def _on_cache_event(self, kind: str) -> None:
        c = self._m_cache.get(kind)
        if c is not None:
            c.inc()

    @property
    def narrowing_ratio(self) -> float:
        """Cumulative fraction of (line, group) scans the index let
        through (1.0 = no narrowing; lower is better)."""
        return (self.candidate_cells / self.swept_cells
                if self.swept_cells else 1.0)

    @property
    def engine_kinds(self) -> "dict[str, int]":
        out: "dict[str, int]" = {}
        for g in self.groups:
            out[g.kind] = out.get(g.kind, 0) + 1
        return out

    # -- matching -----------------------------------------------------

    def match_lines(self, lines: "list[bytes]") -> "list[bool]":
        payload, offsets, _ = frame_lines(lines)
        return self._match_frame(payload, np.asarray(offsets)).tolist()

    def dispatch_framed(self, payload: bytes, offsets: Any) -> Any:
        return self._match_frame(
            payload, np.ascontiguousarray(offsets, dtype=np.int32))

    def fetch_framed(self, handle: Any) -> np.ndarray:
        return np.asarray(handle, dtype=bool)

    def _match_frame(self, payload: bytes,
                     offsets: np.ndarray) -> np.ndarray:
        n = len(offsets) - 1
        out = np.zeros(n, dtype=bool)
        # Zero-copy slab views: a bytes slice would copy the whole
        # slab (~8 MB, ~1 ms/dispatch at 100k lines); every consumer
        # downstream (native "y*" parsers, np.frombuffer, re) takes
        # any buffer object.
        view = memoryview(payload)
        slab = SLAB_LINES
        native = (self.narrow and not self.bypassed
                  and self._sweep_path != "device"
                  and self.index.native_ready())
        if native:
            slab = NATIVE_SLAB_LINES
        if native and self._pipe_depth >= 2 and n > slab:
            self._match_frame_pipelined(view, offsets, out, slab)
            return out
        for lo in range(0, n, slab):
            hi = min(n, lo + slab)
            base = int(offsets[lo])
            sub_off = (offsets[lo:hi + 1] - base).astype(np.int32)
            sub_pay = view[base:int(offsets[hi])]
            out[lo:hi] = self._match_slab(sub_pay, sub_off)
        return out

    def _match_frame_pipelined(self, view: memoryview,
                               offsets: np.ndarray, out: np.ndarray,
                               slab: int) -> None:
        """Bounded slab pipeline (KLOGS_SWEEP_PIPELINE): a small worker
        pool sweeps slabs i+1..i+depth-1 while the main thread confirms
        slab i. Safe because the prefetched stage is stateless
        (FactorIndex.sweep_packed_stateless: immutable program blob,
        call-local stats buffer, kernel drops the GIL for the whole
        scan) and EVERY shared mutation — stats folds, adaptive
        bypass/re-guard probes, verdict writes — stays on the main
        thread in slab order, so verdicts and cumulative stats are
        byte-identical to the serial schedule (the off path below is
        the parity oracle).

        An adaptive flip mid-frame (bypass, or a re-guard swapping
        ``self.index``) invalidates in-flight prefetches — they swept
        the OLD index's program — so the rest of the frame finishes on
        the serial path, which re-reads the adaptive state per slab."""
        from collections import deque
        from concurrent.futures import ThreadPoolExecutor

        n = len(offsets) - 1
        index = self.index
        # Build the shared read-only blobs on the MAIN thread before a
        # worker can race their lazy, unlocked caches.
        index.native_sweep_blob()
        if len(self._dfa_cols):
            self._multidfa()
        bounds = [(lo, min(n, lo + slab)) for lo in range(0, n, slab)]

        def subframe(lo: int, hi: int):
            base = int(offsets[lo])
            sub_off = (offsets[lo:hi + 1] - base).astype(np.int32)
            return view[base:int(offsets[hi])], sub_off

        def guard_state():
            return (self.bypassed, self._reguarded, id(self.index),
                    self._sweep_path)

        state = guard_state()
        pending: "deque" = deque()
        nxt = 0
        with ThreadPoolExecutor(
                max_workers=self._pipe_depth - 1) as pool:
            for i, (lo, hi) in enumerate(bounds):
                # Keep up to depth slabs in flight: this one (about to
                # confirm) plus depth-1 prefetched sweeps.
                while nxt < len(bounds) and nxt - i < self._pipe_depth:
                    sp, so = subframe(*bounds[nxt])
                    pending.append(pool.submit(
                        index.sweep_packed_stateless, sp, so))
                    nxt += 1
                sp, so = subframe(lo, hi)
                out[lo:hi] = self._match_slab(
                    sp, so, prefetched=pending.popleft().result())
                if guard_state() != state:
                    for f in pending:
                        f.cancel()
                    pending.clear()
                    for lo2, hi2 in bounds[i + 1:]:
                        sp, so = subframe(lo2, hi2)
                        out[lo2:hi2] = self._match_slab(sp, so)
                    return

    def _match_slab(self, payload: bytes, offsets: np.ndarray,
                    prefetched: "tuple | None" = None) -> np.ndarray:
        B = len(offsets) - 1
        if self.narrow and not self.bypassed:
            t0 = time.perf_counter()
            path = "host"
            gm = None
            packed = None
            with trace.TRACER.span("device.sweep", lines=B) as sp:
                if self._sweep_path == "device":
                    gm = self._device_candidates(payload, offsets)
                    if gm is not None:
                        path = "device"
                if gm is None:
                    # Keep the sweep's packed bit words when the native
                    # kernel ran: the packed group_scan consumes them
                    # zero-copy, so neither the per-slab unpackbits nor
                    # the bool matrix ever materializes on the fast
                    # path. A pipelined caller hands the sweep result
                    # in pre-computed; folding it here keeps the stats
                    # in slab order.
                    if prefetched is not None:
                        packed = self.index.adopt_sweep(prefetched, B)
                    else:
                        packed = self.index.group_candidates_packed(
                            payload, offsets)
                    if packed is None:
                        gm = self.index.group_candidates(payload,
                                                         offsets)
                sp.set_attr("path", path)
            G = len(self.groups)
            if path == "host":
                # group_candidates already tallied this gm into
                # last_stats — reuse it instead of re-reducing a
                # multi-MB bool matrix (a measured ~4ms/slab at
                # K=1024, pure duplication).
                cand_lines = self.index.last_stats.candidate_lines
                cand_cells = self.index.last_stats.candidate_cells
            else:
                cand_lines = int(gm.any(axis=1).sum())
                cand_cells = int(gm.sum())
            self.swept_lines += B
            self.swept_cells += B * G
            self.candidate_cells += cand_cells
            self.candidate_lines += cand_lines
            ratio = cand_cells / (B * G) if B and G else 1.0
            self._m_ratio.observe(ratio)
            # Which implementation narrowed: the device kernel, the
            # native SIMD kernel, or the numpy fallback (host path).
            impl = ("device" if path == "device"
                    else self.index.last_impl)
            self._m_sweep_impl.labels(impl=impl).inc()
            self._m_sweep_batches.labels(path=path).inc()
            self._m_sweep_lines.labels(path=path).inc(B)
            self._m_sweep_cand.labels(path=path).inc(cand_lines)
            dt = time.perf_counter() - t0
            self.stage_s["sweep"] += dt
            self._m_sweep_s.labels(path=path).observe(dt)
            self._maybe_bypass()
            if not self._reguarded \
                    and self.swept_lines >= self._dense_min_lines:
                self._maybe_reguard(payload, offsets)
            colsums = (self.index.last_stats.col_cells
                       if path == "host" else None)
            return self._scan_candidates(payload, offsets, gm,
                                         colsums=colsums,
                                         cand_lines=cand_lines,
                                         packed=packed)
        gm = np.ones((B, len(self.groups)), dtype=bool)
        self.swept_lines += B
        self.swept_cells += B * len(self.groups)
        self.candidate_cells += B * len(self.groups)
        self.candidate_lines += B
        return self._scan_candidates(
            payload, offsets, gm,
            colsums=np.full(len(self.groups), B, dtype=np.int64))

    def _scan_candidates(self, payload: bytes, offsets: np.ndarray,
                         gm: "np.ndarray | None",
                         colsums: "np.ndarray | None" = None,
                         cand_lines: "int | None" = None,
                         packed: "np.ndarray | None" = None
                         ) -> np.ndarray:
        """The confirm stage: run each line's candidate groups until
        one accepts. DFA-backed groups go through ONE batched native
        group_scan call per slab (zero sub-frame copies, GIL released;
        the per-group loop below is the KLOGS_NATIVE_GROUPSCAN=off /
        no-toolchain fallback and the parity oracle — mask-identical
        by construction since every (row, group) verdict is the same
        DFA table walk). The combined-re/re remainder always takes the
        per-group path, after the DFA groups so it inherits their
        accepts as early-outs.

        ``packed`` (with ``gm=None``) is the sweep's raw u32 bitset;
        the native group_scan reads it directly and the bool matrix is
        only materialized if the Python fallback has to run."""
        B = len(offsets) - 1
        out = np.zeros(B, dtype=bool)
        arr = np.frombuffer(payload, dtype=np.uint8)
        lens = np.diff(offsets)
        t0 = time.perf_counter()
        impl = "python"
        rows_in = 0
        with trace.TRACER.span("device.groupscan", lines=B,
                               groups=len(self.groups)) as sp:
            scanned: "int | None" = None
            if self._dfa_cols and B:
                if gm is not None:
                    gm = np.ascontiguousarray(gm)
                # Per-member candidate counts drive the scan order
                # (most selective first) and the rows-in figure; the
                # sweep's own column reduction is reused when it ran
                # (the engine always passes it alongside packed bits —
                # the unpack below only serves direct test callers).
                if colsums is None:
                    if gm is None:
                        gm = np.unpackbits(packed.view(np.uint8),
                                           axis=1, bitorder="little",
                                           count=len(self.groups)
                                           ).view(bool)
                    colsums = gm.sum(axis=0, dtype=np.int64)
                dsum = colsums[self._dfa_cols_arr]
                # Lines entering confirm: the sweep's C-side count
                # when it ran (re-reducing a multi-MB bool matrix here
                # costs ~4ms/slab); the tiny overcount from rest-only
                # candidate rows is irrelevant to the gauge.
                rows_in = (B if len(dsum) and int(dsum.max()) == B
                           else cand_lines if cand_lines is not None
                           else int(gm[:, self._dfa_cols]
                                    .any(axis=1).sum()))
                scanned = self._groupscan_native(
                    payload, offsets,
                    gm if packed is None else packed, dsum, out,
                    packed=packed is not None)
            if scanned is None:
                if gm is None:
                    gm = np.unpackbits(packed.view(np.uint8), axis=1,
                                       bitorder="little",
                                       count=len(self.groups)
                                       ).view(bool)
                scanned = 0
                for g in self._dfa_cols:
                    scanned += self._scan_group(g, gm[:, g], out,
                                                payload, offsets, arr,
                                                lens)
            else:
                impl = "native"
            dt = time.perf_counter() - t0
            self.stage_s["group_scan"] += dt
            self.group_scan_impl = impl
            sp.set_attr("impl", impl)
            sp.set_attr("rows", rows_in)
            sp.set_attr("cells", int(scanned))
            m_batches, m_rows, m_cells, m_s = self._m_gs[impl]
            m_batches.inc()
            m_rows.inc(rows_in)
            m_cells.inc(int(scanned))
            m_s.observe(dt)
        t1 = time.perf_counter()
        for g in self._rest_cols:
            # Packed fast path: extract just this group's column (one
            # shift+mask over B words) instead of unpacking the whole
            # bitset for a handful of rest groups.
            col = (gm[:, g] if gm is not None
                   else ((packed[:, g >> 5] >> np.uint32(g & 31))
                         & np.uint32(1)).astype(bool))
            self._scan_group(g, col, out, payload, offsets, arr, lens)
        self.stage_s["merge"] += time.perf_counter() - t1
        return out

    def _scan_group(self, g: int, col: np.ndarray, out: np.ndarray,
                    payload: bytes, offsets: np.ndarray,
                    arr: np.ndarray, lens: np.ndarray) -> int:
        """One group's engine over its candidate rows (``col``, bool
        [B]) not yet accepted (the per-group path). Returns the number
        of rows scanned."""
        grp = self.groups[g]
        B = len(out)
        if not col.any():
            return 0
        rows = np.nonzero(col & ~out)[0]  # already-kept rows skip
        if not len(rows):
            return 0
        if col.all() and 2 * len(rows) >= B:
            # Whole slab is candidate and most rows still undecided
            # (always-candidate groups, the scan-all comparator): the
            # engine's framed fast path — gathering a near-full
            # sub-frame copy costs more than re-scanning the few
            # already-kept rows. Once MOST rows are accepted, the
            # gathered branch below takes over so a cheap earlier
            # group's accepts are not re-scanned wholesale (they
            # were, before PR 14).
            verd = np.asarray(grp.filt.fetch_framed(
                grp.filt.dispatch_framed(payload, offsets)))
            out |= verd[:B]
            return B  # the whole frame was scanned (cells metric)
        # Candidate rows ride the framed path too: a vectorized
        # ragged gather builds the sub-frame (no per-line PyBytes —
        # the whole narrow path stays at C speed).
        sub_pay, sub_off = _gather_frame(arr, offsets, lens, rows)
        verd = np.asarray(grp.filt.fetch_framed(
            grp.filt.dispatch_framed(sub_pay, sub_off)))
        out[rows[verd[:len(rows)]]] = True
        return len(rows)

    # -- batched native group scan ------------------------------------

    def _multidfa(self) -> bytes:
        """The cached MultiDFA program blob over the DFA-backed
        groups' tables (compiler/index.py multidfa_blob). Rebuilt —
        reusing unchanged members' serialized chunks — only when a
        member's tables object changed (DFA LRU refresh)."""
        from klogs_tpu.filters.compiler.index import multidfa_blob

        tables = [self.groups[g].filt.tables for g in self._dfa_cols]
        key = tuple(id(t) for t in tables)
        if self._mdfa_key != key or self._mdfa_blob is None:
            live = set(key)
            for stale in [k for k in self._mdfa_chunks
                          if k not in live]:
                del self._mdfa_chunks[stale]
            self._mdfa_blob = multidfa_blob(tables,
                                            chunks=self._mdfa_chunks)
            self._mdfa_key = key
        return self._mdfa_blob

    def _groupscan_native(self, payload: bytes, offsets: np.ndarray,
                          cand: np.ndarray, dsum: np.ndarray,
                          out: np.ndarray,
                          packed: bool = False) -> "int | None":
        """One batched group_scan call over every (row, DFA-group)
        candidate cell, writing verdicts into ``out`` in place (native
        kernel in _hostops.c; monotonic 0->1 writes only). ``cand`` is
        passed WHOLE — zero copies — with a stride + member-column
        map: the bool [B, G] matrix, or with ``packed=True`` the
        sweep's raw u32[B, ceil(G/32)] bitset (the kernel indexes bit
        cols[m] instead of byte column cols[m], so the same
        ``_dfa_cols_arr`` serves both shapes). ``dsum`` is the
        per-DFA-member candidate count. Returns the scanned-cell
        count, or None when the per-group Python loop should run
        instead (KLOGS_NATIVE_GROUPSCAN=off, no toolchain, or a
        previous kernel failure)."""
        from klogs_tpu.filters.compiler.index import (
            native_groupscan_mode,
        )

        mode = native_groupscan_mode()
        if mode == "off" or self._groupscan_broken:
            return None
        from klogs_tpu.native import hostops

        if hostops is None or not hasattr(hostops, "group_scan"):
            if mode == "native":
                raise RuntimeError(
                    "native group scan unavailable (extension not "
                    "loaded) with KLOGS_NATIVE_GROUPSCAN=native")
            global _warned_no_groupscan
            if not _warned_no_groupscan:
                _warned_no_groupscan = True
                from klogs_tpu.ui import term

                term.warning(
                    "native group scan unavailable (no C toolchain?); "
                    "confirming on the per-group loop for this process")
            return None
        # Most selective group first: rows accepted by a rarely-
        # candidate group (a factor hit is a strong match signal) skip
        # the broader — and the always-candidate — groups entirely.
        # Members with zero candidates are omitted outright (the
        # kernel pays a full column skip-walk per listed member).
        order = np.argsort(dsum, kind="stable").astype(np.int32)
        order = np.ascontiguousarray(order[dsum[order] > 0])
        off = np.ascontiguousarray(offsets, dtype=np.int32)
        try:
            return int(hostops.group_scan(
                self._multidfa(), payload, off, len(off) - 1, cand,
                cand.shape[1], self._dfa_cols_arr, order, out,
                1 if packed else 0))
        except Exception as e:
            if mode == "native":
                raise
            # Loud, counted, permanent: the per-group loop is mask-
            # identical, so verdicts cannot change — but a fleet
            # silently confirming several times slower than
            # provisioned is a capacity incident.
            self._groupscan_broken = True
            self._m_gs_fallback.inc()
            trace.flight_trigger("groupscan-fallback", error=str(e))
            from klogs_tpu.ui import term

            term.warning(
                "native group scan failed (%s); per-group loop from "
                "here on", str(e)[:120])
            return None

    def _maybe_bypass(self) -> None:
        """Adaptive bypass: after the probation window, a cumulative
        narrowing ratio above the threshold means the sweep is not
        ruling out enough scans to pay for itself — switch this stream
        to scan-all for subsequent batches and say so ONCE."""
        if (self.bypassed
                or self.swept_lines < self._bypass_min_lines
                or self.narrowing_ratio <= self._bypass_ratio):
            return
        self.bypassed = True
        self._m_bypass.inc()
        from klogs_tpu.ui import term

        term.info(
            "index narrowing ratio %.2f stayed above %.2f after %d "
            "lines; switching to scan-all for subsequent batches",
            self.narrowing_ratio, self._bypass_ratio, self.swept_lines)

    def _maybe_reguard(self, payload: bytes,
                       offsets: np.ndarray) -> None:
        """One-shot adaptive re-tune of the narrowing tables
        (constructor comment), two measurements off one probation
        slab:

        - **re-guard**: per-FACTOR line-hit density via the numpy
          sweep's own hit extraction — factors present in ~every line
          are banned and their patterns re-guarded on next-best
          clauses;
        - **re-anchor**: observed 4-byte-code densities — probe
          windows the static prior placed on corpus-dense text
          (``errcode=00881`` anchored on ``code``) move to the
          window the corpus actually keeps rare.

        Only the index tables rebuild; groups and compiled engines
        are untouched and verdicts cannot change (necessity holds
        under any ban, and anchoring only moves probe windows WITHIN
        factors)."""
        B = len(offsets) - 1
        # The measurement slab must itself be representative: a tiny
        # follow-mode batch crossing the probation threshold would
        # otherwise ban a needle factor that merely appeared in it
        # (B=1, thresh 0.5 -> one occurrence reads as "dense",
        # permanently). Keep the one-shot ARMED until a big-enough
        # slab arrives; an explicit low KLOGS_INDEX_DENSE_LINES opts
        # into smaller measurement slabs.
        if B < min(1024, self._dense_min_lines):
            return
        self._reguarded = True
        if self._dense_ratio >= 1.0:
            return
        thresh = self._dense_ratio * B
        # Aggregate hit lines PER FACTOR before thresholding: the
        # ext tier (3-byte factors) reports up to 256 separate
        # (fid, lines) tuples — one per extension code — and exactly
        # the omnipresent short guards this measurement targets would
        # otherwise slip under the threshold piecewise.
        agg: "dict[int, np.ndarray]" = {}
        for fi, lines in self.index._hits(payload, offsets):
            prev = agg.get(fi)
            agg[fi] = lines if prev is None else np.union1d(prev, lines)
        ban = {self.index.factors[fi]
               for fi, hit in agg.items() if len(hit) > thresh}
        code_freq = self._dense_codes(payload)
        if not ban and not code_freq:
            return
        from klogs_tpu.filters.compiler.groups import reguard_infos
        from klogs_tpu.filters.compiler.index import (
            FactorIndex,
            sweep_factor,
        )

        infos2 = (reguard_infos(
            self.infos, ignore_case=self._ignore_case,
            banned=lambda f: sweep_factor(f) in ban)
            if ban else self.infos)
        new_index = FactorIndex(infos2, self.plan,
                                code_freq=code_freq)
        if self._sweep_path == "device":
            try:
                from klogs_tpu.ops.sweep import device_sweep_tables

                self._sweep_tables = device_sweep_tables(
                    new_index.sweep_program())
            except Exception as e:
                # Same terminal degrade as a device-sweep failure: the
                # host sweep is the parity oracle, verdicts unchanged.
                self._sweep_path = "host"
                self._m_sweep_fallback.inc()
                trace.flight_trigger("sweep-fallback", error=str(e))
        self.infos = infos2
        self.index = new_index
        self.banned_factors = tuple(sorted(ban))
        if ban:
            self._m_reguard.inc(len(ban))
        from klogs_tpu.ui import term

        term.info(
            "re-tuned index after %d lines: %d dense guard factor(s) "
            "banned (density > %.2f), %d dense probe code(s) "
            "re-anchored around", self.swept_lines, len(ban),
            self._dense_ratio, len(code_freq))

    @staticmethod
    def _dense_codes(payload: bytes) -> "dict[int, int]":
        """Observed-dense 4-byte codes of (a sample of) the slab: the
        re-anchor's density map. Only codes at per-line-ish density
        survive (the map stays tens of entries, not a corpus
        histogram); everything absent reads as rare."""
        cap = min(len(payload), 1 << 21)
        if cap < 4096:
            return {}
        arr = np.frombuffer(payload, dtype=np.uint8, count=cap)
        b = arr[:cap - 3].astype(np.uint32)
        code = (b | (arr[1:cap - 2].astype(np.uint32) << np.uint32(8))
                | (arr[2:cap - 1].astype(np.uint32) << np.uint32(16))
                | (arr[3:cap].astype(np.uint32) << np.uint32(24)))
        if not np.little_endian:  # match _code_at's native-order codes
            code = ((code & np.uint32(0xFF)) << np.uint32(24)
                    | (code & np.uint32(0xFF00)) << np.uint32(8)
                    | (code >> np.uint32(8)) & np.uint32(0xFF00)
                    | code >> np.uint32(24))
        vals, counts = np.unique(code, return_counts=True)
        # Keep anything near or above ~0.2% of sample positions (a few
        # hundred entries): the re-anchor compares candidate windows
        # by MINIMUM observed count, so mid-density codes (a literal
        # on 25% of lines) must be visible too, not just omnipresent
        # ones.
        keep = counts > max(8, cap >> 12)
        return {int(v): int(c)
                for v, c in zip(vals[keep], counts[keep])}

    def _device_candidates(self, payload: bytes,
                           offsets: np.ndarray) -> "np.ndarray | None":
        """Device-sweep narrowing for one slab: pack the framed rows
        into a width-bucketed [B', W] batch (vectorized ragged scatter,
        power-of-two buckets for jit-cache discipline) and run the
        jitted sweep. Returns None — host takes over — when the slab
        holds a line past SWEEP_MAX_WIDTH, or permanently after a
        device failure (loud, counted)."""
        lens = np.diff(offsets).astype(np.int64)
        B = len(lens)
        wmax = int(lens.max()) if B else 0
        if wmax > SWEEP_MAX_WIDTH:
            return None
        width = 128
        while width < wmax:
            width *= 2
        rows = 8
        while rows < B:
            rows *= 2
        if rows * width > SWEEP_MAX_BATCH_BYTES:
            return None
        try:
            from klogs_tpu.filters.base import pack_framed_rows
            from klogs_tpu.ops.sweep import (
                sweep_group_candidates,
                sweep_span_attrs,
            )

            sp = trace.TRACER.current_span()
            if sp is not None and sp.sampled:
                for k, v in sweep_span_attrs(self._sweep_tables).items():
                    sp.set_attr(k, v)
            batch, _ = pack_framed_rows(payload, offsets, width,
                                        rows=rows)
            gm = np.asarray(sweep_group_candidates(
                self._sweep_tables, batch,
                np.pad(lens.astype(np.int32), (0, rows - B))))
            return gm[:B]
        except Exception as e:
            from klogs_tpu.ui import term

            term.warning(
                "device sweep failed (%s); narrowing on the host sweep "
                "from here on", str(e)[:120])
            self._sweep_path = "host"
            self._m_sweep_fallback.inc()
            trace.flight_trigger("sweep-fallback", error=str(e))
            return None


def _env_float(name: str, default: float) -> float:
    """Env override parsed strictly: a malformed value raises (silent
    misconfiguration of a degrade knob hides real regressions). The
    shared strict dialect from klogs_tpu.utils.env."""
    from klogs_tpu.utils.env import nonneg_float

    return nonneg_float(name, default)


def _sweep_pipeline_depth() -> int:
    """KLOGS_SWEEP_PIPELINE -> in-flight slab count (1 = serial).

    ``auto`` (the default) keeps depth 2 on multi-core hosts and the
    serial schedule on 1-core ones: overlap needs a second core to run
    the sweep kernel's GIL-free scan beside the confirm stage; on one
    core the pipeline is pure thread-switch overhead. ``off`` (or 0/1)
    pins the serial schedule — the parity oracle. An explicit integer
    pins the depth, clamped to 4 (the win saturates at one slab of
    prefetch because the confirm stage is main-thread-bound).
    Malformed values raise — the strict dialect, same as the other
    index knobs."""
    import os

    from klogs_tpu.utils.env import read

    raw = read("KLOGS_SWEEP_PIPELINE", "auto")
    val = str(raw).strip().lower()
    if val in ("off", "0", "1"):
        return 1
    if val == "auto":
        return 2 if (os.cpu_count() or 1) >= 2 else 1
    try:
        depth = int(val)
    except ValueError:
        raise ValueError(
            f"KLOGS_SWEEP_PIPELINE={raw!r}: expected auto, off, or an "
            "integer pipeline depth") from None
    if depth < 0:
        raise ValueError(
            f"KLOGS_SWEEP_PIPELINE={raw!r}: depth must be >= 0")
    return min(depth, 4)


def _gather_frame(arr: np.ndarray, offsets: np.ndarray, lens: np.ndarray,
                  rows: np.ndarray) -> "tuple[bytes, np.ndarray]":
    """Sub-frame of ``rows`` out of a framed batch, fully vectorized:
    (payload bytes, int32 offsets). ``arr`` is the uint8 view of the
    parent payload."""
    sub_lens = lens[rows].astype(np.int64)
    # Safe outside frame_lines: the sub-frame is a subset of a parent
    # payload whose offsets already passed the int32 guard, so the
    # int64 cumsum can never exceed the parent's int32 total.
    ends = np.cumsum(sub_lens)  # klogs: ignore[int32-guard]
    total = int(ends[-1]) if len(ends) else 0
    sub_off = np.zeros(len(rows) + 1, dtype=np.int32)
    sub_off[1:] = ends.astype(np.int32)
    if not total:
        return b"", sub_off
    # Standard ragged-range trick: absolute source index for every byte.
    starts = offsets[rows].astype(np.int64)
    firsts = np.repeat(starts - np.concatenate(([0], ends[:-1])), sub_lens)
    pos = firsts + np.arange(total, dtype=np.int64)
    return arr[pos].tobytes(), sub_off
