"""Incremental line framing.

Upstream chunks come from HTTP chunked transfer (cmd/root.go:325 analog)
so line boundaries never align with chunk boundaries. The framer turns a
chunk sequence into complete lines (newline retained) plus a final
unterminated remainder at flush.

Two implementations: LineFramer (pure Python, list-of-lines — the
fallback and the oracle) and FramedBatcher (native fast path: one
contiguous buffer + C newline sweep, zero per-line objects — what
FilteredSink rides in production; the reference's one native aspect is
being a compiled binary, SURVEY.md §2).
"""


class LineFramer:
    def __init__(self) -> None:
        self._rest = b""

    def feed(self, chunk: bytes) -> list[bytes]:
        """Returns the complete lines made available by this chunk, each
        including its trailing newline."""
        data = self._rest + chunk if self._rest else chunk
        if b"\n" not in data:
            self._rest = data
            return []
        body, _, rest = data.rpartition(b"\n")
        self._rest = rest
        return [ln + b"\n" for ln in body.split(b"\n")]

    def flush(self) -> bytes | None:
        """The final unterminated line, if any (stream ended mid-line)."""
        rest, self._rest = self._rest, b""
        return rest if rest else None


class FramedBatcher:
    """Chunk stream -> framed pending batch with ZERO per-line Python
    objects: chunks append to one contiguous buffer, a C memchr sweep
    (native.find_newlines) records each complete line's end offset, and
    take() hands the whole pending batch to the framed filter path as
    (payload, int32 offsets, n) — lines keep their trailing newline
    (every engine strips it at match time), so the kept-line join is a
    plain span gather of the same buffer (join_kept_framed).

    This replaces LineFramer + list[bytes] pending in FilteredSink when
    the native module is present: the per-line split/append/len work
    was the last Python-level cost on the collector hot path.
    Requires the native module (callers fall back to LineFramer).
    """

    def __init__(self) -> None:
        from klogs_tpu.native import hostops

        if hostops is None or not hasattr(hostops, "find_newlines"):
            raise RuntimeError("FramedBatcher requires the native module")
        self._hostops = hostops
        self._buf = bytearray()
        self._ends: list[bytes] = []  # raw int32[...] buffers from C
        self.pending_lines = 0

    def feed(self, chunk: bytes) -> int:
        """Returns the number of COMPLETE pending lines after this
        chunk."""
        base = len(self._buf)
        self._buf += chunk
        ends = self._hostops.find_newlines(chunk, base)
        if ends:
            self._ends.append(ends)
            self.pending_lines += len(ends) // 4
        return self.pending_lines

    def take(self, final: bool = False):
        """(payload: bytes, offsets: int32[n+1], n) of every complete
        pending line; resets, carrying the unterminated tail forward.
        ``final`` emits the tail as a last unterminated line (stream
        end, ≙ LineFramer.flush)."""
        import numpy as np

        n = self.pending_lines
        ends = (np.frombuffer(b"".join(self._ends), dtype=np.int32)
                if self._ends else np.zeros(0, dtype=np.int32))
        cut = int(ends[-1]) if n else 0
        tail_len = len(self._buf) - cut
        if final and tail_len:
            payload = bytes(self._buf)
            offsets = np.empty(n + 2, dtype=np.int32)
            offsets[0] = 0
            offsets[1:n + 1] = ends
            offsets[n + 1] = len(payload)
            self._buf = bytearray()
            n += 1
        else:
            payload = bytes(self._buf[:cut])
            offsets = np.empty(n + 1, dtype=np.int32)
            offsets[0] = 0
            offsets[1:] = ends
            self._buf = bytearray(self._buf[cut:]) if tail_len else bytearray()
        self._ends = []
        self.pending_lines = 0
        return payload, offsets, n
