"""Incremental line framing.

Upstream chunks come from HTTP chunked transfer (cmd/root.go:325 analog)
so line boundaries never align with chunk boundaries. The framer turns a
chunk sequence into complete lines (newline retained) plus a final
unterminated remainder at flush.

A pure-Python implementation; a C-extension fast path can slot in here
for the host-side hot loop (the reference's one native aspect is being a
compiled binary, SURVEY.md §2).
"""


class LineFramer:
    def __init__(self) -> None:
        self._rest = b""

    def feed(self, chunk: bytes) -> list[bytes]:
        """Returns the complete lines made available by this chunk, each
        including its trailing newline."""
        data = self._rest + chunk if self._rest else chunk
        if b"\n" not in data:
            self._rest = data
            return []
        body, _, rest = data.rpartition(b"\n")
        self._rest = rest
        return [ln + b"\n" for ln in body.split(b"\n")]

    def flush(self) -> bytes | None:
        """The final unterminated line, if any (stream ended mid-line)."""
        rest, self._rest = self._rest, b""
        return rest if rest else None
