"""Host-regex LogFilter — the CPU baseline.

The north-star analog of klogs + Go ``regexp``: every line is tested
against K compiled patterns with re.search; a line is kept if any
pattern matches. This is both the default ``--backend=cpu`` engine and
the correctness oracle / performance baseline for the TPU path.
"""

import re

from klogs_tpu.filters.base import LogFilter


class RegexFilter(LogFilter):
    def __init__(self, patterns: list[str], ignore_case: bool = False):
        if not patterns:
            raise ValueError("RegexFilter needs at least one pattern")
        flags = re.IGNORECASE if ignore_case else 0
        self._compiled = [re.compile(p.encode(), flags) for p in patterns]

    def match_lines(self, lines: list[bytes]) -> list[bool]:
        compiled = self._compiled
        out = []
        for line in lines:
            body = line.rstrip(b"\n")
            out.append(any(p.search(body) for p in compiled))
        return out
