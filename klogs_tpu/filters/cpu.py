"""Host-side CPU LogFilters: the baseline and the strong opponents.

Three engines, in ascending strength:

- RegexFilter: K sequential ``re.search`` calls per line — the
  north-star analog of klogs + Go ``regexp`` (one compiled regexp per
  pattern, tried in order: /root/reference/cmd/root.go:366 semantics)
  and the correctness oracle for everything else.
- CombinedRegexFilter: ONE compiled alternation ``(?:p1)|(?:p2)|...``
  — a single `re` pass per line.
- DFAFilter: subset-construction DFA over the compiler's class
  alphabet (filters/compiler/dfa.py) scanned by the native C loop —
  one table lookup per byte, early exit on accept. The strongest
  honest CPU opponent; the TPU multiple in BASELINE.md row 3 is
  quoted against this (round-4 verdict: the K-sequential baseline was
  soft).

``best_host_filter`` picks the fastest engine the pattern set admits
(DFA needs the compiler's RE2 subset and a bounded determinization;
fallbacks keep full `re` syntax working).
"""

import re

from klogs_tpu.filters.base import LogFilter
from klogs_tpu.filters.compiler.parser import GROUP_REF_TOKENS

# Renumbering-sensitive feature classifier for best_host_filter's
# combined-alternation fallback, built from the compiler's own table
# (one source of truth; the dispatch-parity pass in tools/analysis
# keeps it honest — see docs/STATIC_ANALYSIS.md).
_GROUP_REF_RE = re.compile("|".join(GROUP_REF_TOKENS))


class RegexFilter(LogFilter):
    def __init__(self, patterns: list[str], ignore_case: bool = False):
        if not patterns:
            raise ValueError("RegexFilter needs at least one pattern")
        flags = re.IGNORECASE if ignore_case else 0
        self._compiled = [re.compile(p.encode(), flags) for p in patterns]

    def match_lines(self, lines: list[bytes]) -> list[bool]:
        compiled = self._compiled
        out = []
        for line in lines:
            body = line.rstrip(b"\n")
            out.append(any(p.search(body) for p in compiled))
        return out


class CombinedRegexFilter(LogFilter):
    """One alternation, one `re` scan per line. Same verdicts as
    RegexFilter for any-match semantics (group numbering differs, but
    no captures are consumed)."""

    def __init__(self, patterns: list[str], ignore_case: bool = False):
        if not patterns:
            raise ValueError("CombinedRegexFilter needs at least one pattern")
        flags = re.IGNORECASE if ignore_case else 0
        joined = b"|".join(b"(?:%s)" % p.encode() for p in patterns)
        self._compiled = re.compile(joined, flags)

    def match_lines(self, lines: list[bytes]) -> list[bool]:
        search = self._compiled.search
        return [search(line.rstrip(b"\n")) is not None for line in lines]


class DFAStateOverflow(ValueError):
    """Subset construction exceeded the state budget — the pattern set
    is DFA-compilable, just not together. Callers that can split the
    set (the indexed engine's group builder) retry on halves; anything
    else treats it as the generic DFAFilter failure."""


class DFAFilter(LogFilter):
    """Determinized union automaton + native flat-table scan.

    Raises ValueError (or RegexSyntaxError) when the pattern set is
    outside the compiler subset, or DFAStateOverflow when the subset
    construction exceeds ``max_states`` — callers fall back to
    CombinedRegexFilter (or bisect, see DFAStateOverflow)."""

    def __init__(self, patterns: list[str], ignore_case: bool = False,
                 max_states: int | None = None, cache: bool = True,
                 cache_events=None):
        from klogs_tpu.filters.compiler.dfa import (
            DEFAULT_MAX_STATES,
            build_dfa,
            build_dfa_cached,
        )

        if not patterns:
            raise ValueError("DFAFilter needs at least one pattern")
        if cache:
            t = build_dfa_cached(patterns, ignore_case=ignore_case,
                                 max_states=max_states or DEFAULT_MAX_STATES,
                                 on_event=cache_events)
        else:
            # cache=False: throwaway table sets (fuzz sweeps build one
            # per trial — writing each to disk would be pure waste).
            from klogs_tpu.filters.compiler.glushkov import compile_patterns

            t = build_dfa(compile_patterns(patterns,
                                           ignore_case=ignore_case),
                          max_states or DEFAULT_MAX_STATES)
        if t is None:
            raise DFAStateOverflow(
                f"DFA for {len(patterns)} pattern(s) exceeds "
                f"{max_states or DEFAULT_MAX_STATES} states")
        self._t = t
        self._table_b = t.table.tobytes()
        self._accept_b = t.accept.tobytes()
        self._bclass_b = t.byte_class.tobytes()

    @property
    def tables(self):
        """The compiled DFATables — the indexed engine's MultiDFA
        program builder packs these (filters/compiler/index.py)."""
        return self._t

    def match_lines(self, lines: list[bytes]) -> list[bool]:
        from klogs_tpu.filters.base import frame_lines

        payload, offsets, _ = frame_lines(lines)
        return self._scan(payload, offsets).tolist()

    def dispatch_framed(self, payload: bytes, offsets):
        return self._scan(payload, offsets)

    def fetch_framed(self, handle):
        return handle

    def _scan(self, payload: bytes, offsets):
        import numpy as np

        from klogs_tpu.native import hostops

        n = len(offsets) - 1
        t = self._t
        if t.match_all:
            return np.ones(n, dtype=bool)
        if hostops is not None and hasattr(hostops, "dfa_scan"):
            mask = hostops.dfa_scan(
                payload, np.ascontiguousarray(offsets, dtype=np.int32), n,
                self._table_b, t.n_classes, self._accept_b, self._bclass_b,
                t.start, t.end_class,
                1 if t.table.dtype == np.uint32 else 0)
            return np.frombuffer(mask, dtype=np.uint8).astype(bool)
        from klogs_tpu.filters.base import split_frame
        from klogs_tpu.filters.compiler.dfa import scan_python

        return np.asarray(scan_python(t, split_frame(payload, offsets)),
                          dtype=bool)


# Pattern-set size from which the factor-index engine takes over in
# auto mode: one union DFA stops determinizing well past the north-star
# scale, and scan-all-K cost grows linearly while the indexed engine's
# tracks candidates (docs/PATTERNS.md). Below it, the single-DFA path
# is both faster and simpler — K=32 behavior is unchanged.
INDEX_MIN_K = 64


def index_min_k() -> int:
    """The auto-mode thousand-pattern threshold (KLOGS_INDEX_MIN_K,
    default INDEX_MIN_K). One reading shared by best_host_filter's
    indexed-engine choice and the TPU engine's device-sweep auto rule,
    so the host and device paths flip to index mode at the same K."""
    from klogs_tpu.utils.env import read as env_read

    try:
        return int(env_read("KLOGS_INDEX_MIN_K", str(INDEX_MIN_K)))
    except ValueError:
        return INDEX_MIN_K


def device_sweep_env() -> str:
    """Validated KLOGS_TPU_SWEEP (auto | 0 | 1). Malformed values
    raise — a typo'd knob silently running without the sweep would be
    an unexplained ~10x at thousand-pattern K. One reading shared by
    the single-chip engine and the mesh so the contract cannot
    diverge."""
    from klogs_tpu.utils.env import read as env_read

    env = env_read("KLOGS_TPU_SWEEP", "auto")
    if env not in ("auto", "0", "1"):
        raise ValueError(
            f"KLOGS_TPU_SWEEP={env!r}: expected auto, 0 or 1")
    return env


def device_sweep_wanted(n_patterns: int,
                        interpret: bool = False) -> bool:
    """The shared engine/mesh device-sweep decision: forced by
    KLOGS_TPU_SWEEP=1, off by =0, and in auto mode on only past the
    SAME K threshold that flips best_host_filter to the indexed
    engine AND on a real accelerator backend — the CPU backend's
    dense sweep is gather-bound and loses to the host sweep
    (BENCH_SWEEP.json). ``interpret`` keeps auto off for interpret-
    mode meshes (debug shape, nothing to win)."""
    env = device_sweep_env()
    if env != "auto":
        return env == "1"
    if n_patterns < index_min_k() or interpret:
        return False
    import jax

    return jax.default_backend() not in ("cpu",)


def device_gate_choice(n_patterns: int, have_prefilter: bool,
                       interpret: bool = False) -> str:
    """THE sweep-vs-prefilter precedence decision, shared by the
    single-chip engine (tpu.py _init_sweep) and the mesh
    (parallel/mesh.py) so the two copies can never drift (deferred
    from PR 8). Returns:

    - ``"off"``: the sweep is not wanted (auto rule / kill switch) —
      keep whatever prefilter the caller built.
    - ``"prefilter"``: the sweep IS wanted but an explicit
      KLOGS_TPU_PREFILTER=1 opt-in wins (the kernel takes one gate);
      the operator notice is printed here.
    - ``"sweep"``: build the sweep tables. The caller must only
      discard a working prefilter AFTER the tables actually build
      (note_sweep_supersedes prints the notice) — a failed build must
      not leave the engine with neither gate.
    """
    if not device_sweep_wanted(n_patterns, interpret=interpret):
        return "off"
    if have_prefilter and device_sweep_env() != "1":
        from klogs_tpu.ui import term

        term.info(
            "KLOGS_TPU_PREFILTER=1 active; device sweep stays "
            "off (set KLOGS_TPU_SWEEP=1 to prefer the sweep)")
        return "prefilter"
    return "sweep"


def note_sweep_supersedes(mesh: bool = False) -> None:
    """The operator notice when a FORCED sweep replaces a working
    prefilter — printed only after the sweep tables built (see
    device_gate_choice)."""
    from klogs_tpu.ui import term

    term.info(
        "KLOGS_TPU_SWEEP=1 supersedes KLOGS_TPU_PREFILTER%s: "
        "the literal sweep subsumes the pair-CNF gate",
        " on the mesh" if mesh else "")


def best_host_filter(patterns: list[str], ignore_case: bool = False,
                     registry=None):
    """Strongest CPU engine this pattern set admits: the factor-index
    engine (filters/indexed.py) for thousand-pattern sets; a single
    union DFA when the compiler subset + determinization allow it; else
    one combined alternation; else K-sequential `re` (an alternation of
    valid `re` patterns is usually valid `re`, but mid-pattern global
    flags like "(?i)x" poison a combined expression). Returns
    (filter, kind). ``registry`` (an obs.Registry) receives the
    indexed engine's klogs_prefilter_* families when given, so a
    --metrics-port sidecar scrapes them.

    KLOGS_CPU_ENGINE={auto,indexed,dfa,combined,re} forces a specific
    engine (re = the reference-parity K-sequential baseline);
    KLOGS_INDEX_MIN_K moves the auto-mode indexed threshold."""
    from klogs_tpu.utils.env import read as env_read

    choice = env_read("KLOGS_CPU_ENGINE", "auto")
    if choice == "re":
        return RegexFilter(patterns, ignore_case=ignore_case), "re"
    if choice == "combined":
        return (CombinedRegexFilter(patterns, ignore_case=ignore_case),
                "combined-re")
    min_k = index_min_k()
    if choice == "indexed" or (choice == "auto" and len(patterns) >= min_k):
        from klogs_tpu.filters.indexed import IndexedFilter

        try:
            return (IndexedFilter(patterns, ignore_case=ignore_case,
                                  registry=registry),
                    "indexed")
        except Exception as e:
            if choice == "indexed":
                raise
            # Auto-mode fallthrough must be LOUD: at this K the ladder
            # below degrades badly (a union DFA rarely determinizes,
            # combined-re scans all K), and a silent ~15x throughput
            # drop with the index never attempted is undebuggable.
            from klogs_tpu.ui import term

            term.warning(
                "indexed engine failed for this %d-pattern set (%s: %s); "
                "falling back to the DFA/combined-re ladder",
                len(patterns), type(e).__name__, e)
    try:
        return DFAFilter(patterns, ignore_case=ignore_case), "dfa"
    except Exception:
        if choice == "dfa":
            raise
    # A combined alternation RENUMBERS groups, so numbered/named
    # backreferences — and conditional group references (?(1)...) /
    # (?(name)...), which bind by the same numbering — would silently
    # resolve to the wrong group and drop lines (ADVICE r5 repro:
    # ['(x)y', '(a)?b(?(1)c|d)'] on b'abc'). Those sets stay on the
    # K-sequential engine.
    if any(_GROUP_REF_RE.search(p) for p in patterns):
        return RegexFilter(patterns, ignore_case=ignore_case), "re"
    try:
        return (CombinedRegexFilter(patterns, ignore_case=ignore_case),
                "combined-re")
    except re.error:
        return RegexFilter(patterns, ignore_case=ignore_case), "re"
