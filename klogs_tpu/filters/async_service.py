"""AsyncFilterService — pipelined, coalescing batch execution for
device filters.

Two problems it solves:

1. **Round-trip latency.** A synchronous ``match_lines`` pays the full
   host<->device round trip per batch (tens of ms on a remote-attached
   TPU), serializing every sink's flush behind it. Device dispatch in
   jax is asynchronous, so dispatch happens on the event loop (cheap
   enqueue) and completion on a small thread pool, N batches in flight.

2. **Tiny-batch flood.** In follow mode, hundreds of rate-limited
   streams each flush a handful of lines every deadline tick; per-sink
   round trips would cap throughput at (workers / RTT) batches/s. The
   service therefore COALESCES concurrent match() calls into jumbo
   device batches — callers' lines are concatenated, one kernel runs,
   and verdict slices resolve each caller's future. The device sees
   large batches (its efficient regime) no matter how fragmented the
   callers are; p99 latency gains the coalesce window (few ms) and
   loses the queueing collapse.

Per-sink write ordering is the sink's concern (FilteredSink holds its
flush lock across the await); cross-sink batches merge and overlap
freely. In-flight device work is bounded (backpressure).

The reference has no counterpart — its write path is synchronous
io.Copy per goroutine (/root/reference/cmd/root.go:359-374); this plays
the role the Go scheduler plays there, adapted to a device whose
dispatch has ms-scale fixed cost.
"""

import asyncio
import time
from concurrent.futures import ThreadPoolExecutor

from klogs_tpu.filters.base import FilterStats, LogFilter, frame_lines
from klogs_tpu.obs import trace

# Each in-flight fetch blocks one worker thread for a full host<->device
# round trip, so sustained batches/s caps at workers / RTT. On a remote
# attach (~74ms RTT) that cap binds well before the engine does; both
# knobs are env-tunable for such deployments. Malformed values warn and
# fall back rather than crashing module import (the shared
# warn-and-default dialect in klogs_tpu.utils.env).
from klogs_tpu.utils.env import warn_positive_int as _env_int


DEFAULT_MAX_IN_FLIGHT = _env_int("KLOGS_MAX_IN_FLIGHT", 16)
DEFAULT_FETCH_WORKERS = _env_int("KLOGS_FETCH_WORKERS", 8)
DEFAULT_COALESCE_LINES = _env_int("KLOGS_COALESCE_LINES", 8192)
DEFAULT_COALESCE_DELAY_S = 0.005

# Offsets ride int32: a coalesced group whose combined payload passes
# this would wrap member offset shifts into negative values (the C
# validators then fail the WHOLE group with an obscure range error).
# Groups are split below the limit instead. Module-level so tests can
# exercise the split without allocating 2 GiB.
GROUP_PAYLOAD_LIMIT = 2**31 - 1


class AsyncFilterService:
    def __init__(self, log_filter: LogFilter,
                 max_in_flight: int = DEFAULT_MAX_IN_FLIGHT,
                 fetch_workers: int = DEFAULT_FETCH_WORKERS,
                 coalesce_lines: int = DEFAULT_COALESCE_LINES,
                 coalesce_delay_s: float = DEFAULT_COALESCE_DELAY_S,
                 stats: FilterStats | None = None,
                 executor: "ThreadPoolExecutor | None" = None,
                 in_flight: "asyncio.Semaphore | None" = None):
        self._filter = log_filter
        # Optional split-latency recording (queue wait vs device time) so
        # --stats can tell saturation queueing from engine latency.
        self._stats = stats
        # Coalescer instrumentation rides the stats' registry (one
        # source of truth with the /metrics scrape); stats=None keeps
        # the zero-overhead path.
        self._m = None
        if stats is not None:
            r = stats.registry
            self._m = {
                "depth": r.family("klogs_coalescer_queue_depth"),
                "pending": r.family("klogs_coalescer_pending_lines"),
                "groups": r.family("klogs_coalescer_groups_total"),
                "members": r.family("klogs_coalescer_group_members"),
                "lines": r.family("klogs_coalescer_group_lines"),
                "splits": r.family("klogs_coalescer_group_splits_total"),
                "bp_wait": r.family(
                    "klogs_coalescer_backpressure_wait_seconds"),
                "dispatch": r.family("klogs_coalescer_dispatch_seconds"),
            }
        # The multi-tenant registry (service/tenancy.py) injects ONE
        # shared fetch pool + ONE in-flight semaphore across every
        # set's service: the process owns one device, so the budget is
        # global. A service only shuts down a pool it created itself.
        # An owned semaphore is created lazily at first dispatch: on
        # Py3.10 it binds the loop alive at CONSTRUCTION, and services
        # are built by make_pipeline before asyncio.run() starts.
        self._sem: "asyncio.Semaphore | None" = in_flight
        self._max_in_flight = max_in_flight
        self._own_pool = executor is None
        self._pool = executor if executor is not None else ThreadPoolExecutor(
            max_workers=fetch_workers, thread_name_prefix="klogs-fetch"
        )
        self._coalesce_lines = coalesce_lines
        self._coalesce_delay_s = coalesce_delay_s
        # Utilization-profiler probes (obs/profiler.py): the live
        # queue-depth / in-flight / executor-saturation samples the
        # /profile snapshot carries. Registered only on instrumented
        # pipelines (stats present), dropped at close; name collisions
        # (multi-set registries build one service per set over the
        # SHARED pool) resolve last-writer-wins, which is the shared
        # budget's one true value anyway.
        self._probes: "dict[str, object]" = {}
        if stats is not None:
            from klogs_tpu.obs.profiler import PROFILER

            self._probes = {
                "coalescer.queue_depth":
                    lambda: float(len(self._pending)),
                "coalescer.pending_lines":
                    lambda: float(self._pending_lines),
                "device.in_flight_used": self._in_flight_used,
                "device.fetch_queue": self._fetch_queue_depth,
            }
            for name, fn in self._probes.items():
                PROFILER.add_probe(name, fn)
        # (payload, offsets, n_lines, future, enqueue_time) per caller.
        self._pending: list[tuple] = []
        self._pending_lines = 0
        self._kick_handle: asyncio.TimerHandle | None = None
        self._closed = False
        # Strong refs: the loop only weakly references tasks, so a
        # coalesced-batch task could be GC'd mid-flight, stranding every
        # caller future in its group.
        self._tasks: set[asyncio.Task] = set()
        self.batches_dispatched = 0  # for tests / stats

    @property
    def coalesce_lines(self) -> int:
        return self._coalesce_lines

    @property
    def max_in_flight(self) -> int:
        return self._max_in_flight

    def apply_tuning(self, coalesce_lines: "int | None" = None,
                     max_in_flight: "int | None" = None) -> None:
        """Adopt a new operating point (ops/tune.py AdaptiveController).
        Coalesce sizing applies from the next enqueue; in-flight depth
        resizes the semaphore LIVE — an increase releases fresh permits
        immediately, a decrease absorbs permits in the background as
        in-flight batches retire (work already dispatched is never
        cancelled). Values are trusted: the controller validates and
        bounds them against the committed operating surface."""
        if coalesce_lines is not None:
            self._coalesce_lines = int(coalesce_lines)
        if max_in_flight is None:
            return
        new = int(max_in_flight)
        delta = new - self._max_in_flight
        if delta == 0:
            return
        self._max_in_flight = new
        sem = self._sem
        if sem is None:
            return  # not yet created: first dispatch builds it at `new`
        if delta > 0:
            for _ in range(delta):
                sem.release()
            return

        async def _absorb(n: int = -delta) -> None:
            # Permits always return as groups retire, so this settles
            # once the pipeline drains to the new depth; aclose gathers
            # it after the group tasks for the same reason. Acquire-
            # and-HOLD is the point (capacity shrinks for good), and
            # the semaphore dies with the service, so a cancelled
            # absorb strands nothing.
            for _ in range(n):
                await sem.acquire()  # klogs: ignore[cancel-safety] — hold is intentional, sem dies with service

        task = asyncio.get_running_loop().create_task(_absorb())
        self._tasks.add(task)
        task.add_done_callback(self._tasks.discard)

    def _in_flight_used(self) -> float:
        """Occupied in-flight dispatch slots (0 before first dispatch
        creates the semaphore)."""
        sem = self._sem
        if sem is None:
            return 0.0
        return float(max(0, self._max_in_flight - sem._value))

    def _fetch_queue_depth(self) -> float:
        """Fetches waiting for a free executor worker — the executor-
        saturation sample (>0 means every fetch worker is mid-round-
        trip and dispatches queue behind them)."""
        q = getattr(self._pool, "_work_queue", None)
        return float(q.qsize()) if q is not None else 0.0

    def _drop_probes(self) -> None:
        if self._probes:
            from klogs_tpu.obs.profiler import PROFILER

            for name, fn in self._probes.items():
                PROFILER.remove_probe(name, fn)  # type: ignore[arg-type]
            self._probes = {}

    async def match(self, lines: list[bytes]) -> list[bool]:
        """Resolves with one verdict per line. Concurrent calls coalesce
        into shared device batches. Internally the batch is framed
        immediately (one contiguous payload + offsets, see
        filters.base.frame_lines) so coalescing and dispatch never touch
        per-line Python objects again."""
        if not lines:
            return []
        payload, offsets, _ = frame_lines(lines)
        arr = await self._enqueue(payload, offsets, len(lines))
        return arr.tolist()

    async def match_framed(self, payload: bytes, offsets):
        """Framed-batch entry: offsets is an int32[n+1] prefix-sum
        array. Resolves with a numpy bool verdict array (a view-slice of
        the coalesced group's verdicts — zero per-line work)."""
        n = len(offsets) - 1
        if n <= 0:  # includes the pathological empty-offsets array
            import numpy as np

            if n < 0:
                raise ValueError("framed batch: empty offsets array")
            return np.zeros(0, dtype=bool)
        return await self._enqueue(payload, offsets, n)

    async def _enqueue(self, payload: bytes, offsets, n: int):
        if self._closed:
            raise RuntimeError("AsyncFilterService is closed")
        loop = asyncio.get_running_loop()
        fut: asyncio.Future = loop.create_future()
        # The caller's span context rides the pending entry: the
        # coalesced group's dispatch span parents under the FIRST
        # caller's trace (one trace carries the full downstream story)
        # and the other members are linked as events.
        ctx = trace.TRACER.current_context()
        if ctx is not None:
            trace.TRACER.event("coalescer.enqueue", lines=n,
                               queue_depth=len(self._pending))
        self._pending.append((payload, offsets, n, fut,
                              time.perf_counter(), ctx))
        self._pending_lines += n
        if self._m is not None:
            self._m["depth"].set(len(self._pending))
            self._m["pending"].set(self._pending_lines)
        if self._pending_lines >= self._coalesce_lines:
            self._kick(loop)
        elif self._kick_handle is None:
            self._kick_handle = loop.call_later(
                self._coalesce_delay_s, self._kick, loop
            )
        return await fut

    def _kick(self, loop) -> None:
        if self._kick_handle is not None:
            self._kick_handle.cancel()
            self._kick_handle = None
        if not self._pending:
            return
        group, self._pending = self._pending, []
        self._pending_lines = 0
        if self._m is not None:
            self._m["depth"].set(0)
            self._m["pending"].set(0)
        task = loop.create_task(self._run_group(group))
        self._tasks.add(task)
        task.add_done_callback(self._tasks.discard)

    async def _run_group(self, group) -> None:
        import numpy as np

        loop = asyncio.get_running_loop()
        if len(group) > 1 and (
                sum(len(e[0]) for e in group) > GROUP_PAYLOAD_LIMIT):
            # A concatenated payload past int32 would wrap the member
            # offset shifts below into negative values. Split into
            # subgroups under the limit (each member is itself bounded:
            # frame_lines and the framed-wire decode both reject >int32
            # single batches) and run them sequentially — correctness
            # over peak batch size in this pathological regime.
            subs, sub, size = [], [], 0
            for e in group:
                if sub and size + len(e[0]) > GROUP_PAYLOAD_LIMIT:
                    subs.append(sub)
                    sub, size = [], 0
                sub.append(e)
                size += len(e[0])
            subs.append(sub)
            if self._m is not None:
                self._m["splits"].inc(len(subs) - 1)
            for sub in subs:
                await self._run_group(sub)
            return
        if len(group) == 1:
            payload, offsets = group[0][0], group[0][1]
        else:
            # Concatenate framed batches: payloads join; each offsets
            # array shifts by the cumulative payload base. All
            # vectorized over the (few) group members, never per line.
            payload = b"".join(e[0] for e in group)
            parts = []
            base = 0
            for e in group:
                parts.append(e[1][:-1] + base)
                base += len(e[0])
            parts.append(np.asarray([base], dtype=np.int32))
            offsets = np.concatenate(parts)
        # One trace carries the group's downstream story: the first
        # member with a recording context parents the dispatch span;
        # the other members' traces are linked as events (a span cannot
        # have N parents, but the flight recorder can still connect
        # them through the link events).
        parent = next(
            (e[5] for e in group
             if e[5] is not None and e[5].sampled),
            next((e[5] for e in group if e[5] is not None), None))
        with trace.TRACER.span("coalescer.dispatch", parent=parent,
                               members=len(group),
                               lines=len(offsets) - 1) as sp:
            for e in group:
                ctx = e[5]
                if (ctx is not None and ctx is not parent
                        and getattr(ctx, "sampled", False)):
                    sp.add_event("coalescer.link",
                                 trace_id=f"{ctx.trace_id:032x}",
                                 span_id=f"{ctx.span_id:016x}")
            try:
                t_sem = time.perf_counter()
                if self._sem is None:
                    self._sem = asyncio.Semaphore(self._max_in_flight)
                async with self._sem:
                    t_dispatch = time.perf_counter()
                    if self._stats is not None:
                        self._stats.mark_batch_started(t_dispatch)
                        for e in group:
                            self._stats.record_queue_wait(t_dispatch - e[4])
                    if self._m is not None:
                        self._m["bp_wait"].observe(t_dispatch - t_sem)
                        self._m["groups"].inc()
                        self._m["members"].observe(len(group))
                        self._m["lines"].observe(len(offsets) - 1)
                    sp.add_event("coalescer.dispatching",
                                 backpressure_wait_s=t_dispatch - t_sem)
                    handle = self._filter.dispatch_framed(payload, offsets)
                    self.batches_dispatched += 1
                    if self._m is not None:
                        self._m["dispatch"].observe(
                            time.perf_counter() - t_dispatch)
                    # The fetch blocks an executor thread for the full
                    # device round trip; the span wraps the AWAIT (the
                    # context var does not cross into the thread — the
                    # await site owns the timing).
                    with trace.TRACER.span("device.fetch"):
                        verdicts = await loop.run_in_executor(
                            self._pool, self._filter.fetch_framed, handle
                        )
                    if self._stats is not None:
                        self._stats.record_device_batch(
                            time.perf_counter() - t_dispatch)
            except Exception as e:
                # The exception is consumed here (routed to the member
                # futures), so __exit__ would record status=ok — mark
                # the span explicitly or the flight dump shows a
                # clean-looking dispatch for the batch that failed.
                sp.set_status("error")
                sp.set_attr("error", f"{type(e).__name__}: {e}")
                for _, _, _, fut, *_ in group:
                    if not fut.done():
                        fut.set_exception(e)
                return
        off = 0
        for _, _, n, fut, *_ in group:
            if not fut.done():
                fut.set_result(verdicts[off : off + n])
            off += n

    async def aclose(self) -> None:
        """Graceful shutdown: dispatch any coalescing (un-kicked) lines,
        then drain in-flight batch tasks, so no caller future is
        stranded and no task dies with the loop."""
        self._closed = True
        self._drop_probes()
        if self._pending:
            self._kick(asyncio.get_running_loop())
        elif self._kick_handle is not None:
            self._kick_handle.cancel()
            self._kick_handle = None
        if self._tasks:
            await asyncio.gather(*list(self._tasks), return_exceptions=True)
        # All in-flight fetches were just gathered, so the join is
        # near-instant — but it still joins threads, which must not
        # happen on the event loop (every other stream's flush would
        # stall behind it). An injected (shared) pool outlives this
        # service: its owner shuts it down.
        if self._own_pool:
            await asyncio.to_thread(self._pool.shutdown)
        self._filter.close()

    def close(self) -> None:
        self._closed = True
        self._drop_probes()
        if self._kick_handle is not None:
            self._kick_handle.cancel()
            self._kick_handle = None
        if self._own_pool:
            self._pool.shutdown(wait=True)
        self._filter.close()
